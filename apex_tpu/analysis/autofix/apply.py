"""The autofix applier: rebuild, re-audit, repeat until clean.

Library ``StepTarget``s are auto-fixable because their specs are data:
the step builders in ``targets.py`` take injected in/out specs and
donate tuples, so applying a :class:`~.patches.Patch` is a builder
re-invocation with merged kwargs — never a source edit. Each round:

1. run the full pass suite over the current target (one shared
   ``StepContext`` — one compile — feeds the passes, the derivation,
   and the ledger),
2. derive prescriptions from the unsuppressed findings,
3. apply every AUTO patch (one with a builder slot) by rebuilding the
   target with merged overrides,

until a round derives zero auto patches (the fixpoint — which is also
the idempotence proof: re-applying the final patch set changes no
override) or :data:`MAX_ROUNDS` is hit, at which point the applier
REFUSES rather than loops (conflicting spec prescriptions for one slot
refuse immediately). Non-auto patches — user code — are rendered as a
unified diff (:func:`render_user_diff`) and left to the user.

The :class:`FixReport` carries the before/after ``predict_comms``
per-axis ledger numbers so the CLI (and tests) can show the predicted
weight-update wire-byte drop the prescriptions bought.
"""

import dataclasses
import difflib
import os
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis.autofix.derive import derive_patches, update_axis
from apex_tpu.analysis.autofix.patches import KIND_DONATE, KIND_SPEC, Patch
from apex_tpu.analysis.findings import Allowlist, Finding, merge_findings
from apex_tpu.analysis.passes import JAXPR_PASSES, StepContext

__all__ = ["MAX_ROUNDS", "FixReport", "apply_fixes", "render_user_diff"]

#: fixpoint bound — a prescription set that has not converged after this
#: many rebuild-and-reaudit rounds is refused, not looped (each round is
#: a fresh compile; a healthy fix lands in round 1 and proves itself in
#: round 2)
MAX_ROUNDS = 4

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)))


@dataclasses.dataclass
class FixReport:
    """What one ``apply_fixes`` run did to one target."""

    target: str
    #: every auto patch applied, in application order across rounds
    applied: List[Patch] = dataclasses.field(default_factory=list)
    #: prescriptions the applier may not touch (user code / no slot)
    manual: List[Patch] = dataclasses.field(default_factory=list)
    #: unsuppressed findings before round 1 and after the last rebuild
    findings_before: List[Finding] = dataclasses.field(default_factory=list)
    findings_after: List[Finding] = dataclasses.field(default_factory=list)
    #: ``predict_comms(...).per_axis()[axis]`` before/after, for the
    #: weight-update axis ({} when the ledger predicts no traffic there)
    axis: str = ""
    ledger_before: Dict = dataclasses.field(default_factory=dict)
    ledger_after: Dict = dataclasses.field(default_factory=dict)
    rounds: int = 0
    #: the fixpoint proof: the final round derived zero auto patches,
    #: i.e. applying the patch set again would change nothing
    idempotent: bool = False
    refused: bool = False
    reason: str = ""
    #: the fixed target (rebuilt) — callers re-audit or reuse it
    final_target: object = None

    @property
    def clean(self) -> bool:
        """No non-info findings survive on the fixed target."""
        return all(f.severity == "info" for f in self.findings_after)

    @property
    def ok(self) -> bool:
        """The CLI exit-0 condition for this target: every pass clean,
        nothing auto-appliable left undone, and the apply is proven
        idempotent. Manual (user-code) prescriptions do NOT fail a
        library target — they are advice, printed as diffs."""
        return self.clean and self.idempotent and not self.refused

    def describe(self) -> List[str]:
        lines = [
            f"[autofix] {self.target}: {len(self.applied)} patch(es) "
            f"applied over {self.rounds} round(s); "
            f"{len(self.manual)} manual prescription(s); "
            + ("idempotent" if self.idempotent else "NOT idempotent")
            + (f"; REFUSED: {self.reason}" if self.refused else "")
        ]
        for p in self.applied:
            lines.append(f"  applied: {p.describe()}")
        for p in self.manual:
            lines.append(f"  manual:  {p.describe()}")
        if self.axis and self.ledger_before:
            b = self.ledger_before
            a = self.ledger_after or {}
            lines.append(
                f"  predicted {self.axis!r}-axis wire bytes/step: "
                f"{b.get('ici_bytes', 0)} -> {a.get('ici_bytes', 0)} "
                f"(payload {b.get('bytes', 0)} -> {a.get('bytes', 0)})"
            )
        n_err = sum(1 for f in self.findings_after if f.severity != "info")
        lines.append(
            f"  residual non-info findings: {n_err} "
            f"({'clean' if self.clean else 'NOT clean'})"
        )
        return lines


def _run_suite(target, passes: Optional[Sequence[str]],
               allowlist: Optional[Allowlist]):
    """One audit round sharing a single StepContext (= one compile)
    between the passes and the derivation inputs. Returns
    ``(kept_findings, ctx, ledger)``."""
    names = list(passes) if passes is not None else sorted(JAXPR_PASSES)
    ctx = StepContext(target)
    raw: List[Finding] = []
    for name in names:
        raw.extend(JAXPR_PASSES[name](ctx))
    merged = merge_findings(raw)
    kept = (
        allowlist.apply(merged, check_stale=False).findings
        if allowlist is not None else merged
    )
    from apex_tpu.monitor.xray.ledger import predict_comms

    try:
        ledger = predict_comms(target.fn, *target.args)
    except Exception:
        ledger = None
    return kept, ctx, ledger


def _axis_totals(ledger, axis: str) -> Dict:
    if ledger is None or not axis:
        return {}
    return dict(ledger.per_axis().get(axis, {}))


def _merge_overrides(target, patches: Sequence[Patch]):
    """Fold auto patches into the builder kwargs. Returns
    ``(overrides, applied, conflict_reason)`` — ``applied`` holds only
    the patches that actually CHANGE an override (the no-progress
    guard), ``conflict_reason`` is non-empty when two prescriptions
    disagree about one slot (the refuse-immediately case)."""
    overrides = dict(target.build_overrides)
    applied: List[Patch] = []
    want_spec: Dict[str, Patch] = {}
    for p in patches:
        if p.kind == KIND_SPEC and p.slot:
            prev = want_spec.get(p.slot)
            if prev is not None and tuple(prev.spec) != tuple(p.spec):
                return overrides, [], (
                    f"conflicting specs for builder slot {p.slot!r}: "
                    f"{prev.spec} vs {p.spec}"
                )
            want_spec[p.slot] = p
    for slot, p in want_spec.items():
        cur = overrides.get(slot)
        if cur is None or tuple(cur) != tuple(p.spec):
            overrides[slot] = p.spec
            applied.append(p)
    donate_adds = [p for p in patches if p.kind == KIND_DONATE and p.slot]
    if donate_adds:
        slot = donate_adds[0].slot
        cur = tuple(overrides.get(slot) or ())
        new = tuple(sorted(set(cur) | {p.argnum for p in donate_adds}))
        if new != cur:
            overrides[slot] = new
            applied.extend(
                p for p in donate_adds if p.argnum not in cur
            )
    return overrides, applied, ""


def apply_fixes(
    target,
    *,
    passes: Optional[Sequence[str]] = None,
    allowlist: Optional[Allowlist] = None,
    max_rounds: int = MAX_ROUNDS,
) -> FixReport:
    """Drive one target to its audit fixpoint; see the module docstring.

    The target must carry a ``builder`` to be auto-fixable; without one
    every derived patch lands in ``report.manual`` and the (unchanged)
    target is re-reported as-is."""
    report = FixReport(target=target.name)
    kept, ctx, ledger = _run_suite(target, passes, allowlist)
    report.findings_before = list(kept)
    report.axis = update_axis(target.mesh, ledger) or ""
    report.ledger_before = _axis_totals(ledger, report.axis)
    report.findings_after = list(kept)
    report.ledger_after = dict(report.ledger_before)
    report.final_target = target

    for round_no in range(1, max_rounds + 1):
        try:
            module = ctx.hlo_module()
        except ValueError:
            module = None
        patches = derive_patches(
            target, kept, module=module, mesh=target.mesh, ledger=ledger
        )
        auto = [p for p in patches if p.auto and target.builder is not None]
        manual = [p for p in patches if not (p.auto and target.builder)]
        _merge_manual(report, manual)
        if not auto:
            # fixpoint: nothing auto-appliable derives from the current
            # target — by construction a second apply is a no-op
            report.idempotent = True
            break
        report.rounds = round_no
        overrides, applied, conflict = _merge_overrides(target, auto)
        if conflict:
            report.refused, report.reason = True, conflict
            break
        if not applied:
            # prescriptions derive but change no builder kwarg: applying
            # again would spin forever — refuse, don't loop
            report.refused, report.reason = True, (
                f"{len(auto)} auto prescription(s) change no builder "
                f"override — the flagged defect survives its own fix"
            )
            break
        target = target.builder(target.mesh, **overrides)
        report.applied.extend(applied)
        report.final_target = target
        kept, ctx, ledger = _run_suite(target, passes, allowlist)
        report.findings_after = list(kept)
        report.ledger_after = _axis_totals(ledger, report.axis)
    else:
        report.refused = True
        report.reason = (
            f"no fixpoint within {max_rounds} rounds — prescriptions "
            f"keep deriving after every rebuild"
        )
    return report


def _merge_manual(report: FixReport, manual: Sequence[Patch]):
    seen = {
        (p.kind, p.argnum, p.site, str(p.spec)) for p in report.manual
    }
    for p in manual:
        key = (p.kind, p.argnum, p.site, str(p.spec))
        if key not in seen:
            seen.add(key)
            report.manual.append(p)


def render_user_diff(patches: Sequence[Patch],
                     root: Optional[str] = None) -> str:
    """A unified diff inserting each constraint prescription at its HLO
    provenance site (``file.py:line``) — printed for the user, NEVER
    written back: user code is theirs. Patches whose site is not a
    resolvable source location fall back to a comment-only hunk header
    describing the prescription."""
    root = root or _REPO_ROOT
    out: List[str] = []
    by_file: Dict[str, List[Patch]] = {}
    for p in patches:
        if p.slot is not None:
            continue  # auto patches apply through the builder, no diff
        path, _, line = p.site.rpartition(":")
        if path and line.isdigit() and os.path.isfile(
            os.path.join(root, path)
        ):
            by_file.setdefault(path, []).append(p)
        else:
            out.append(f"# unapplied prescription (no source site): "
                       f"{p.describe()}")
    for path, plist in sorted(by_file.items()):
        with open(os.path.join(root, path)) as f:
            src = f.readlines()
        patched = list(src)
        # bottom-up so earlier insertion points stay valid
        for p in sorted(plist, key=lambda q: -int(p_site_line(q))):
            line_no = min(p_site_line(p), len(patched))
            indent = ""
            if line_no >= 1 and line_no <= len(patched):
                ref = patched[line_no - 1]
                indent = ref[: len(ref) - len(ref.lstrip())]
            spec_src = (
                p.payload()["spec"] or "PartitionSpec()"
            )
            patched.insert(line_no - 1, (
                f"{indent}# autofix: {p.reason}\n"
                f"{indent}# x = jax.lax.with_sharding_constraint(\n"
                f"{indent}#     x, NamedSharding(mesh, {spec_src}))\n"
            ))
        out.extend(difflib.unified_diff(
            src, patched, fromfile=f"a/{path}", tofile=f"b/{path}"
        ))
    return "".join(
        ln if ln.endswith("\n") else ln + "\n" for ln in out
    )


def p_site_line(p: Patch) -> int:
    _, _, line = p.site.rpartition(":")
    return int(line) if line.isdigit() else 1
