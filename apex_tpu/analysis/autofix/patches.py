"""Typed prescription records: what the autofix derivation emits.

A :class:`Patch` is one concrete, checkable fix for one flagged entry
buffer — a ``PartitionSpec`` in the GSPMD ``NamedSharding`` idiom, a
``with_sharding_constraint`` insertion site, or a ``donate_argnums``
addition. Patches are *data about code*, never code edits: the applier
(apply.py) injects them into library step builders whose specs are data
(``targets.py``), and renders a unified diff for user code instead of
mutating it.

Every prescription carries the predicted dp-axis wire-byte delta under
the xray ledger's ici convention (``monitor/xray/ledger.py``): sharding
a replicated weight update turns the full-payload grad allreduce into a
reduce-scatter, saving ``ici(psum, B) - ici(psum_scatter, B)`` wire
bytes per step for a buffer of ``B`` bytes — the arXiv:2004.13336
accounting the sharding auditor cites.

Patches export as ``kind="analysis"`` findings with the ``fix=``
payload (``to_finding``), so prescriptions ride the same jsonl stream
as the defects they fix.
"""

import dataclasses
from typing import Optional, Tuple

from apex_tpu.analysis.findings import Finding, SEV_INFO

__all__ = ["Patch", "KIND_SPEC", "KIND_DONATE", "KIND_CONSTRAINT"]

KIND_SPEC = "shard-spec"
KIND_DONATE = "donate"
KIND_CONSTRAINT = "constraint"
_KINDS = (KIND_SPEC, KIND_DONATE, KIND_CONSTRAINT)


@dataclasses.dataclass(frozen=True)
class Patch:
    """One prescription.

    - ``kind``: ``shard-spec`` (inject a PartitionSpec for an entry
      arg), ``donate`` (add an argnum to the donate tuple), or
      ``constraint`` (insert ``with_sharding_constraint`` at ``site`` —
      user code, rendered as a diff, never auto-applied).
    - ``target``: the StepTarget name the prescription belongs to.
    - ``argnum``/``leaf``: the flagged entry argument / its human label.
    - ``spec``: the prescribed ``jax.sharding.PartitionSpec`` (None for
      ``donate``).
    - ``site``: where to apply — a builder slot (``<builder:kwarg>``)
      for library targets, a ``file.py:line`` insertion site for user
      code.
    - ``slot``: the builder kwarg the applier injects into; None means
      not auto-appliable (user code, or no builder hook).
    - ``wire_delta``: predicted per-step wire-byte saving on ``axis``
      under the ledger's ici convention (0 for donation — that saving
      is HBM, carried in ``hbm_delta``).
    """

    kind: str
    target: str
    argnum: Optional[int]
    leaf: str
    spec: Optional[Tuple] = None
    site: str = ""
    reason: str = ""
    axis: str = ""
    wire_delta: int = 0
    hbm_delta: int = 0
    slot: Optional[str] = None

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"patch kind must be one of {_KINDS}, "
                             f"got {self.kind!r}")

    @property
    def auto(self) -> bool:
        """Auto-appliable: the target's builder exposes a slot for it."""
        return self.slot is not None

    def payload(self) -> dict:
        """The ``fix=`` payload: JSON-safe, spec rendered as source."""
        return {
            "kind": self.kind,
            "argnum": self.argnum,
            "leaf": self.leaf,
            "spec": _spec_src(self.spec) if self.spec is not None else None,
            "site": self.site,
            "reason": self.reason,
            "axis": self.axis,
            "wire_delta_bytes": self.wire_delta,
            "hbm_delta_bytes": self.hbm_delta,
            "auto": self.auto,
        }

    def to_finding(self) -> Finding:
        """The prescription as a ``kind="analysis"`` finding (info: a
        prescription is the fix, not a defect — the defect it fixes is
        already on the stream)."""
        return Finding(
            rule="autofix.prescription",
            message=self.describe(),
            site=self.site or f"<fix:{self.target}>",
            severity=SEV_INFO,
            target=self.target,
            data={"kind": self.kind, "leaf": self.leaf},
            fix=self.payload(),
        )

    def describe(self) -> str:
        if self.kind == KIND_DONATE:
            return (
                f"add argnum {self.argnum} ({self.leaf}) to donate_argnums "
                f"— frees {self.hbm_delta} B of double-buffered HBM "
                f"({self.reason})"
            )
        spec_src = _spec_src(self.spec)
        how = (
            f"inject via builder kwarg {self.slot!r}" if self.auto
            else f"insert with_sharding_constraint at {self.site}"
        )
        return (
            f"shard {self.leaf} (arg {self.argnum}) as NamedSharding(mesh, "
            f"{spec_src}) — {how}; predicted {self.axis!r}-axis wire delta "
            f"{self.wire_delta} B/step ({self.reason})"
        )


def _spec_src(spec) -> str:
    """A PartitionSpec as the source text users would write."""
    if spec is None:
        return "PartitionSpec()"
    parts = ", ".join(repr(a) for a in tuple(spec))
    return f"PartitionSpec({parts})"
