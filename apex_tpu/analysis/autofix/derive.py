"""Prescription derivation: findings -> concrete Patches.

The derivation leg of the autofix loop. Input is what the pass suite
already computed and shares per target — the parsed ``HloModule``
(entry-param shardings + ``metadata.source_file/line`` provenance), the
mesh, and the ``predict_comms`` ledger — plus the unsuppressed findings.
Output is a list of typed :class:`~.patches.Patch` records:

- ``sharding.replicated-param`` -> a ``PartitionSpec`` over the weight-
  update axis (the mesh axis carrying the gradient-reduction traffic in
  the ledger — arXiv:2004.13336's dp axis), sharding the first dimension
  the axis size divides. The ZeRO flat-buffer convention guarantees
  divisibility for flat opt state (``flatten_pytree`` pads to a chunk
  multiple); a buffer with no divisible dim gets a non-auto constraint
  prescription instead (refuse, don't guess).
- ``sharding.replicated-output`` -> the same spec, resolved to the entry
  argument whose shape/dtype the output mirrors (functional step
  updates return their state).
- ``donation.missed``            -> a ``donate_argnums`` addition.
- ``comms.reshard``              -> a ``with_sharding_constraint``
  insertion at the finding's HLO-provenance site, seeded from the
  finding's ``suggestion`` field (never auto-applied: that is user
  code).

Whether a patch is AUTO-appliable is the target's call, not ours: a
``StepTarget`` whose builder exposes the flagged argument through
``spec_slots``/``donate_slot`` gets the builder kwarg recorded in
``Patch.slot``; everything else stays a printed prescription.
"""

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np

from apex_tpu.analysis.autofix.patches import (
    KIND_CONSTRAINT, KIND_DONATE, KIND_SPEC, Patch,
)
from apex_tpu.analysis.findings import Finding

__all__ = ["derive_patches", "update_axis"]


def update_axis(mesh, ledger=None) -> Optional[str]:
    """The weight-update (gradient-sync) axis: among the mesh's >1-sized
    axes, the one moving the most allreduce-class bytes in the ledger's
    prediction — per arXiv:2004.13336 the axis whose update replication
    is worth sharding. Falls back to the largest axis (ties: first in
    mesh order) when no ledger traffic distinguishes them."""
    if mesh is None:
        return None
    shape = dict(mesh.shape)
    live = [n for n in mesh.axis_names if shape[n] > 1]
    if not live:
        return None
    reduce_bytes = {n: 0 for n in live}
    if ledger is not None:
        for e in ledger.entries:
            if e.axis in reduce_bytes and e.op in (
                "psum", "pmean", "psum_scatter"
            ):
                reduce_bytes[e.axis] += e.bytes * e.count
    return max(live, key=lambda n: (reduce_bytes[n], shape[n]))


def _leaf_owners(args: Sequence[Any], fn=None) -> List[Tuple[int, str]]:
    """Flat leaf index -> (argnum, human label), the donation auditor's
    labeling (keep_unused=True makes HLO params map 1:1 onto these)."""
    names = None
    if fn is not None:
        import inspect

        try:
            names = list(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            names = None
    owners: List[Tuple[int, str]] = []
    for i, arg in enumerate(args):
        name = names[i] if names and i < len(names) else f"arg{i}"
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        for path, _leaf in flat:
            owners.append((i, name + jax.tree_util.keystr(path)))
    return owners


def _shard_spec_for(shape: Tuple[int, ...], axis: str, axis_size: int):
    """P(..., axis, ...) over the first dimension ``axis_size`` divides,
    or None when no dimension is divisible (the refusal case)."""
    from jax.sharding import PartitionSpec as P

    for dim, extent in enumerate(shape):
        if extent and extent % axis_size == 0:
            return P(*([None] * dim + [axis]))
    return None


def _ici_delta(nbytes: int, n: int) -> int:
    """Wire-byte saving of sharding a replicated weight update over an
    ``n``-sized axis, ledger ici convention (monitor/xray/ledger.py):
    the full-payload grad allreduce (``2(n-1)B/n``) becomes a
    reduce-scatter (``(n-1)B/n``) — the update's all-gather replaces
    the resync traffic replicated updates need, so the reduction-half
    saving is the per-step delta."""
    if n <= 1:
        return 0
    return (
        math.ceil(2 * (n - 1) * nbytes / n)
        - math.ceil((n - 1) * nbytes / n)
    )


def derive_patches(
    target,
    findings: Sequence[Finding],
    *,
    module=None,
    mesh=None,
    ledger=None,
) -> List[Patch]:
    """Turn one target's unsuppressed findings into Patches; see the
    module docstring for the per-rule derivation. ``module``/``mesh``/
    ``ledger`` are the pass suite's shared products (the parsed
    ``HloModule``, the audit mesh, the ``predict_comms`` ledger) —
    None degrades gracefully (axis falls back to mesh shape, labels to
    arg flattening)."""
    mesh = mesh if mesh is not None else getattr(target, "mesh", None)
    axis = update_axis(mesh, ledger)
    if axis is None:
        return []
    axis_size = int(dict(mesh.shape)[axis])
    owners = _leaf_owners(target.args, getattr(target, "fn", None))
    in_leaves = jax.tree_util.tree_leaves(target.args)
    spec_slots = dict(getattr(target, "spec_slots", None) or {})
    donate_slot = getattr(target, "donate_slot", None)
    out_leaves = None  # lazily built for replicated-output resolution

    patches: List[Patch] = []
    seen = set()

    def emit(p: Patch):
        key = (p.kind, p.slot, p.argnum, p.spec, p.site)
        if key not in seen:
            seen.add(key)
            patches.append(p)

    for f in findings:
        if f.rule == "sharding.replicated-param":
            idx = f.data.get("index")
            if idx is None or idx >= len(owners):
                continue
            argnum, label = owners[idx]
            shape = tuple(in_leaves[idx].shape)
            nbytes = int(f.data.get("bytes", 0))
            spec = _shard_spec_for(shape, axis, axis_size)
            if spec is None:
                emit(Patch(
                    kind=KIND_CONSTRAINT, target=target.name,
                    argnum=argnum, leaf=label, spec=None, site=f.site,
                    axis=axis, reason=(
                        f"refused: no dimension of {shape} divisible by "
                        f"{axis!r}={axis_size} — repad or reshape before "
                        f"sharding"
                    ),
                ))
                continue
            slot = spec_slots.get(argnum)
            emit(Patch(
                kind=KIND_SPEC if slot else KIND_CONSTRAINT,
                target=target.name, argnum=argnum, leaf=label, spec=spec,
                site=(f"<builder:{slot}>" if slot else f.site),
                axis=axis, wire_delta=_ici_delta(nbytes, axis_size),
                hbm_delta=nbytes - nbytes // axis_size,
                slot=slot,
                reason=(
                    f"{nbytes} B replicated {axis_size}x over {axis!r} — "
                    f"ZeRO weight-update sharding (arXiv:2004.13336)"
                ),
            ))
        elif f.rule == "sharding.replicated-output":
            # a functional step returns its state: resolve the output to
            # the spec-slot argument it mirrors (shape+dtype), so the
            # in/out specs move together through the one builder kwarg
            oi = f.data.get("output")
            if oi is None:
                continue
            if out_leaves is None:
                try:
                    out_leaves = jax.tree_util.tree_leaves(
                        jax.eval_shape(target.fn, *target.args)
                    )
                except Exception:
                    out_leaves = []
            if oi >= len(out_leaves):
                continue
            out = out_leaves[oi]
            for idx, (argnum, label) in enumerate(owners):
                leaf = in_leaves[idx]
                if (argnum in spec_slots
                        and tuple(leaf.shape) == tuple(out.shape)
                        and leaf.dtype == out.dtype):
                    spec = _shard_spec_for(tuple(out.shape), axis, axis_size)
                    if spec is None:
                        break
                    nbytes = int(f.data.get("bytes", 0))
                    emit(Patch(
                        kind=KIND_SPEC, target=target.name, argnum=argnum,
                        leaf=label, spec=spec,
                        site=f"<builder:{spec_slots[argnum]}>",
                        axis=axis, slot=spec_slots[argnum],
                        wire_delta=_ici_delta(nbytes, axis_size),
                        hbm_delta=nbytes - nbytes // axis_size,
                        reason=(
                            f"output #{oi} mirrors arg {argnum} ({label}) "
                            f"— shard the state spec, in and out move "
                            f"together"
                        ),
                    ))
                    break
        elif f.rule == "donation.missed":
            label = f.data.get("leaf", "")
            argnum = next(
                (a for a, lb in owners if lb == label), None
            )
            if argnum is None:
                continue
            emit(Patch(
                kind=KIND_DONATE, target=target.name, argnum=argnum,
                leaf=label,
                site=(f"<builder:{donate_slot}>" if donate_slot else f.site),
                slot=donate_slot,
                hbm_delta=int(f.data.get("bytes", 0)),
                reason="output of same shape/dtype has no alias",
            ))
        elif f.rule == "comms.reshard":
            suggestion = f.data.get("suggestion") or (
                f"insert with_sharding_constraint(..., NamedSharding(mesh, "
                f"PartitionSpec({f.data.get('axis', axis)!r}))) at the "
                f"reshard site"
            )
            from jax.sharding import PartitionSpec as P

            emit(Patch(
                kind=KIND_CONSTRAINT, target=target.name, argnum=None,
                leaf="(entry param)", spec=P(f.data.get("axis", axis)),
                site=f.site, axis=f.data.get("axis", axis),
                wire_delta=int(np.int64(f.data.get("hlo_bytes", 0))),
                reason=suggestion,
            ))
    return patches
