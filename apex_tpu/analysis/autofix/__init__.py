"""Autofix: turn analysis findings into applied fixes.

The prescriptive half of ``apex_tpu.analysis`` (ROADMAP item 2a). The
pass suite *finds* replicated weight updates, missed donations, and
partitioner reshards; this package *derives* concrete prescriptions for
them (``derive.py`` -> typed ``Patch`` records, ``patches.py``), applies
the auto-appliable ones to library step builders whose specs are data,
and re-audits to a bounded fixpoint (``apply.py``). User code is never
mutated — those prescriptions render as unified diffs.

Entry point: ``python -m apex_tpu.analysis --fix``.
"""

from apex_tpu.analysis.autofix.apply import (
    MAX_ROUNDS, FixReport, apply_fixes, render_user_diff,
)
from apex_tpu.analysis.autofix.derive import derive_patches, update_axis
from apex_tpu.analysis.autofix.patches import (
    KIND_CONSTRAINT, KIND_DONATE, KIND_SPEC, Patch,
)

__all__ = [
    "MAX_ROUNDS",
    "FixReport",
    "Patch",
    "KIND_SPEC",
    "KIND_DONATE",
    "KIND_CONSTRAINT",
    "apply_fixes",
    "derive_patches",
    "render_user_diff",
    "update_axis",
]
