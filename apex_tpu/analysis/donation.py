"""Donation auditor: declared donate_argnums vs XLA's realized aliasing.

Donation is how a functional-update train step stops double-buffering
the model: ``jit(step, donate_argnums=(params, opt_state, ...))`` lets
XLA write the new state into the old state's HBM. It fails SILENTLY in
two places, both invisible until a step OOMs:

1. at LOWERING — jax drops a donated buffer that matches no output's
   shape/dtype (a UserWarning nobody reads in a training log); the MLIR
   simply lacks the ``tf.aliasing_output`` mark for that parameter;
2. at COMPILE — XLA declines to realize a marked alias (layout/backend
   constraints); the optimized HLO's ``input_output_alias`` config is
   the ground truth of what actually aliases.

This auditor compiles the step (``.lower().compile()`` — the one pass
here that is not pure tracing; CPU-safe, a few seconds for the tiny CLI
targets) and cross-checks three layers:

- requested: flat input buffers covered by ``donate_argnums``,
- marked:    parameters carrying ``tf.aliasing_output`` in the lowered
             MLIR,
- realized:  the compiled module's ``input_output_alias`` entries,

emitting ``donation.rejected`` for requested-but-not-realized buffers
(with the stage that dropped them) and ``donation.missed`` for large
non-donated inputs whose shape/dtype matches an un-aliased output —
the params/opt-state buffer someone forgot, which is a whole extra copy
of the model in HBM.
"""

import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from apex_tpu.analysis.findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING
from apex_tpu.analysis.passes import jaxpr_pass

__all__ = ["audit_donation", "donation_pass"]

#: buffers below this size are not worth donating (the alias bookkeeping
#: outweighs scalar-sized savings); "could be donated" findings only fire
#: above it
DEFAULT_MIN_DONATABLE_BYTES = 1 << 20


def _nbytes(aval) -> int:
    return int(np.prod(aval.shape, dtype=np.int64)) * np.dtype(
        aval.dtype
    ).itemsize


def _leaf_labels(args, arg_names: Optional[Sequence[str]]) -> List[str]:
    """One human label per flat input leaf: ``params['w']['kernel']``."""
    labels = []
    for i, arg in enumerate(args):
        name = arg_names[i] if arg_names and i < len(arg_names) else f"arg{i}"
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        if not flat:
            continue
        for path, _leaf in flat:
            labels.append(name + jax.tree_util.keystr(path))
    return labels


def _donated_leaf_indices(args, donate_argnums) -> set:
    donated, offset = set(), 0
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in donate_argnums:
            donated.update(range(offset, offset + n))
        offset += n
    return donated


def _main_signature(mlir_text: str) -> Optional[str]:
    """The argument list of the entry ``@main`` func, by paren matching."""
    m = re.search(r"func\.func\s+public\s+@main\s*\(", mlir_text)
    if m is None:
        return None
    depth, start = 1, m.end()
    for i in range(start, len(mlir_text)):
        c = mlir_text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return mlir_text[start:i]
    return None


def _marked_aliases(
    mlir_text: str,
) -> Tuple[Optional[Dict[int, Optional[int]]], int]:
    """``{param_index: output_index_or_None}`` for parameters jax marked
    donated, plus the entry parameter count. jax spells the mark two
    ways: ``tf.aliasing_output = N`` when it matched the donated input to
    output N itself, or ``jax.buffer_donor = true`` when it hands XLA the
    buffer and lets the compiler pick the alias (value None). (None, 0)
    when the signature cannot be found."""
    sig = _main_signature(mlir_text)
    if sig is None:
        return None, 0
    marked: Dict[int, Optional[int]] = {}
    chunks = re.split(r"%arg(\d+)\s*:", sig)
    # chunks: [prefix, idx0, body0, idx1, body1, ...]
    nparams = 0
    for i in range(1, len(chunks) - 1, 2):
        param = int(chunks[i])
        nparams = max(nparams, param + 1)
        m = re.search(r"tf\.aliasing_output\s*=\s*(\d+)", chunks[i + 1])
        if m:
            marked[param] = int(m.group(1))
        elif re.search(r"jax\.buffer_donor\s*=\s*true", chunks[i + 1]):
            marked[param] = None
    return marked, nparams


def _realized_aliases(hlo_text: str) -> Dict[int, int]:
    """``{param_index: output_index}`` from the optimized HLO module's
    ``input_output_alias`` config (absent section = nothing realized)."""
    m = re.search(r"input_output_alias=\{", hlo_text)
    if m is None:
        return {}
    depth, start = 1, m.end()
    end = start
    for i in range(start, len(hlo_text)):
        c = hlo_text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                end = i
                break
    section = hlo_text[start:end]
    realized: Dict[int, int] = {}
    for mm in re.finditer(r"\{([\d ,]*)\}:\s*\((\d+)", section):
        out_idx = int(mm.group(1).split(",")[0]) if mm.group(1).strip() else 0
        realized[int(mm.group(2))] = out_idx
    return realized


def audit_donation(
    fn,
    *args,
    donate_argnums: Optional[Sequence[int]] = None,
    min_donatable_bytes: int = DEFAULT_MIN_DONATABLE_BYTES,
    arg_names: Optional[Sequence[str]] = None,
    target: str = "",
) -> List[Finding]:
    """Audit one step's donation story; see the module docstring.

    ``fn`` may be a plain function (``donate_argnums`` required — the
    auditor builds the jit with ``keep_unused=True`` so HLO parameters
    map 1:1 onto flat input leaves) or an already-jitted function whose
    own ``donate_argnums`` are used (pass nothing). Args may be arrays
    or ``ShapeDtypeStruct``s — nothing executes, but the step IS
    compiled.
    """
    if donate_argnums is None:
        if not hasattr(fn, "lower"):
            raise ValueError(
                "donate_argnums is required for a non-jitted step function"
            )
        lowered = fn.lower(*args)
        compiled = lowered.compile()
        # Compiled.donate_argnums reports FLAT input-leaf indices (not the
        # user-level argnums the jit was built with) — exactly the set we
        # need, no tree math
        requested = set(compiled.donate_argnums)
    else:
        donate_argnums = tuple(donate_argnums)
        lowered = jax.jit(
            fn, donate_argnums=donate_argnums, keep_unused=True
        ).lower(*args)
        compiled = lowered.compile()
        requested = _donated_leaf_indices(args, set(donate_argnums))

    labels = _leaf_labels(args, arg_names)
    in_leaves = jax.tree_util.tree_leaves(args)
    marked, nparams = _marked_aliases(lowered.as_text())
    realized = _realized_aliases(compiled.as_text())

    findings: List[Finding] = []
    site = f"<step:{target or getattr(fn, '__name__', 'fn')}>"
    if marked is None or nparams != len(in_leaves):
        # pruned/unparseable parameter list: leaf<->parameter numbering no
        # longer lines up, so report honestly instead of guessing
        findings.append(Finding(
            rule="donation.unverifiable",
            message=(
                f"cannot map HLO parameters to input leaves "
                f"({nparams} entry params vs {len(in_leaves)} leaves; "
                f"args pruned or MLIR shape unexpected) — donation not "
                f"verified"
            ),
            site=site, severity=SEV_INFO, target=target,
        ))
        return findings

    for idx in sorted(requested):
        label = labels[idx] if idx < len(labels) else f"leaf{idx}"
        nbytes = _nbytes(in_leaves[idx])
        # a rejected scalar/tiny donation wastes no memory worth chasing:
        # report it as advisory (info), not a gate failure
        sev = SEV_ERROR if nbytes >= min_donatable_bytes else SEV_INFO
        if idx not in marked:
            findings.append(Finding(
                rule="donation.rejected",
                message=(
                    f"{label} ({nbytes} B) is donated but matches no "
                    f"output shape/dtype: jax dropped the donation at "
                    f"lowering (its HBM is freed, never reused)"
                ),
                site=site, severity=sev, target=target,
                data={"leaf": label, "bytes": nbytes, "stage": "lowering"},
            ))
        elif idx not in realized:
            findings.append(Finding(
                rule="donation.rejected",
                message=(
                    f"{label} ({nbytes} B) is marked for donation but XLA "
                    f"did not realize the input/output alias"
                ),
                site=site, severity=sev, target=target,
                data={"leaf": label, "bytes": nbytes, "stage": "compile"},
            ))

    # large non-donated inputs that COULD alias an output nothing claims
    out_leaves = jax.tree_util.tree_leaves(jax.eval_shape(fn, *args))
    taken_outputs = set(realized.values())
    free_out_shapes = {}
    for oi, leaf in enumerate(out_leaves):
        if oi not in taken_outputs:
            key = (tuple(leaf.shape), np.dtype(leaf.dtype))
            free_out_shapes[key] = free_out_shapes.get(key, 0) + 1
    for idx, leaf in enumerate(in_leaves):
        if idx in requested:
            continue
        nbytes = _nbytes(leaf)
        if nbytes < min_donatable_bytes:
            continue
        key = (tuple(leaf.shape), np.dtype(leaf.dtype))
        if free_out_shapes.get(key, 0) > 0:
            free_out_shapes[key] -= 1
            label = labels[idx] if idx < len(labels) else f"leaf{idx}"
            findings.append(Finding(
                rule="donation.missed",
                message=(
                    f"{label} ({nbytes} B) is not donated but an output "
                    f"of the same shape/dtype has no alias — donating it "
                    f"would reuse the buffer instead of double-buffering"
                ),
                site=site, severity=SEV_WARNING, target=target,
                data={"leaf": label, "bytes": nbytes},
            ))
    return findings


@jaxpr_pass("donation")
def donation_pass(ctx) -> Iterable[Finding]:
    if ctx.donate_argnums is None:
        return []
    import inspect

    try:
        names = list(inspect.signature(ctx.fn).parameters)
    except (TypeError, ValueError):
        names = None
    return audit_donation(
        ctx.fn, *ctx.args, donate_argnums=ctx.donate_argnums,
        arg_names=names, target=ctx.name,
    )
