"""Donation auditor: declared donate_argnums vs XLA's realized aliasing.

Donation is how a functional-update train step stops double-buffering
the model: ``jit(step, donate_argnums=(params, opt_state, ...))`` lets
XLA write the new state into the old state's HBM. It fails SILENTLY in
two places, both invisible until a step OOMs:

1. at LOWERING — jax drops a donated buffer that matches no output's
   shape/dtype (a UserWarning nobody reads in a training log); the MLIR
   simply lacks the ``tf.aliasing_output`` mark for that parameter;
2. at COMPILE — XLA declines to realize a marked alias (layout/backend
   constraints); the optimized HLO's ``input_output_alias`` config is
   the ground truth of what actually aliases.

This auditor compiles the step (``.lower().compile()`` — the one pass
here that is not pure tracing; CPU-safe, a few seconds for the tiny CLI
targets) and cross-checks three layers:

- requested: flat input buffers covered by ``donate_argnums``,
- marked:    parameters carrying ``tf.aliasing_output`` in the lowered
             MLIR,
- realized:  the compiled module's ``input_output_alias`` entries,

emitting ``donation.rejected`` for requested-but-not-realized buffers
(with the stage that dropped them) and ``donation.missed`` for large
non-donated inputs whose shape/dtype matches an un-aliased output —
the params/opt-state buffer someone forgot, which is a whole extra copy
of the model in HBM.
"""

from typing import Iterable, List, Optional, Sequence

import jax
import numpy as np

from apex_tpu.analysis.findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING
from apex_tpu.analysis.hlo.parser import mlir_marked_aliases, realized_aliases
from apex_tpu.analysis.passes import jaxpr_pass

__all__ = ["audit_donation", "donation_pass"]

#: buffers below this size are not worth donating (the alias bookkeeping
#: outweighs scalar-sized savings); "could be donated" findings only fire
#: above it
DEFAULT_MIN_DONATABLE_BYTES = 1 << 20


def _nbytes(aval) -> int:
    return int(np.prod(aval.shape, dtype=np.int64)) * np.dtype(
        aval.dtype
    ).itemsize


def _leaf_labels(args, arg_names: Optional[Sequence[str]]) -> List[str]:
    """One human label per flat input leaf: ``params['w']['kernel']``."""
    labels = []
    for i, arg in enumerate(args):
        name = arg_names[i] if arg_names and i < len(arg_names) else f"arg{i}"
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        if not flat:
            continue
        for path, _leaf in flat:
            labels.append(name + jax.tree_util.keystr(path))
    return labels


def _donated_leaf_indices(args, donate_argnums) -> set:
    donated, offset = set(), 0
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in donate_argnums:
            donated.update(range(offset, offset + n))
        offset += n
    return donated


def audit_donation(
    fn,
    *args,
    donate_argnums: Optional[Sequence[int]] = None,
    min_donatable_bytes: int = DEFAULT_MIN_DONATABLE_BYTES,
    arg_names: Optional[Sequence[str]] = None,
    target: str = "",
    lowered=None,
    compiled=None,
    hlo_module=None,
) -> List[Finding]:
    """Audit one step's donation story; see the module docstring.

    ``fn`` may be a plain function (``donate_argnums`` required — the
    auditor builds the jit with ``keep_unused=True`` so HLO parameters
    map 1:1 onto flat input leaves) or an already-jitted function whose
    own ``donate_argnums`` are used (pass nothing). Args may be arrays
    or ``ShapeDtypeStruct``s — nothing executes, but the step IS
    compiled (pass ``lowered``/``compiled`` to reuse an existing AOT
    pair — the CLI's shared per-target compile; ``hlo_module``, a
    parsed :class:`~apex_tpu.analysis.hlo.parser.HloModule` of that
    same compiled, additionally skips re-serializing the optimized HLO
    for the realized aliases). The MLIR/HLO scraping itself lives in
    ``analysis/hlo/parser.py``, the one blessed home of ``.as_text()``
    parsing.
    """
    if compiled is not None and lowered is None:
        raise ValueError(
            "pass lowered alongside compiled — the donation marks (from "
            "lowered) and the realized aliases (from compiled) must come "
            "from the same AOT pair"
        )
    if donate_argnums is None:
        if not hasattr(fn, "lower"):
            raise ValueError(
                "donate_argnums is required for a non-jitted step function"
            )
        if lowered is None:
            lowered = fn.lower(*args)
        if compiled is None:
            compiled = lowered.compile()
        # Compiled.donate_argnums reports FLAT input-leaf indices (not the
        # user-level argnums the jit was built with) — exactly the set we
        # need, no tree math
        requested = set(compiled.donate_argnums)
    else:
        donate_argnums = tuple(donate_argnums)
        if lowered is None:
            lowered = jax.jit(
                fn, donate_argnums=donate_argnums, keep_unused=True
            ).lower(*args)
        if compiled is None:
            compiled = lowered.compile()
        requested = _donated_leaf_indices(args, set(donate_argnums))

    labels = _leaf_labels(args, arg_names)
    in_leaves = jax.tree_util.tree_leaves(args)
    marked, nparams = mlir_marked_aliases(lowered)

    findings: List[Finding] = []
    site = f"<step:{target or getattr(fn, '__name__', 'fn')}>"
    if hlo_module is not None:
        realized = dict(hlo_module.input_output_alias)
    else:
        try:
            realized = realized_aliases(compiled)
        except ValueError as e:
            # malformed/unexpected HLO text: report honestly instead of
            # crashing the gate or guessing an empty alias map
            findings.append(Finding(
                rule="donation.unverifiable",
                message=(
                    f"optimized HLO input_output_alias section could "
                    f"not be parsed ({e}) — donation not verified"
                ),
                site=site, severity=SEV_INFO, target=target,
            ))
            return findings
    if marked is None or nparams != len(in_leaves):
        # pruned/unparseable parameter list: leaf<->parameter numbering no
        # longer lines up, so report honestly instead of guessing
        findings.append(Finding(
            rule="donation.unverifiable",
            message=(
                f"cannot map HLO parameters to input leaves "
                f"({nparams} entry params vs {len(in_leaves)} leaves; "
                f"args pruned or MLIR shape unexpected) — donation not "
                f"verified"
            ),
            site=site, severity=SEV_INFO, target=target,
        ))
        return findings

    for idx in sorted(requested):
        label = labels[idx] if idx < len(labels) else f"leaf{idx}"
        nbytes = _nbytes(in_leaves[idx])
        # a rejected scalar/tiny donation wastes no memory worth chasing:
        # report it as advisory (info), not a gate failure
        sev = SEV_ERROR if nbytes >= min_donatable_bytes else SEV_INFO
        if idx not in marked:
            findings.append(Finding(
                rule="donation.rejected",
                message=(
                    f"{label} ({nbytes} B) is donated but matches no "
                    f"output shape/dtype: jax dropped the donation at "
                    f"lowering (its HBM is freed, never reused)"
                ),
                site=site, severity=sev, target=target,
                data={"leaf": label, "bytes": nbytes, "stage": "lowering"},
            ))
        elif idx not in realized:
            findings.append(Finding(
                rule="donation.rejected",
                message=(
                    f"{label} ({nbytes} B) is marked for donation but XLA "
                    f"did not realize the input/output alias"
                ),
                site=site, severity=sev, target=target,
                data={"leaf": label, "bytes": nbytes, "stage": "compile"},
            ))

    # large non-donated inputs that COULD alias an output nothing claims
    out_leaves = jax.tree_util.tree_leaves(jax.eval_shape(fn, *args))
    taken_outputs = set(realized.values())
    free_out_shapes = {}
    for oi, leaf in enumerate(out_leaves):
        if oi not in taken_outputs:
            key = (tuple(leaf.shape), np.dtype(leaf.dtype))
            free_out_shapes[key] = free_out_shapes.get(key, 0) + 1
    for idx, leaf in enumerate(in_leaves):
        if idx in requested:
            continue
        nbytes = _nbytes(leaf)
        if nbytes < min_donatable_bytes:
            continue
        key = (tuple(leaf.shape), np.dtype(leaf.dtype))
        if free_out_shapes.get(key, 0) > 0:
            free_out_shapes[key] -= 1
            label = labels[idx] if idx < len(labels) else f"leaf{idx}"
            findings.append(Finding(
                rule="donation.missed",
                message=(
                    f"{label} ({nbytes} B) is not donated but an output "
                    f"of the same shape/dtype has no alias — donating it "
                    f"would reuse the buffer instead of double-buffering"
                ),
                site=site, severity=SEV_WARNING, target=target,
                data={"leaf": label, "bytes": nbytes},
            ))
    return findings


@jaxpr_pass("donation")
def donation_pass(ctx) -> Iterable[Finding]:
    if ctx.donate_argnums is None:
        return []
    import inspect

    try:
        names = list(inspect.signature(ctx.fn).parameters)
    except (TypeError, ValueError):
        names = None
    lowered, compiled = ctx.aot()
    try:
        hlo_module = ctx.hlo_module()
    except ValueError:
        hlo_module = None  # audit_donation reports unverifiable itself
    return audit_donation(
        ctx.fn, *ctx.args, donate_argnums=ctx.donate_argnums,
        min_donatable_bytes=(
            ctx.target.donation_min_bytes or DEFAULT_MIN_DONATABLE_BYTES
        ),
        arg_names=names, target=ctx.name,
        lowered=lowered, compiled=compiled, hlo_module=hlo_module,
    )
