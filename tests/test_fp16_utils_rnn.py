"""fp16_utils legacy API + RNN tests (ref: tests/L0/run_fp16util, apex/RNN)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    DynamicLossScaler,
    LossScaler,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)
from apex_tpu.rnn import GRU, LSTM, RNN, LSTMCell, ReLU, Tanh, mLSTM


class TestFP16Util:
    def params(self):
        return {
            "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))},
            "batch_norm": {"scale": jnp.ones((4,)), "mean": jnp.zeros((4,))},
            "step": jnp.asarray(3, jnp.int32),
        }

    def test_network_to_half_keeps_norm_fp32(self):
        half = network_to_half(self.params())
        assert half["dense"]["kernel"].dtype == jnp.float16
        assert half["batch_norm"]["scale"].dtype == jnp.float32
        assert half["step"].dtype == jnp.int32  # non-float untouched

    def test_convert_network_bf16(self):
        conv = convert_network(self.params(), jnp.bfloat16)
        assert conv["dense"]["kernel"].dtype == jnp.bfloat16
        assert conv["batch_norm"]["scale"].dtype == jnp.float32

    def test_master_model_round_trip(self):
        model = network_to_half(self.params())
        model_p, master = prep_param_lists(model)
        assert master["dense"]["kernel"].dtype == jnp.float32
        back = master_params_to_model_params(model_p, master)
        assert back["dense"]["kernel"].dtype == jnp.float16
        grads = model_grads_to_master_grads(model)
        assert grads["dense"]["kernel"].dtype == jnp.float32
        assert to_python_float(jnp.asarray([2.5])) == 2.5


class TestLegacyScalers:
    def test_static(self):
        s = LossScaler(128.0)
        assert s.loss_scale == 128.0
        assert not s.has_overflow({"g": jnp.array([jnp.inf])})
        s.update_scale(True)
        assert s.loss_scale == 128.0

    def test_dynamic_schedule(self):
        s = DynamicLossScaler(init_scale=2.0**8, scale_window=4)
        assert s.has_overflow({"g": jnp.array([jnp.nan])})
        assert not s.has_overflow({"g": jnp.array([1.0])})
        s.update_scale(True)
        assert s.cur_scale == 2.0**7
        for _ in range(4):
            s.update_scale(False)
        assert s.cur_scale == 2.0**8
        d = s.state_dict()
        s2 = DynamicLossScaler()
        s2.load_state_dict(d)
        assert s2.cur_scale == s.cur_scale and s2.cur_iter == s.cur_iter


class TestFP16Optimizer:
    def test_trains_and_skips_overflow(self, rng):
        params = {"w": jax.random.normal(rng, (8, 8), jnp.float16)}
        opt = FP16_Optimizer(optax.sgd(0.1), dynamic_loss_scale=True)
        state = opt.init(params)
        x = jax.random.normal(jax.random.fold_in(rng, 1), (4, 8), jnp.float16)

        def loss_fn(p):
            return jnp.mean((x @ p["w"]) ** 2)

        losses = []
        for _ in range(5):
            scaled = lambda p: opt.scale_loss(loss_fn(p), state)  # noqa: E731
            grads = jax.grad(scaled)(params)
            params, state, info = opt.step(grads, state, params)
            losses.append(float(loss_fn(params)))
            assert params["w"].dtype == jnp.float16
        assert losses[-1] < losses[0]
        # forced overflow skips the step
        bad = {"w": jnp.full((8, 8), jnp.inf, jnp.float16)}
        before = params["w"].copy()
        params, state, info = opt.step(bad, state, params)
        assert bool(info["found_inf"])
        np.testing.assert_array_equal(params["w"], before)


class TestRNN:
    def naive_lstm(self, params, xs):
        wi = np.asarray(params["wi"], np.float32)
        wh = np.asarray(params["wh"], np.float32)
        b = np.asarray(params["bias"], np.float32)
        hsz = wh.shape[0]
        h = np.zeros((xs.shape[1], hsz), np.float32)
        c = np.zeros_like(h)
        sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
        out = []
        for t in range(xs.shape[0]):
            gates = np.asarray(xs[t], np.float32) @ wi + h @ wh + b
            i, f, g, o = np.split(gates, 4, axis=-1)
            c = sig(f) * c + sig(i) * np.tanh(g)
            h = sig(o) * np.tanh(c)
            out.append(h)
        return np.stack(out)

    def test_lstm_matches_naive(self, rng):
        xs = jax.random.normal(rng, (6, 2, 4), jnp.float32)
        mod = LSTM(4, 8)
        variables = mod.init(rng, xs)
        ys, finals = mod.apply(variables, xs)
        cell_params = variables["params"]["layer0"]["cell"]
        want = self.naive_lstm(cell_params, np.asarray(xs))
        np.testing.assert_allclose(ys, want, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(finals[0][0], want[-1], rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("factory", [GRU, ReLU, Tanh, mLSTM])
    def test_variants_shapes_and_grads(self, rng, factory):
        xs = jax.random.normal(rng, (5, 2, 4), jnp.float32)
        mod = factory(4, 8, num_layers=2)
        variables = mod.init(rng, xs)
        ys, _ = mod.apply(variables, xs)
        assert ys.shape == (5, 2, 8)
        g = jax.grad(
            lambda v: jnp.sum(mod.apply(v, xs)[0] ** 2)
        )(variables)
        flat = jax.tree_util.tree_leaves(g)
        assert all(bool(jnp.all(jnp.isfinite(x))) for x in flat)
        assert any(float(jnp.abs(x).sum()) > 0 for x in flat)

    def test_bidirectional(self, rng):
        xs = jax.random.normal(rng, (5, 2, 4), jnp.float32)
        mod = LSTM(4, 8, bidirectional=True)
        variables = mod.init(rng, xs)
        ys, _ = mod.apply(variables, xs)
        assert ys.shape == (5, 2, 16)
        # reverse half equals running the net on time-reversed input
        fwd_half = np.asarray(ys)[..., :8]
        mod_uni = LSTM(4, 8)
        uni_vars = {
            "params": {"layer0": variables["params"]["layer0"]}
        }
        ys_uni, _ = mod_uni.apply(uni_vars, xs)
        np.testing.assert_allclose(fwd_half, ys_uni, rtol=1e-5, atol=1e-6)
