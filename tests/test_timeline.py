"""Timeline analyzer: the math pinned digit-for-digit on synthetic traces.

Every number the analyzer reports — compute/collective/memcpy union
seconds, exposed-comms time, overlap and bubble fractions, achieved
bytes/s per axis — is asserted here against hand-counted fixtures
(including async ``-start``/``-done`` pairs and overlapping device
lanes), the same pinning discipline as tests/test_xray.py's byte
formulas and test_analysis.py's HLO inventory. The trace-event PARSER
is fed synthetic dicts (the ``parse_trace(data)`` seam, mirroring
``parse_hlo_module(text)``); whether the RUNNING jax still writes that
schema is the analysis gate's trace-schema smoke
(apex_tpu/analysis/trace_smoke.py), exercised directly at the bottom.

The end-to-end round trip over the real dp2xtp2 GPT example
(``--profile-analyze``) lives in tests/test_examples.py
(test_gpt_pretrain_profile_analyze, slow tier).
"""

import gzip
import json
import os

import pytest

from apex_tpu.monitor.xray.timeline import (
    StepSpan,
    TimelineReport,
    analyze,
    classify_op,
    pair_async_collectives,
    parse_logdir,
    parse_trace,
)
from apex_tpu.monitor.xray.timeline.analyzer import (
    StepBreakdown,
    intersect_intervals,
    merge_intervals,
    op_base,
    subtract_intervals,
    total_us,
)
from apex_tpu.monitor.xray.timeline.parser import TraceEvent


def ev(name, ts, dur, pid=2, tid=0, **args):
    """A device-op event dict (args.hlo_op = its own stem, the CPU
    exporter's shape)."""
    return {"ph": "X", "name": name, "pid": pid, "tid": tid, "ts": ts,
            "dur": dur, "args": {"hlo_op": name, **args}}


def step_marker(step, ts, dur, pid=1, tid=0):
    """A StepTraceAnnotation span (step_num stringified, as on the wire)."""
    return {"ph": "X", "name": "train", "pid": pid, "tid": tid, "ts": ts,
            "dur": dur, "args": {"step_num": str(step)}}


def trace_dict(*events):
    return {"traceEvents": list(events), "displayTimeUnit": "ns"}


# ---------------------------------------------------------------------------
# parser


class TestParser:
    def test_not_a_trace_raises(self):
        with pytest.raises(ValueError, match="traceEvents"):
            parse_trace({"foo": 1})

    def test_metadata_lanes_and_events(self):
        tl = parse_trace(trace_dict(
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "name": "thread_name", "pid": 7, "tid": 3,
             "args": {"name": "python"}},
            ev("fusion.1", 10.0, 5.0, pid=7, tid=3),
        ))
        assert tl.process_names == {7: "/host:CPU"}
        assert tl.thread_names == {(7, 3): "python"}
        (e,) = tl.events
        assert tl.lane(e) == "/host:CPU/python"
        assert e.end == 15.0

    def test_step_spans_stringified_and_sorted(self):
        tl = parse_trace(trace_dict(
            step_marker(1, 100.0, 50.0),
            step_marker(0, 0.0, 100.0),
            # unparseable step_num is not a marker
            {"ph": "X", "name": "train", "pid": 1, "tid": 0, "ts": 0,
             "dur": 1, "args": {"step_num": "warmup"}},
        ))
        spans = tl.step_spans()
        assert [(s.step, s.ts, s.end) for s in spans] == [
            (0, 0.0, 100.0), (1, 100.0, 150.0),
        ]
        assert spans[0].dur == 100.0

    def test_device_ops_prefer_hlo_op_and_exclude_markers(self):
        tl = parse_trace(trace_dict(
            step_marker(0, 0.0, 100.0),
            ev("dot.1", 10.0, 5.0),
            # host noise without hlo_op is not a device op
            {"ph": "X", "name": "ThreadpoolListener::run", "pid": 1,
             "tid": 0, "ts": 0.0, "dur": 90.0, "args": {}},
        ))
        assert [e.name for e in tl.device_op_events()] == ["dot.1"]

    def test_device_process_fallback_tpu_layout(self):
        # no args.hlo_op anywhere (TPU exporter): /device: processes are
        # the op lanes, "XLA Ops" threads preferred when labeled
        tl = parse_trace(trace_dict(
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "thread_name", "pid": 9, "tid": 1,
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "name": "thread_name", "pid": 9, "tid": 2,
             "args": {"name": "Steps"}},
            {"ph": "X", "name": "fusion.3", "pid": 9, "tid": 1,
             "ts": 5.0, "dur": 2.0, "args": {}},
            {"ph": "X", "name": "bookkeeping", "pid": 9, "tid": 2,
             "ts": 5.0, "dur": 2.0, "args": {}},
            {"ph": "X", "name": "host_thing", "pid": 1, "tid": 0,
             "ts": 5.0, "dur": 2.0, "args": {}},
        ))
        assert [e.name for e in tl.device_op_events()] == ["fusion.3"]

    def test_parse_logdir_newest_capture_merged(self, tmp_path):
        def write(run, host, *events):
            d = tmp_path / "plugins" / "profile" / run
            d.mkdir(parents=True, exist_ok=True)
            with gzip.open(d / f"{host}.trace.json.gz", "wt") as f:
                json.dump(trace_dict(*events), f)

        write("2026_01_01_00_00_00", "old", ev("stale.1", 0.0, 1.0))
        write("2026_01_02_00_00_00", "host_a", ev("dot.1", 0.0, 1.0))
        write("2026_01_02_00_00_00", "host_b", ev("dot.2", 0.0, 1.0, pid=3))
        tl, files = parse_logdir(str(tmp_path))
        assert len(files) == 2
        assert all("2026_01_02" in f for f in files)
        assert sorted(e.name for e in tl.events) == ["dot.1", "dot.2"]

    def test_parse_logdir_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="trace.json"):
            parse_logdir(str(tmp_path))

    def test_plain_json_also_readable(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "run"
        d.mkdir(parents=True)
        (d / "h.trace.json").write_text(
            json.dumps(trace_dict(ev("dot.1", 0.0, 1.0)))
        )
        tl, _ = parse_logdir(str(tmp_path))
        assert [e.name for e in tl.events] == ["dot.1"]


# ---------------------------------------------------------------------------
# op classification


class TestClassify:
    @pytest.mark.parametrize("name,cls", [
        ("fusion.42", "compute"),
        ("dot.1", "compute"),
        ("%convolution.7", "compute"),
        ("reduce.7", "compute"),           # a plain reduce is NOT comms
        ("transpose.5", "compute"),        # burns core time, not wire
        ("all-reduce.17", "collective"),
        ("all-reduce-start.3", "collective"),
        ("all-reduce-done.4", "collective"),
        ("all-gather.2", "collective"),
        ("reduce-scatter.9", "collective"),
        ("collective-permute-start.1", "collective"),
        ("all-to-all.5", "collective"),
        ("copy.3", "memcpy"),
        ("copy-start.8", "memcpy"),
        ("MemcpyD2H", "memcpy"),
        ("infeed.1", "memcpy"),
    ])
    def test_classes(self, name, cls):
        assert classify_op(name) == cls

    def test_op_base_strips_one_ordinal(self):
        assert op_base("all-reduce.17") == "all-reduce"
        assert op_base("%Fusion.2") == "fusion"
        assert op_base("all-reduce") == "all-reduce"
        assert op_base("name.v2.3") == "name.v2"


# ---------------------------------------------------------------------------
# interval algebra


class TestIntervals:
    def test_merge(self):
        assert merge_intervals([(5.0, 7.0), (0.0, 2.0), (1.0, 3.0),
                                (3.0, 4.0), (9.0, 9.0)]) == [
            (0.0, 4.0), (5.0, 7.0),
        ]

    def test_intersect(self):
        a = [(0.0, 10.0), (20.0, 30.0)]
        b = [(5.0, 25.0)]
        assert intersect_intervals(a, b) == [(5.0, 10.0), (20.0, 25.0)]

    def test_subtract(self):
        a = [(0.0, 10.0)]
        b = [(2.0, 3.0), (5.0, 7.0)]
        assert subtract_intervals(a, b) == [
            (0.0, 2.0), (3.0, 5.0), (7.0, 10.0),
        ]
        assert total_us(subtract_intervals(a, b)) == 7.0

    def test_subtract_disjoint_noop(self):
        assert subtract_intervals([(0.0, 5.0)], [(6.0, 8.0)]) == [(0.0, 5.0)]


# ---------------------------------------------------------------------------
# async start/done fusion


class TestAsyncPairing:
    def test_fifo_pairing_ignores_ordinals(self):
        # XLA's -done ordinal does NOT match its -start's; FIFO per
        # (pid, kind) in time order is the pairing rule
        events = [
            TraceEvent("all-gather-start.7", 2, 0, 0.0, 1.0),
            TraceEvent("all-gather-start.8", 2, 0, 2.0, 1.0),
            TraceEvent("all-gather-done.21", 2, 0, 10.0, 1.0),
            TraceEvent("all-gather-done.22", 2, 0, 12.0, 1.0),
        ]
        out = sorted(pair_async_collectives(events), key=lambda o: o.ts)
        assert [(o.name, o.ts, o.end) for o in out] == [
            ("all-gather-start.7", 0.0, 11.0),
            ("all-gather-start.8", 2.0, 13.0),
        ]
        assert all(o.cls == "collective" for o in out)

    def test_unpaired_start_keeps_own_span(self):
        (o,) = pair_async_collectives(
            [TraceEvent("all-reduce-start.1", 2, 0, 5.0, 3.0)]
        )
        assert (o.ts, o.end) == (5.0, 8.0)

    def test_cross_pid_never_pairs(self):
        out = pair_async_collectives([
            TraceEvent("all-reduce-start.1", 2, 0, 0.0, 1.0),
            TraceEvent("all-reduce-done.2", 3, 0, 5.0, 1.0),
        ])
        assert sorted((o.ts, o.end) for o in out) == [(0.0, 1.0), (5.0, 6.0)]

    def test_sync_ops_pass_through(self):
        (o,) = pair_async_collectives(
            [TraceEvent("%all-reduce.4", 2, 0, 1.0, 2.0)]
        )
        assert o.name == "all-reduce.4" and o.cls == "collective"


# ---------------------------------------------------------------------------
# per-step breakdown: the partition, hand-counted


class TestBreakdown:
    def fixture_a(self):
        """One step [0,100]: compute [10,40]+[50,70], collective [30,60],
        memcpy [80,85]."""
        return parse_trace(trace_dict(
            step_marker(0, 0.0, 100.0),
            ev("fusion.1", 10.0, 30.0),
            ev("fusion.2", 50.0, 20.0),
            ev("all-reduce.3", 30.0, 30.0),
            ev("copy.4", 80.0, 5.0),
        ))

    def test_partition_hand_counted(self):
        (s,) = analyze(self.fixture_a()).steps
        assert s.span_us == 100.0
        assert s.compute_us == 50.0          # [10,40] u [50,70]
        assert s.collective_us == 30.0       # [30,60]
        assert s.exposed_collective_us == 10.0   # [40,50]
        assert s.memcpy_us == 5.0
        assert s.exposed_memcpy_us == 5.0    # [80,85] hides under nothing
        assert s.busy_us == 65.0             # [10,70] u [80,85]
        assert s.idle_us == 35.0
        assert s.bubble_fraction == pytest.approx(0.35)
        assert s.overlap_fraction == pytest.approx(1.0 - 10.0 / 30.0)
        assert s.n_ops == 4

    def test_predicted_bubble_join(self):
        """The schedule-algebra join: the caller's predicted bubble
        fraction rides every per-step kind="profile" record next to the
        measured one, and the summary prints the comparison — the
        predicted-vs-measured leg of the zero-bubble proof loop."""
        from apex_tpu.parallel.pipeline import schedule_cost

        cost = schedule_cost("zero_bubble", 4, 8)
        report = analyze(
            self.fixture_a(),
            predicted_bubble_fraction=cost.bubble_fraction,
            schedule="zero_bubble",
        )
        (s,) = report.steps
        recs = [r for r in report.to_records() if "bubble_fraction" in r]
        (r,) = recs
        assert r["predicted_bubble_fraction"] == cost.bubble_fraction
        assert r["schedule"] == "zero_bubble"
        assert r["bubble_fraction"] == pytest.approx(0.35)
        summary = report.summary()
        assert "bubble join (zero_bubble)" in summary
        assert "predicted" in summary and "measured" in summary
        # without the join, neither field appears (the analyzer never
        # invents a prediction)
        plain = analyze(self.fixture_a())
        assert all(
            "predicted_bubble_fraction" not in r for r in plain.to_records()
        )
        assert "bubble join" not in plain.summary()

    def test_cli_schedule_choices_in_sync(self):
        """The CLI's literal --schedule choices (spelled out so the
        no-jax CLI contract holds) must track the algebra registry."""
        from apex_tpu.monitor.xray.timeline.__main__ import (
            _SCHEDULE_CHOICES,
        )
        from apex_tpu.parallel.pipeline.algebra import SCHEDULES

        assert sorted(_SCHEDULE_CHOICES) == sorted(SCHEDULES)

    def test_partition_identity(self):
        (s,) = analyze(self.fixture_a()).steps
        assert (
            s.compute_us + s.exposed_collective_us + s.exposed_memcpy_us
            + s.idle_us
        ) == pytest.approx(s.span_us)

    def test_async_pair_and_overlapping_lanes(self):
        """Step 0: fused async collective [10,50] fully hidden under a
        two-lane compute union [0,60] -> overlap 1.0. Step 1: an
        unpaired -start, no compute -> overlap 0.0, bubble 0.9."""
        tl = parse_trace(trace_dict(
            step_marker(0, 0.0, 100.0),
            step_marker(1, 100.0, 100.0),
            ev("all-gather-start.7", 10.0, 5.0, pid=2),
            ev("all-gather-done.9", 40.0, 10.0, pid=2),
            ev("fusion.1", 0.0, 30.0, pid=3),
            ev("dot.2", 20.0, 40.0, pid=3),
            ev("all-reduce-start.11", 110.0, 10.0, pid=2),
        ))
        s0, s1 = analyze(tl).steps
        assert s0.collective_us == 40.0      # fused [10,50]
        assert s0.compute_us == 60.0         # [0,30] u [20,60] = [0,60]
        assert s0.exposed_collective_us == 0.0
        assert s0.overlap_fraction == pytest.approx(1.0)
        assert s0.busy_us == 60.0 and s0.idle_us == 40.0
        assert s1.collective_us == 10.0
        assert s1.exposed_collective_us == 10.0
        assert s1.overlap_fraction == pytest.approx(0.0)
        assert s1.bubble_fraction == pytest.approx(0.9)

    def test_op_straddling_boundary_clipped_to_each_step(self):
        tl = parse_trace(trace_dict(
            step_marker(0, 0.0, 100.0),
            step_marker(1, 100.0, 100.0),
            ev("fusion.1", 90.0, 20.0),      # [90,110] straddles
        ))
        s0, s1 = analyze(tl).steps
        assert s0.compute_us == 10.0 and s1.compute_us == 10.0
        assert s0.n_ops == 1 and s1.n_ops == 1

    def test_no_markers_synthetic_whole_span(self):
        tl = parse_trace(trace_dict(
            ev("fusion.1", 10.0, 5.0), ev("dot.2", 30.0, 10.0),
        ))
        report = analyze(tl)
        assert report.synthetic_step
        (s,) = report.steps
        assert (s.step, s.ts, s.end) == (-1, 10.0, 40.0)
        assert s.compute_us == 15.0 and s.idle_us == 15.0

    def test_no_ops_no_steps(self):
        report = analyze(parse_trace(trace_dict()))
        assert report.steps == [] and report.n_device_ops == 0
        assert "no steps" in report.summary()

    def test_overlap_none_without_collectives(self):
        s = StepBreakdown(step=0, ts=0, end=10, compute_us=5,
                          collective_us=0, memcpy_us=0,
                          exposed_collective_us=0, exposed_memcpy_us=0,
                          busy_us=5, n_ops=1)
        assert s.overlap_fraction is None


# ---------------------------------------------------------------------------
# the bandwidth join: measured seconds -> predicted bytes, hand-counted


JOIN_HLO = """\
HloModule join_mod, num_partitions=4

%add.1 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main.5 (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %all-reduce.1 = f32[8]{0} all-reduce(f32[8]{0} %p0), channel_id=1, replica_groups={{0,2},{1,3}}, use_global_device_ids=true, to_apply=%add.1
  ROOT %all-reduce.2 = f32[8]{0} all-reduce(f32[8]{0} %all-reduce.1), channel_id=2, replica_groups={{0,1},{2,3}}, use_global_device_ids=true, to_apply=%add.1
}
"""


def dp2tp2_mesh():
    import numpy as np
    import jax

    return jax.sharding.Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp")
    )


class TestBandwidthJoin:
    def make_ledger(self):
        from apex_tpu.monitor.xray.ledger import CollectiveEntry, CommsLedger

        led = CommsLedger()
        led.entries.append(CollectiveEntry(
            op="psum", axis="dp", axis_size=2, shape=(400,),
            dtype="float32", bytes=1600, ici_bytes=1600,
        ))
        led.entries.append(CollectiveEntry(
            op="psum", axis="tp", axis_size=2, shape=(200,),
            dtype="float32", bytes=800, ici_bytes=400,
        ))
        return led

    def joined_report(self, ici_bandwidth=None):
        from apex_tpu.analysis.hlo import parse_hlo_module

        tl = parse_trace(trace_dict(
            step_marker(0, 0.0, 1000.0),
            # groups {{0,2},{1,3}} vary the dp coordinate -> axis "dp"
            ev("all-reduce.1", 100.0, 200.0),
            # groups {{0,1},{2,3}} vary the tp coordinate -> axis "tp"
            ev("all-reduce.2", 400.0, 100.0),
            # matches no HLO instruction -> counted unattributed
            ev("all-gather.9", 600.0, 50.0),
        ))
        return analyze(
            tl, module=parse_hlo_module(JOIN_HLO), mesh=dp2tp2_mesh(),
            ledger=self.make_ledger(), ici_bandwidth=ici_bandwidth,
        )

    def test_join_hand_counted(self):
        report = self.joined_report(ici_bandwidth=1e8)
        assert report.n_unattributed_collectives == 1
        dp, tp = report.axes
        assert (dp.axis, tp.axis) == ("dp", "tp")
        assert dp.n_events == 1 and tp.n_events == 1
        assert dp.measured_us_per_step == 200.0
        assert tp.measured_us_per_step == 100.0
        assert dp.predicted_bytes_per_step == 1600
        assert dp.predicted_ici_bytes_per_step == 1600
        assert tp.predicted_ici_bytes_per_step == 400
        # 1600 B in 200us = 8e6 B/s; vs 1e8 roofline = 8%
        assert dp.achieved_bytes_per_s == pytest.approx(8e6)
        assert dp.utilization == pytest.approx(0.08)
        # 400 B in 100us = 4e6 B/s
        assert tp.achieved_bytes_per_s == pytest.approx(4e6)

    def test_unknown_roofline_is_none_not_fake(self):
        dp = self.joined_report().axes[0]
        assert dp.roofline_bytes_per_s is None
        assert dp.utilization is None
        assert "roofline unknown" in self.joined_report().summary()

    def test_predicted_axis_without_events_still_reported(self):
        # a predicted axis whose events all vanished from the capture
        # must surface with zero measured time, not silently drop
        from apex_tpu.analysis.hlo import parse_hlo_module

        tl = parse_trace(trace_dict(
            step_marker(0, 0.0, 1000.0),
            ev("all-reduce.1", 100.0, 200.0),   # dp only
        ))
        report = analyze(tl, module=parse_hlo_module(JOIN_HLO),
                         mesh=dp2tp2_mesh(), ledger=self.make_ledger())
        tp = next(a for a in report.axes if a.axis == "tp")
        assert tp.n_events == 0
        assert tp.measured_us_per_step == 0.0
        assert tp.achieved_bytes_per_s is None

    def test_records_share_router_schema(self):
        recs = self.joined_report(ici_bandwidth=1e8).to_records()
        assert all(r["kind"] == "profile" for r in recs)
        assert all({"t", "step", "kind"} <= set(r) for r in recs)
        step_recs = [r for r in recs if "span_ms" in r]
        (s,) = step_recs
        assert s["span_ms"] == pytest.approx(1.0)
        assert (
            s["compute_ms"] + s["exposed_comms_ms"] + s["exposed_memcpy_ms"]
            + s["idle_ms"]
        ) == pytest.approx(s["span_ms"])
        axis_recs = [r for r in recs if "axis" in r]
        assert [r["axis"] for r in axis_recs] == ["dp", "tp"]
        assert axis_recs[0]["utilization"] == pytest.approx(0.08)


# ---------------------------------------------------------------------------
# CLI


class TestCli:
    def write_capture(self, tmp_path, *events):
        d = tmp_path / "plugins" / "profile" / "run"
        d.mkdir(parents=True)
        with gzip.open(d / "h.trace.json.gz", "wt") as f:
            json.dump(trace_dict(*events), f)

    def test_cli_analyzes_and_emits_jsonl(self, tmp_path, capsys):
        from apex_tpu.monitor.xray.timeline.__main__ import main

        self.write_capture(
            tmp_path, step_marker(0, 0.0, 100.0), ev("fusion.1", 10.0, 30.0),
        )
        out_jsonl = tmp_path / "profile.jsonl"
        assert main([str(tmp_path), "--json", str(out_jsonl)]) == 0
        out = capsys.readouterr().out
        assert "timeline: 1 step(s)" in out
        (rec,) = [json.loads(l) for l in out_jsonl.read_text().splitlines()]
        assert rec["kind"] == "profile" and rec["compute_ms"] == 0.03

    def test_cli_empty_dir_fails(self, tmp_path, capsys):
        from apex_tpu.monitor.xray.timeline.__main__ import main

        assert main([str(tmp_path)]) == 1
        assert "timeline:" in capsys.readouterr().err

    def test_cli_works_without_jax(self, tmp_path):
        """The docs' offline claim, pinned: a capture is analyzable on a
        box with NO jax at all (docs/benchmarking.md — the relay's
        grab-and-run economics). The subprocess poisons jax/jaxlib/flax
        in sys.modules so any import along the CLI path fails loudly;
        the lazy PEP-562 package inits are what make this hold."""
        import subprocess
        import sys

        self.write_capture(
            tmp_path, step_marker(0, 0.0, 100.0), ev("fusion.1", 10.0, 30.0),
        )
        code = (
            "import sys\n"
            "for m in ('jax', 'jaxlib', 'flax', 'optax'):\n"
            "    sys.modules[m] = None\n"
            "from apex_tpu.monitor.xray.timeline.__main__ import main\n"
            f"sys.exit(main([{str(tmp_path)!r}]))\n"
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": repo}, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "timeline: 1 step(s)" in proc.stdout


# ---------------------------------------------------------------------------
# the gate's trace-schema smoke, run directly: THIS jax's exporter must
# still produce captures the analyzer can segment


def test_trace_schema_smoke_clean():
    from apex_tpu.analysis.trace_smoke import timeline_smoke_findings

    fins = timeline_smoke_findings()
    assert fins == [], "\n".join(f.format() for f in fins)
