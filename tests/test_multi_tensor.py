"""Tests for the multi-tensor engine.

Mirrors reference tests/L0/run_amp/test_multi_tensor_scale.py,
test_multi_tensor_axpby.py, test_multi_tensor_l2norm.py: compare fused ops
against manual math, including overflow injection.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    flatten,
    unflatten,
    flatten_pytree,
    unflatten_pytree,
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
)
from apex_tpu.utils import tree_any_non_finite


def _tree(rng, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "a": jax.random.normal(k1, (33, 17), dtype),
        "b": {"c": jax.random.normal(k2, (128,), dtype)},
        "d": jax.random.normal(k3, (5, 4, 3), dtype),
    }


def test_flatten_unflatten_roundtrip(rng):
    tensors = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,)), jnp.zeros((2, 2))]
    flat = flatten(tensors)
    assert flat.shape == (14,)
    out = unflatten(flat, tensors)
    for a, b in zip(out, tensors):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_flatten_pytree_roundtrip(rng):
    tree = _tree(rng)
    flat, spec = flatten_pytree(tree)
    assert flat.shape[0] % (2048 * 32) == 0  # padded to chunk
    out = unflatten_pytree(flat, spec)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        out,
        tree,
    )


@pytest.mark.parametrize("scale", [1.0, 0.25, 65536.0])
def test_multi_tensor_scale(rng, scale):
    tree = _tree(rng)
    out, flag = multi_tensor_scale(tree, scale)
    jax.tree_util.tree_map(
        lambda o, t: np.testing.assert_allclose(
            np.asarray(o), np.asarray(t) * scale, rtol=1e-6
        ),
        out,
        tree,
    )
    assert not bool(flag)


def test_multi_tensor_scale_overflow(rng):
    tree = _tree(rng)
    tree["a"] = tree["a"].at[0, 0].set(jnp.inf)
    _, flag = multi_tensor_scale(tree, 2.0)
    assert bool(flag)
    tree["a"] = tree["a"].at[0, 0].set(jnp.nan)
    _, flag = multi_tensor_scale(tree, 2.0)
    assert bool(flag)


def test_multi_tensor_axpby(rng):
    x = _tree(rng)
    y = _tree(jax.random.PRNGKey(1))
    out, flag = multi_tensor_axpby(2.0, -0.5, x, y)
    jax.tree_util.tree_map(
        lambda o, a, b: np.testing.assert_allclose(
            np.asarray(o), 2.0 * np.asarray(a) - 0.5 * np.asarray(b), rtol=1e-6
        ),
        out,
        x,
        y,
    )
    assert not bool(flag)


def test_multi_tensor_l2norm(rng):
    tree = _tree(rng)
    total, per = multi_tensor_l2norm(tree, per_tensor=True)
    leaves = jax.tree_util.tree_leaves(tree)
    expected = np.sqrt(sum(float(jnp.sum(x.astype(jnp.float32) ** 2)) for x in leaves))
    np.testing.assert_allclose(float(total), expected, rtol=1e-6)
    assert per.shape == (len(leaves),)
    np.testing.assert_allclose(
        float(jnp.sqrt(jnp.sum(per**2))), expected, rtol=1e-6
    )


def test_tree_any_non_finite(rng):
    tree = _tree(rng)
    assert not bool(tree_any_non_finite(tree))
    tree["b"]["c"] = tree["b"]["c"].at[3].set(-jnp.inf)
    assert bool(tree_any_non_finite(tree))
    # integer leaves are ignored
    assert not bool(tree_any_non_finite({"i": jnp.arange(3)}))
