"""Context-parallel (ring / Ulysses) attention parity tests.

No reference counterpart (the reference has no CP — SURVEY.md §2.5); the
test strategy mirrors its fused-vs-reference style: exact parity of outputs
AND gradients against single-device full attention, causal and bidirectional,
on the virtual CPU mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import flash_attention
from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.ring_attention import (
    ring_attention,
    ulysses_attention,
    zigzag_shard,
    zigzag_unshard,
)

B, H, D = 2, 4, 8
SEQ = 32


def _skip_if_old_jaxlib_noncausal(causal, window=None):
    """The non-causal, windowless ring schedule visits every chunk, which
    this old jaxlib lowers through a PartitionId instruction that its SPMD
    partitioner rejects ('PartitionId instruction is not supported for
    SPMD partitioning'). Current jax lowers it fine; skip there-only."""
    from apex_tpu.compat import HAS_VMA

    if not HAS_VMA and not causal and window is None:
        pytest.skip("old jaxlib: PartitionId unsupported in SPMD lowering "
                    "of the non-causal ring schedule")


def full_reference(q, k, v, causal):
    return flash_attention(q, k, v, causal=causal, impl="xla")


def seq_spec():
    return P(None, None, "cp", None)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("cp", [4, 8])
    def test_forward_parity(self, rng, causal, cp):
        _skip_if_old_jaxlib_noncausal(causal)
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(seq_spec(),) * 3,
            out_specs=seq_spec(),
            check_vma=False,
        )
        def run(q, k, v):
            return ring_attention(q, k, v, axis_name="cp", causal=causal)

        np.testing.assert_allclose(
            run(q, k, v), full_reference(q, k, v, causal), rtol=2e-4, atol=2e-5
        )

    def test_zigzag_shard_roundtrip(self, rng):
        x = jax.random.normal(rng, (B, H, SEQ, D))
        for cp in (2, 4, 8):
            z = zigzag_shard(x, cp)
            assert z.shape == x.shape
            np.testing.assert_array_equal(
                np.asarray(zigzag_unshard(z, cp)), np.asarray(x)
            )
        # rank 0's shard is pieces (0, 2P-1): first piece of the sequence
        # followed by the last
        cp, half = 4, SEQ // 8
        z = zigzag_shard(x, cp)
        np.testing.assert_array_equal(
            np.asarray(z[..., :half, :]), np.asarray(x[..., :half, :])
        )
        np.testing.assert_array_equal(
            np.asarray(z[..., half : 2 * half, :]),
            np.asarray(x[..., -half:, :]),
        )

    @pytest.mark.parametrize("cp", [4, 8])
    @pytest.mark.parametrize("window", [None, 12])
    def test_zigzag_matches_single_device(self, rng, cp, window):
        """Load-balanced layout == contiguous math: zigzag_shard -> ring
        (zigzag=True) -> zigzag_unshard equals full single-device causal
        attention, forward and grads."""
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv, kc = jax.random.split(rng, 4)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)
        ct = jax.random.normal(kc, (B, H, SEQ, D), jnp.float32)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(seq_spec(),) * 3,
            out_specs=seq_spec(), check_vma=False,
        )
        def run_local(q, k, v):
            return ring_attention(
                q, k, v, axis_name="cp", causal=True, window=window,
                zigzag=True, block_size=8,
            )

        def run(q, k, v):
            zq, zk, zv = (zigzag_shard(t, cp) for t in (q, k, v))
            return zigzag_unshard(run_local(zq, zk, zv), cp)

        ref = flash_attention(q, k, v, causal=True, window=window, impl="xla")
        np.testing.assert_allclose(
            run(q, k, v), ref, rtol=2e-4, atol=2e-5
        )

        gz = jax.grad(lambda q, k, v: jnp.sum(run(q, k, v) * ct), (0, 1, 2))(
            q, k, v
        )
        gr = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True, window=window,
                                impl="xla") * ct
            ),
            (0, 1, 2),
        )(q, k, v)
        for a, b in zip(gz, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    @pytest.mark.parametrize("window", [3, 12, 100])
    def test_sliding_window_matches_single_device(self, rng, window):
        """Global-position banding across ring chunks: windows inside one
        chunk, spanning chunks, and wider than the sequence (== causal)."""
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv, kc = jax.random.split(rng, 4)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)
        ct = jax.random.normal(kc, (B, H, SEQ, D), jnp.float32)

        def ring_run(window):
            @jax.jit
            @functools.partial(
                shard_map,
                mesh=mesh,
                in_specs=(seq_spec(),) * 3,
                out_specs=seq_spec(),
                check_vma=False,
            )
            def run(q, k, v):
                return ring_attention(
                    q, k, v, axis_name="cp", causal=True, window=window
                )

            return run

        ref = flash_attention(q, k, v, causal=True, window=window, impl="xla")
        np.testing.assert_allclose(
            ring_run(window)(q, k, v), ref, rtol=2e-4, atol=2e-5
        )
        # grads through the banded ring
        gp = jax.grad(
            lambda q, k, v: jnp.sum(ring_run(window)(q, k, v) * ct), (0, 1, 2)
        )(q, k, v)
        gr = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True, window=window,
                                impl="xla") * ct
            ),
            (0, 1, 2),
        )(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_ulysses_sliding_window_matches_single_device(self, rng):
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(seq_spec(),) * 3,
            out_specs=seq_spec(),
            check_vma=False,
        )
        def run(q, k, v):
            return ulysses_attention(
                q, k, v, axis_name="cp", causal=True, window=8
            )

        ref = flash_attention(q, k, v, causal=True, window=8, impl="xla")
        np.testing.assert_allclose(run(q, k, v), ref, rtol=2e-4, atol=2e-5)

    def test_bf16_forward_close_to_fp32_reference(self, rng):
        """bf16 path: einsum operands stay bf16 (MXU-rate policy, as in
        ops/attention.py) with fp32 online-softmax state — the only test
        where those casts are not no-ops."""
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv = jax.random.split(rng, 3)
        qf = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        kf = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        vf = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(seq_spec(),) * 3,
            out_specs=seq_spec(),
            check_vma=False,
        )
        def run(q, k, v):
            return ring_attention(q, k, v, axis_name="cp", causal=True)

        out_b = run(*(x.astype(jnp.bfloat16) for x in (qf, kf, vf)))
        ref = full_reference(qf, kf, vf, True)
        assert out_b.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out_b, np.float32), np.asarray(ref), atol=0.08
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_parity(self, rng, causal):
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv, kt = jax.random.split(rng, 4)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)
        tgt = jax.random.normal(kt, (B, H, SEQ, D), jnp.float32)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(seq_spec(),) * 4,
            out_specs=(P(), (seq_spec(),) * 3),
            check_vma=False,
        )
        def run(q, k, v, tgt):
            def loss(q, k, v):
                o = ring_attention(q, k, v, axis_name="cp", causal=causal)
                # local-mean then sum over cp chunks == global sum scaled;
                # keep the psum off the grad path (shard_map transpose rule)
                l = jnp.sum((o - tgt) ** 2)
                return l + jax.lax.stop_gradient(
                    jax.lax.psum(l, "cp") - l
                )

            l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return l, grads

        loss, (dq, dk, dv) = run(q, k, v, tgt)

        def ref_loss(q, k, v):
            o = full_reference(q, k, v, causal)
            return jnp.sum((o - tgt) ** 2)

        ref_l, ref_grads = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(loss, ref_l, rtol=1e-4)
        for got, want in zip((dq, dk, dv), ref_grads):
            np.testing.assert_allclose(got, want, rtol=2e-3, atol=1e-4)


class TestRingGQAAndKeyPadding:
    """GQA x causal x window x kpm through the ring (VERDICT r3 item 3):
    grouped K/V rotate (not repeated pre-ring), the sequence-sharded
    key_padding_mask rides with its chunk, and an all-padded visiting
    chunk is skipped like an out-of-band one."""

    def _kpm(self):
        # last ring chunk (positions 24..31 at cp=4) fully padded in EVERY
        # batch row -> exercises whole-chunk skipping; row 0 additionally
        # pads a partial tail inside chunk 2
        kpm = jnp.zeros((B, SEQ), bool)
        kpm = kpm.at[:, 24:].set(True).at[0, 20:].set(True)
        return kpm

    @pytest.mark.parametrize("h_kv", [4, 2, 1])
    @pytest.mark.parametrize("causal,window",
                             [(False, None), (True, None), (True, 12)])
    @pytest.mark.parametrize("use_kpm", [False, True])
    def test_parity_and_grads(self, rng, h_kv, causal, window, use_kpm):
        _skip_if_old_jaxlib_noncausal(causal, window)
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv, kc = jax.random.split(rng, 4)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, h_kv, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, h_kv, SEQ, D), jnp.float32)
        ct = jax.random.normal(kc, (B, H, SEQ, D), jnp.float32)
        kpm = self._kpm() if use_kpm else None

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(seq_spec(), seq_spec(), seq_spec(), P(None, "cp")),
            out_specs=seq_spec(), check_vma=False,
        )
        def run(q, k, v, kpm):
            return ring_attention(
                q, k, v, axis_name="cp", causal=causal, window=window,
                key_padding_mask=kpm, block_size=8,
            )

        def ring(q, k, v):
            if kpm is None:
                # shard_map in_specs are fixed; route None via a zero mask
                return run(q, k, v, jnp.zeros((B, SEQ), bool))
            return run(q, k, v, kpm)

        ref_fn = lambda q, k, v: flash_attention(
            q, k, v, causal=causal, window=window, key_padding_mask=kpm,
            impl="xla",
        )
        np.testing.assert_allclose(
            ring(q, k, v), ref_fn(q, k, v), rtol=2e-4, atol=2e-5
        )
        gp = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) * ct),
                      (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(ref_fn(q, k, v) * ct),
                      (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_zigzag_gqa_kpm(self, rng):
        """The load-balanced layout composes with GQA + kpm: the mask is
        zigzag-reordered exactly like the keys it pads."""
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv, kc = jax.random.split(rng, 4)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, 2, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, 2, SEQ, D), jnp.float32)
        ct = jax.random.normal(kc, (B, H, SEQ, D), jnp.float32)
        kpm = self._kpm()

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(seq_spec(), seq_spec(), seq_spec(), P(None, "cp")),
            out_specs=seq_spec(), check_vma=False,
        )
        def run_local(q, k, v, kpm):
            return ring_attention(
                q, k, v, axis_name="cp", causal=True,
                key_padding_mask=kpm, zigzag=True, block_size=8,
            )

        def run(q, k, v):
            zq, zk, zv = (zigzag_shard(t, cp) for t in (q, k, v))
            zm = zigzag_shard(kpm, cp, axis=-1)
            return zigzag_unshard(run_local(zq, zk, zv, zm), cp)

        ref_fn = lambda q, k, v: flash_attention(
            q, k, v, causal=True, key_padding_mask=kpm, impl="xla"
        )
        np.testing.assert_allclose(
            run(q, k, v), ref_fn(q, k, v), rtol=2e-4, atol=2e-5
        )
        gp = jax.grad(lambda q, k, v: jnp.sum(run(q, k, v) * ct),
                      (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(ref_fn(q, k, v) * ct),
                      (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_ring_rejects_indivisible_heads(self, rng):
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=4, devices=jax.devices()[:4]
        )
        q = jnp.zeros((B, 4, SEQ, D))
        k = jnp.zeros((B, 3, SEQ, D))
        with pytest.raises(ValueError, match="not divisible"):

            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(seq_spec(),) * 3,
                out_specs=seq_spec(), check_vma=False,
            )
            def run(q, k, v):
                return ring_attention(q, k, v, axis_name="cp")

            run(q, k, k)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_parity(self, rng, causal):
        cp = 4  # heads=4 divisible by cp
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(seq_spec(),) * 3,
            out_specs=seq_spec(),
            check_vma=False,
        )
        def run(q, k, v):
            return ulysses_attention(q, k, v, axis_name="cp", causal=causal)

        np.testing.assert_allclose(
            run(q, k, v), full_reference(q, k, v, causal), rtol=2e-4, atol=2e-5
        )

    def test_gqa_and_kpm_parity(self, rng):
        """GQA K/V (kv_heads % cp == 0) plus an all-gathered sequence-
        sharded key-padding mask through the all-to-all path."""
        cp = 2
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv, kc = jax.random.split(rng, 4)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, 2, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, 2, SEQ, D), jnp.float32)
        ct = jax.random.normal(kc, (B, H, SEQ, D), jnp.float32)
        kpm = jnp.zeros((B, SEQ), bool).at[0, 20:].set(True)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(seq_spec(), seq_spec(), seq_spec(), P(None, "cp")),
            out_specs=seq_spec(), check_vma=False,
        )
        def run(q, k, v, kpm):
            return ulysses_attention(
                q, k, v, axis_name="cp", causal=True, key_padding_mask=kpm
            )

        ref_fn = lambda q, k, v: flash_attention(
            q, k, v, causal=True, key_padding_mask=kpm, impl="xla"
        )
        np.testing.assert_allclose(
            run(q, k, v, kpm), ref_fn(q, k, v), rtol=2e-4, atol=2e-5
        )
        gp = jax.grad(lambda q, k, v: jnp.sum(run(q, k, v, kpm) * ct),
                      (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(ref_fn(q, k, v) * ct),
                      (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_grad_flows(self, rng):
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        q = jax.random.normal(rng, (B, H, SEQ, D), jnp.float32)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=seq_spec(),
            out_specs=seq_spec(),
            check_vma=False,
        )
        def grad_q(q):
            def loss(q):
                o = ulysses_attention(q, q, q, axis_name="cp", causal=True)
                l = jnp.sum(o**2)
                return l + jax.lax.stop_gradient(jax.lax.psum(l, "cp") - l)

            return jax.grad(loss)(q)

        def ref(q):
            return jnp.sum(full_reference(q, q, q, True) ** 2)

        np.testing.assert_allclose(
            grad_q(q), jax.grad(ref)(q), rtol=2e-3, atol=1e-4
        )


class TestGPTWithCP:
    @pytest.mark.parametrize("pos_emb", ["rope", "learned"])
    def test_gpt_ring_cp_matches_single_device(self, rng, pos_emb):
        """End-to-end: GPT with context_parallel_mode='ring' on a cp=4 mesh
        reproduces single-device per-token losses (both rotary and learned
        positions — the latter must offset by the cp rank)."""
        from apex_tpu.models import GPTModel
        from apex_tpu.transformer import TransformerConfig

        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )

        def cfg(cp_mode):
            return TransformerConfig(
                num_layers=2,
                hidden_size=32,
                num_attention_heads=4,
                vocab_size=64,
                max_position_embeddings=SEQ,
                hidden_dropout=0.0,
                attention_dropout=0.0,
                position_embedding_type=pos_emb,
                compute_dtype=jnp.float32,
                context_parallel_mode=cp_mode,
            )

        tokens = jax.random.randint(rng, (2, SEQ), 0, 64)
        labels = jnp.roll(tokens, -1, axis=1)

        ref_model = GPTModel(config=cfg(None))
        params = ref_model.init(jax.random.PRNGKey(1), tokens)
        ref_losses = ref_model.apply(params, tokens, labels=labels)

        cp_model = GPTModel(config=cfg("ring"))

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(None, "cp"), P(None, "cp")),
            out_specs=P(None, "cp"),
            check_vma=False,
        )
        def run(params, tokens, labels):
            return cp_model.apply(params, tokens, labels=labels)

        cp_losses = run(params, tokens, labels)
        np.testing.assert_allclose(cp_losses, ref_losses, rtol=2e-4, atol=2e-5)


class TestCPDecode:
    def test_gpt_ring_cp_kv_cache_decode_matches_single_device(self, rng):
        """KV-cache decode over a context-parallel-sharded cache (VERDICT
        r4 item 8, formerly a NotImplementedError guard): prefill writes
        each rank's contiguous prompt shard into its local cache, decode
        tokens land round-robin (token t -> rank t % cp), and each step
        merges per-rank partial softmax stats via cp_decode_attention's
        log-sum-exp identity.  Per-step logits must equal the
        single-device uncached forward at every decoded position."""
        from apex_tpu.models import GPTModel
        from apex_tpu.transformer import TransformerConfig

        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        total, prompt = 16, 8

        def cfg(cp_mode):
            return TransformerConfig(
                num_layers=2,
                hidden_size=32,
                num_attention_heads=4,
                vocab_size=64,
                max_position_embeddings=total,
                hidden_dropout=0.0,
                attention_dropout=0.0,
                position_embedding_type="rope",
                compute_dtype=jnp.float32,
                context_parallel_mode=cp_mode,
            )

        tokens = jax.random.randint(rng, (2, total), 0, 64)
        ref_model = GPTModel(config=cfg(None))
        params = ref_model.init(jax.random.PRNGKey(1), tokens)
        full = np.asarray(ref_model.apply(params, tokens))  # (b, total, v)
        cp_model = GPTModel(config=cfg("ring"))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
        def decode_all(params, tokens):
            r = jax.lax.axis_index("cp")
            s_local = prompt // cp
            local = jax.lax.dynamic_slice_in_dim(
                tokens[:, :prompt], r * s_local, s_local, 1
            )
            _, st = cp_model.apply(
                params, local, cache_len=total, mutable=["cache"]
            )
            cache = st["cache"]
            outs = []
            for pos in range(prompt, total):
                sl, upd = cp_model.apply(
                    {**params, "cache": cache},
                    tokens[:, pos : pos + 1],
                    decode_step=True,
                    mutable=["cache"],
                )
                cache = upd["cache"]
                outs.append(sl[:, 0])
            return jnp.stack(outs, axis=1)  # (b, total-prompt, v)

        got = np.asarray(decode_all(params, tokens))
        np.testing.assert_allclose(
            got, full[:, prompt:], rtol=2e-4, atol=2e-4
        )


class TestRingBlockwise:
    @pytest.mark.parametrize("block_size", [2, 4, 8])
    def test_inner_blocking_matches(self, rng, block_size):
        """block_size < s_local exercises the inner kv-block scan (the
        O(s x block) memory path) — results must be block-size invariant."""
        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (B, H, SEQ, D), jnp.float32)
        k = jax.random.normal(kk, (B, H, SEQ, D), jnp.float32)
        v = jax.random.normal(kv, (B, H, SEQ, D), jnp.float32)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(seq_spec(),) * 3,
            out_specs=(seq_spec(),) * 3, check_vma=False,
        )
        def run(q, k, v):
            def loss(q, k, v):
                o = ring_attention(
                    q, k, v, axis_name="cp", causal=True, block_size=block_size
                )
                l = jnp.sum(o**2)
                return l + jax.lax.stop_gradient(jax.lax.psum(l, "cp") - l)

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def ref(q, k, v):
            return jnp.sum(full_reference(q, k, v, True) ** 2)

        got = run(q, k, v)
        want = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, rtol=2e-3, atol=1e-4)


class TestShardAwareDropout:
    def test_masks_differ_across_cp_ranks(self, rng):
        from apex_tpu.transformer.layer import ShardAwareDropout

        cp = 4
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size=cp, devices=jax.devices()[:cp]
        )
        mod = ShardAwareDropout(rate=0.5, axis_names=("cp",))
        x = jnp.ones((4, 64))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P("cp"),
            check_vma=False,
        )
        def run(x):
            y = mod.apply({}, x, deterministic=False,
                          rngs={"dropout": jax.random.PRNGKey(7)})
            return y[None]

        per_rank = run(x)  # (cp, 4, 64) — same input, same key, per-rank mask
        masks = np.asarray(per_rank) != 0.0
        assert not all(
            np.array_equal(masks[0], masks[i]) for i in range(1, cp)
        ), "cp ranks drew identical dropout masks"

    def test_identity_without_axes(self, rng):
        from apex_tpu.transformer.layer import ShardAwareDropout

        mod = ShardAwareDropout(rate=0.5, axis_names=("cp",))
        x = jnp.ones((8, 8))
        # outside shard_map the unbound axis is skipped, not an error
        y = mod.apply({}, x, deterministic=False,
                      rngs={"dropout": jax.random.PRNGKey(0)})
        assert y.shape == x.shape
        z = mod.apply({}, x, deterministic=True)
        np.testing.assert_array_equal(z, x)


class TestCPComposition:
    """cp composed with tp sequence parallelism — the axis combination
    Megatron-style long-context training actually runs (no reference
    counterpart).  Parity target: the tp-only run on the same mesh — that
    path is itself pinned to the single-device model by the tp test suite,
    so this test isolates exactly what turning cp on changes."""

    @pytest.mark.parametrize("sp", [False, True])
    def test_gpt_cp_tp_sp_matches_tp_only(self, rng, sp):
        from apex_tpu.models import GPTModel
        from apex_tpu.transformer import TransformerConfig

        cp, tp = 2, 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp, context_parallel_size=cp,
            devices=jax.devices()[: cp * tp * 2],  # dp=2 as well
        )

        def cfg(cp_mode, sp_flag):
            return TransformerConfig(
                num_layers=2,
                hidden_size=32,
                num_attention_heads=4,
                num_query_groups=2,  # GQA through the ring
                vocab_size=64,
                max_position_embeddings=SEQ,
                hidden_dropout=0.0,
                attention_dropout=0.0,
                compute_dtype=jnp.float32,
                context_parallel_mode=cp_mode,
                sequence_parallel=sp_flag,
            )

        tokens = jax.random.randint(rng, (4, SEQ), 0, 64)
        labels = jnp.roll(tokens, -1, axis=1)

        cp_model = GPTModel(config=cfg("ring", sp))

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P("dp", "cp"), P("dp", "cp")),
            out_specs=P("dp", "cp"),
            check_vma=False,
        )
        def run(params, tokens, labels):
            return cp_model.apply(params, tokens, labels=labels)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def init(tokens):
            return cp_model.init(jax.random.PRNGKey(1), tokens)

        params = init(tokens[:1, : SEQ // cp])
        cp_losses = run(params, tokens, labels)

        # reference: the tp-only run (cp disabled) with the SAME params on
        # the same mesh — tp shards live per-rank so a true single-device
        # evaluation cannot consume them; the tp path itself is pinned to
        # single-device by tests/test_tensor_parallel.py
        tp_model = GPTModel(config=cfg(None, sp))

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P("dp"), P("dp")),
            out_specs=P("dp"),
            check_vma=False,
        )
        def run_tp(params, tokens, labels):
            return tp_model.apply(params, tokens, labels=labels)

        tp_losses = run_tp(params, tokens, labels)
        np.testing.assert_allclose(
            np.asarray(cp_losses), np.asarray(tp_losses),
            rtol=2e-4, atol=2e-5,
        )
