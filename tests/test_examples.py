"""End-to-end smoke of every example script, as a user would run them.

The reference ships runnable examples (examples/imagenet/main_amp.py etc.)
and its L1 tier drives them; these tests are the equivalent guard — each
example is executed in a subprocess with tiny shapes and must train to
completion. They are the only tests exercising the examples' argparse
surface, so a flag rename that would break a user shows up here.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, args, extra_env=None):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        **(extra_env or {}),
    )
    # examples force the CPU backend themselves is NOT guaranteed — do it
    # the way a user on this box must (tests/conftest.py pattern)
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.argv={['x'] + args!r}\n"
        f"exec(open({script!r}).read())\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=600,
    )
    assert proc.returncode == 0, (
        f"{script} failed rc={proc.returncode}\nstdout tail: "
        f"{proc.stdout[-800:]}\nstderr tail: {proc.stderr[-800:]}"
    )
    return proc.stdout


# each of these trains a real model for a few steps => slow tier
def test_amp_mlp_example():
    out = _run("examples/simple/amp_mlp_train.py",
               ["--steps", "12", "--opt-level", "O2", "--half", "float16"])
    assert "done: 12 steps" in out


def test_imagenet_example():
    out = _run("examples/imagenet/main_amp.py",
               ["--steps", "3", "--batch-size", "4", "--image-size", "32"])
    assert "done: 3 steps" in out


def test_gpt_pretrain_example(tmp_path):
    # conftest's XLA_FLAGS gives the subprocess 8 virtual devices => dp=8;
    # micro-batch 1 x dp 8 must divide the global batch. The telemetry
    # flags ride along: the jsonl sink must produce parseable records
    # carrying the full acceptance set (loss, grad-norm, loss-scale,
    # tokens/s, MFU) per interval; the peak-FLOPs pin makes MFU a real
    # number on the CPU mesh instead of null.
    import json

    jsonl = tmp_path / "metrics.jsonl"
    out = _run("examples/gpt/pretrain_gpt.py",
               ["--steps", "3", "--layers", "2", "--hidden", "64",
                "--heads", "4", "--seq-len", "32", "--micro-batch", "1",
                "--global-batch", "16", "--log-interval", "2",
                "--fleet-interval", "1",
                "--metrics-jsonl", str(jsonl)],
               extra_env={"APEX_TPU_PEAK_FLOPS": "1e12"})
    assert "step " in out
    records = [json.loads(line) for line in jsonl.read_text().splitlines()]
    metrics = [r for r in records if r["kind"] == "metrics"]
    assert len(metrics) == 2  # steps 0..2, interval 2 -> steps 0 and 2
    for rec in metrics:
        for key in ("loss", "grad_norm", "loss_scale", "tokens_per_s", "mfu"):
            assert isinstance(rec[key], float), (key, rec)
        # the bounded skip-and-log loader's host counter rides along
        assert rec["data_skipped"] == 0
    # live fleet health (--fleet-interval): the in-job check emits its
    # summary records into the same stream; a single-host run can never
    # flag (the verdicts need >= 2 hosts), so summaries are ALL of them
    fleet = [r for r in records if r["kind"] == "fleet"]
    assert fleet and all(r["check"] == "summary" for r in fleet)
    assert all(r["ok"] and r["n_hosts"] <= 1 for r in fleet)
    assert any(r["kind"] == "timer" for r in records)
    assert any(r["kind"] == "summary" for r in records)
    # run-level goodput ledger (PR 7): every record carries the host
    # field, the incarnation announces itself with a run header, phase
    # spans cover the lifecycle, and the end-of-run summary record's
    # partition identity holds digit-for-digit through the jsonl
    assert all(r["host"] == 0 for r in records)
    (run_rec,) = [r for r in records if r["kind"] == "run"]
    phases = {r["phase"] for r in records if r["kind"] == "span"}
    assert {"init", "compile", "step", "data_wait"} <= phases
    (g,) = [r for r in records if r["kind"] == "goodput"]
    assert g["run_id"] == run_rec["run_id"]
    assert g["productive_s"] > 0 and g["badput_compile_s"] > 0
    total = g["productive_s"]
    for phase in ("ckpt_save", "ckpt_restore", "rollback", "compile",
                  "data_wait", "stall", "init", "shutdown"):
        total = total + g[f"badput_{phase}_s"]
    assert total + g["unattributed_s"] == g["wall_s"]  # ==, not approx


def test_gpt_pretrain_xray(tmp_path):
    """The X-ray flags through the real example: startup banners (memory
    breakdown + predicted comms/step) on stdout, and kind='comms'/
    'memory'/'compile' records in the SAME jsonl stream as metrics and
    anomalies — the one-tailer contract. --audit-donation rides along:
    the donation auditor (apex_tpu.analysis) must verify the example's
    donate_argnums=(0,1,2,3) against XLA's realized aliasing.
    --audit-comms likewise: the ghost-collective differ must match every
    collective XLA emitted for the real tp=2 step against the ledger
    prediction (vmapped microbatch batching and XLA's reduce
    reassociation included) — and must refuse to print ok otherwise."""
    import json

    jsonl = tmp_path / "metrics.jsonl"
    out = _run("examples/gpt/pretrain_gpt.py",
               ["--steps", "3", "--layers", "2", "--hidden", "64",
                "--heads", "4", "--seq-len", "32", "--micro-batch", "1",
                "--global-batch", "16", "--log-interval", "2", "--tp", "2",
                "--metrics-jsonl", str(jsonl),
                "--xray-report", "--xray-comms", "--audit-donation",
                "--audit-comms"])
    assert "comms ledger (per step):" in out
    assert "memory report (per device):" in out
    assert "donation audit: ok" in out
    assert "comms audit: ok" in out
    records = [json.loads(line) for line in jsonl.read_text().splitlines()]
    by_kind = {}
    for r in records:
        by_kind.setdefault(r["kind"], []).append(r)
    comms = by_kind["comms"]
    # startup emission at step 0 plus one re-emission per log interval
    assert {r["axis"] for r in comms} == {"dp", "tp"}
    assert all(r["bytes"] > 0 for r in comms)
    assert len(comms) > 2  # periodic re-emission happened
    (mem,) = by_kind["memory"]
    assert mem["argument_bytes"] > 0 and mem["temp_bytes"] > 0
    # warmup compile of the jitted step is accounted, not flagged
    assert any(r["recompile"] is False for r in by_kind["compile"])
    assert not any(r["recompile"] for r in by_kind["compile"])
    assert "metrics" in by_kind


def test_gpt_pretrain_profile_analyze(tmp_path):
    """ACCEPTANCE round trip: a real CPU-captured profiler trace of the
    dp4xtp2 GPT step, analyzed by the timeline module end to end. The
    run wraps each step in a step_annotation, ProfilerTrigger captures a
    window at step 1, and --profile-analyze must segment >= 2 steps,
    report a non-degenerate device-time partition (identity: compute +
    exposed comms + exposed memcpy + idle == span), and join measured
    collective seconds to the ledger's predicted per-axis bytes —
    kind='profile' records landing in the SAME jsonl stream as metrics
    (the one-tailer contract)."""
    import json

    jsonl = tmp_path / "metrics.jsonl"
    out = _run("examples/gpt/pretrain_gpt.py",
               ["--steps", "4", "--layers", "2", "--hidden", "64",
                "--heads", "4", "--seq-len", "32", "--micro-batch", "1",
                "--global-batch", "16", "--tp", "2",
                "--save", str(tmp_path / "ckpt"),
                "--metrics-jsonl", str(jsonl), "--profile-analyze"])
    assert "profile timeline" in out
    assert "2 step(s)" in out
    records = [json.loads(line) for line in jsonl.read_text().splitlines()]
    profile = [r for r in records if r["kind"] == "profile"]
    steps = [r for r in profile if "span_ms" in r]
    axes = [r for r in profile if "axis" in r]
    assert len(steps) >= 2          # the capture window held >= 2 steps
    for rec in steps:
        assert rec["span_ms"] > 0 and rec["compute_ms"] > 0
        assert rec["collective_ms"] > 0 and rec["n_ops"] > 0
        # the partition identity survives the record round trip
        total = (rec["compute_ms"] + rec["exposed_comms_ms"]
                 + rec["exposed_memcpy_ms"] + rec["idle_ms"])
        assert total == pytest.approx(rec["span_ms"], rel=1e-6)
    # >= 1 collective event joined to a ledger-predicted byte bucket on
    # each mesh axis -> an achieved-bandwidth record
    assert {r["axis"] for r in axes} == {"dp", "tp"}
    for rec in axes:
        assert rec["events"] > 0
        assert rec["predicted_ici_bytes"] > 0
        assert rec["achieved_bytes_per_s"] > 0
    # the shared stream still carries the ordinary metrics
    assert any(r["kind"] == "metrics" for r in records)


def test_gpt_compression_parity(tmp_path):
    """ACCEPTANCE (ISSUE 11, slow tier): compressed-DDP and
    compressed-ZeRO GPT loss trajectories stay within pinned tolerance
    of their exact-path twins over the drill horizon, and the found_inf
    skip behavior under chaos NaN poison is IDENTICAL — every run is
    poisoned at the same step, and exactly that step is skipped on both
    the exact and the int8 wire."""
    import json

    base = ["--layers", "2", "--hidden", "64", "--heads", "4",
            "--seq-len", "32", "--micro-batch", "1", "--global-batch", "16",
            "--log-interval", "1", "--steps", "10",
            # one poisoned step: the gate must fire identically on the
            # exact and the compressed wire (skip, no rollback)
            "--chaos-nan-steps", "5", "--skip-budget", "2"]

    def run(tag, extra):
        jsonl = tmp_path / f"{tag}.jsonl"
        _run("examples/gpt/pretrain_gpt.py",
             base + ["--metrics-jsonl", str(jsonl)] + extra)
        losses, skipped = {}, {}
        for line in jsonl.read_text().splitlines():
            rec = json.loads(line)
            if rec.get("kind") == "metrics":
                losses[rec["step"]] = rec["loss"]
                skipped[rec["step"]] = rec["skipped"]
        return losses, skipped

    for mode, extra in (
        ("ddp", []),
        ("zero", ["--zero"]),
    ):
        exact, skip_e = run(f"{mode}-exact", extra)
        comp, skip_c = run(f"{mode}-int8", extra + ["--compression", "int8"])
        assert set(exact) == set(comp) == set(range(10))
        # found_inf parity: the poisoned step (and ONLY it) skipped, on
        # both wires — the NaN crossed the int8 payload via the
        # poisoned-scale contract
        assert skip_e == skip_c, (mode, skip_e, skip_c)
        assert skip_e[5] == 1.0 and sum(skip_e.values()) == 1.0
        # convergence parity: pinned tolerance over the horizon (the
        # block-quantization error on ~1e-2 grads with error feedback
        # moves a 6.2-ish loss by far less than this)
        for s in range(10):
            assert comp[s] == pytest.approx(exact[s], abs=3e-2), (
                mode, s, comp[s], exact[s])


def test_gpt_compression_resume_migration(tmp_path):
    """Enabling --compression on an EXISTING same-topology checkpoint
    must resume it (zero error-feedback residuals), not discard the run
    on the opt-slot structure diff."""
    base = ["--layers", "2", "--hidden", "64", "--heads", "4",
            "--seq-len", "32", "--micro-batch", "1", "--global-batch", "16",
            "--save", str(tmp_path), "--save-interval", "2"]
    _run("examples/gpt/pretrain_gpt.py", ["--steps", "3"] + base)
    out = _run("examples/gpt/pretrain_gpt.py",
               ["--steps", "5", "--compression", "int8"] + base)
    assert "resumed a pre-compression checkpoint" in out
    assert "resumed from step 2" in out
    assert "starting fresh" not in out


def test_gpt_pretrain_resume(tmp_path):
    """Checkpoint-then-resume through the example's AutoResume wiring: the
    second invocation must pick up at the saved step, not step 0 (the
    preemption-signal path itself is unit-tested in test_utils.py)."""
    base = ["--layers", "2", "--hidden", "64", "--heads", "4",
            "--seq-len", "32", "--micro-batch", "1", "--global-batch", "16",
            "--save", str(tmp_path), "--save-interval", "2"]
    _run("examples/gpt/pretrain_gpt.py", ["--steps", "3"] + base)
    out = _run("examples/gpt/pretrain_gpt.py", ["--steps", "5"] + base)
    assert "resumed from step 2" in out
    assert "step     4" in out


def test_gpt_pretrain_chaos(tmp_path):
    """The resilience drill through the real example script: run A hits
    an injected NaN step (rollback) and a SIGTERM (durable termination
    checkpoint); run B starts with that newest checkpoint bit-flipped
    and must fall back to the previous verified step, then finish."""
    import json

    base = ["--layers", "2", "--hidden", "64", "--heads", "4",
            "--seq-len", "32", "--micro-batch", "1", "--global-batch", "16",
            "--save", str(tmp_path), "--save-interval", "4",
            "--snapshot-interval", "2", "--skip-budget", "0"]
    jsonl = tmp_path / "metrics.jsonl"
    out = _run("examples/gpt/pretrain_gpt.py",
               ["--steps", "12", "--chaos-nan-steps", "6",
                "--chaos-sigterm-step", "9",
                "--metrics-jsonl", str(jsonl)] + base)
    assert "rolled back to step 6" in out
    assert "termination checkpoint at step 10; exiting" in out
    # anomalies and metrics share one record schema in ONE stream: the
    # rollback events land in the same jsonl as the interval metrics
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    kinds = {r["kind"] for r in records}
    assert {"metrics", "rollback", "rollback_restore"} <= kinds
    # ACCEPTANCE (PR 7): the goodput summary record in the shared jsonl
    # books real ckpt_save + compile + rollback badput, and the partition
    # identity holds digit-for-digit THROUGH the chaos rollback
    (g,) = [r for r in records if r["kind"] == "goodput"]
    assert g["badput_compile_s"] > 0
    assert g["badput_ckpt_save_s"] > 0    # interval + termination saves
    assert g["badput_rollback_s"] > 0     # the chaos rollback's recovery
    assert g["productive_s"] > 0
    total = g["productive_s"]
    for phase in ("ckpt_save", "ckpt_restore", "rollback", "compile",
                  "data_wait", "stall", "init", "shutdown"):
        total = total + g[f"badput_{phase}_s"]
    assert total + g["unattributed_s"] == g["wall_s"]  # ==, not approx

    out = _run("examples/gpt/pretrain_gpt.py",
               ["--steps", "12", "--chaos-corrupt-latest", "bitflip",
                "--metrics-jsonl", str(tmp_path / "m2.jsonl")] + base)
    assert "[chaos] corrupted newest checkpoint" in out
    # newest (step 10) is corrupt -> verified fallback to the interval save
    assert "resumed from step 8" in out
    assert "step    11" in out  # ran to completion
    records = [json.loads(l)
               for l in (tmp_path / "m2.jsonl").read_text().splitlines()]
    # the restart shares run A's run id (both anchor on --save) and its
    # verified-fallback restore books as ckpt_restore badput
    (g2,) = [r for r in records if r["kind"] == "goodput"]
    assert g2["run_id"] == g["run_id"]
    assert g2["badput_ckpt_restore_s"] > 0


def test_llama_finetune_example(tmp_path):
    # --audit-donation: the donation auditor must verify that params AND
    # the ZeRO opt-state alias in place (the opt-state donation is what
    # keeps ZeRO-2 from double-buffering its fp32 master+moments).
    # --audit-comms: the ZeRO gather/scatter collectives XLA emits for
    # the scanned train step must all match the ledger prediction.
    # --profile-analyze: the post-run capture of the single-step variant
    # must segment into the annotated steps and produce a joined
    # breakdown (pins the whole llama profile path — train_one's
    # shard_map closure, the capture loop, and the bandwidth join)
    import json

    jsonl = tmp_path / "metrics.jsonl"
    # --run-deadline: the incident ladder guards the compiled scan as one
    # unit (apex_tpu.resilience.health); generous here, so this pins the
    # wiring (start -> scan -> beat -> stop) without ever escalating
    out = _run("examples/llama/finetune_llama.py",
               ["--steps", "20", "--audit-donation", "--audit-comms",
                "--profile-analyze", "--profile-steps", "2",
                "--profile-dir", str(tmp_path / "prof"),
                "--run-deadline", "300",
                "--metrics-jsonl", str(jsonl)])
    assert "donation audit: ok" in out
    assert "comms audit: ok" in out
    assert "profile timeline" in out
    assert "timeline: 2 step(s)" in out
    assert "axis 'dp'" in out
    assert "final loss" in out
    # memorization demo: loss must fall well below the uniform floor
    final = float(out.split("final loss")[1].split(";")[0])
    assert final < 5.0, out
    # run-level goodput (PR 7): the scanned run's one compile books as
    # compile badput (the AOT split), the scan itself as productive, and
    # the summary record's identity holds exactly in the shared jsonl
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert any(r["kind"] == "run" for r in records)
    (g,) = [r for r in records if r["kind"] == "goodput"]
    assert g["productive_s"] > 0 and g["badput_compile_s"] > 0
    total = g["productive_s"]
    for phase in ("ckpt_save", "ckpt_restore", "rollback", "compile",
                  "data_wait", "stall", "init", "shutdown"):
        total = total + g[f"badput_{phase}_s"]
    assert total + g["unattributed_s"] == g["wall_s"]


def test_sparsity_example():
    out = _run("examples/sparsity/prune_mlp.py", ["--steps", "6"])
    assert "2:4 zeros preserved through training" in out


def test_long_context_ring_cp_example():
    out = _run("examples/long_context/train_ring_cp.py",
               ["--steps", "4", "--cp", "4", "--seq-len", "64",
                "--doc-len-min", "32", "--hidden", "32", "--heads", "4",
                "--kv-heads", "2"])
    assert "done" in out and "step    3" in out


def test_dcgan_example():
    # fp16 + dynamic scalers: the D-real/D-fake/G losses each own a scaler
    # (ref examples/dcgan/main_amp.py num_losses=3); trained losses finite
    out = _run("examples/dcgan/main_amp.py",
               ["--steps", "25", "--half", "float16",
                "--batch-size", "8", "--image-size", "16"])
    assert "done: 25 steps" in out
    last = [l for l in out.splitlines() if l.startswith("step")][-1]
    errd = float(last.split("errD")[1].split()[0])
    errg = float(last.split("errG")[1].split()[0])
    assert errd == errd and errg == errg  # not NaN
    assert 0.0 < errd < 50.0 and 0.0 < errg < 50.0


def test_fp8_example():
    out = _run("examples/fp8/train_fp8_mlp.py", ["--steps", "25"])
    assert "done: 25 steps" in out
    # the delayed-scaling demo must show recovery after one amax update
    assert "[demo]" in out
