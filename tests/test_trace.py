"""Request x-ray tests (apex_tpu.serving.trace, docs/serving.md
"Tracing & critical path").

Tier-1, jax-free: the trace-span emitter (one causal tree per request,
driven by the lifecycle machine on a fake clock), the offline
critical-path analyzer (completeness, the partition identity with ``==``
through a json round trip, the failover PIN — recovery is its own phase
and is never double-booked as queue wait), the goodput reconciliation,
the SLO burn-rate monitor, the autoscaler's burn-alert debounce
semantics, and the ``python -m apex_tpu.serving.trace`` gate's exit
codes. The live end-to-end closure (real engines, chaos kill, KV
handoff) is asserted by the fleet selftest and tests/test_fleet.py.
"""

import json

import pytest

from apex_tpu.serving import lifecycle
from apex_tpu.serving.fleet import FleetAutoscaler
from apex_tpu.serving.lifecycle import Request, emit_request_record, transition
from apex_tpu.serving.trace import ROOT_SPAN, SLOMonitor, TraceEmitter
from apex_tpu.serving.trace import analyze as az


class _CapRouter:
    """MetricRouter.event-shaped capture (the test_fleet.py idiom)."""

    def __init__(self):
        self.records = []

    def event(self, kind, step, **fields):
        rec = {"kind": kind, "step": int(step), **fields}
        self.records.append(rec)
        return rec


class _Clock:
    """Injectable virtual clock (the lint.serving-clock discipline)."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _drive(emitter, router, rid, submit_t, admit_t, first_t, end_t,
           clock, tick=0, attempt=1):
    """Walk one request through the full happy path on the emitter."""
    req = Request(rid=rid, prompt=[1, 2], max_new_tokens=4,
                  submit_t=submit_t)
    if attempt > 1:
        req.tags["attempt"] = attempt
    transition(req, lifecycle.QUEUED)
    emit_request_record(router, tick, req, trace=emitter)
    transition(req, lifecycle.ADMITTED, now=admit_t)
    emit_request_record(router, tick, req, trace=emitter)
    clock.t = admit_t
    transition(req, lifecycle.PREFILL)
    emit_request_record(router, tick, req, trace=emitter)
    req.first_token_t = first_t
    req.tokens_out.append(1)
    transition(req, lifecycle.DECODE)
    emit_request_record(router, tick, req, trace=emitter)
    transition(req, lifecycle.COMPLETED, now=end_t, reason="eos")
    emit_request_record(router, tick, req, trace=emitter)
    return req


# -- the span emitter -------------------------------------------------------


class TestTraceEmitter:
    def test_happy_path_emits_one_complete_tree(self):
        cap = _CapRouter()
        clock = _Clock()
        em = TraceEmitter(cap, site="r0.0", time_fn=clock)
        _drive(em, cap, 7, 0.0, 1.0, 2.0, 4.0, clock)
        spans = [r for r in cap.records if r["kind"] == "trace"]
        by_name = {r["name"]: r for r in spans}
        assert set(by_name) == {"queue", "prefill", "decode", "request"}
        root = by_name["request"]
        assert root["span"] == ROOT_SPAN and root["parent"] is None
        assert root["start"] == 0.0 and root["dur_s"] == 4.0
        assert root["state"] == "completed" and root["ttft_s"] == 2.0
        for name, (s, d, phase) in {
            "queue": (0.0, 1.0, "queue"),
            "prefill": (1.0, 1.0, "prefill"),
            "decode": (2.0, 2.0, "decode"),
        }.items():
            rec = by_name[name]
            assert rec["parent"] == ROOT_SPAN
            assert rec["start"] == s and rec["dur_s"] == d
            assert rec["phase"] == phase and rec["site"] == "r0.0"
            assert rec["attempt"] == 1

    def test_shed_at_the_door_is_a_root_only_tree(self):
        cap = _CapRouter()
        em = TraceEmitter(cap, site="r0.0", time_fn=_Clock())
        req = Request(rid=1, prompt=[1], max_new_tokens=2, submit_t=3.0)
        transition(req, lifecycle.REJECTED, now=3.0, reason="queue_full")
        emit_request_record(cap, 0, req, trace=em)
        spans = [r for r in cap.records if r["kind"] == "trace"]
        assert len(spans) == 1 and spans[0]["span"] == ROOT_SPAN
        assert spans[0]["state"] == "rejected"

    def test_terminal_from_queue_books_the_wait_as_queue(self):
        cap = _CapRouter()
        em = TraceEmitter(cap, site="r0.0", time_fn=_Clock())
        req = Request(rid=2, prompt=[1], max_new_tokens=2, submit_t=1.0)
        transition(req, lifecycle.QUEUED)
        emit_request_record(cap, 0, req, trace=em)
        transition(req, lifecycle.TIMED_OUT, now=6.0, reason="deadline")
        emit_request_record(cap, 0, req, trace=em)
        spans = {r["name"]: r for r in cap.records if r["kind"] == "trace"}
        assert spans["queue"]["start"] == 1.0
        assert spans["queue"]["dur_s"] == 5.0
        assert spans["request"]["state"] == "timed_out"

    def test_router_none_is_a_noop_with_consistent_state(self):
        em = TraceEmitter(None, site="r0.0", time_fn=_Clock())
        _drive(em, None, 3, 0.0, 1.0, 2.0, 3.0, _Clock())
        assert not em._seg and not em._enq and not em._pf

    def test_markers_are_informational(self):
        cap = _CapRouter()
        clock = _Clock(5.0)
        em = TraceEmitter(cap, site="fleet", time_fn=clock)
        req = Request(rid=4, prompt=[1], max_new_tokens=2)
        em.dispatched(0, req, replica="r1")
        em.stall(0, [req], start=5.0, dur_s=0.5)
        assert all(r["phase"] is None for r in cap.records)
        assert cap.records[0]["dur_s"] == 0.0
        assert cap.records[0]["replica"] == "r1"

    def test_extract_adopt_closes_and_reopens_the_decode_segment(self):
        cap = _CapRouter()
        ca, cb = _Clock(), _Clock()
        src = TraceEmitter(cap, site="p0.0", time_fn=ca)
        dst = TraceEmitter(cap, site="d0.0", time_fn=cb)
        req = Request(rid=5, prompt=[1, 2], max_new_tokens=4, submit_t=0.0)
        transition(req, lifecycle.QUEUED)
        emit_request_record(cap, 0, req, trace=src)
        transition(req, lifecycle.ADMITTED, now=1.0)
        emit_request_record(cap, 0, req, trace=src)
        ca.t = 1.0
        transition(req, lifecycle.PREFILL)
        emit_request_record(cap, 0, req, trace=src)
        req.first_token_t = 2.0
        transition(req, lifecycle.DECODE)
        emit_request_record(cap, 0, req, trace=src)
        ca.t = 3.0
        src.extracted(0, req)           # closes [2, 3] on the source
        cb.t = 4.0
        dst.adopted(0, req)             # opens at 4 on the adopter
        transition(req, lifecycle.COMPLETED, now=6.0, reason="eos")
        emit_request_record(cap, 0, req, trace=dst)
        decodes = [r for r in cap.records
                   if r["kind"] == "trace" and r["name"] == "decode"]
        assert [(r["site"], r["start"], r["dur_s"]) for r in decodes] == [
            ("p0.0", 2.0, 1.0), ("d0.0", 4.0, 2.0)]
        # span ids stay unique across the two emitters
        ids = [r["span"] for r in cap.records if r["kind"] == "trace"]
        assert len(ids) == len(set(ids))


# -- the analyzer -----------------------------------------------------------


def _failover_stream(cap=None):
    """The satellite PIN scenario: attempt 1 dies mid-decode, the fleet
    books a recovery envelope [5, 8], attempt 2 re-enqueues locally at
    t=8 and completes at t=12 — with the ORIGINAL submit time restored
    on the flat records (client-visible latencies)."""
    cap = cap if cap is not None else _CapRouter()
    ca = _Clock()
    em_a = TraceEmitter(cap, site="r0.0", time_fn=ca)
    req = Request(rid=1, prompt=[1, 2], max_new_tokens=4, submit_t=0.0)
    transition(req, lifecycle.QUEUED)
    emit_request_record(cap, 0, req, trace=em_a)
    transition(req, lifecycle.ADMITTED, now=1.0)
    emit_request_record(cap, 0, req, trace=em_a)
    ca.t = 1.0
    transition(req, lifecycle.PREFILL)
    emit_request_record(cap, 0, req, trace=em_a)
    req.first_token_t = 2.0
    transition(req, lifecycle.DECODE)
    emit_request_record(cap, 0, req, trace=em_a)
    # the replica dies here: the open decode segment is never closed —
    # [2, 5] is honest lost work (overhead), not a phase

    fleet = TraceEmitter(cap, site="fleet", time_fn=_Clock())
    fleet.recovery(12, rid=1, attempt=2, start=5.0, end=8.0, gp=None,
                   replica="r1")

    # attempt 2: the engine stamps the LOCAL enqueue instant; the fleet
    # captures it as redispatch_t, then restores the original submit
    cb = _Clock()
    em_b = TraceEmitter(cap, site="r1.0", time_fn=cb)
    req2 = Request(rid=1, prompt=[1, 2], max_new_tokens=4, submit_t=8.0,
                   tags={"attempt": 2})
    transition(req2, lifecycle.QUEUED)
    emit_request_record(cap, 12, req2, trace=em_b)
    req2.tags["redispatch_t"] = float(req2.submit_t)
    req2.submit_t = 0.0
    transition(req2, lifecycle.ADMITTED, now=9.0)
    emit_request_record(cap, 13, req2, trace=em_b)
    cb.t = 9.0
    transition(req2, lifecycle.PREFILL)
    emit_request_record(cap, 13, req2, trace=em_b)
    req2.first_token_t = 10.0
    req2.tokens_out.append(1)
    transition(req2, lifecycle.DECODE)
    emit_request_record(cap, 13, req2, trace=em_b)
    transition(req2, lifecycle.COMPLETED, now=12.0, reason="eos")
    emit_request_record(cap, 14, req2, trace=em_b)
    return cap


class TestAnalyzer:
    def test_failover_pin_recovery_is_its_own_phase(self):
        """ISSUE 17 satellite: recovery time matches the failover
        envelope and is NEVER double-booked as queue wait, while the
        flat records keep client-visible original-submit latencies."""
        cap = _failover_stream()
        report = az.analyze(cap.records)
        assert report.ok, report.summary()
        (d,) = report.decompositions
        assert d["recovery_s"] == 3.0          # the [5, 8] envelope
        # queue = [0,1] + the LOCAL re-enqueue wait [8,9] only — the
        # recovery envelope swallowed nothing into queue
        assert d["queue_s"] == 2.0
        assert d["prefill_s"] == 2.0 and d["decode_s"] == 2.0
        assert d["overhead_s"] == 3.0          # the orphaned [2, 5]
        assert d["wall_s"] == 12.0 and d["attempt"] == 2
        # flat-record semantics pinned: latencies from ORIGINAL submit
        terminal = [r for r in cap.records if r.get("kind") == "request"
                    and r.get("terminal")][-1]
        assert terminal["queue_wait_s"] == 9.0
        assert terminal["ttft_s"] == 10.0
        assert terminal["redispatch_t"] == 8.0
        # the TTFT window decomposes the same way
        parts = d["ttft_parts"]
        assert parts["recovery_s"] == 3.0 and parts["queue_s"] == 2.0

    def test_identity_through_json_round_trip(self):
        cap = _failover_stream()
        report = az.analyze(
            json.loads(json.dumps(r)) for r in cap.records)
        assert not report.identity_violations
        for d in report.decompositions:
            assert az.check_identity(json.loads(json.dumps(d)))

    def test_handoff_is_its_own_phase(self):
        cap = _CapRouter()
        clock = _Clock()
        em = TraceEmitter(cap, site="d0.0", time_fn=clock)
        _drive(em, cap, 9, 0.0, 1.0, 2.0, 6.0, clock)
        fleet = TraceEmitter(cap, site="fleet", time_fn=_Clock())
        fleet.handoff(0, rid=9, attempt=1, start=3.0, end=4.0, gp=None,
                      src="p0", dst="d0")
        report = az.analyze(cap.records)
        assert report.ok, report.summary()
        (d,) = report.decompositions
        # handoff outranks decode: [3, 4] leaves decode [2,3] + [4,6]
        assert d["handoff_s"] == 1.0 and d["decode_s"] == 3.0

    def test_missing_root_fails_the_gate(self):
        cap = _failover_stream()
        recs = [r for r in cap.records
                if not (r.get("kind") == "trace"
                        and r.get("span") == ROOT_SPAN)]
        report = az.analyze(recs)
        assert not report.ok
        assert any("no root" in p for probs in report.problems.values()
                   for p in probs)

    def test_duplicate_span_id_and_dangling_parent_are_problems(self):
        tr = az.build_traces([
            {"kind": "trace", "trace": 1, "span": "r", "parent": None,
             "start": 0.0, "dur_s": 1.0},
            {"kind": "trace", "trace": 1, "span": "a", "parent": "r",
             "start": 0.0, "dur_s": 1.0},
            {"kind": "trace", "trace": 1, "span": "a", "parent": "r",
             "start": 0.0, "dur_s": 1.0},
            {"kind": "trace", "trace": 1, "span": "b", "parent": "ghost",
             "start": 0.0, "dur_s": 1.0},
        ])[1]
        assert any("duplicate span id" in p for p in tr.problems)
        assert any("dangling parent" in p for p in tr.problems)

    def test_untraced_terminal_fails_the_gate(self):
        cap = _failover_stream()
        cap.records.append({"kind": "request", "step": 0, "id": 99,
                            "state": "completed", "terminal": True})
        report = az.analyze(cap.records)
        assert report.untraced_terminals == [99] and not report.ok

    def test_reconciliation_matches_and_twinless_badput_fails(self):
        from apex_tpu.monitor import MemorySink, MetricRouter
        from apex_tpu.monitor.goodput import run_header
        from apex_tpu.monitor.goodput.spans import begin_span, emit_span

        mem = MemorySink()
        router = MetricRouter([mem])
        run_header(router, "trace-reconcile-test")
        gp = begin_span("failover", router=router, step=0).close()
        cap = _CapRouter()
        _failover_stream(cap)
        for rec in cap.records:
            router.emit(rec)
        # stamp the gp twins onto the recovery span (verbatim copies,
        # the emitter's _gp_twin contract)
        for rec in mem.records:
            if rec.get("kind") == "trace" and rec.get("phase") == "recovery":
                rec["gp_phase"] = gp["phase"]
                rec["gp_start"] = gp["start"]
                rec["gp_dur_s"] = gp["dur_s"]
        report = az.analyze(mem.snapshot())
        assert report.reconcile is not None
        assert report.reconcile["recovery"]["match"], report.summary()
        assert report.ok, report.summary()
        # a failover second no request observed is itself a finding
        emit_span(router, "failover", start=gp["start"] + 10.0,
                  dur_s=0.5, step=1)
        report2 = az.analyze(mem.snapshot())
        assert not report2.reconcile["recovery"]["match"]
        assert not report2.ok


# -- the SLO burn-rate monitor ----------------------------------------------


def _terminal(state, ttft=None):
    rec = {"kind": "request", "step": 0, "state": state, "terminal": True}
    if ttft is not None:
        rec["ttft_s"] = ttft
    return rec


class TestSLOMonitor:
    def _monitor(self, cap=None, **kw):
        kw.setdefault("ttft_budget_s", 1.0)
        kw.setdefault("target", 0.9)
        kw.setdefault("window", 16)
        kw.setdefault("min_count", 4)
        return SLOMonitor(cap, **kw)

    def test_target_validation(self):
        with pytest.raises(ValueError, match="target"):
            SLOMonitor(None, ttft_budget_s=1.0, target=1.0)

    def test_sink_keeps_only_terminal_request_records(self):
        mon = self._monitor()
        tap = mon.sink()
        tap.emit({"kind": "span", "phase": "step"})
        tap.emit({"kind": "request", "state": "queued"})
        tap.emit(_terminal("completed", ttft=0.5))
        assert len(mon._pending) == 1

    def test_quiet_window_emits_nothing(self):
        cap = _CapRouter()
        mon = self._monitor(cap)
        assert mon.poll(0) is None
        assert cap.records == []

    def test_fast_burn_alert_fires_and_clears(self):
        cap = _CapRouter()
        mon = self._monitor(cap)
        tap = mon.sink()
        for _ in range(4):
            tap.emit(_terminal("rejected"))
        rec = mon.poll(1)
        # 4/4 violations, burn = 1.0/0.1 = 10x >= 14.4? no — use the
        # numbers: burn 10 < 14.4 with default fast_burn, so set state
        assert rec["violations"] == 4 and rec["sheds"] == 4
        assert rec["burn_rate"] == pytest.approx(10.0)
        assert not mon.burning
        mon2 = self._monitor(cap, fast_burn=5.0)
        tap2 = mon2.sink()
        for _ in range(4):
            tap2.emit(_terminal("rejected"))
        assert mon2.poll(2)["alert"] and mon2.burning
        # recovery: enough clean terminals dilute the window
        for _ in range(12):
            tap2.emit(_terminal("completed", ttft=0.1))
        rec = mon2.poll(3)
        assert not rec["alert"] and not mon2.burning

    def test_min_count_gates_the_alert(self):
        mon = self._monitor(_CapRouter(), fast_burn=5.0, min_count=8)
        tap = mon.sink()
        for _ in range(4):
            tap.emit(_terminal("failed"))
        mon.poll(0)
        assert not mon.burning     # 100% violations but n < min_count

    def test_cancelled_is_neutral_unless_the_token_was_late(self):
        mon = self._monitor(_CapRouter(), min_count=1)
        tap = mon.sink()
        tap.emit(_terminal("cancelled"))
        tap.emit(_terminal("cancelled", ttft=5.0))
        tap.emit(_terminal("completed", ttft=5.0))
        rec = mon.poll(0)
        assert rec["n"] == 3 and rec["violations"] == 2

    def test_unmoved_window_does_not_spam(self):
        cap = _CapRouter()
        mon = self._monitor(cap)
        mon.sink().emit(_terminal("completed", ttft=0.1))
        assert mon.poll(0) is not None
        assert mon.poll(1) is None     # nothing new, no flip
        assert len(cap.records) == 1

    def test_router_none_still_tracks_state(self):
        mon = self._monitor(None, fast_burn=5.0)
        tap = mon.sink()
        for _ in range(4):
            tap.emit(_terminal("timed_out"))
        assert mon.poll(0) is None     # no router, nothing emitted
        assert mon.burning and mon.last["alert"]


# -- the autoscaler's burn-alert semantics ----------------------------------


class TestAutoscalerBurning:
    def _scaler(self, **kw):
        kw.setdefault("ttft_budget_s", 1.0)
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("breach_ticks", 2)
        kw.setdefault("clear_ticks", 1)
        return FleetAutoscaler(**kw)

    def test_corroborated_evidence_counts_double(self):
        sc = self._scaler()
        # breach AND burning on one tick satisfies breach_ticks=2
        assert sc.observe(0, 2.0, 2, burning=True) == "scale_up"

    def test_burn_alone_counts_without_a_signal(self):
        # a shed-heavy fleet burns budget with no TTFT estimate at all
        sc = self._scaler()
        assert sc.observe(0, None, 2, burning=True) is None
        assert sc.observe(1, None, 2, burning=True) == "scale_up"

    def test_burning_vetoes_the_clear_path(self):
        cap = _CapRouter()
        sc = self._scaler(router=cap, clear_ticks=1)
        # the estimate is deep below low-water, but a fleet on fire
        # never looks surplus: the clear streak stays 0 and the burn
        # keeps counting toward the breach debounce instead
        assert sc.observe(0, 0.01, 2, burning=True) is None
        assert sc.stats()["clear_streak"] == 0
        assert sc.observe(1, 0.01, 2, burning=True) == "scale_up"
        assert sc.stats()["scale_downs"] == 0
        # the scale-up record carries the burn flag (None-safe signal)
        sc2 = self._scaler(router=cap, breach_ticks=1)
        sc2.observe(0, None, 2, burning=True)
        rec = cap.records[-1]
        assert rec["action"] == "scale_up"
        assert rec["signal_s"] is None and rec["slo_burning"] is True


# -- the CLI gate -----------------------------------------------------------


def test_trace_gate(tmp_path, capsys):
    """The ``python -m apex_tpu.serving.trace`` gate: exit 0 on a
    complete stream, nonzero on a stream with a broken tree, nonzero on
    a stream with no trace records at all."""
    from apex_tpu.serving.trace.__main__ import main

    cap = _failover_stream()
    good = tmp_path / "good.jsonl"
    good.write_text(
        "".join(json.dumps(r) + "\n" for r in cap.records))
    assert main([str(good)]) == 0
    out = capsys.readouterr().out
    assert "1 request tree(s), 1 complete" in out

    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(
        json.dumps(r) + "\n" for r in cap.records
        if not (r.get("kind") == "trace" and r.get("span") == ROOT_SPAN)))
    assert main([str(bad)]) == 1

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main([str(empty)]) == 1

    decomp = tmp_path / "decomp.jsonl"
    assert main([str(good), "--json", str(decomp), "-v"]) == 0
    rows = [json.loads(line) for line in
            decomp.read_text().splitlines()]
    assert len(rows) == 1 and rows[0]["kind"] == "trace_decomp"
    assert az.check_identity(rows[0])
