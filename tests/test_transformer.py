"""Transformer layer + GPT/BERT model tests (single device, tp=1).

Mirrors the reference's L0 run_transformer tier: numeric sanity of the
parallel layers against unfused compositions, and minimal end-to-end
loss-decrease training (ref: tests/L0/run_transformer/test_gpt_minimal.py,
test_bert_minimal.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.models import BertModel, GPTModel, gpt_loss_fn
from apex_tpu.transformer import (
    AttnMaskType,
    ParallelTransformerLayer,
    TransformerConfig,
)

VOCAB = 64


def tiny_cfg(**kw):
    defaults = dict(
        num_layers=2,
        hidden_size=32,
        num_attention_heads=4,
        vocab_size=VOCAB,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


def data(key, b=2, s=16):
    tokens = jax.random.randint(key, (b, s), 0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


class TestTransformerLayer:
    def test_forward_shape_and_dtype(self, rng):
        cfg = tiny_cfg(compute_dtype=jnp.bfloat16)
        layer = ParallelTransformerLayer(config=cfg)
        x = jax.random.normal(rng, (16, 2, cfg.hidden_size), jnp.bfloat16)
        params = layer.init(rng, x)
        y = layer.apply(params, x)
        assert y.shape == x.shape and y.dtype == jnp.bfloat16

    def test_flash_matches_core_attention(self, rng):
        """Causal flash path == CoreAttention with an explicit causal mask."""
        cfg = tiny_cfg()
        layer = ParallelTransformerLayer(config=cfg, attn_mask_type=AttnMaskType.causal)
        s = 16
        x = jax.random.normal(rng, (s, 2, cfg.hidden_size), jnp.float32)
        params = layer.init(rng, x)
        y_flash = layer.apply(params, x)  # no mask -> flash path
        keep = jnp.ones((2, s), jnp.int32)
        # all-ones padding mask forces the CoreAttention path but masks nothing
        mask = ~(keep[:, None, :].astype(bool) & keep[:, :, None].astype(bool))[:, None]
        y_core = layer.apply(params, x, mask)
        np.testing.assert_allclose(y_flash, y_core, rtol=2e-4, atol=2e-4)

    def test_remat_matches_plain(self, rng):
        cfg = tiny_cfg()
        cfg_r = tiny_cfg(recompute_granularity="full")
        from apex_tpu.transformer import ParallelTransformer

        x = jax.random.normal(rng, (16, 2, cfg.hidden_size), jnp.float32)
        m, mr = ParallelTransformer(config=cfg), ParallelTransformer(config=cfg_r)
        params = m.init(rng, x)
        np.testing.assert_allclose(
            m.apply(params, x), mr.apply(params, x), rtol=1e-5, atol=1e-5
        )

    def test_selective_remat_matches_plain(self, rng):
        cfg = tiny_cfg()
        cfg_s = tiny_cfg(recompute_granularity="selective")
        from apex_tpu.transformer import ParallelTransformer

        x = jax.random.normal(rng, (16, 2, cfg.hidden_size), jnp.float32)
        m, ms = ParallelTransformer(config=cfg), ParallelTransformer(config=cfg_s)
        params = m.init(rng, x)

        def loss(mod, p):
            return jnp.sum(mod.apply(p, x) ** 2)

        np.testing.assert_allclose(
            loss(m, params), loss(ms, params), rtol=1e-6, atol=1e-6
        )
        g1 = jax.grad(lambda p: loss(m, p))(params)
        g2 = jax.grad(lambda p: loss(ms, p))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5),
            g1,
            g2,
        )

    @pytest.mark.parametrize("act", ["geglu", "swiglu"])
    def test_gated_activations(self, rng, act):
        cfg = tiny_cfg(activation=act)
        layer = ParallelTransformerLayer(config=cfg)
        x = jax.random.normal(rng, (8, 2, cfg.hidden_size), jnp.float32)
        params = layer.init(rng, x)
        assert layer.apply(params, x).shape == x.shape


class TestGPT:
    def test_forward_logits_and_loss(self, rng):
        cfg = tiny_cfg()
        model = GPTModel(config=cfg)
        tokens, labels = data(rng)
        params = model.init(rng, tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, VOCAB)
        losses = model.apply(params, tokens, labels=labels)
        assert losses.shape == (2, 16)
        assert bool(jnp.all(jnp.isfinite(losses)))

    def test_key_padding_mask_blocks_padded_keys(self, rng):
        """key_padding_mask through GPTModel: tokens at padded-out MIDDLE
        positions must not influence later positions' logits (causally they
        would, so this isolates the mask), matching the flash kernel's kpm
        semantics end to end."""
        cfg = tiny_cfg()
        model = GPTModel(config=cfg)
        tokens, _ = data(rng)
        kpm = jnp.zeros(tokens.shape, bool).at[:, 5:8].set(True)
        params = model.init(rng, tokens)
        tokens2 = tokens.at[:, 5:8].set((tokens[:, 5:8] + 7) % VOCAB)

        l1 = model.apply(params, tokens, key_padding_mask=kpm)
        l2 = model.apply(params, tokens2, key_padding_mask=kpm)
        np.testing.assert_allclose(
            np.asarray(l1[:, 8:]), np.asarray(l2[:, 8:]), atol=1e-5
        )
        # and without the mask the same perturbation DOES propagate
        l3 = model.apply(params, tokens2)
        assert float(jnp.max(jnp.abs(
            l3[:, 8:] - model.apply(params, tokens)[:, 8:]
        ))) > 1e-3

    def test_dropout_training_path(self, rng):
        """deterministic=False with dropout>0 must run (regression: inline
        Dropout in a setup()-based module crashed the training path)."""
        cfg = tiny_cfg(hidden_dropout=0.1, attention_dropout=0.1)
        model = GPTModel(config=cfg)
        tokens, labels = data(rng)
        params = model.init(rng, tokens)
        losses = model.apply(
            params,
            tokens,
            labels=labels,
            deterministic=False,
            rngs={"dropout": jax.random.PRNGKey(7)},
        )
        assert bool(jnp.all(jnp.isfinite(losses)))

    def test_rope_forward(self, rng):
        cfg = tiny_cfg(position_embedding_type="rope")
        model = GPTModel(config=cfg)
        tokens, _ = data(rng)
        params = model.init(rng, tokens)
        assert model.apply(params, tokens).shape == (2, 16, VOCAB)

    def test_loss_decreases(self, rng):
        """ref: test_gpt_minimal.py:146-218 asserts the training loss drops."""
        cfg = tiny_cfg()
        model = GPTModel(config=cfg)
        tokens, labels = data(rng, b=4, s=16)
        params = model.init(rng, tokens)
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                return gpt_loss_fn(model.apply(p, tokens, labels=labels))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(20):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.5, losses

    def test_untied_output_weights(self, rng):
        """share_embeddings_and_output_weights=False must use a separate
        output projection — including on a last pipeline stage that has no
        embedding at all (regression: this crashed / silently stayed tied)."""
        cfg = tiny_cfg(share_embeddings_and_output_weights=False)
        model = GPTModel(config=cfg)
        tokens, labels = data(rng)
        params = model.init(rng, tokens)
        assert "output_layer" in params["params"]
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, VOCAB)

        last = GPTModel(config=cfg, pre_process=False, num_layers=1)
        h = jax.random.normal(rng, (16, 2, cfg.hidden_size))
        p_last = last.init(rng, h)
        assert "embedding" not in p_last["params"]
        assert last.apply(p_last, h).shape == (2, 16, VOCAB)

    def test_pipeline_stage_slicing(self, rng):
        """pre/post_process chunks compose to the full model (ref:
        build_model pre/post flags, schedules/common.py:83-108)."""
        cfg = tiny_cfg()
        full = GPTModel(config=cfg)
        first = GPTModel(config=cfg, post_process=False, num_layers=1)
        last = GPTModel(config=cfg, pre_process=False, num_layers=1)
        tokens, _ = data(rng)
        params = full.init(rng, tokens)
        p_first = {
            "params": {
                "embedding": params["params"]["embedding"],
                "transformer": {
                    "layer_0": params["params"]["transformer"]["layer_0"]
                },
            }
        }
        p_last = {
            "params": {
                "embedding": params["params"]["embedding"],
                "transformer": {
                    "layer_0": params["params"]["transformer"]["layer_1"],
                    "final_layernorm": params["params"]["transformer"][
                        "final_layernorm"
                    ],
                },
            }
        }
        h = first.apply(p_first, tokens)
        assert h.shape == (16, 2, cfg.hidden_size)
        logits = last.apply(p_last, h)
        np.testing.assert_allclose(
            logits, full.apply(params, tokens), rtol=1e-5, atol=1e-5
        )


class TestBert:
    def test_forward_and_heads(self, rng):
        cfg = tiny_cfg()
        model = BertModel(config=cfg)
        tokens, labels = data(rng)
        mask = jnp.ones_like(tokens)
        tokentype = jnp.zeros_like(tokens)
        params = model.init(rng, tokens, mask, tokentype)
        logits, binary = model.apply(params, tokens, mask, tokentype)
        assert logits.shape == (2, 16, VOCAB)
        assert binary.shape == (2, 2)
        losses, _ = model.apply(params, tokens, mask, tokentype, lm_labels=labels)
        assert losses.shape == (2, 16)

    def test_gqa_layer_matches_mha_with_tied_kv(self, rng):
        """num_query_groups < heads: GQA with every kv group's projection
        set equal to the corresponding MHA slices must reproduce... (can't
        be exactly tied since MHA has per-head kv) — instead pin internal
        consistency: flash path (grouped kv in the kernel) == CoreAttention
        path (explicitly repeated kv) on the same params."""
        from apex_tpu.transformer.layer import ParallelAttention

        cfg = tiny_cfg(num_query_groups=2)
        attn = ParallelAttention(
            config=cfg, attn_mask_type=AttnMaskType.causal
        )
        h = jax.random.normal(rng, (16, 2, 32), jnp.float32)
        params = attn.init(rng, h)
        out_flash = attn.apply(params, h)
        # force the unfused path with an all-False dense mask (semantically
        # no-op) -> CoreAttention with repeated kv heads
        mask = jnp.zeros((2, 1, 16, 16), bool)
        out_core = attn.apply(params, h, attention_mask=mask)
        np.testing.assert_allclose(
            np.asarray(out_flash), np.asarray(out_core), atol=2e-5
        )

    def test_gqa_param_shapes(self, rng):
        from apex_tpu.transformer.layer import ParallelAttention

        cfg = tiny_cfg(num_query_groups=1)  # MQA extreme
        attn = ParallelAttention(config=cfg, attn_mask_type=AttnMaskType.causal)
        h = jax.random.normal(rng, (8, 2, 32), jnp.float32)
        params = attn.init(rng, h)["params"]
        hn = cfg.hidden_size // cfg.num_attention_heads
        assert params["query"]["kernel"].shape == (32, 32)
        assert params["key_value"]["kernel"].shape == (32, 2 * hn)

    def test_kpm_fast_path_matches_dense_mask_path(self, rng):
        """The (b, s) key-padding row through the flash kernel must equal
        the same mask expressed densely through CoreAttention (key-side
        broadcast), for every position."""
        from apex_tpu.transformer.layer import ParallelTransformer

        cfg = tiny_cfg()
        model = ParallelTransformer(config=cfg, attn_mask_type=AttnMaskType.padding)
        h = jax.random.normal(rng, (16, 2, 32), jnp.float32)  # (s, b, h)
        kpm = jnp.zeros((2, 16), bool).at[0, 11:].set(True)
        params = model.init(rng, h)

        out_kpm = model.apply(params, h, key_padding_mask=kpm)
        dense = kpm[:, None, None, :]  # key-side-only dense equivalent
        out_dense = model.apply(params, h, attention_mask=dense)
        np.testing.assert_allclose(
            np.asarray(out_kpm), np.asarray(out_dense), atol=2e-5
        )

    def test_padding_mask_blocks_attention(self, rng):
        """Masked-out positions must not influence kept positions' outputs."""
        cfg = tiny_cfg()
        model = BertModel(config=cfg, add_binary_head=False)
        tokens, _ = data(rng)
        mask = jnp.concatenate(
            [jnp.ones((2, 8), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
        )
        params = model.init(rng, tokens, mask)
        logits1, _ = model.apply(params, tokens, mask)
        tokens2 = tokens.at[:, 8:].set((tokens[:, 8:] + 7) % VOCAB)
        logits2, _ = model.apply(params, tokens2, mask)
        np.testing.assert_allclose(
            logits1[:, :8], logits2[:, :8], rtol=1e-5, atol=1e-5
        )
