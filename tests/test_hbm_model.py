"""Analytic HBM ledger (apex_tpu.monitor.xray.hbm.model): digit pins.

The load-bearing contracts:

- BYTE EXACTNESS: every closed-form count is pinned against a
  hand-derived number (the test IS the derivation — a ledger that is
  "roughly right" cannot reconcile against ``memory_analysis()``);
- PARTITION IDENTITY: the predicted peak is DEFINED as the component
  sum, the identity survives a json round trip ``==``-for-``==``, and a
  breakdown whose declared peak disagrees with its components is
  rejected at parse;
- AGREEMENT WITH THE ALGEBRA: ``stash_depth`` duplicates (not imports)
  ``pipeline/algebra.schedule_cost``'s geometry validation so the
  ledger stays importable with jax absent — the two must accept and
  reject EXACTLY the same (schedule, P, M, V) tuples, and the schedule
  vocabularies must be equal;
- JAX-FREE: the whole predict path (model + oom forensics + kv-pool
  arithmetic) imports and computes with jax poisoned out of the
  interpreter — the feasibility oracle's any-box contract.
"""

import itertools
import json
import os
import subprocess
import sys

import pytest

from apex_tpu.monitor.xray.hbm import model as hbm
from apex_tpu.monitor.xray.hbm.model import (
    Component,
    HbmBreakdown,
    TransformerDims,
    adam_state_bytes,
    distributed_adam_state_bytes,
    dtype_bytes,
    gpt_param_elements,
    kv_pool_bytes,
    predict_fits,
    predict_serving_memory,
    predict_train_memory,
    stash_depth,
    zero_padded_total,
    zero_shard_elements,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the dp2tp2 audit target's geometry (analysis/targets._tiny_cfg)
TINY = TransformerDims(
    num_layers=2, hidden_size=16, num_attention_heads=2,
    vocab_size=32, max_position_embeddings=8,
)


# ---------------------------------------------------------------------------
# dtype table


class TestDtypeBytes:
    def test_jax_and_hlo_spellings_agree(self):
        # the differ feeds parser dtypes (f32, bf16) straight in
        assert dtype_bytes("float32") == dtype_bytes("f32") == 4
        assert dtype_bytes("bfloat16") == dtype_bytes("bf16") == 2
        assert dtype_bytes("int8") == dtype_bytes("s8") == 1
        assert dtype_bytes("float8_e4m3fn") == 1

    def test_name_attribute_wins(self):
        class _D:
            name = "bfloat16"

        assert dtype_bytes(_D()) == 2

    def test_unknown_dtype_refused(self):
        with pytest.raises(ValueError, match="unknown dtype"):
            dtype_bytes("complex128")


# ---------------------------------------------------------------------------
# GPT parameter-element counts (the exact flax tree, leaf for leaf)


class TestGptParamElements:
    def test_tp2_pin(self):
        """Hand count at h=16, ffn=64, heads=2, V=32, P=8, tp=2:

        per layer: ln1 32 + qkv (16*24 + 24)=408 + attn-out
        (8*16 + 16)=144 + ln2 32 + h->ffn (16*32 + 32)=544 + ffn->h
        (32*16 + 16)=528  ->  1688.
        total: pos 8*16=128 + vocab-shard 16*16=256 + final-ln 32
        + 2*1688=3376  ->  3792.
        """
        assert gpt_param_elements(TINY, tp=2) == 3792

    def test_tp1_pin(self):
        # per layer: 32 + (16*48+48)=816 + (16*16+16)=272 + 32
        # + (16*64+64)=1088 + (64*16+16)=1040 -> 3280;
        # total: 128 + 32*16=512 + 32 + 2*3280=6560 -> 7232
        assert gpt_param_elements(TINY, tp=1) == 7232

    def test_tp_sharding_saves_exactly_the_sharded_kernels(self):
        # the delta tp=1 -> tp=2 is half of every column/row kernel +
        # column bias + the vocab shard; replicated leaves don't move
        assert gpt_param_elements(TINY, tp=1) > gpt_param_elements(TINY, tp=2)

    def test_indivisible_geometry_refused(self):
        with pytest.raises(ValueError, match="not divisible"):
            gpt_param_elements(TINY, tp=3)


# ---------------------------------------------------------------------------
# optimizer state


class TestOptimizerState:
    def test_fused_adam_pin(self):
        # 2 fp32 moment trees + int32 step scalar
        assert adam_state_bytes(3792) == 2 * 4 * 3792 + 4 == 30340

    def test_zero_flat_chunk_matches_multi_tensor(self):
        # the ledger MIRRORS the padding quantum (no import — jax-free);
        # this pin is the agreement contract
        from apex_tpu.ops import multi_tensor

        assert hbm.ZERO_FLAT_CHUNK == multi_tensor.CHUNK_SIZE == 65536

    def test_zero_padded_total_pins(self):
        # 7744 elements pad up to one 65536 chunk; 2 divides it
        assert zero_padded_total(7744, 2) == 65536
        # one element past a chunk boundary books a whole second chunk
        assert zero_padded_total(65537, 2) == 131072
        # minimum one chunk even for an empty tree
        assert zero_padded_total(0, 1) == 65536
        # the axis rounding is the SECOND padding (after the chunk pad)
        assert zero_padded_total(65536, 3) == 65538
        assert zero_shard_elements(65536, 3) == 21846

    def test_zero_padded_total_refuses_bad_geometry(self):
        with pytest.raises(ValueError):
            zero_padded_total(-1, 2)
        with pytest.raises(ValueError):
            zero_padded_total(10, 0)

    def test_distributed_adam_pin(self):
        """The gpt-pp ZeRO ground truth: 7744 f32 elements over 2 ranks
        -> 32768-element shards; 4 (step) + 32768*4 (fp32 master)
        + 2*32768*4 (moments) + 4 (ef scalar) = 393224."""
        assert distributed_adam_state_bytes(7744, 2) == 393224

    def test_param_remainders_halve_the_master_shard(self):
        # uint16 remainders: the bf16 param IS the high half
        base = distributed_adam_state_bytes(7744, 2)
        slim = distributed_adam_state_bytes(
            7744, 2, store_param_remainders=True
        )
        assert base - slim == 32768 * 2

    def test_error_feedback_books_a_full_residual_shard(self):
        base = distributed_adam_state_bytes(7744, 2)
        ef = distributed_adam_state_bytes(7744, 2, error_feedback=True)
        assert ef - base == 32768 * 4 - 4


# ---------------------------------------------------------------------------
# stash depths vs the schedule algebra (agreement, not import)


class TestStashDepth:
    def test_depth_pins(self):
        assert stash_depth("no_pipelining", 1, 4).activation_depth == 1
        assert stash_depth("no_pipelining", 1, 4).w_depth == 0
        # compiled two-scan 1f1b: all M stashes live at the boundary
        assert stash_depth("1f1b", 4, 8).activation_depth == 8
        assert stash_depth("1f1b", 4, 8).w_depth == 0
        # M per model chunk
        assert stash_depth("interleaved", 2, 4, 2).activation_depth == 8
        # zero-bubble's memory price: a second stash of deferred-W inputs
        zb = stash_depth("zero_bubble", 4, 8)
        assert (zb.activation_depth, zb.w_depth) == (8, 8)
        assert zb.total_depth == 16

    def test_schedule_vocabulary_matches_algebra(self):
        from apex_tpu.parallel.pipeline import algebra

        assert set(hbm.STASH_SCHEDULES) == set(algebra.SCHEDULES)

    @pytest.mark.parametrize(
        "schedule,p,m,v",
        [
            (s, p, m, v)
            for s in ("no_pipelining", "1f1b", "interleaved", "zero_bubble")
            for (p, m, v) in [
                (1, 1, 1), (2, 4, 1), (4, 8, 2), (2, 3, 2),
                (0, 4, 1), (2, 0, 1), (2, 4, 0), (3, 4, 2),
            ]
        ],
    )
    def test_geometry_agreement_with_algebra(self, schedule, p, m, v):
        """stash_depth duplicates schedule_cost's validation rather than
        importing it (the jax-free contract); this pin proves the two
        accept and reject exactly the same (schedule, P, M, V) tuples —
        including interleaved's V >= 2 and M % P == 0 rules."""
        from apex_tpu.parallel.pipeline import algebra

        def outcome(fn):
            try:
                fn()
                return "ok"
            except ValueError:
                return "rejected"

        ours = outcome(lambda: stash_depth(schedule, p, m, v))
        theirs = outcome(lambda: algebra.schedule_cost(schedule, p, m, v))
        assert ours == theirs, (
            f"stash_depth and schedule_cost disagree on "
            f"({schedule}, P={p}, M={m}, V={v}): {ours} vs {theirs}"
        )

    def test_unknown_schedule_refused(self):
        with pytest.raises(ValueError, match="no stash model"):
            stash_depth("gpipe", 2, 4)

    def test_activation_stash_pins(self):
        # remat="none": 10 stream-widths/token; 2 layers * 10 * 8 tokens
        # * 16 hidden * 2 B bf16 = 5120 (the dp2tp2 target's stash)
        kw = dict(compute_dtype="bfloat16")
        assert hbm.activation_stash_bytes(TINY, 8, remat="none", **kw) == 5120
        assert hbm.activation_stash_bytes(TINY, 8, remat="full", **kw) == 512
        assert (
            hbm.activation_stash_bytes(TINY, 8, remat="selective", **kw)
            == 1024
        )
        # schedule multiplies by the stash depth: 1f1b at M=4 holds 4
        assert hbm.activation_stash_bytes(
            TINY, 8, remat="full", schedule="1f1b",
            num_stages=2, num_microbatches=4, **kw
        ) == 4 * 512

    def test_unknown_remat_refused(self):
        with pytest.raises(ValueError, match="unknown remat"):
            hbm.activation_stash_bytes(TINY, 8, remat="magic")


# ---------------------------------------------------------------------------
# the breakdown partition identity


class TestBreakdown:
    def _bd(self, **kw):
        return HbmBreakdown(
            components=(
                Component("weights", 1000),
                Component("grads", 1000, transient=True),
                Component("optimizer_state", 2004),
            ),
            label="t", **kw,
        )

    def test_peak_is_defined_as_the_component_sum(self):
        bd = self._bd()
        assert bd.peak_bytes == 4004
        assert bd.resident_bytes == 3004
        assert bd.transient_bytes == 1000
        assert bd.resident_bytes + bd.transient_bytes == bd.peak_bytes

    def test_round_trip_preserves_identity_exactly(self):
        bd = self._bd(capacity_bytes=10_000)
        back = bd.round_trip()
        assert back == bd
        assert back.peak_bytes == bd.peak_bytes

    def test_from_dict_rejects_violated_identity(self):
        d = self._bd().to_dict()
        d["peak_bytes"] += 1
        with pytest.raises(ValueError, match="partition identity"):
            HbmBreakdown.from_dict(d)

    def test_duplicate_component_names_refused(self):
        with pytest.raises(ValueError, match="duplicate"):
            HbmBreakdown(
                components=(Component("w", 1), Component("w", 2))
            )

    def test_negative_bytes_refused(self):
        with pytest.raises(ValueError, match="negative"):
            Component("w", -1)

    def test_component_accessors(self):
        bd = self._bd()
        assert bd.component("weights").bytes == 1000
        assert bd.component("nope") is None
        assert bd.component_bytes("nope") == 0
        assert bd.headroom_bytes() is None
        assert self._bd(capacity_bytes=5000).headroom_bytes() == 996

    def test_with_components_extends(self):
        bd = self._bd().with_components(Component("kv_pool", 96))
        assert bd.peak_bytes == 4100
        assert bd.component_bytes("kv_pool") == 96


# ---------------------------------------------------------------------------
# the train-step prediction (the dp2tp2 target's exact table)


class TestPredictTrainMemory:
    def test_dp2tp2_component_pins(self):
        """The audit target's breakdown, digit for digit — the numbers
        the hlo-memory differ reconciles against ``memory_analysis()``
        in the gate (analysis/targets._gpt_hbm_prediction)."""
        bd = predict_train_memory(
            TINY, tp=2, microbatch_size=1, seq_len=8,
            optimizer="fused_adam", grad_scaler=True, remat="none",
            label="gpt-dp2tp2",
        )
        assert {c.name: c.bytes for c in bd.components} == {
            "weights": 15168,          # 3792 el x f32
            "grads": 15168,            # transient mirror
            "optimizer_state": 30340,  # 2*4*3792 + 4
            "scaler_state": 16,        # GradScaler: 4 scalars
            "batch_data": 64,          # 2 x (1x8) int32
            "activation_stash": 5120,  # remat=none: 2*10*8*16*2
        }
        assert bd.peak_bytes == 65876
        assert bd.transient_bytes == 15168 + 5120

    def test_matches_the_registered_audit_target(self):
        """ISSUE acceptance: the dp2tp2 GPT target's analytic sum equals
        the predicted peak digit-for-digit THROUGH a json round trip."""
        from apex_tpu.analysis.targets import dp2tp2_mesh, gpt_step_target

        tgt = gpt_step_target(dp2tp2_mesh())
        assert tgt.hbm is not None
        back = tgt.hbm.round_trip()
        assert back == tgt.hbm
        assert back.peak_bytes == sum(c.bytes for c in back.components)
        assert back.peak_bytes == 65876

    def test_zero_path_books_padded_shard_and_wire_buffer(self):
        bd = predict_train_memory(
            TINY, tp=2, microbatch_size=1, seq_len=8,
            optimizer="distributed_fused_adam", zero_axis_size=2,
            error_feedback=True, compression_wire_dtype="int8",
        )
        assert bd.component_bytes("optimizer_state") == (
            distributed_adam_state_bytes(3792, 2, error_feedback=True)
        )
        # one flat padded grad buffer at the wire dtype
        assert bd.component_bytes("compression_buffers") == (
            zero_padded_total(3792, 2) * 1
        )
        assert bd.component("compression_buffers").transient

    def test_distributed_needs_axis_size(self):
        with pytest.raises(ValueError, match="zero_axis_size"):
            predict_train_memory(
                TINY, seq_len=8, optimizer="distributed_fused_adam"
            )

    def test_unknown_optimizer_refused(self):
        with pytest.raises(ValueError, match="no optimizer-state model"):
            predict_train_memory(TINY, seq_len=8, optimizer="sgd")

    def test_no_scaler_no_component(self):
        bd = predict_train_memory(TINY, seq_len=8, grad_scaler=False)
        assert bd.component("scaler_state") is None


# ---------------------------------------------------------------------------
# the serving pool model vs CacheSpec.pool_shapes


class _Leaf:
    def __init__(self, shape, dtype="bfloat16"):
        self.shape, self.dtype = shape, dtype


class TestKvPool:
    def test_pin(self):
        # 2 layers x (K + V) x (4 blocks x 2 kv-heads x 8 slots x 8 hd)
        # x 2 B bf16
        assert kv_pool_bytes(
            num_layers=2, num_kv_heads=2, head_dim=8,
            num_blocks=4, block_size=8,
        ) == 2 * 2 * (4 * 2 * 8 * 8) * 2 == 4096

    def test_matches_cache_spec_pool_shapes(self):
        """The ledger's pool formula vs the REAL pool the engine
        allocates: sum of products over ``CacheSpec.pool_shapes``."""
        from apex_tpu.serving import kvcache

        shapes = {
            "transformer": {
                f"layers_{i}": {"attention": {
                    "cached_key": _Leaf((1, 4, 32, 8)),
                    "cached_value": _Leaf((1, 4, 32, 8)),
                    "cache_index": _Leaf(()),
                }}
                for i in range(3)
            }
        }
        spec = kvcache.CacheSpec.from_cache_shapes(shapes)
        pools = spec.pool_shapes(num_blocks=10, block_size=16)
        real = sum(
            shape[0] * shape[1] * shape[2] * shape[3]
            * dtype_bytes(dtype)
            for shape, dtype in pools.values()
        )
        assert real == kv_pool_bytes(
            num_layers=3, num_kv_heads=4, head_dim=8,
            num_blocks=10, block_size=16, cache_dtype="bfloat16",
        )

    def test_predict_serving_memory(self):
        bd = predict_serving_memory(
            num_layers=2, num_kv_heads=2, head_dim=8,
            num_blocks=4, block_size=8, weights_bytes=1000,
            label="serve",
        )
        assert bd.component_bytes("kv_pool") == 4096
        assert bd.peak_bytes == 5096
        assert bd.round_trip() == bd


# ---------------------------------------------------------------------------
# the feasibility oracle


class TestPredictFits:
    def _bd(self, n):
        return HbmBreakdown(components=(Component("weights", n),))

    def test_exact_fit_at_zero_headroom(self):
        v = predict_fits(self._bd(100), 100)
        assert v.fits and v.headroom_bytes == 0 and v.utilization == 1.0

    def test_headroom_fraction_shrinks_the_budget(self):
        assert predict_fits(self._bd(91), 100).fits
        assert not predict_fits(self._bd(91), 100, 0.1).fits

    def test_verdict_is_serializable(self):
        v = predict_fits(self._bd(50), 200, 0.25)
        d = json.loads(json.dumps(v.to_dict()))
        assert d["fits"] is True and d["peak_bytes"] == 50

    def test_bad_inputs_refused(self):
        with pytest.raises(ValueError):
            predict_fits(self._bd(1), 0)
        with pytest.raises(ValueError):
            predict_fits(self._bd(1), 100, 1.0)


# ---------------------------------------------------------------------------
# the jax-free contract (the test_goodput subprocess convention)


_CHILD_PRELUDE = """
import sys
class _Poison:
    def find_module(self, name, path=None):
        if name in ("jax", "jaxlib", "flax"):
            raise ImportError("poisoned: " + name)
sys.meta_path.insert(0, _Poison())
import json
from apex_tpu.monitor.xray.hbm import model as hbm
from apex_tpu.monitor.xray.hbm import oom
from apex_tpu.monitor.xray.hbm.live import kv_pool_fields
"""


def _run_child(code, timeout=60):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, "-c", _CHILD_PRELUDE + code],
        capture_output=True, text=True, env=env, timeout=timeout,
    )


class TestJaxFree:
    def test_predict_and_forensics_with_jax_poisoned(self):
        """The any-box contract: predict a breakdown, round-trip it,
        build + re-read an OOM incident, and compute KV-pool occupancy
        — all with jax UNIMPORTABLE (the feasibility oracle must run on
        the analysis box that has only the jsonl)."""
        code = """
dims = hbm.TransformerDims(
    num_layers=2, hidden_size=16, num_attention_heads=2,
    vocab_size=32, max_position_embeddings=8,
)
bd = hbm.predict_train_memory(
    dims, tp=2, microbatch_size=1, seq_len=8,
    optimizer="fused_adam", grad_scaler=True, remat="none",
)
assert bd.round_trip().peak_bytes == bd.peak_bytes == 65876

rec = oom_rec = oom.oom_record(
    7, RuntimeError("RESOURCE_EXHAUSTED: out of memory"),
    breakdown=bd, capacity_bytes=1000,
)
lines = [json.dumps(rec), "", "not json", json.dumps({"kind": "metrics"})]
(inc,) = oom.read_oom_records(lines)
assert inc.step == 7
assert inc.dominant_component == "optimizer_state"
assert "--micro-batch" in inc.suggested_knobs()

kv = kv_pool_fields(num_blocks=8, free_blocks=2, block_size=4,
                    live_tokens=18)
assert kv["occupancy"] == 0.75 and kv["used_blocks"] == 6
assert abs(kv["fragmentation"] - 0.25) < 1e-9

fit = hbm.predict_fits(bd, 2 ** 20)
assert fit.fits

assert "jax" not in sys.modules
print("PEAK", bd.peak_bytes)
"""
        proc = _run_child(code)
        assert proc.returncode == 0, proc.stderr
        assert "PEAK 65876" in proc.stdout
