"""Fused optimizer tests.

Mirrors reference tests/L0/run_optimizers/test_fused_optimizer.py,
test_adam.py, test_lamb.py: compare fused transforms against reference
implementations (optax / manual math) with tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    fused_adam,
    fused_sgd,
    fused_lamb,
    fused_novograd,
    fused_adagrad,
    larc,
    clip_grad_norm,
)


def _params(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w": jax.random.normal(k1, (17, 5), jnp.float32),
        "b": jax.random.normal(k2, (5,), jnp.float32),
    }


def _run(tx, params, grads_fn, steps=5):
    state = tx.init(params)
    for i in range(steps):
        updates, state = tx.update(grads_fn(i, params), state, params)
        params = optax.apply_updates(params, updates)
    return params


class TestFusedAdam:
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_matches_optax_adamw(self, rng, wd):
        params = _params(rng)
        gkey = jax.random.PRNGKey(7)
        grads_fn = lambda i, p: jax.tree_util.tree_map(
            lambda x: jax.random.normal(jax.random.fold_in(gkey, i), x.shape), p
        )
        ours = _run(fused_adam(lr=1e-2, weight_decay=wd), dict(params), grads_fn)
        ref_tx = (
            optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)
            if wd
            else optax.adam(1e-2, b1=0.9, b2=0.999, eps=1e-8)
        )
        ref = _run(ref_tx, dict(params), grads_fn)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            ),
            ours,
            ref,
        )

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    @pytest.mark.parametrize("wd", [0.0, 0.1])
    def test_flat_engine_matches_tree(self, rng, wd, impl, monkeypatch):
        """fuse="flat" (one Pallas kernel over the padded flat buffer, ref
        csrc/multi_tensor_adam.cu) matches the tree_map engine bit-for-bit
        in fp32."""
        import apex_tpu.optimizers._fused_kernels as fk

        monkeypatch.setattr(
            fk, "resolve_impl",
            lambda _: (impl == "pallas", impl == "pallas"),
        )
        params = _params(rng)
        gkey = jax.random.PRNGKey(7)
        grads_fn = lambda i, p: jax.tree_util.tree_map(
            lambda x: jax.random.normal(jax.random.fold_in(gkey, i), x.shape), p
        )
        tree = _run(fused_adam(lr=1e-2, weight_decay=wd), dict(params), grads_fn)
        flat = _run(
            fused_adam(lr=1e-2, weight_decay=wd, fuse="flat"),
            dict(params), grads_fn,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            tree, flat,
        )

    def test_flat_l2norm_matches(self, rng):
        from apex_tpu.ops.multi_tensor import flatten_pytree
        from apex_tpu.optimizers._fused_kernels import l2norm_flat

        params = _params(rng)
        flat, _ = flatten_pytree(params, dtype=jnp.float32)
        ref = float(jnp.sqrt(sum(
            jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(params)
        )))
        np.testing.assert_allclose(float(l2norm_flat(flat, impl="xla")), ref, rtol=1e-6)
        np.testing.assert_allclose(float(l2norm_flat(flat, impl="pallas")), ref, rtol=1e-6)

    def test_l2_mode(self, rng):
        # adam_w_mode=False folds wd into the gradient (L2), diverging from adamw
        params = _params(rng)
        grads_fn = lambda i, p: jax.tree_util.tree_map(jnp.ones_like, p)
        l2 = _run(fused_adam(lr=1e-2, weight_decay=0.5, adam_w_mode=False), dict(params), grads_fn, 3)
        dec = _run(fused_adam(lr=1e-2, weight_decay=0.5, adam_w_mode=True), dict(params), grads_fn, 3)
        diffs = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))), l2, dec
        )
        assert max(jax.tree_util.tree_leaves(diffs)) > 1e-5


class TestFusedAdamSWA:
    """Ref apex/contrib/openfold_triton/fused_adam_swa.py:208 + its test
    (tests/L0/run_openfold_triton/test_fused_adam_swa.py): Adam on fp32
    masters, EMA into the SWA stream, bf16 compute params re-materialized."""

    def _grads_fn(self):
        gkey = jax.random.PRNGKey(7)
        return lambda i, p: jax.tree_util.tree_map(
            lambda x: jax.random.normal(
                jax.random.fold_in(gkey, i), x.shape, jnp.float32
            ).astype(x.dtype),
            p,
        )

    @pytest.mark.parametrize("mode,wd_mode", [("apex", False), ("apexw", True),
                                              ("pytorch", False)])
    def test_master_trajectory_matches_fused_adam(self, rng, mode, wd_mode):
        from apex_tpu.optimizers import fused_adam_swa

        params = _params(rng)
        grads_fn = self._grads_fn()
        tx = fused_adam_swa(swa_decay_rate=0.9, lr=1e-2, weight_decay=0.1,
                            adam_math_mode=mode)
        state = tx.init(params)
        p = dict(params)
        for i in range(5):
            updates, state = tx.update(grads_fn(i, p), state, p)
            p = optax.apply_updates(p, updates)
        ref = _run(
            fused_adam(lr=1e-2, weight_decay=0.1, adam_w_mode=wd_mode),
            dict(params), grads_fn,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            state.master, ref,
        )
        # compute params track the master cast to their dtype
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b.astype(a.dtype)), rtol=1e-6
            ),
            p, state.master,
        )

    def test_swa_math(self, rng):
        """_swa_math (fused_adam_swa.py:120-131): first average copies,
        then swa += (1-decay)*(param-swa)."""
        from apex_tpu.optimizers import fused_adam_swa

        params = _params(rng)
        grads_fn = self._grads_fn()
        decay = 0.75
        tx = fused_adam_swa(swa_decay_rate=decay, lr=1e-2)
        state = tx.init(params)
        p = dict(params)
        updates, state = tx.update(grads_fn(0, p), state, p)
        p = optax.apply_updates(p, updates)
        # n_averaged was 0 -> swa is a copy of the new master
        jax.tree_util.tree_map(
            lambda s, m: np.testing.assert_array_equal(
                np.asarray(s), np.asarray(m)
            ),
            state.swa, state.master,
        )
        swa1 = state.swa
        m1 = state.master
        updates, state = tx.update(grads_fn(1, p), state, p)
        expected = jax.tree_util.tree_map(
            lambda s, m1_, m2: s + (1.0 - decay) * (m2 - s),
            swa1, m1, state.master,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6
            ),
            state.swa, expected,
        )
        assert int(state.n_averaged) == 2

    def test_bf16_compute_params(self, rng):
        """The openfold configuration: bf16 compute params + fp32 state."""
        from apex_tpu.optimizers import fused_adam_swa, swa_params

        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), _params(rng)
        )
        tx = fused_adam_swa(swa_decay_rate=0.9, lr=1e-2)
        state = tx.init(params)
        assert all(
            l.dtype == jnp.float32
            for l in jax.tree_util.tree_leaves((state.master, state.swa))
        )
        updates, state = tx.update(self._grads_fn()(0, params), state, params)
        assert all(
            l.dtype == jnp.bfloat16 for l in jax.tree_util.tree_leaves(updates)
        )
        avg = swa_params(state, like=params)
        assert all(
            l.dtype == jnp.bfloat16 for l in jax.tree_util.tree_leaves(avg)
        )

    def test_grad_clip_scale(self, rng):
        from apex_tpu.optimizers import fused_adam_swa

        params = _params(rng)
        grads_fn = self._grads_fn()
        halved = lambda i, p: jax.tree_util.tree_map(
            lambda g: 2.0 * g, grads_fn(i, p)
        )
        a = fused_adam_swa(swa_decay_rate=0.9, lr=1e-2, grad_clip_scale=0.5)
        b = fused_adam_swa(swa_decay_rate=0.9, lr=1e-2)
        sa, sb = a.init(params), b.init(params)
        ua, sa = a.update(halved(0, params), sa, params)
        ub, sb = b.update(grads_fn(0, params), sb, params)
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6
            ),
            ua, ub,
        )

    def test_rejects_unknown_mode_and_amsgrad(self, rng):
        from apex_tpu.optimizers import FusedAdamSWA, fused_adam_swa

        with pytest.raises(ValueError, match="math mode"):
            fused_adam_swa(swa_decay_rate=0.9, adam_math_mode="nope")
        with pytest.raises(NotImplementedError):
            FusedAdamSWA(swa_decay_rate=0.9, amsgrad=True)


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov", [(0.0, False), (0.9, False), (0.9, True)])
    def test_matches_torch_semantics(self, rng, momentum, nesterov):
        # manual torch-style reference
        params = _params(rng)
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        tx = fused_sgd(lr=0.1, momentum=momentum, nesterov=nesterov)
        state = tx.init(params)
        p_ref = {k: np.asarray(v).copy() for k, v in params.items()}
        buf = {k: None for k in params}
        p = params
        for _ in range(4):
            updates, state = tx.update(g, state, p)
            p = optax.apply_updates(p, updates)
            for k in p_ref:
                gk = np.ones_like(p_ref[k])
                if momentum:
                    buf[k] = gk if buf[k] is None else momentum * buf[k] + gk
                    d = gk + momentum * buf[k] if nesterov else buf[k]
                else:
                    d = gk
                p_ref[k] = p_ref[k] - 0.1 * d
        for k in p_ref:
            np.testing.assert_allclose(np.asarray(p[k]), p_ref[k], rtol=1e-5, atol=1e-6)


class TestFusedLAMB:
    def test_trust_ratio_scales_step(self, rng):
        params = {"w": jnp.full((4, 4), 10.0)}
        g = {"w": jnp.full((4, 4), 1e-3)}
        tx = fused_lamb(lr=0.1, weight_decay=0.0, max_grad_norm=0.0)
        state = tx.init(params)
        updates, _ = tx.update(g, state, params)
        # trust ratio ||p||/||u|| should scale the tiny update up
        assert float(jnp.abs(updates["w"]).max()) > 1e-3

    def test_grad_clipping_applied(self, rng):
        params = _params(rng)
        big = jax.tree_util.tree_map(lambda p: 100.0 * jnp.ones_like(p), params)
        tx = fused_lamb(lr=0.1, max_grad_norm=1.0)
        state = tx.init(params)
        updates, _ = tx.update(big, state, params)
        assert np.isfinite(
            np.asarray(jax.tree_util.tree_leaves(updates)[0])
        ).all()

    def test_loss_decreases(self, rng):
        params = {"w": jax.random.normal(rng, (8, 1))}
        x = jax.random.normal(jax.random.PRNGKey(2), (32, 8))
        y = x @ jnp.ones((8, 1))
        tx = fused_lamb(lr=0.05)
        state = tx.init(params)

        def loss(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        l0 = float(loss(params))
        for _ in range(40):
            g = jax.grad(loss)(params)
            updates, state = tx.update(g, state, params)
            params = optax.apply_updates(params, updates)
        assert float(loss(params)) < l0 * 0.5


class TestFusedNovoGradAdagrad:
    def test_novograd_loss_decreases(self, rng):
        params = {"w": jax.random.normal(rng, (8, 1))}
        x = jax.random.normal(jax.random.PRNGKey(2), (32, 8))
        y = x @ jnp.ones((8, 1))
        tx = fused_novograd(lr=0.3)
        state = tx.init(params)

        def loss(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        l0 = float(loss(params))
        for _ in range(40):
            g = jax.grad(loss)(params)
            updates, state = tx.update(g, state, params)
            params = optax.apply_updates(params, updates)
        assert float(loss(params)) < l0 * 0.5

    def test_adagrad_matches_manual(self, rng):
        params = {"w": jnp.ones((3,))}
        g = {"w": jnp.asarray([1.0, 2.0, 3.0])}
        tx = fused_adagrad(lr=0.1, eps=1e-10)
        state = tx.init(params)
        updates, state = tx.update(g, state, params)
        expected = -0.1 * np.asarray([1.0, 2.0, 3.0]) / (
            np.sqrt(np.asarray([1.0, 4.0, 9.0])) + 1e-10
        )
        np.testing.assert_allclose(np.asarray(updates["w"]), expected, rtol=1e-6)


class TestLarcClip:
    def test_larc_clips_effective_lr(self, rng):
        params = {"w": jnp.full((4,), 1e-3)}  # tiny weights
        g = {"w": jnp.full((4,), 10.0)}  # huge grads
        tx = larc(fused_sgd(lr=1.0), lr=1.0, trust_coefficient=0.02)
        state = tx.init(params)
        updates, _ = tx.update(g, state, params)
        # LARC should have shrunk the grads drastically
        assert float(jnp.abs(updates["w"]).max()) < 1.0

    def test_clip_grad_norm(self, rng):
        grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((10,), 4.0)}
        clipped, norm = clip_grad_norm(grads, max_norm=1.0)
        total = float(jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree_util.tree_leaves(clipped))))
        np.testing.assert_allclose(float(norm), np.sqrt(90 + 160), rtol=1e-5)
        np.testing.assert_allclose(total, 1.0, rtol=1e-4)

    def test_clip_noop_below_threshold(self, rng):
        grads = {"a": jnp.asarray([0.1, 0.2])}
        clipped, _ = clip_grad_norm(grads, max_norm=10.0)
        np.testing.assert_allclose(
            np.asarray(clipped["a"]), np.asarray(grads["a"]), rtol=1e-6
        )
