"""FP8 delayed-scaling recipe tests (VERDICT r3 item 7).

The reference ships only the amax process groups
(apex/transformer/parallel_state.py:280-292); the recipe pinned here is
the minimal delayed-scaling state machine those groups exist to serve:
real fp8 dtypes, a history window, scale derivation, and amax sync over
the mesh's amax group inside shard_map.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.fp8 import (
    FP8_MAX,
    Fp8TensorState,
    dequantize,
    fp8_dense,
    init_fp8_state,
    quantize,
    update_fp8_state,
)
from apex_tpu.parallel import parallel_state


class TestQuantize:
    def test_real_fp8_dtypes(self):
        x = jnp.linspace(-2.0, 2.0, 64)
        q = quantize(x, jnp.float32(1.0), "e4m3")
        assert q.dtype == jnp.float8_e4m3fn
        q5 = quantize(x, jnp.float32(1.0), "e5m2")
        assert q5.dtype == jnp.float8_e5m2

    @pytest.mark.parametrize("fmt", ["e4m3", "e5m2"])
    def test_roundtrip_error_bounded(self, rng, fmt):
        """With the scale placing amax at the format max, relative q-error
        is bounded by the format's epsilon (2^-3 e4m3, 2^-2 e5m2)."""
        x = jax.random.normal(rng, (512,))
        amax = jnp.max(jnp.abs(x))
        scale = FP8_MAX[fmt] / amax
        err = np.abs(
            np.asarray(dequantize(quantize(x, scale, fmt), scale) - x)
        )
        eps = 2.0 ** (-3 if fmt == "e4m3" else -2)
        assert (err <= eps * np.abs(np.asarray(x)) + 1e-7).all()

    def test_saturation_not_inf(self):
        """Values beyond the representable range clamp to ±fp8_max instead
        of overflowing to inf/nan (saturating cast)."""
        x = jnp.asarray([1e6, -1e6, 3.0])
        out = np.asarray(dequantize(quantize(x, jnp.float32(1.0), "e4m3"),
                                    jnp.float32(1.0)))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out[:2], [448.0, -448.0])


class TestDelayedScaling:
    def test_scale_tracks_window_max(self):
        s = init_fp8_state(history_len=4)
        s = update_fp8_state(s, 2.0, "e4m3")
        np.testing.assert_allclose(float(s.scale), 448.0 / 2.0)
        # a bigger amax takes over immediately
        s = update_fp8_state(s, 8.0, "e4m3")
        np.testing.assert_allclose(float(s.scale), 448.0 / 8.0)
        # ...and persists while it stays inside the window
        for _ in range(3):
            s = update_fp8_state(s, 1.0, "e4m3")
            np.testing.assert_allclose(float(s.scale), 448.0 / 8.0)
        # after history_len more updates the spike ages out
        s = update_fp8_state(s, 1.0, "e4m3")
        np.testing.assert_allclose(float(s.scale), 448.0 / 1.0)

    def test_margin_halves_scale_per_unit(self):
        s = update_fp8_state(init_fp8_state(4), 2.0, "e4m3", margin=1)
        np.testing.assert_allclose(float(s.scale), 448.0 / 2.0 / 2.0)

    def test_zero_window_keeps_scale_one(self):
        s = update_fp8_state(init_fp8_state(4), 0.0, "e4m3")
        np.testing.assert_allclose(float(s.scale), 1.0)


class TestFp8Dense:
    def test_delayed_semantics(self, rng):
        """Step t quantizes with step t-1's statistics: the first call (scale
        1) saturates a large input, the second call — same input — uses the
        amax recorded by the first and recovers accuracy."""
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (8, 16)) * 1000.0  # >> 448
        w = jax.random.normal(k2, (16, 4))
        sx, sw = init_fp8_state(4), init_fp8_state(4)
        ref = jnp.dot(x, w)

        y1, (sx, sw) = fp8_dense(x, w, sx, sw)
        err1 = float(jnp.max(jnp.abs(y1 - ref)) / jnp.max(jnp.abs(ref)))
        y2, _ = fp8_dense(x, w, sx, sw)
        err2 = float(jnp.max(jnp.abs(y2 - ref)) / jnp.max(jnp.abs(ref)))
        assert err2 < err1 * 0.2, (err1, err2)
        assert err2 < 0.1

    def test_amax_synced_over_mesh_group(self, rng):
        """Inside shard_map over dp x tp, every rank's returned state must
        carry the GLOBAL amax (pmax over the amax group), not its local
        shard's — the contract of the reference's amax groups."""
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2
        )
        # per-(dp, tp)-shard x: one shard holds the global max
        x = jax.random.normal(rng, (8, 16))
        x = x.at[0, 0].set(37.0)
        w = jax.random.normal(jax.random.fold_in(rng, 1), (16, 4))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(("dp", "tp")), P()),
            out_specs=P(("dp", "tp")),
            check_vma=False,
        )
        def run(x, w):
            sx, sw = init_fp8_state(4), init_fp8_state(4)
            _, (sx, _) = fp8_dense(x, w, sx, sw)
            return sx.amax_history[:1][None]

        amaxes = np.asarray(run(x, w))  # (dp*tp, 1)
        np.testing.assert_allclose(amaxes, 37.0, rtol=1e-6)
