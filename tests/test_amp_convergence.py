"""L1-style amp convergence traces.

Mirrors the reference's strongest amp evidence — the L1 harness
(/root/reference/tests/L1/common/run_test.sh:20-49) that trains RN50 over
the opt-level x loss-scale x keep-batchnorm-fp32 cross-product and asserts
trace equality (compare.py:36-47: distributed == single, per-iteration) —
on a CPU-sized ResNet stand-in over the virtual device mesh.

Three families of assertion:
1. distributed (dp=2, sync BN) loss trace == single-device trace, the
   reference's True_/False_ file comparison;
2. every amp config's loss/grad-norm trace tracks the O0 (fp32) trace
   within half-precision tolerance — the "amp didn't change convergence"
   regression bar;
3. fp16 loss-scaling invariants: static scales 1.0 vs 128.0 produce the
   same updates; dynamic scaling trains through its own backoffs.

Everything is deterministic (fixed PRNG keys, fixed synthetic batch —
the stand-in for the reference's --deterministic flag).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.compat import HAS_VMA, shard_map
from apex_tpu.models.resnet import BasicBlock, ResNet, cross_entropy_loss
from apex_tpu.optimizers import clip_grad_norm, fused_adam, fused_sgd

pytestmark = pytest.mark.slow

STEPS = 8
BATCH = 16
IMAGE = 16
CLASSES = 10


def _data():
    k = jax.random.PRNGKey(7)
    images = jax.random.normal(k, (BATCH, IMAGE, IMAGE, 3), jnp.float32)
    labels = jax.random.randint(jax.random.fold_in(k, 1), (BATCH,), 0, CLASSES)
    return images, labels


def _model(half_dtype, dp=False):
    return ResNet(
        stage_sizes=[1, 1],
        block_cls=BasicBlock,
        num_filters=8,
        num_classes=CLASSES,
        dtype=half_dtype if half_dtype is not None else jnp.float32,
        bn_axes=("dp",) if dp else (),
    )


@functools.lru_cache(maxsize=32)
def run_trace(opt_level, half_name=None, loss_scale=None, keep_bn=None,
              fused=False, dp=False, steps=STEPS):
    """Train the stand-in for ``steps`` and return (losses, grad_norms,
    skipped) as numpy arrays — the in-memory analogue of the reference's
    torch.save'd {Iteration, Loss, Speed} trace files."""
    half = {None: None, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[half_name]
    # model compute dtype follows the opt level (O0/O1 fp32 graph, O2/O3 half)
    model_dtype = half if opt_level in ("O2", "O3") else None
    model = _model(model_dtype, dp=dp)
    images, labels = _data()

    variables = model.init(jax.random.PRNGKey(0), images, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    tx = (fused_adam(lr=2e-3, weight_decay=1e-4) if fused
          else fused_sgd(lr=0.05, momentum=0.9))
    overrides = {}
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    if keep_bn is not None:
        overrides["keep_batchnorm_fp32"] = keep_bn
    params, amp_opt, policy = amp.initialize(
        params, tx, opt_level=opt_level,
        half_dtype=half or jnp.bfloat16, **overrides,
    )
    state = amp_opt.init(params)

    def loss_fn(p, bs, im, lb):
        logits, mut = policy.wrap_apply(model.apply)(
            {"params": p, "batch_stats": bs}, im, train=True,
            mutable=["batch_stats"],
        )
        return cross_entropy_loss(logits, lb), mut["batch_stats"]

    def step(params, bs, state, im, lb):
        def scaled(p):
            loss, new_bs = loss_fn(p, bs, im, lb)
            if dp:
                # differentiate the GLOBAL loss: sync BN's psum creates
                # cross-shard gradient terms, so grad-then-pmean of the
                # local loss is wrong — pmean must sit inside the vjp
                loss = jax.lax.pmean(loss, "dp")
            return amp_opt.scale_loss(loss, state), (loss, new_bs)

        grads, (loss, new_bs) = jax.grad(scaled, has_aux=True)(params)
        _, gnorm_scaled = clip_grad_norm(grads, 1e9)
        gnorm = gnorm_scaled / state.scaler.scale
        params, state, info = amp_opt.step(grads, state, params)
        return params, new_bs, state, loss, gnorm, info["found_inf"]

    if dp:
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        sharded = jax.jit(
            shard_map(
                step, mesh=mesh,
                in_specs=(P(), P(), P(), P("dp"), P("dp")),
                out_specs=(P(), P(), P(), P(), P(), P()),
            )
        )
        step_fn = sharded
    else:
        step_fn = jax.jit(step)

    losses, gnorms, skipped = [], [], []
    for _ in range(steps):
        params, batch_stats, state, loss, gnorm, inf = step_fn(
            params, batch_stats, state, images, labels
        )
        losses.append(float(loss))
        gnorms.append(float(gnorm))
        skipped.append(bool(inf))
    return np.array(losses), np.array(gnorms), np.array(skipped)


def _rel(a, b):
    return np.abs(a - b) / np.maximum(np.abs(b), 1e-3)


@pytest.mark.skipif(
    not HAS_VMA,
    reason=(
        "pre-vma jax (check_rep era) cannot infer replication for this "
        "step's replicated out_specs: the amp step returns opt-state "
        "leaves whose replication flows through fused-optimizer "
        "internals check_rep's inference does not see through (vma "
        "tracking handles it) — fails at HEAD since before PR 5, "
        "jax-version skew, not a convergence regression"
    ),
)
class TestDistributedMatchesSingle:
    """compare.py:36-47 — per-iteration loss equality, distributed vs not."""

    def test_o0_dp2_trace_equals_single(self):
        single = run_trace("O0")
        dist = run_trace("O0", dp=True)
        np.testing.assert_allclose(dist[0], single[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(dist[1], single[1], rtol=1e-4, atol=1e-6)

    def test_o2_bf16_dp2_trace_matches_single(self):
        single = run_trace("O2", "bfloat16")
        dist = run_trace("O2", "bfloat16", dp=True)
        # bf16 compute reassociates across shards; tolerance is half-precision
        assert _rel(dist[0], single[0]).max() < 3e-2


class TestAmpTracksO0:
    """The O-level x keep-BN cross-product (run_test.sh:29-49): every bf16
    config's trace must follow the fp32 baseline."""

    @pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
    @pytest.mark.parametrize("keep_bn", [True, False])
    def test_bf16_trace_tracks_o0(self, opt_level, keep_bn):
        base_l, base_g, _ = run_trace("O0")
        l, g, sk = run_trace(opt_level, "bfloat16", keep_bn=keep_bn)
        assert not sk.any()  # bf16 never overflows at these magnitudes
        assert np.isfinite(l).all()
        assert _rel(l, base_l).max() < 0.15, (l, base_l)
        assert _rel(g, base_g).max() < 0.35, (g, base_g)
        assert l[-1] < l[0]  # actually converging, not just finite

    def test_fused_adam_o2_tracks_o0_adam(self):
        """Ref ADAM_ARGS config: --opt-level O2 --keep-batchnorm-fp32 False
        --fused-adam (run_test.sh:29)."""
        base_l, _, _ = run_trace("O0", fused=True)
        l, _, sk = run_trace("O2", "bfloat16", keep_bn=False, fused=True)
        assert not sk.any()
        assert _rel(l, base_l).max() < 0.15
        assert l[-1] < l[0]


class TestLossScaleInvariance:
    """run_test.sh loss_scales x fp16: the update must not depend on a
    static scale's magnitude, and dynamic must train through backoffs."""

    def test_fp16_static_scales_match(self):
        l1, g1, s1 = run_trace("O2", "float16", loss_scale=1.0)
        l128, g128, s128 = run_trace("O2", "float16", loss_scale=128.0)
        assert not s1.any() and not s128.any()
        np.testing.assert_allclose(l1, l128, rtol=2e-3)
        np.testing.assert_allclose(g1, g128, rtol=5e-3, atol=1e-4)

    def test_fp16_dynamic_trains(self):
        l, _, sk = run_trace("O2", "float16", loss_scale="dynamic",
                             steps=STEPS + 4)
        assert sk.sum() <= (STEPS + 4) // 2  # backoffs allowed, runaway not
        done = ~sk
        assert l[done][-1] < l[done][0]

    def test_fp16_static_tracks_o0(self):
        base_l, _, _ = run_trace("O0")
        l, _, sk = run_trace("O2", "float16", loss_scale=128.0)
        assert not sk.any()
        assert _rel(l, base_l).max() < 0.15
