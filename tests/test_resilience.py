"""Resilience subsystem: sentinel, rollback, integrity, chaos recovery.

Covers apex_tpu/resilience end to end — unit behavior of each piece, the
AmpOptimizer sentinel wiring, and the acceptance scenario: an
examples/gpt-style training loop (dynamic scaler + fused_adam + vma_cond
skip gate + AutoResume, the exact wiring of examples/gpt/pretrain_gpt.py,
sized down for tier-1) driven through an injected NaN-loss step, a
bit-flipped newest checkpoint, and a real SIGTERM — completing with the
uninjected run's trajectory after each recovery point and restoring only
from checksum-verified checkpoints.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import resilience
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers import fused_adam
from apex_tpu.parallel.utils import vma_cond
from apex_tpu.resilience import chaos
from apex_tpu.resilience.sentinel import (
    VERDICT_HALT,
    VERDICT_OK,
    VERDICT_ROLLBACK,
    VERDICT_SKIP,
)
from apex_tpu.utils import AutoResume
from apex_tpu.utils.checkpoint import finalized_steps, latest_step, save_checkpoint
from apex_tpu.utils.pytree import tree_any_non_finite

CHAOS_SEED = 1234


@pytest.fixture
def chaos_seed():
    """Deterministic seed for every injected-fault test: the fault step,
    the injected payload, and the data stream all derive from it, so a
    failing chaos test replays identically under ``-k`` reruns."""
    np.random.seed(CHAOS_SEED)
    return CHAOS_SEED


# ---------------------------------------------------------------------------
# sentinel


class TestAnomalySentinel:
    def _warm(self, sent, losses=(1.0, 0.98, 1.02, 0.99, 1.01)):
        st = sent.init()
        for l in losses:
            an = sent.is_anomalous_loss(st, l)
            st, v = sent.update(st, l, an)
            assert int(v) == VERDICT_OK
        return st

    def test_no_false_positive_on_smooth_losses(self):
        sent = resilience.AnomalySentinel(warmup_steps=3)
        st = self._warm(sent)
        assert int(st.anomalies) == 0
        # a loss inside the observed band is not a spike (1.03 at ~7 running
        # sigma WOULD be — the z-test is about the run's own variance)
        assert not bool(sent.is_anomalous_loss(st, 1.02))

    def test_spike_detected_after_warmup_only(self):
        sent = resilience.AnomalySentinel(warmup_steps=3, z_threshold=6.0)
        st = sent.init()
        # during warmup even a huge loss passes (variance estimate is junk)
        assert not bool(sent.is_anomalous_loss(st, 1e6))
        st = self._warm(sent)
        assert bool(sent.is_anomalous_loss(st, 50.0))

    def test_nonfinite_loss_always_anomalous(self):
        sent = resilience.AnomalySentinel()
        st = sent.init()
        assert bool(sent.is_anomalous_loss(st, float("nan")))
        assert bool(sent.is_anomalous_loss(st, float("inf")))

    def test_anomalous_loss_never_pollutes_ema(self):
        sent = resilience.AnomalySentinel(warmup_steps=3)
        st = self._warm(sent)
        ema_before = float(st.ema)
        st, v = sent.update(st, jnp.nan, True)
        assert int(v) == VERDICT_SKIP
        assert float(st.ema) == ema_before  # NaN never folded in

    def test_escalation_ladder_and_reset(self):
        sent = resilience.AnomalySentinel(skip_budget=1, rollback_budget=1)
        st = sent.init()
        st, v1 = sent.update(st, jnp.nan, True)
        st, v2 = sent.update(st, jnp.nan, True)
        st, v3 = sent.update(st, jnp.nan, True)
        assert [int(v1), int(v2), int(v3)] == [
            VERDICT_SKIP, VERDICT_ROLLBACK, VERDICT_HALT]
        # one clean step re-arms the ladder
        st, v = sent.update(st, 1.0, False)
        assert int(v) == VERDICT_OK and int(st.consecutive) == 0
        st, v = sent.update(st, jnp.nan, True)
        assert int(v) == VERDICT_SKIP

    def test_bad_params_forces_at_least_rollback(self):
        sent = resilience.AnomalySentinel(skip_budget=5)
        st = sent.init()
        st, v = sent.update(st, 1.0, False, bad_params=True)
        assert int(v) == VERDICT_ROLLBACK
        assert bool(sent.check_params({"w": jnp.array([1.0, jnp.nan])}))
        assert not bool(sent.check_params({"w": jnp.ones(2)}))

    def test_jit_compatible_and_verdict_is_int32(self):
        sent = resilience.AnomalySentinel()

        @jax.jit
        def step(st, loss):
            return sent.check(st, loss, params={"w": jnp.ones(2)})

        st, v = step(sent.init(), 1.0)
        assert v.dtype == jnp.int32 and int(v) == VERDICT_OK


# ---------------------------------------------------------------------------
# rollback


class TestRollbackBuffer:
    def test_snapshot_restore_roundtrip_and_isolation(self):
        buf = resilience.RollbackBuffer(capacity=2, interval=1)
        state = {"w": jnp.arange(4.0), "n": jnp.asarray(1, jnp.int32)}
        buf.snapshot(3, state)
        # mutating the live state must not reach the snapshot
        state["w"] = state["w"] * 0 - 7.0
        step, restored = buf.rollback()
        assert step == 3
        np.testing.assert_allclose(restored["w"], np.arange(4.0))
        assert restored["w"].sharding is not None  # real jax.Array again

    def test_ring_capacity_and_cadence(self):
        buf = resilience.RollbackBuffer(capacity=2, interval=5)
        for s in range(1, 21):
            buf.maybe_snapshot(s, {"s": jnp.asarray(s)})
        assert buf.steps == [15, 20]  # only cadence steps, only newest 2

    def test_pop_falls_back_to_older_snapshot(self):
        buf = resilience.RollbackBuffer(capacity=3, interval=1)
        for s in (1, 2, 3):
            buf.snapshot(s, {"s": jnp.asarray(s)})
        assert buf.rollback()[0] == 3
        assert buf.rollback(pop=True)[0] == 2
        assert buf.rollback(pop=True)[0] == 1
        assert buf.rollback(pop=True)[0] == 1  # never pops the last one

    def test_empty_rollback_raises(self):
        with pytest.raises(RuntimeError):
            resilience.RollbackBuffer().rollback()


class TestResilienceManager:
    def _mgr(self, tmp_path, **pol):
        return resilience.ResilienceManager(
            buffer=resilience.RollbackBuffer(capacity=2, interval=1),
            policy=resilience.EscalationPolicy(**pol),
            log_path=str(tmp_path / "anomalies.jsonl"),
        )

    def test_actions_and_anomaly_log(self, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.buffer.snapshot(0, {"w": jnp.ones(2)})
        assert mgr.resolve(1, VERDICT_OK) == "ok"
        assert mgr.resolve(2, VERDICT_SKIP, loss=9.9) == "skip"
        assert mgr.resolve(3, VERDICT_ROLLBACK) == "rollback"
        assert mgr.resolve(4, VERDICT_HALT) == "halt"
        lines = [json.loads(l) for l in
                 open(tmp_path / "anomalies.jsonl").read().splitlines()]
        kinds = [l["kind"] for l in lines]
        assert kinds == ["skip", "rollback", "halt"]  # one event per step
        assert lines[0]["loss"] == 9.9

    def test_rollback_dampens_lr_and_is_bounded(self, tmp_path):
        mgr = self._mgr(tmp_path, max_rollbacks=2, lr_dampen=0.5)
        mgr.buffer.snapshot(5, {"w": jnp.ones(2)})
        assert mgr.resolve(6, VERDICT_ROLLBACK) == "rollback"
        step, _ = mgr.do_rollback()
        assert step == 5 and mgr.lr_scale == 0.5
        assert mgr.resolve(6, VERDICT_ROLLBACK) == "rollback"
        mgr.do_rollback()
        assert mgr.lr_scale == 0.25
        # budget exhausted -> rollback verdicts degrade to halt
        assert mgr.resolve(6, VERDICT_ROLLBACK) == "halt"

    def test_rollback_without_snapshots_halts(self, tmp_path):
        mgr = resilience.ResilienceManager(buffer=None)
        assert mgr.resolve(1, VERDICT_ROLLBACK) == "halt"

    def test_repeat_rollback_backs_off_to_older_snapshot(self, tmp_path):
        mgr = self._mgr(tmp_path, max_rollbacks=5)
        mgr.buffer.snapshot(2, {"s": jnp.asarray(2)})
        mgr.buffer.snapshot(4, {"s": jnp.asarray(4)})
        assert mgr.do_rollback()[0] == 4
        # same newest snapshot again -> pops to the older one
        assert mgr.do_rollback()[0] == 2


# ---------------------------------------------------------------------------
# integrity (manifest, verification, retention, retry)


class TestIntegrity:
    def _tree(self, seed=0):
        k = jax.random.PRNGKey(seed)
        return {"w": jax.random.normal(k, (16, 16)),
                "n": jnp.asarray(seed, jnp.int32)}

    def test_manifest_commit_and_verify(self, tmp_path):
        d = str(tmp_path)
        path = resilience.save_checkpoint_verified(d, 1, self._tree())
        ok, why = resilience.verify_checkpoint(path)
        assert ok, why
        assert resilience.verified_latest_step(d) == 1
        m = resilience.read_manifest(path)
        assert m["fingerprint"]["structure_hash"]
        assert m["files"]

    def test_missing_manifest_means_uncommitted(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 2, self._tree())  # plain save: no manifest
        ok, why = resilience.verify_checkpoint(os.path.join(d, "step_2"))
        assert not ok and "manifest" in why
        assert resilience.verified_latest_step(d) is None

    @pytest.mark.chaos
    @pytest.mark.parametrize("mode", ["bitflip", "truncate"])
    def test_corruption_detected_and_restore_falls_back(
        self, tmp_path, chaos_seed, mode
    ):
        d = str(tmp_path)
        t1, t2 = self._tree(1), self._tree(2)
        resilience.save_checkpoint_verified(d, 1, t1)
        resilience.save_checkpoint_verified(d, 2, t2)
        touched = chaos.corrupt_latest_checkpoint(d, mode=mode, seed=chaos_seed)
        assert touched and touched.endswith("step_2")
        ok, why = resilience.verify_checkpoint(os.path.join(d, "step_2"))
        assert not ok
        step, tree = resilience.load_checkpoint_verified(d, target=t1)
        assert step == 1
        np.testing.assert_allclose(tree["w"], t1["w"])

    def test_corrupt_manifest_is_not_legacy(self, tmp_path):
        """A present-but-unparseable manifest is corruption, not a
        pre-manifest legacy checkpoint: even with allow_unverified the
        restore must fall back rather than trust it."""
        d = str(tmp_path)
        t1 = self._tree(1)
        resilience.save_checkpoint_verified(d, 1, t1)
        resilience.save_checkpoint_verified(d, 2, self._tree(2))
        with open(resilience.manifest_path(os.path.join(d, "step_2")), "w") as f:
            f.write("{definitely not json")
        step, tree = resilience.load_checkpoint_verified(
            d, target=t1, allow_unverified=True
        )
        assert step == 1
        np.testing.assert_allclose(tree["w"], t1["w"])

    def test_nothing_restorable_raises(self, tmp_path):
        d = str(tmp_path)
        resilience.save_checkpoint_verified(d, 1, self._tree())
        chaos.corrupt_checkpoint(os.path.join(d, "step_1"), mode="truncate")
        with pytest.raises(FileNotFoundError):
            resilience.load_checkpoint_verified(d, target=self._tree())

    def test_retention_keeps_last_n_and_sweeps_tmp(self, tmp_path):
        d = str(tmp_path)
        for s in range(1, 6):
            resilience.save_checkpoint_verified(d, s, self._tree(s))
        os.makedirs(tmp_path / "step_9.orbax-checkpoint-tmp-0")
        deleted = resilience.apply_retention(d, keep_last_n=2)
        assert deleted == [1, 2, 3]
        assert finalized_steps(d) == [4, 5]
        assert not (tmp_path / "step_9.orbax-checkpoint-tmp-0").exists()
        assert not (tmp_path / "step_1.apex-manifest.json").exists()

    def test_retention_never_drops_newest_verified(self, tmp_path):
        d = str(tmp_path)
        for s in (1, 2, 3):
            resilience.save_checkpoint_verified(d, s, self._tree(s))
        chaos.corrupt_checkpoint(os.path.join(d, "step_3"), mode="truncate")
        chaos.corrupt_checkpoint(os.path.join(d, "step_2"), mode="truncate")
        # keep_last_n=1 would keep only corrupt step 3; verified step 1 must
        # survive as the fallback restore point
        resilience.apply_retention(d, keep_last_n=1)
        assert 1 in finalized_steps(d)
        assert resilience.load_checkpoint_verified(d, target=self._tree())[0] == 1

    def test_retention_torn_dirs_do_not_push_verified_out(self, tmp_path):
        """PR 8 satellite pin: torn/uncommitted NEWER step dirs (an
        interrupted async save: bytes on disk, manifest never committed)
        must neither push verified restore points out of the keep window
        nor be swept themselves (they may be an in-flight save)."""
        d = str(tmp_path)
        for s in (1, 2, 3):
            resilience.save_checkpoint_verified(d, s, self._tree(s))
        for s in (4, 5):
            sd = tmp_path / f"step_{s}"
            sd.mkdir()
            (sd / "payload.bin").write_bytes(b"torn")
        deleted = resilience.apply_retention(d, keep_last_n=2)
        # the verified window still holds TWO verified steps (2, 3); the
        # raw window {4, 5} alone would have left ONE
        assert deleted == [1]
        assert finalized_steps(d) == [2, 3, 4, 5]
        assert resilience.verified_latest_step(d, deep=False) == 3
        step, _ = resilience.load_checkpoint_verified(
            d, target=self._tree())
        assert step == 3

    def test_retention_abandoned_marker_fails_verification(self, tmp_path):
        """An abandoned async save (deadline-budgeted preemption skip)
        is tombstoned: the dir may complete on disk, but it must never
        verify NOR be accepted as a legacy pre-manifest checkpoint."""
        d = str(tmp_path)
        resilience.save_checkpoint_verified(d, 1, self._tree(1))
        save_checkpoint(d, 2, self._tree(2))  # completed, uncommitted
        resilience.write_abandoned_marker(os.path.join(d, "step_2"))
        ok, why = resilience.verify_checkpoint(os.path.join(d, "step_2"))
        assert not ok and "abandoned" in why
        step, _ = resilience.load_checkpoint_verified(
            d, target=self._tree(), allow_unverified=True
        )
        assert step == 1  # NOT legacy-accepted despite allow_unverified

    def test_save_with_retry_recovers_transient_failures(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "done"

        assert resilience.save_with_retry(flaky, retries=3, backoff=0.0) == "done"
        assert calls["n"] == 3

    def test_save_with_retry_reraises_after_budget(self):
        def always():
            raise OSError("disk on fire")

        with pytest.raises(OSError):
            resilience.save_with_retry(always, retries=2, backoff=0.0)


# ---------------------------------------------------------------------------
# chaos harness itself


class TestChaosHarness:
    def test_poison_loss_poisons_value_and_grads(self):
        def f(w, armed):
            return chaos.poison_loss(jnp.sum(w * w), armed)

        w = jnp.ones(3)
        assert float(f(w, 0.0)) == 3.0
        assert not np.isfinite(float(f(w, 1.0)))
        g = jax.grad(f)(w, 1.0)
        assert bool(tree_any_non_finite(g))  # multiplicative: grads die too
        g0 = jax.grad(f)(w, 0.0)
        np.testing.assert_allclose(g0, 2 * np.ones(3))

    def test_fault_plan_consumed_once_vs_persistent(self):
        plan = chaos.FaultPlan(nan_steps="3,5-6")
        assert plan.take_nan(3) == 1.0
        assert plan.take_nan(3) == 0.0  # consumed: the replay runs clean
        assert plan.take_nan(4) == 0.0
        persistent = chaos.FaultPlan(nan_steps={3}, persistent=True)
        assert persistent.take_nan(3) == 1.0
        assert persistent.take_nan(3) == 1.0

    def test_corruption_is_deterministic(self, tmp_path, chaos_seed):
        import shutil

        save_checkpoint(str(tmp_path / "a"), 1, {"w": jnp.arange(64.0)})
        # identical dir contents (orbax randomizes payload names per save,
        # so two saves can't be compared — two copies of one save can)
        shutil.copytree(tmp_path / "a", tmp_path / "b")
        f1 = chaos.corrupt_checkpoint(
            str(tmp_path / "a" / "step_1"), "bitflip", seed=chaos_seed)
        f2 = chaos.corrupt_checkpoint(
            str(tmp_path / "b" / "step_1"), "bitflip", seed=chaos_seed)
        assert (os.path.relpath(f1, tmp_path / "a")
                == os.path.relpath(f2, tmp_path / "b"))
        assert open(f1, "rb").read() == open(f2, "rb").read()


# ---------------------------------------------------------------------------
# AmpOptimizer sentinel wiring


class TestAmpOptimizerSentinel:
    def _setup(self):
        from apex_tpu import amp

        params = {"w": jnp.ones((4,), jnp.float32)}
        params, amp_opt, _ = amp.initialize(
            params, optax.sgd(0.1), opt_level="O2", half_dtype=jnp.float16,
        )
        return params, amp_opt, amp_opt.init(params)

    def _warm_sentinel(self, sent):
        st = sent.init()
        for l in (1.0, 1.01, 0.99, 1.0, 1.02):
            st, _ = sent.update(st, l, False)
        return st

    def test_clean_step_updates_and_reports_ok(self):
        params, amp_opt, state = self._setup()
        sent = resilience.AnomalySentinel(warmup_steps=3)
        grads = {"w": jnp.full((4,), float(state.scaler.scale))}
        new_params, new_state, info = amp_opt.step(
            grads, state, params, sentinel=sent,
            sentinel_state=self._warm_sentinel(sent), unscaled_loss=1.0,
        )
        assert int(info["verdict"]) == VERDICT_OK
        assert not bool(info["skipped"])
        assert float(np.asarray(new_params["w"])[0]) != 1.0  # stepped
        assert int(info["sentinel_state"].count) == 6

    def test_spike_skips_update_but_not_scaler_schedule(self):
        params, amp_opt, state = self._setup()
        sent = resilience.AnomalySentinel(warmup_steps=3, z_threshold=6.0)
        grads = {"w": jnp.full((4,), float(state.scaler.scale))}
        scale_before = float(state.scaler.scale)
        new_params, new_state, info = amp_opt.step(
            grads, state, params, sentinel=sent,
            sentinel_state=self._warm_sentinel(sent), unscaled_loss=1e4,
        )
        assert int(info["verdict"]) == VERDICT_SKIP
        assert bool(info["skipped"]) and not bool(info["found_inf"])
        np.testing.assert_allclose(np.asarray(new_params["w"]), 1.0)  # untouched
        # a spike is not an overflow: the loss scale must NOT back off
        assert float(new_state.scaler.scale) == scale_before

    def test_overflow_still_backs_off_scale(self):
        params, amp_opt, state = self._setup()
        sent = resilience.AnomalySentinel(warmup_steps=3)
        grads = {"w": jnp.array([jnp.inf, 1.0, 1.0, 1.0])}
        new_params, new_state, info = amp_opt.step(
            grads, state, params, sentinel=sent,
            sentinel_state=self._warm_sentinel(sent), unscaled_loss=1.0,
        )
        assert bool(info["found_inf"]) and int(info["verdict"]) == VERDICT_SKIP
        assert float(new_state.scaler.scale) < float(state.scaler.scale)

    def test_corrupt_params_escalate_to_rollback(self):
        params, amp_opt, state = self._setup()
        params = {"w": jnp.array([jnp.nan, 1.0, 1.0, 1.0], jnp.float16)}
        state = state.replace(master={"w": jnp.array([jnp.nan, 1.0, 1.0, 1.0])})
        sent = resilience.AnomalySentinel(warmup_steps=3)
        grads = {"w": jnp.full((4,), float(state.scaler.scale))}
        _, _, info = amp_opt.step(
            grads, state, params, sentinel=sent,
            sentinel_state=self._warm_sentinel(sent), unscaled_loss=1.0,
        )
        assert int(info["verdict"]) >= VERDICT_ROLLBACK

    def test_sentinel_requires_loss_and_state(self):
        params, amp_opt, state = self._setup()
        with pytest.raises(ValueError):
            amp_opt.step({"w": jnp.ones(4)}, state, params,
                         sentinel=resilience.AnomalySentinel())


# ---------------------------------------------------------------------------
# end-to-end: the gpt-example wiring under all three fault classes


def _batch(step, n=32, d=8):
    """Deterministic per-step batch (stands in for the indexed dataset's
    consumed_samples-keyed stream: rebuild-at-step == identical data)."""
    r = np.random.RandomState(CHAOS_SEED + step)
    x = r.randn(n, d).astype(np.float32)
    w = np.linspace(-1, 1, d, dtype=np.float32)
    y = (x @ w[:, None] + 0.1 * r.randn(n, 1)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _mini_gpt_style_trainer(
    steps,
    save_dir=None,
    interval=None,
    keep_last_n=3,
    plan=None,
    snapshot_interval=2,
    skip_budget=0,
    max_rollbacks=3,
    lr_dampen=1.0,
):
    """The pretrain_gpt.py wiring at tier-1 scale: dynamic LossScaler,
    fused_adam, sentinel gate through vma_cond, donation-free toy model,
    AutoResume with verified restore, rollback ring + escalation."""
    scaler = LossScaler(loss_scale="dynamic")
    sentinel = resilience.AnomalySentinel(
        warmup_steps=4, skip_budget=skip_budget, rollback_budget=2,
    )
    opt = fused_adam(lr=0.05)
    plan = plan or chaos.FaultPlan()

    @jax.jit
    def train_step(params, opt_state, scaler_state, sent_state, x, y,
                   inject_nan, lr_scale):
        def scaled_loss(p):
            h = jnp.tanh(x @ p["w1"])
            loss = jnp.mean((h @ p["w2"] - y) ** 2)
            return chaos.poison_loss(scaler.scale(scaler_state, loss), inject_nan)

        loss, grads = jax.value_and_grad(scaled_loss)(params)
        grads, found_inf = scaler.unscale(scaler_state, grads)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        unscaled = loss / scaler_state.scale
        gate = jnp.logical_or(
            found_inf, sentinel.is_anomalous_loss(sent_state, unscaled)
        )

        def apply():
            updates, new_opt = opt.update(grads, opt_state, params)
            updates = jax.tree_util.tree_map(lambda u: u * lr_scale, updates)
            return optax.apply_updates(params, updates), new_opt

        new_params, new_opt_state = vma_cond(
            gate, lambda: (params, opt_state), apply
        )
        new_sent_state, verdict = sentinel.update(
            sent_state, unscaled, anomaly=gate,
            bad_params=tree_any_non_finite(new_params),
        )
        return (new_params, new_opt_state, new_scaler_state, new_sent_state,
                unscaled, verdict)

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        "w1": 0.5 * jax.random.normal(k1, (8, 16)),
        "w2": 0.5 * jax.random.normal(k2, (16, 1)),
    }
    opt_state = opt.init(params)
    scaler_state = scaler.init()
    sent_state = sentinel.init()

    ar = (
        AutoResume(save_dir, interval=interval, keep_last_n=keep_last_n)
        if save_dir else None
    )
    step0 = 0
    if ar is not None:
        step0, (params, opt_state, scaler_state, sent_state) = ar.restore(
            (params, opt_state, scaler_state, sent_state)
        )
    mgr = resilience.ResilienceManager(
        buffer=resilience.RollbackBuffer(
            capacity=2, interval=snapshot_interval
        ),
        policy=resilience.EscalationPolicy(
            max_rollbacks=max_rollbacks, lr_dampen=lr_dampen
        ),
    )
    mgr.buffer.snapshot(step0, (params, opt_state, scaler_state, sent_state))

    losses, result = {}, {
        "resumed_from": step0, "halted": False, "terminated": False,
        "halt_saved_step": None,
    }
    try:
        step_i = step0
        while step_i < steps:
            x, y = _batch(step_i)
            params, opt_state, scaler_state, sent_state, loss, verdict = (
                train_step(
                    params, opt_state, scaler_state, sent_state, x, y,
                    jnp.asarray(plan.take_nan(step_i), jnp.float32),
                    jnp.asarray(mgr.lr_scale, jnp.float32),
                )
            )
            state = (params, opt_state, scaler_state, sent_state)
            action = mgr.resolve(step_i, int(verdict), loss=float(loss))
            if action == "halt":
                good_step, good_state = (
                    mgr.buffer.rollback() if len(mgr.buffer)
                    else (step_i, state)
                )
                if save_dir:
                    ar.finalize()  # never race an in-flight interval save
                    resilience.save_checkpoint_verified(
                        save_dir, good_step, good_state,
                        keep_last_n=keep_last_n,
                    )
                    result["halt_saved_step"] = good_step
                result["halted"] = True
                break
            if action == "rollback":
                step_i, (params, opt_state, scaler_state, sent_state) = (
                    mgr.do_rollback()
                )
                continue
            losses[step_i] = float(loss)
            if action == "ok":
                mgr.observe_good(step_i + 1, state)
            plan.maybe_sigterm(step_i)
            if ar is not None and ar.step(step_i + 1, state):
                result["terminated"] = True
                result["terminated_at"] = step_i + 1
                break
            step_i += 1
    finally:
        if ar is not None:
            ar.close()  # finalize pending saves + restore SIGTERM handler
    result.update(losses=losses, params=params, events=mgr.events, mgr=mgr)
    return result


@pytest.mark.chaos
class TestChaosEndToEnd:
    STEPS = 20

    def test_run_survives_nan_corruption_and_sigterm(self, tmp_path, chaos_seed):
        """The acceptance scenario, one continuous story:

        phase A trains with a NaN injected at step 6 (escalates straight
        to rollback: skip_budget=0) and a real SIGTERM after step 13;
        the newest checkpoint is then bit-flipped; phase B resumes —
        necessarily from an older verified step — and completes. Both
        phases replay the baseline's exact trajectory after each
        recovery point (the anomalous update never committed and the
        data stream rewound), so the final loss matches the uninjected
        run's to float tolerance.
        """
        base = _mini_gpt_style_trainer(self.STEPS)
        assert not base["halted"] and len(base["losses"]) == self.STEPS

        d = str(tmp_path / "ck")
        plan = chaos.FaultPlan(nan_steps={6}, sigterm_steps={13})
        prev = signal.getsignal(signal.SIGTERM)
        a = _mini_gpt_style_trainer(
            self.STEPS, save_dir=d, interval=4, plan=plan
        )
        assert signal.getsignal(signal.SIGTERM) == prev  # handler restored
        # (a) NaN step: rollback event recorded, then the replayed step 6
        # is clean and matches baseline exactly
        kinds = [e["kind"] for e in a["events"]]
        assert "rollback" in kinds and "rollback_restore" in kinds
        assert not np.isfinite(
            next(e["loss"] for e in a["events"] if e["kind"] == "rollback")
        )
        for s in range(self.STEPS):
            if s in a["losses"]:
                np.testing.assert_allclose(
                    a["losses"][s], base["losses"][s], rtol=1e-5,
                    err_msg=f"post-recovery divergence at step {s}",
                )
        # (c) SIGTERM: durable termination checkpoint, immediately verified
        assert a["terminated"] and a["terminated_at"] == 14
        assert resilience.verified_latest_step(d) == 14
        # retention bounded the directory
        assert len(finalized_steps(d)) <= 3

        # (b) bit-flip the newest checkpoint; resume must fall back to the
        # newest VERIFIED step, never the corrupt one
        chaos.corrupt_latest_checkpoint(d, mode="bitflip", seed=chaos_seed)
        fallback = resilience.verified_latest_step(d)
        assert fallback is not None and fallback < 14
        b = _mini_gpt_style_trainer(self.STEPS, save_dir=d, interval=4)
        assert b["resumed_from"] == fallback
        assert not b["halted"]
        for s, l in b["losses"].items():
            np.testing.assert_allclose(l, base["losses"][s], rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(b["params"]["w1"]), np.asarray(base["params"]["w1"]),
            rtol=1e-5,
        )
        assert not bool(tree_any_non_finite(b["params"]))

    def test_persistent_fault_halts_with_known_good_checkpoint(
        self, tmp_path, chaos_seed
    ):
        d = str(tmp_path / "ck")
        plan = chaos.FaultPlan(nan_steps="5-19", persistent=True)
        res = _mini_gpt_style_trainer(
            self.STEPS, save_dir=d, interval=100, plan=plan,
            max_rollbacks=1, snapshot_interval=2,
        )
        assert res["halted"] and not res["terminated"]
        # the halt checkpoint is a verified, finite, known-good state
        s = res["halt_saved_step"]
        assert s is not None and s <= 5
        assert resilience.verified_latest_step(d) == s
        _, tree = resilience.load_checkpoint_verified(d, target=None)
        assert not bool(tree_any_non_finite(tree))

    def test_lr_dampening_applies_after_rollback(self, chaos_seed):
        plan = chaos.FaultPlan(nan_steps={6})
        res = _mini_gpt_style_trainer(
            self.STEPS, plan=plan, lr_dampen=0.5,
        )
        assert not res["halted"]
        assert res["mgr"].lr_scale == 0.5
        assert res["mgr"].rollbacks_used == 1
        assert len(res["losses"]) == self.STEPS


@pytest.mark.chaos
class TestPreemptionDuringFinalize:
    """PR 8 satellite: preemption arriving DURING the async-save
    finalize. A SIGTERM mid-``AsyncCheckpointWriter.wait`` must still
    commit the manifest (the handler only flips a flag; the wait and
    commit run to completion); a hard kill mid-write must leave a
    cleanly-torn dir that the verified walk skips — never a
    plausible-but-unverified restore source."""

    _CHILD_PRELUDE = """
import os, threading, time, signal
import numpy as np
import jax; jax.config.update('jax_platforms', 'cpu')
from apex_tpu.utils import AutoResume
from apex_tpu import resilience

d = {save_dir!r}
big = {{"w": np.random.RandomState(0).randn(6_000_000).astype(np.float32)}}
"""

    def _run_child(self, body, save_dir, expect_rc=0, kill_on=None,
                   kill_sig=None):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        code = self._CHILD_PRELUDE.format(save_dir=save_dir) + body
        if kill_on is None:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True, env=env,
                                  timeout=240)
            assert proc.returncode == expect_rc, (proc.returncode,
                                                  proc.stdout[-500:],
                                                  proc.stderr[-800:])
            return proc.stdout
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE, text=True, env=env)
        try:
            for line in proc.stdout:
                if kill_on in line:
                    proc.send_signal(kill_sig)
                    break
        finally:
            proc.wait(timeout=240)
        return None

    def test_sigterm_mid_finalize_still_commits(self, tmp_path):
        """SIGTERM while finalize() blocks in wait(): the AutoResume
        handler is flag-only, so the wait completes and the manifest
        commit lands — the checkpoint IS durable, not torn."""
        body = """
ar = AutoResume(d, interval=1)  # handlers installed: the real signal path
ar._save_ema = 1e-3             # defeat first-save calibration: the save
                                # must still be PENDING when SIGTERM lands
ar.step(1, big)                 # async save issued, manifest pending
# deliver a REAL SIGTERM racing the finalize's wait()
threading.Timer(0.02, lambda: os.kill(os.getpid(), signal.SIGTERM)).start()
ar.finalize()                   # must run to completion regardless
ar.close()
ok, why = resilience.verify_checkpoint(os.path.join(d, "step_1"))
print(f"COMMITTED ok={ok} why={why}")
assert ok, why
assert ar.termination_requested()
"""
        out = self._run_child(body, str(tmp_path))
        assert "COMMITTED ok=True" in out
        assert resilience.verified_latest_step(str(tmp_path)) == 1

    def test_kill_mid_async_save_leaves_clean_torn_dir(self, tmp_path):
        """SIGKILL mid-background-write (the preemption the grace window
        did NOT cover): whatever is left of step_2 — an orbax tmp dir,
        or a completed dir with no manifest — the verified walk must
        skip it and restore the previously finalized step."""
        body = """
small = {"w": np.ones((4,), np.float32)}
ar = AutoResume(d, interval=1, install_handlers=False)
ar.step(1, small)
ar.finalize()                   # step 1 committed: the durable anchor
ar.step(2, big)                 # background write starts...
print("ISSUED", flush=True)
time.sleep(60)                  # ...and is killed under it
"""
        self._run_child(body, str(tmp_path), kill_on="ISSUED",
                        kill_sig=signal.SIGKILL)
        d = str(tmp_path)
        assert resilience.verified_latest_step(d) == 1
        # strict walk (no legacy tolerance): whether the kill left an
        # orbax tmp dir or a completed-but-uncommitted step_2, the
        # restore lands on the finalized step
        step, tree = resilience.load_checkpoint_verified(
            d, target={"w": np.ones((4,), np.float32)},
        )
        assert step == 1
        np.testing.assert_array_equal(tree["w"], np.ones((4,), np.float32))
        # whatever step_2 left behind, it is not offered as restorable
        s2 = os.path.join(d, "step_2")
        if os.path.isdir(s2) and s2 in [
            os.path.join(d, f"step_{s}") for s in finalized_steps(d)
        ]:
            ok, _ = resilience.verify_checkpoint(s2)
            assert not ok


class TestSigtermSpanFlush:
    """PR 7 satellite: the chaos harness's real SIGTERM must not tear
    the final goodput spans off the record stream. The router module
    installs a best-effort SIGTERM/atexit teardown (over the DEFAULT
    handler only — AutoResume's preemption handler keeps precedence when
    installed later); it flushes in-flight spans as interrupted records,
    then re-raises so the process still dies by SIGTERM — a drill the
    flush must never convert into a survival."""

    def test_real_sigterm_lands_interrupted_spans(self, tmp_path):
        import json
        import subprocess
        import sys

        stream = tmp_path / "run.jsonl"
        code = f"""
import os, signal, time
from apex_tpu.monitor import JsonlSink, MetricRouter
from apex_tpu.monitor import goodput

router = MetricRouter([JsonlSink({str(stream)!r})])
goodput.run_header(router, "run-sig")
goodput.set_router(router)
goodput.begin_span("step", step=12)
goodput.begin_span("ckpt_save", step=12)
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(30)  # never reached
"""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, env=env,
                              timeout=60)
        # still died BY SIGTERM (default disposition restored + re-kill)
        assert proc.returncode == -signal.SIGTERM, (proc.returncode,
                                                    proc.stderr)
        recs = [json.loads(l) for l in open(stream)]
        spans_flushed = [r for r in recs if r["kind"] == "span"]
        assert {r["phase"] for r in spans_flushed} == {"step", "ckpt_save"}
        assert all(r["interrupted"] for r in spans_flushed)
        # the stream is accountable: the interrupted partials partition
        from apex_tpu.monitor.goodput import account

        rep = account(recs, run_id="run-sig")
        assert rep.n_interrupted == 2 and rep.wall_s >= 0.0
