"""O1 per-op cast semantics.

Mirrors /root/reference/tests/L0/run_amp/test_basic_casts.py (whitelist ops
half, blacklist ops float, backward grads match input dtype) and
test_promotion.py (mixed-input promotion to widest, cat/stack sequence
promotion) — against the TPU cast engine (apex_tpu/amp/cast_engine.py)
instead of the patched torch namespace.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from apex_tpu import amp
from apex_tpu.amp.cast_engine import cast_ops

HALF_DTYPES = [jnp.bfloat16, jnp.float16]


def _ctx(half):
    return cast_ops(half)


class TestBasicCasts:
    """Ref TestBasicCasts (test_basic_casts.py:23-140)."""

    @pytest.mark.parametrize("half", HALF_DTYPES)
    @pytest.mark.parametrize("in_dtype", [jnp.float32, None])
    def test_matmul_is_half(self, half, in_dtype):
        in_dtype = in_dtype or half
        x = jnp.ones((4, 8), in_dtype)
        w = jnp.ones((8, 4), in_dtype)
        with _ctx(half):
            y = jnp.matmul(x, w)
        assert y.dtype == half  # ALWAYS_HALF

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_dot_general_is_half(self, half):
        """lax.dot_general is the primitive every flax Dense lowers to —
        patching it is the analogue of patching torch.addmm."""
        x = jnp.ones((4, 8), jnp.float32)
        w = jnp.ones((8, 4), jnp.float32)
        with _ctx(half):
            y = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())))
        assert y.dtype == half

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_flax_dense_is_half(self, half):
        """Ref test_linear_is_half: an nn layer (weights held outside the
        patched function) comes out half because its inner dot is patched."""
        m = nn.Dense(4)
        x = jnp.ones((2, 8), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        with _ctx(half):
            y = m.apply(params, x)
        assert y.dtype == half

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_conv_is_half(self, half):
        m = nn.Conv(4, (3, 3))
        x = jnp.ones((1, 8, 8, 3), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        with _ctx(half):
            y = m.apply(params, x)
        assert y.dtype == half

    @pytest.mark.parametrize("half", HALF_DTYPES)
    @pytest.mark.parametrize("in_dtype", [jnp.float32, None])
    def test_softmax_is_float(self, half, in_dtype):
        x = jnp.ones((4, 8), in_dtype or half)
        with _ctx(half):
            y = jax.nn.softmax(x, axis=-1)
        assert y.dtype == jnp.float32  # ALWAYS_FLOAT

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_sum_is_float(self, half):
        x = jnp.ones((4, 8), half)
        with _ctx(half):
            y = jnp.sum(x)
        assert y.dtype == jnp.float32

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_pow_is_float(self, half):
        x = jnp.ones((4,), half)
        with _ctx(half):
            y = jnp.power(x, 2.0)
        assert y.dtype == jnp.float32

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_exp_log_are_float(self, half):
        x = jnp.ones((4,), half)
        with _ctx(half):
            assert jnp.exp(x).dtype == jnp.float32
            assert jnp.log(x + 1.0).dtype == jnp.float32

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_relu_is_match(self, half):
        """Ref test_relu_is_match: unlisted ops preserve input dtype."""
        for dt in (half, jnp.float32):
            x = jnp.ones((4,), dt)
            with _ctx(half):
                assert jax.nn.relu(x).dtype == dt

    def test_backward_grads_match_input_dtype(self):
        """Ref run_layer_test's backward check: d/dx of a whitelist op on an
        fp32 input arrives fp32 (the cast's VJP casts back)."""
        x = jnp.ones((4, 8), jnp.float32)
        w = jnp.ones((8, 4), jnp.float32)
        with _ctx(jnp.bfloat16):
            g = jax.grad(lambda a: jnp.matmul(a, w).astype(jnp.float32).sum())(x)
        assert g.dtype == jnp.float32

    def test_inactive_outside_context(self):
        x = jnp.ones((4, 8), jnp.float32)
        w = jnp.ones((8, 4), jnp.float32)
        assert jnp.matmul(x, w).dtype == jnp.float32
        with _ctx(jnp.bfloat16):
            pass
        assert jnp.matmul(x, w).dtype == jnp.float32
        assert not hasattr(jnp.matmul, "__wrapped_by_apex_tpu_amp__")

    def test_casts_compile_into_jit(self):
        """Tracing inside the context bakes the casts into the jaxpr —
        the compiled fn keeps O1 behavior outside the context (the torch
        analogue: a cuda graph captured while the handle was active)."""
        w = jnp.ones((8, 4), jnp.float32)
        with _ctx(jnp.bfloat16):
            f = jax.jit(lambda a: jnp.matmul(a, w))
            y = f(jnp.ones((4, 8), jnp.float32))  # traced inside
        assert y.dtype == jnp.bfloat16
        assert f(jnp.ones((4, 8), jnp.float32)).dtype == jnp.bfloat16


class TestPromotion:
    """Ref TestPromotion (test_promotion.py:42-75)."""

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_atan2_matches_widest(self, half):
        a = jnp.ones((4,), half)
        b = jnp.ones((4,), jnp.float32)
        with _ctx(half):
            assert jnp.arctan2(a, b).dtype == jnp.float32
            assert jnp.arctan2(b, a).dtype == jnp.float32

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_mul_matches_widest(self, half):
        a = jnp.ones((4,), half)
        b = jnp.ones((4,), jnp.float32)
        with _ctx(half):
            assert jnp.multiply(a, b).dtype == jnp.float32

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_single_type_untouched(self, half):
        a = jnp.ones((4,), half)
        b = jnp.ones((4,), half)
        with _ctx(half):
            assert jnp.add(a, b).dtype == half

    @pytest.mark.parametrize("half", HALF_DTYPES)
    def test_cat_matches_widest(self, half):
        """Ref test_cat_matches_widest via SEQUENCE_CASTS."""
        seq = [jnp.ones((4,), half), jnp.ones((4,), jnp.float32)]
        with _ctx(half):
            assert jnp.concatenate(seq).dtype == jnp.float32
            assert jnp.stack(seq).dtype == jnp.float32

    def test_nested_same_dtype_ok_mismatch_raises(self):
        with _ctx(jnp.bfloat16):
            with _ctx(jnp.bfloat16):
                assert jnp.sum(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32
            with pytest.raises(ValueError, match="different half dtypes"):
                with _ctx(jnp.float16):
                    pass
        # fully restored after nesting
        assert not hasattr(jnp.sum, "__wrapped_by_apex_tpu_amp__")


class TestO1Policy:
    """End-to-end: the O1 policy drives the engine through wrap_apply."""

    def test_o1_has_patch_functions(self):
        assert amp.O1().patch_functions
        assert not amp.O2().patch_functions and not amp.O0().patch_functions

    def test_o1_wrap_apply_blacklist_inside_model(self):
        """A model whose head is a blacklisted op produces fp32 internally
        under O1 even though inputs were cast half."""
        policy = amp.O1(jnp.bfloat16)
        seen = {}

        def apply_fn(params, x):
            y = jnp.matmul(x, params["w"])  # whitelist -> half
            seen["mm"] = y.dtype
            z = jnp.sum(y)  # blacklist -> fp32
            seen["sum"] = z.dtype
            return z

        params = {"w": jnp.ones((8, 4), jnp.float32)}
        out = policy.wrap_apply(apply_fn)(params, jnp.ones((2, 8), jnp.float32))
        assert seen["mm"] == jnp.bfloat16
        assert seen["sum"] == jnp.float32
        assert out.dtype == jnp.float32

    def test_o2_wrap_apply_does_not_patch(self):
        policy = amp.O2(jnp.bfloat16)
        seen = {}

        def apply_fn(params, x):
            seen["sum"] = jnp.sum(x).dtype
            return x

        policy.wrap_apply(apply_fn)({}, jnp.ones((2,), jnp.float32))
        assert seen["sum"] == jnp.bfloat16  # no fp32 blacklist under O2


class TestUserRegistries:
    """Ref amp/amp.py:33-71: user-annotated functions join the cast lists."""

    def test_half_and_float_decorators(self):
        from apex_tpu.amp import float_function, half_function

        @half_function
        def my_matmul(a, b):
            return a @ b

        @float_function
        def my_reduce(x):
            return x.sum()

        a = jnp.ones((4, 4), jnp.float32)
        h = jnp.ones((4,), jnp.bfloat16)
        # inactive outside a context
        assert my_matmul(a, a).dtype == jnp.float32
        assert my_reduce(h).dtype == jnp.bfloat16
        with _ctx(jnp.bfloat16):
            assert my_matmul(a, a).dtype == jnp.bfloat16
            assert my_reduce(h).dtype == jnp.float32

    def test_promote_decorator(self):
        from apex_tpu.amp import promote_function

        @promote_function
        def my_mix(a, b):
            return a * b

        with _ctx(jnp.bfloat16):
            out = my_mix(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32))
        assert out.dtype == jnp.float32

    def test_register_namespace_functions(self):
        import types

        from apex_tpu.amp import (
            register_float_function,
            register_half_function,
            register_promote_function,
        )

        ns = types.SimpleNamespace(
            mm=lambda a, b: a @ b,
            red=lambda x: x.sum(),
            mix=lambda a, b: a + b,
        )
        register_half_function(ns, "mm")
        register_float_function(ns, "red")
        register_promote_function(ns, "mix")
        a32 = jnp.ones((4, 4), jnp.float32)
        h = jnp.ones((4,), jnp.bfloat16)
        with _ctx(jnp.bfloat16):
            assert ns.mm(a32, a32).dtype == jnp.bfloat16
            assert ns.red(h).dtype == jnp.float32
            assert ns.mix(h, jnp.ones((4,), jnp.float32)).dtype == jnp.float32
        # restored on exit, like the built-in lists
        assert ns.mm(a32, a32).dtype == jnp.float32
        assert ns.red(h).dtype == jnp.bfloat16

    def test_register_missing_name_raises(self):
        import types

        from apex_tpu.amp import register_half_function

        with pytest.raises(ValueError, match="No function named"):
            register_half_function(types.SimpleNamespace(), "nope")

    def test_user_registration_overrides_builtin_list(self):
        """register_float_function on an FP16-whitelisted op must NOT
        round-trip args through the half dtype (precision check: 1+2^-12
        survives fp32 but rounds to 1.0 in bf16)."""
        from apex_tpu.amp import register_float_function
        from apex_tpu.amp import cast_engine

        register_float_function(jnp, "einsum")
        try:
            a = jnp.full((1, 1), 1.0 + 2.0**-12, jnp.float32)
            with _ctx(jnp.bfloat16):
                out = jnp.einsum("ij,jk->ik", a, a)
            assert out.dtype == jnp.float32
            assert float(out[0, 0]) > 1.0  # bf16 truncation would give 1.0
        finally:
            cast_engine._USER_FP32_REGISTRY.remove((jnp, "einsum"))

    def test_patch_failure_unwinds_cleanly(self):
        import types

        from apex_tpu.amp import register_half_function
        from apex_tpu.amp import cast_engine

        ns = types.SimpleNamespace(fn=lambda x: x)
        register_half_function(ns, "fn")
        del ns.fn  # vanishes before the next context enter
        try:
            with pytest.raises(AttributeError):
                with _ctx(jnp.bfloat16):
                    pass
            # nothing leaked: built-ins restored, a fresh context works
            assert not hasattr(jnp.matmul, "__wrapped_by_apex_tpu_amp__")
            ns.fn = lambda x: x
            with _ctx(jnp.bfloat16):
                x = jnp.ones((2, 2), jnp.float32)
                assert jnp.matmul(x, x).dtype == jnp.bfloat16
        finally:
            cast_engine._USER_FP16_REGISTRY.remove((ns, "fn"))

    def test_user_override_on_flax_module_call(self):
        """A float registration on a listed flax layer must defeat the
        built-in half-output wrapper too."""
        from apex_tpu.amp import register_float_function
        from apex_tpu.amp import cast_engine

        register_float_function(nn.Dense, "__call__")
        try:
            m = nn.Dense(4)
            x = jnp.ones((2, 8), jnp.float32)
            params = m.init(jax.random.PRNGKey(0), x)
            with _ctx(jnp.bfloat16):
                assert m.apply(params, x).dtype == jnp.float32
        finally:
            cast_engine._USER_FP32_REGISTRY.remove((nn.Dense, "__call__"))
        # built-in behavior restored
        params = nn.Dense(4).init(jax.random.PRNGKey(0), jnp.ones((2, 8)))
        with _ctx(jnp.bfloat16):
            assert nn.Dense(4).apply(params, jnp.ones((2, 8))).dtype == jnp.bfloat16

    def test_latest_registration_wins(self):
        import types

        from apex_tpu.amp import register_float_function, register_half_function
        from apex_tpu.amp import cast_engine

        ns = types.SimpleNamespace(f=lambda x: x)
        register_half_function(ns, "f")
        register_float_function(ns, "f")  # most recent intent: fp32
        try:
            with _ctx(jnp.bfloat16):
                assert ns.f(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32
        finally:
            cast_engine._USER_FP32_REGISTRY.remove((ns, "f"))
        assert (ns, "f") not in cast_engine._USER_FP16_REGISTRY


class TestCastThroughRNNScan:
    """O1 cast behavior through the rnn/ scan cells (VERDICT r3 item 8;
    ref: apex/amp/rnn_compat.py + SEQUENCE_CASTS in
    apex/amp/lists/torch_overrides.py — the reference needed special RNN
    handling because cuDNN RNNs bypass the functional overrides; here the
    cells are plain flax modules whose gate GEMMs go through the patched
    ``lax.dot_general``, and the contract to pin is that the scan CARRY
    keeps one stable dtype across steps while the GEMMs run in half)."""

    @pytest.mark.parametrize("model_cls", ["LSTM", "GRU", "mLSTM"])
    def test_scan_carry_stable_and_gemms_halved(self, rng, model_cls):
        from apex_tpu import rnn as rnn_mod

        model = getattr(rnn_mod, model_cls)(4, 8)
        xs = jax.random.normal(rng, (5, 2, 4), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), xs)

        # traces (carry dtype stable across scan steps) AND runs under O1
        with _ctx(jnp.bfloat16):
            ys, carry = jax.jit(model.apply)(params, xs)
        # nonlinearity math stays fp32 (cells compute gates at fp32), so
        # outputs/carries are fp32 even with bf16 GEMMs
        assert ys.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(carry):
            assert leaf.dtype == jnp.float32

        # the GEMMs really ran in bf16: O1 output differs from fp32 by
        # bf16-level error but not more
        ys_ref, _ = jax.jit(model.apply)(params, xs)
        err = float(jnp.max(jnp.abs(ys - ys_ref)))
        assert 0 < err < 0.1, err

        # grads flow through the cast scan without dtype errors
        with _ctx(jnp.bfloat16):
            g = jax.grad(
                lambda p: jnp.sum(model.apply(p, xs)[0])
            )(params)
        for leaf in jax.tree_util.tree_leaves(g):
            assert jnp.all(jnp.isfinite(leaf))


def test_disable_casts_inside_cast_ops():
    """ref apex.amp.disable_casts (handle.py:164): a block inside an active
    O1 region runs at full precision, and casting resumes after."""
    from apex_tpu.amp import disable_casts

    x = jnp.ones((4, 4), jnp.float32)
    dot = lambda: jax.lax.dot_general(x, x, (((1,), (0,)), ((), ())))
    with _ctx(jnp.bfloat16):
        assert dot().dtype == jnp.bfloat16
        with disable_casts():
            assert dot().dtype == jnp.float32
        assert dot().dtype == jnp.bfloat16
    assert dot().dtype == jnp.float32


def test_cast_ops_nested_inside_disable_casts():
    """Entering cast_ops inside a disabled region must neither double-patch
    nor strip the outer region's wrappers on exit."""
    from apex_tpu.amp import disable_casts
    from apex_tpu.amp import cast_engine

    x = jnp.ones((4, 4), jnp.float32)
    dot = lambda: jax.lax.dot_general(x, x, (((1,), (0,)), ((), ())))
    with _ctx(jnp.bfloat16):
        n_saved = len(cast_engine._state.saved)
        with disable_casts():
            with _ctx(jnp.bfloat16):  # reentrant enter while disabled
                assert len(cast_engine._state.saved) == n_saved  # no re-patch
                assert dot().dtype == jnp.float32  # still disabled
        # outer region's wrappers intact and active again
        assert len(cast_engine._state.saved) == n_saved
        assert dot().dtype == jnp.bfloat16
    assert not cast_engine._state.saved
    assert dot().dtype == jnp.float32
