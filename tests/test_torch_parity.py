"""Cross-framework numeric parity: apex_tpu ops/optimizers vs PyTorch (CPU).

The reference's L0 tier is built on numerical comparison against
pure-PyTorch implementations (SURVEY.md §4; e.g.
tests/L0/run_optimizers/test_fused_optimizer.py, run_fused_layer_norm/).
The rest of this suite compares our fused engines against our own jnp
references — a closed loop that can't catch a shared formula error.  These
tests close that loop with the SAME external oracle the reference uses:
torch's CPU implementations of Adam/AdamW/SGD, layer_norm, softmax
cross-entropy, group_norm, and scaled_dot_product_attention.

All comparisons run in fp32 on CPU with tolerances sized for
order-of-operations differences, not behavioral slack.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
import torch
import torch.nn.functional as F


def _tree(key, shapes):
    return {
        f"p{i}": jax.random.normal(jax.random.fold_in(key, i), s, jnp.float32)
        for i, s in enumerate(shapes)
    }


def _to_torch(tree):
    return [
        torch.nn.Parameter(torch.from_numpy(np.asarray(x)).clone())
        for x in jax.tree_util.tree_leaves(tree)
    ]


SHAPES = [(64, 128), (128,), (32, 32, 3), (256,)]


class TestOptimizersVsTorch:
    @pytest.mark.parametrize("steps", [5])
    def test_fused_adamw_matches_torch_adamw(self, steps):
        key = jax.random.PRNGKey(0)
        params = _tree(key, SHAPES)
        tparams = _to_torch(params)
        topt = torch.optim.AdamW(
            tparams, lr=1e-2, betas=(0.9, 0.95), eps=1e-8, weight_decay=0.1
        )
        from apex_tpu.optimizers import fused_adam

        opt = fused_adam(lr=1e-2, betas=(0.9, 0.95), eps=1e-8,
                         weight_decay=0.1, adam_w_mode=True)
        state = opt.init(params)
        for s in range(steps):
            gkey = jax.random.fold_in(key, 100 + s)
            grads = jax.tree_util.tree_map(
                lambda x: jax.random.normal(
                    jax.random.fold_in(gkey, hash(x.shape) % 1000), x.shape
                ),
                params,
            )
            for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
                tp.grad = torch.from_numpy(np.asarray(g)).clone()
            topt.step()
            upd, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, upd)
        for ours, theirs in zip(jax.tree_util.tree_leaves(params), tparams):
            np.testing.assert_allclose(
                np.asarray(ours), theirs.detach().numpy(), atol=2e-6
            )

    def test_fused_adam_l2_mode_matches_torch_adam(self):
        key = jax.random.PRNGKey(1)
        params = _tree(key, SHAPES)
        tparams = _to_torch(params)
        # torch.optim.Adam's weight_decay IS L2-into-the-gradient — the
        # semantics our adam_w_mode=False mirrors (ref multi_tensor_adam.cu
        # ADAM_MODE_1)
        topt = torch.optim.Adam(tparams, lr=3e-3, weight_decay=0.05)
        from apex_tpu.optimizers import fused_adam

        opt = fused_adam(lr=3e-3, weight_decay=0.05, adam_w_mode=False)
        state = opt.init(params)
        for s in range(4):
            grads = jax.tree_util.tree_map(
                lambda x: jnp.full(x.shape, 0.01 * (s + 1), jnp.float32), params
            )
            for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
                tp.grad = torch.from_numpy(np.asarray(g)).clone()
            topt.step()
            upd, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, upd)
        for ours, theirs in zip(jax.tree_util.tree_leaves(params), tparams):
            np.testing.assert_allclose(
                np.asarray(ours), theirs.detach().numpy(), atol=2e-6
            )

    @pytest.mark.parametrize("nesterov", [False, True])
    def test_fused_sgd_matches_torch_sgd(self, nesterov):
        key = jax.random.PRNGKey(2)
        params = _tree(key, SHAPES)
        tparams = _to_torch(params)
        topt = torch.optim.SGD(
            tparams, lr=0.1, momentum=0.9, weight_decay=1e-4,
            nesterov=nesterov,
        )
        from apex_tpu.optimizers import fused_sgd

        opt = fused_sgd(lr=0.1, momentum=0.9, weight_decay=1e-4,
                        nesterov=nesterov)
        state = opt.init(params)
        for s in range(5):
            gkey = jax.random.fold_in(key, 200 + s)
            grads = jax.tree_util.tree_map(
                lambda x: jax.random.normal(
                    jax.random.fold_in(gkey, x.size % 997), x.shape
                ) * 0.1,
                params,
            )
            for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
                tp.grad = torch.from_numpy(np.asarray(g)).clone()
            topt.step()
            upd, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, upd)
        for ours, theirs in zip(jax.tree_util.tree_leaves(params), tparams):
            np.testing.assert_allclose(
                np.asarray(ours), theirs.detach().numpy(), atol=1e-6
            )


class TestOpsVsTorch:
    def test_layer_norm_fwd_bwd(self):
        key = jax.random.PRNGKey(3)
        x = jax.random.normal(key, (96, 256), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(key, 1), (256,)) * 0.2 + 1.0
        b = jax.random.normal(jax.random.fold_in(key, 2), (256,)) * 0.1

        from apex_tpu.ops import layer_norm

        def loss(x, w, b):
            return jnp.sum(jnp.tanh(layer_norm(x, w, b, eps=1e-5)))

        ours = layer_norm(x, w, b, eps=1e-5)
        g = jax.grad(loss, (0, 1, 2))(x, w, b)

        tx = torch.from_numpy(np.asarray(x)).requires_grad_()
        tw = torch.from_numpy(np.asarray(w)).requires_grad_()
        tb = torch.from_numpy(np.asarray(b)).requires_grad_()
        ty = F.layer_norm(tx, (256,), tw, tb, eps=1e-5)
        torch.sum(torch.tanh(ty)).backward()

        np.testing.assert_allclose(np.asarray(ours), ty.detach().numpy(), atol=1e-5)
        for a, t in zip(g, (tx.grad, tw.grad, tb.grad)):
            np.testing.assert_allclose(np.asarray(a), t.numpy(), atol=1e-4)

    def test_group_norm_fwd(self):
        key = jax.random.PRNGKey(4)
        x = jax.random.normal(key, (4, 8, 8, 32), jnp.float32)  # NHWC
        w = jnp.ones((32,))
        b = jnp.zeros((32,))
        from apex_tpu.contrib.group_norm import group_norm

        ours = group_norm(x, num_groups=8, weight=w, bias=b, eps=1e-5)
        tx = torch.from_numpy(np.asarray(jnp.transpose(x, (0, 3, 1, 2))))
        ty = F.group_norm(tx, 8, torch.ones(32), torch.zeros(32), eps=1e-5)
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(ours, (0, 3, 1, 2))), ty.numpy(), atol=1e-5
        )

    def test_xentropy_label_smoothing(self):
        key = jax.random.PRNGKey(5)
        logits = jax.random.normal(key, (32, 100), jnp.float32)
        labels = jax.random.randint(jax.random.fold_in(key, 1), (32,), 0, 100)
        from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

        ours = softmax_cross_entropy_loss(logits, labels, smoothing=0.1)
        tl = F.cross_entropy(
            torch.from_numpy(np.asarray(logits)),
            torch.from_numpy(np.asarray(labels)).long(),
            label_smoothing=0.1, reduction="none",
        )
        np.testing.assert_allclose(np.asarray(ours), tl.numpy(), atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_attention_vs_sdpa(self, causal):
        key = jax.random.PRNGKey(6)
        shape = (2, 4, 128, 64)
        q = jax.random.normal(key, shape, jnp.float32)
        k = jax.random.normal(jax.random.fold_in(key, 1), shape, jnp.float32)
        v = jax.random.normal(jax.random.fold_in(key, 2), shape, jnp.float32)
        from apex_tpu.ops import flash_attention

        ours = flash_attention(q, k, v, causal=causal, impl="pallas")
        ref = F.scaled_dot_product_attention(
            torch.from_numpy(np.asarray(q)),
            torch.from_numpy(np.asarray(k)),
            torch.from_numpy(np.asarray(v)),
            is_causal=causal,
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=2e-5)

    def test_softmax_family_vs_torch(self):
        key = jax.random.PRNGKey(7)
        x = jax.random.normal(key, (4, 8, 64, 64), jnp.float32)
        from apex_tpu.ops.softmax import scaled_softmax

        ours = scaled_softmax(x, scale=0.63)
        ref = torch.softmax(torch.from_numpy(np.asarray(x)) * 0.63, dim=-1)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-6)


class TestConvAndBNVsTorch:
    def test_conv_bias_relu_fwd_bwd(self):
        from apex_tpu.contrib.conv_bias_relu import conv_bias_relu

        key = jax.random.PRNGKey(12)
        x = jax.random.normal(key, (2, 16, 16, 8), jnp.float32)  # NHWC
        w = jax.random.normal(jax.random.fold_in(key, 1), (3, 3, 8, 12)) * 0.2
        b = jax.random.normal(jax.random.fold_in(key, 2), (12,)) * 0.1

        ours = conv_bias_relu(x, w, b, padding=1, stride=2)

        tx = torch.from_numpy(
            np.asarray(jnp.transpose(x, (0, 3, 1, 2)))
        ).requires_grad_()
        tw = torch.from_numpy(
            np.asarray(jnp.transpose(w, (3, 2, 0, 1)))  # HWIO -> OIHW
        ).requires_grad_()
        tb = torch.from_numpy(np.asarray(b)).requires_grad_()
        ty = F.relu(F.conv2d(tx, tw, tb, stride=2, padding=1))
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(ours, (0, 3, 1, 2))), ty.detach().numpy(),
            atol=2e-5,
        )

        def loss(x, w, b):
            return jnp.sum(jnp.sin(conv_bias_relu(x, w, b, padding=1, stride=2)))

        gx, gw, gb = jax.grad(loss, (0, 1, 2))(x, w, b)
        torch.sum(torch.sin(ty)).backward()
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(gx, (0, 3, 1, 2))), tx.grad.numpy(), atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(gw, (3, 2, 0, 1))), tw.grad.numpy(), atol=2e-5
        )
        np.testing.assert_allclose(np.asarray(gb), tb.grad.numpy(), atol=2e-5)

    def test_syncbn_single_device_matches_torch_bn_train_mode(self):
        """On one device SyncBatchNorm must equal plain BN; oracle is
        torch.nn.BatchNorm2d in train mode, including the running-stat
        update after one batch."""
        from apex_tpu.parallel.sync_batch_norm import SyncBatchNorm

        key = jax.random.PRNGKey(13)
        x = jax.random.normal(key, (8, 6, 6, 10), jnp.float32)
        # torch-convention momentum (new = (1-m)*old + m*batch), no mesh axes
        bn = SyncBatchNorm(momentum=0.1, epsilon=1e-5, axis_names=())
        variables = bn.init(key, x, use_running_average=False)
        ours, mutated = bn.apply(
            variables, x, use_running_average=False, mutable=["batch_stats"]
        )

        tbn = torch.nn.BatchNorm2d(10, eps=1e-5, momentum=0.1)
        tbn.train()
        tx = torch.from_numpy(np.asarray(jnp.transpose(x, (0, 3, 1, 2))))
        ty = tbn(tx)
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(ours, (0, 3, 1, 2))), ty.detach().numpy(),
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(mutated["batch_stats"]["mean"]),
            tbn.running_mean.numpy(), atol=1e-5,
        )
        # both feed the UNBIASED (Bessel-corrected) batch var into the
        # running mix — torch-convention stats tracking is part of the
        # SyncBatchNorm design, so running var matches directly
        np.testing.assert_allclose(
            np.asarray(mutated["batch_stats"]["var"]),
            tbn.running_var.numpy(), rtol=1e-5,
        )


class TestMLPVsTorch:
    """The reference's own MLP test compares against an equivalent
    nn.Sequential (tests/L0/run_mlp/test_mlp.py) — same oracle here,
    forward AND input/weight gradients."""

    @pytest.mark.parametrize("activation", ["relu", "sigmoid"])
    def test_mlp_fwd_bwd(self, activation):
        from apex_tpu.ops import mlp_apply, mlp_init

        sizes = [40, 64, 32, 10]
        params = mlp_init(jax.random.PRNGKey(10), sizes)
        x = jax.random.normal(jax.random.PRNGKey(11), (16, 40), jnp.float32)

        layers = []
        for i in range(len(sizes) - 1):
            lin = torch.nn.Linear(sizes[i], sizes[i + 1])
            with torch.no_grad():
                lin.weight.copy_(torch.from_numpy(np.asarray(params["weights"][i])))
                lin.bias.copy_(torch.from_numpy(np.asarray(params["biases"][i])))
            layers.append(lin)
            if i < len(sizes) - 2:
                layers.append(torch.nn.ReLU() if activation == "relu"
                              else torch.nn.Sigmoid())
        tmlp = torch.nn.Sequential(*layers)

        ours = mlp_apply(params, x, activation=activation)
        tx = torch.from_numpy(np.asarray(x)).requires_grad_()
        ty = tmlp(tx)
        np.testing.assert_allclose(np.asarray(ours), ty.detach().numpy(), atol=2e-5)

        def loss(params, x):
            return jnp.sum(jnp.tanh(mlp_apply(params, x, activation=activation)))

        gp, gx = jax.grad(loss, (0, 1))(params, x)
        torch.sum(torch.tanh(ty)).backward()
        np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(gp["weights"][0]), tmlp[0].weight.grad.numpy(), atol=2e-5
        )


class TestRNNCellsVsTorch:
    """Gate-order/formula drift in RNN cells is invisible to shape tests;
    torch.nn.LSTMCell/GRUCell are the oracles (ref apex/RNN mirrors torch's
    cell math)."""

    def test_lstm_cell_trajectory(self):
        from apex_tpu.rnn.cells import LSTMCell

        key = jax.random.PRNGKey(8)
        in_dim, hs, batch = 24, 32, 4
        cell = LSTMCell(hidden_size=hs)
        carry = LSTMCell.init_carry(batch, hs)
        x0 = jax.random.normal(key, (batch, in_dim), jnp.float32)
        params = cell.init(key, carry, x0)

        tcell = torch.nn.LSTMCell(in_dim, hs)
        wi = np.asarray(params["params"]["wi"])  # (in, 4h)
        wh = np.asarray(params["params"]["wh"])
        b = np.asarray(params["params"]["bias"])
        with torch.no_grad():
            tcell.weight_ih.copy_(torch.from_numpy(wi.T))
            tcell.weight_hh.copy_(torch.from_numpy(wh.T))
            tcell.bias_ih.copy_(torch.from_numpy(b))
            tcell.bias_hh.zero_()  # ours has ONE bias; torch has two

        th = torch.zeros(batch, hs)
        tc = torch.zeros(batch, hs)
        for s in range(4):
            x = jax.random.normal(jax.random.fold_in(key, 10 + s),
                                  (batch, in_dim), jnp.float32)
            carry, y = cell.apply(params, carry, x)
            with torch.no_grad():
                th, tc = tcell(torch.from_numpy(np.asarray(x)), (th, tc))
        np.testing.assert_allclose(np.asarray(carry[0]), th.numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(carry[1]), tc.numpy(), atol=1e-5)

    def test_gru_cell_trajectory(self):
        from apex_tpu.rnn.cells import GRUCell

        key = jax.random.PRNGKey(9)
        in_dim, hs, batch = 16, 24, 3
        cell = GRUCell(hidden_size=hs)
        carry = GRUCell.init_carry(batch, hs)
        x0 = jax.random.normal(key, (batch, in_dim), jnp.float32)
        params = cell.init(key, carry, x0)
        # non-zero biases so the two-bias split (bi vs bh, which matters in
        # the r*hn term) is actually exercised
        params = jax.tree_util.tree_map(
            lambda x: x + 0.05 if x.ndim == 1 else x, params
        )

        tcell = torch.nn.GRUCell(in_dim, hs)
        p = params["params"]
        with torch.no_grad():
            tcell.weight_ih.copy_(torch.from_numpy(np.asarray(p["wi"]).T))
            tcell.weight_hh.copy_(torch.from_numpy(np.asarray(p["wh"]).T))
            tcell.bias_ih.copy_(torch.from_numpy(np.asarray(p["bi"])))
            tcell.bias_hh.copy_(torch.from_numpy(np.asarray(p["bh"])))

        th = torch.zeros(batch, hs)
        for s in range(4):
            x = jax.random.normal(jax.random.fold_in(key, 20 + s),
                                  (batch, in_dim), jnp.float32)
            carry, y = cell.apply(params, carry, x)
            with torch.no_grad():
                th = tcell(torch.from_numpy(np.asarray(x)), th)
        np.testing.assert_allclose(np.asarray(carry[0]), th.numpy(), atol=1e-5)
