"""Fused LayerNorm/RMSNorm numeric parity tests.

Mirrors reference tests/L0/run_fused_layer_norm/test_fused_layer_norm.py:
fused implementation vs a plain reference, fwd and grads, multiple dtypes,
including the Pallas kernel path (interpret mode on CPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import layer_norm, rms_norm


def _ref_ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    return ((xf - mean) / jnp.sqrt(var + eps) * w + b).astype(x.dtype)


def _ref_rms(x, w, eps=1e-5):
    xf = x.astype(jnp.float32)
    ms = (xf**2).mean(-1, keepdims=True)
    return (xf / jnp.sqrt(ms + eps) * w).astype(x.dtype)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layer_norm_forward(rng, impl, dtype):
    k1, k2, k3 = jax.random.split(rng, 3)
    x = jax.random.normal(k1, (4, 12, 256), dtype)
    w = jax.random.normal(k2, (256,), jnp.float32) * 0.1 + 1.0
    b = jax.random.normal(k3, (256,), jnp.float32) * 0.1
    out = layer_norm(x, w, b, impl=impl)
    ref = _ref_ln(x, w, b)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=tol, rtol=tol
    )


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_layer_norm_grads(rng, impl):
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    x = jax.random.normal(k1, (24, 128), jnp.float32)
    w = jax.random.normal(k2, (128,), jnp.float32) * 0.1 + 1.0
    b = jax.random.normal(k3, (128,), jnp.float32) * 0.1
    ct = jax.random.normal(k4, (24, 128), jnp.float32)

    def loss(fn):
        return lambda x, w, b: jnp.sum(fn(x, w, b) * ct)

    gx, gw, gb = jax.grad(loss(lambda x, w, b: layer_norm(x, w, b, impl=impl)), (0, 1, 2))(
        x, w, b
    )
    rx, rw, rb = jax.grad(loss(_ref_ln), (0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(rb), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_rms_norm_forward_and_grads(rng, impl):
    k1, k2, k4 = jax.random.split(rng, 3)
    x = jax.random.normal(k1, (24, 128), jnp.float32)
    w = jax.random.normal(k2, (128,), jnp.float32) * 0.1 + 1.0
    ct = jax.random.normal(k4, (24, 128), jnp.float32)
    out = rms_norm(x, w, impl=impl)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref_rms(x, w)), atol=1e-5, rtol=1e-5
    )
    gx, gw = jax.grad(lambda x, w: jnp.sum(rms_norm(x, w, impl=impl) * ct), (0, 1))(x, w)
    rx, rw = jax.grad(lambda x, w: jnp.sum(_ref_rms(x, w) * ct), (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=1e-4, rtol=1e-4)


def test_layer_norm_odd_hidden_falls_back(rng):
    # hidden not a multiple of 128 lanes -> XLA path, still correct
    x = jax.random.normal(rng, (7, 100), jnp.float32)
    w = jnp.ones((100,))
    b = jnp.zeros((100,))
    out = layer_norm(x, w, b, impl="auto")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref_ln(x, w, b)), atol=1e-5, rtol=1e-5
    )


def test_layer_norm_non_affine(rng):
    x = jax.random.normal(rng, (7, 64), jnp.float32)
    out = layer_norm(x)
    ref = _ref_ln(x, jnp.ones((64,)), jnp.zeros((64,)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_layer_norm_memory_efficient(rng):
    k1, k2, k3 = jax.random.split(rng, 3)
    x = jax.random.normal(k1, (8, 128), jnp.float32)
    w = jax.random.normal(k2, (128,)) * 0.1 + 1.0
    b = jax.random.normal(k3, (128,)) * 0.1
    a = layer_norm(x, w, b, memory_efficient=True, impl="xla")
    bb = layer_norm(x, w, b, memory_efficient=False, impl="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(bb), atol=1e-6)


class TestModuleStyleAPI:
    """apex.normalization import-surface parity: module classes over the
    functional kernels (ref fused_layer_norm.py:230/329)."""

    def test_fused_layer_norm_module(self, rng):
        from apex_tpu.normalization import FusedLayerNorm, MixedFusedLayerNorm

        x = jax.random.normal(rng, (4, 6, 32))
        m = FusedLayerNorm(normalized_shape=32)
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        ref = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
            x.var(-1, keepdims=True) + 1e-5
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        assert MixedFusedLayerNorm is FusedLayerNorm

    def test_multidim_normalized_shape(self, rng):
        from apex_tpu.normalization import FusedLayerNorm

        x = jax.random.normal(rng, (3, 4, 8))
        m = FusedLayerNorm(normalized_shape=(4, 8))  # reduce over both
        params = m.init(jax.random.PRNGKey(0), x)
        # params keep the reference layout: Parameter(*normalized_shape)
        assert params["params"]["weight"].shape == (4, 8)
        out = m.apply(params, x)
        flat = x.reshape(3, 32)
        ref = ((flat - flat.mean(-1, keepdims=True)) / jnp.sqrt(
            flat.var(-1, keepdims=True) + 1e-5)).reshape(x.shape)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_no_affine_and_rms(self, rng):
        from apex_tpu.normalization import FusedRMSNorm

        x = jax.random.normal(rng, (4, 32))
        m = FusedRMSNorm(normalized_shape=32, elementwise_affine=False)
        params = m.init(jax.random.PRNGKey(0), x)
        assert not jax.tree_util.tree_leaves(params)  # no params at all
        out = m.apply(params, x)
        ref = x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_memory_efficient_grads_match(self, rng):
        from apex_tpu.normalization import FusedLayerNorm

        x = jax.random.normal(rng, (4, 32))

        def loss(params, m):
            return jnp.sum(jnp.sin(m.apply(params, x)))

        m1 = FusedLayerNorm(normalized_shape=32)
        m2 = FusedLayerNorm(normalized_shape=32, memory_efficient=True)
        params = m1.init(jax.random.PRNGKey(0), x)
        g1 = jax.grad(loss)(params, m1)
        g2 = jax.grad(loss)(params, m2)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ), g1, g2,
        )
