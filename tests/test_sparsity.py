"""ASP 2:4 sparsity tests (ref style: apex/contrib/test/sparsity)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.contrib.sparsity import (
    ASP,
    apply_permutation,
    compute_sparse_masks,
    create_mask,
    exhaustive_search,
    fill,
    invert_permutation,
    m4n2_1d,
    m4n2_2d_best,
    masked_update,
    mn_1d_best,
    permute_and_mask,
    prune,
    search_for_good_permutation,
)


class TestMaskLib:
    def test_m4n2_keeps_top2_per_group(self, rng):
        x = jax.random.normal(rng, (8, 16))
        mask = m4n2_1d(x)
        m = np.asarray(mask).reshape(-1, 4)
        assert (m.sum(axis=1) == 2).all()
        # kept entries are the 2 largest |x| per group
        xs = np.abs(np.asarray(x)).reshape(-1, 4)
        for g in range(xs.shape[0]):
            kept = np.sort(xs[g][m[g] == 1])
            dropped = np.sort(xs[g][m[g] == 0])
            assert kept.min() >= dropped.max() - 1e-6

    def test_mn_patterns_other_ratios(self, rng):
        x = jax.random.normal(rng, (4, 8))
        mask = mn_1d_best(x, 2, 1)
        assert (np.asarray(mask).reshape(-1, 2).sum(axis=1) == 1).all()

    def test_create_mask_axis(self, rng):
        x = jax.random.normal(rng, (16, 8))
        mask = create_mask(x, axis=0)  # prune along dim 0
        assert (np.asarray(mask).T.reshape(-1, 4).sum(axis=1) == 2).all()
        with pytest.raises(ValueError):
            create_mask(x, pattern="nope")

    def test_2d_best_is_valid_rowwise(self, rng):
        x = jax.random.normal(rng, (16, 16))
        mask = m4n2_2d_best(x)
        assert (np.asarray(mask).reshape(-1, 4).sum(axis=1) == 2).all()

    def test_2d_best_is_valid_both_directions(self, rng):
        """The 2-D variant's whole purpose: the transpose (dgrad GEMM
        direction) is also 2:4 sparse (ref m4n2_2d_best)."""
        x = jax.random.normal(rng, (16, 24))
        mask = np.asarray(m4n2_2d_best(x))
        assert (mask.reshape(-1, 4).sum(axis=1) == 2).all()  # row-wise
        # column-wise: within each 4x4 block every column keeps exactly 2
        blocks = mask.reshape(4, 4, 6, 4).transpose(0, 2, 1, 3)
        assert (blocks.sum(axis=2) == 2).all()

    def test_2d_best_maximizes_retained_magnitude_per_block(self):
        # a block where the greedy row-then-repair approach is suboptimal:
        # exhaustive search must pick the doubly-balanced argmax
        from apex_tpu.contrib.sparsity import mn_2d_best
        from apex_tpu.contrib.sparsity.sparse_masklib import (
            compute_valid_2d_patterns,
        )

        rngn = np.random.RandomState(3)
        for _ in range(5):
            blk = rngn.randn(4, 4).astype(np.float32)
            mask = np.asarray(mn_2d_best(jnp.asarray(blk), 4, 2))
            pats = compute_valid_2d_patterns(4, 2).reshape(-1, 4, 4)
            best = max(float(np.sum(np.abs(blk) * p)) for p in pats)
            got = float(np.sum(np.abs(blk) * mask))
            assert got == pytest.approx(best, rel=1e-6)

    def test_2d_pattern_count(self):
        from apex_tpu.contrib.sparsity.sparse_masklib import (
            compute_valid_2d_patterns,
        )

        # doubly-balanced 4x4 matrices with row/col sums 2: exactly 90
        assert compute_valid_2d_patterns(4, 2).shape == (90, 16)

    def test_fill(self):
        assert fill(jnp.array([[1.0, 0.0], [0.0, 0.0]])) == 0.25


class TestASP:
    def make_params(self, rng):
        return {
            "dense": {"kernel": jax.random.normal(rng, (32, 16)),
                      "bias": jnp.ones((16,))},
            "norm": {"scale": jnp.ones((32,))},
            "small": {"kernel": jax.random.normal(rng, (4, 4))},
        }

    def test_compute_masks_eligibility(self, rng):
        params = self.make_params(rng)
        masks = compute_sparse_masks(params)
        # eligible: dense/kernel (reduction dim 32); others all-ones
        k = np.asarray(masks["dense"]["kernel"])
        assert (k.T.reshape(-1, 4).sum(axis=1) == 2).all()  # axis=-2
        assert (np.asarray(masks["dense"]["bias"]) == 1).all()
        assert (np.asarray(masks["norm"]["scale"]) == 1).all()
        assert (np.asarray(masks["small"]["kernel"]) == 1).all()

    def test_masked_update_preserves_sparsity(self, rng):
        params = self.make_params(rng)
        masks = compute_sparse_masks(params)
        params = prune(params, masks)
        opt = optax.chain(optax.adam(1e-2), masked_update(masks))
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum(p["dense"]["kernel"] ** 2) + jnp.sum(
                p["small"]["kernel"] ** 2
            )

        for _ in range(3):
            grads = jax.grad(loss_fn)(params)
            updates, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        k = np.asarray(params["dense"]["kernel"])
        zero_pat = np.asarray(masks["dense"]["kernel"]) == 0
        np.testing.assert_array_equal(k[zero_pat], 0.0)
        # unmasked leaves keep training normally
        assert np.abs(np.asarray(params["small"]["kernel"])).sum() > 0

    def test_class_api_prune_trained_model(self, rng):
        asp = ASP()
        assert not asp.is_sparsity_enabled()
        params = self.make_params(rng)
        pruned = asp.prune_trained_model(params)
        assert asp.is_sparsity_enabled()
        k = np.asarray(pruned["dense"]["kernel"])
        assert (np.abs(k).T.reshape(-1, 4) > 0).sum() <= 2 * (32 * 16 // 4)
        opt = asp.init_optimizer_for_pruning(optax.sgd(0.1))
        assert opt.init(pruned) is not None


class TestPermutation:
    def test_search_improves_adversarial_matrix(self):
        # columns arranged so each group of 4 holds 4 equally-large values
        # -> naive 2:4 drops half the magnitude; a permutation that spreads
        # them across groups with the near-zero columns retains almost all
        big = np.ones((8, 8)) * 10.0
        small = np.ones((8, 8)) * 0.01
        mat = np.concatenate([big, small], axis=1)  # groups 0,1 all-big

        def retained(m, mask):
            return float(np.sum(np.abs(m) * np.asarray(mask)))

        naive = retained(mat, m4n2_1d(jnp.asarray(mat)))
        perm, mask = permute_and_mask(mat, max_iters=2000)
        permuted_kept = retained(mat, mask)
        assert permuted_kept > naive * 1.5
        # permutation is a bijection and inverts correctly
        inv = invert_permutation(perm)
        x = jnp.arange(16.0)
        np.testing.assert_array_equal(
            apply_permutation(apply_permutation(x, perm), inv), x
        )

    def test_mask_in_original_order_is_2to4_after_perm(self):
        rngn = np.random.RandomState(0)
        mat = rngn.randn(8, 16).astype(np.float32)
        perm, mask = permute_and_mask(mat, max_iters=500)
        permuted_mask = np.asarray(apply_permutation(mask, perm, axis=-1))
        assert (permuted_mask.reshape(-1, 4).sum(axis=1) == 2).all()


def _retained_after_perm(mat, perm):
    a = np.abs(np.asarray(mat, dtype=np.float64))[:, perm].reshape(
        mat.shape[0], -1, 4
    )
    return float(np.partition(a, 2, axis=-1)[..., 2:].sum())


class TestExhaustiveSearch:
    """Parity with the reference stripe-group search (exhaustive_search.py
    Exhaustive_Search :311; unique-combination count :83-90)."""

    def test_canonical_combination_count(self):
        from apex_tpu.contrib.sparsity.permutation import (
            _unique_group_permutations,
        )

        # predict_unique_combinations: C! / ((M!)^G * G!)
        assert len(_unique_group_permutations(8, 4)) == 35
        assert len(_unique_group_permutations(4, 4)) == 1
        perms = _unique_group_permutations(8, 4)
        np.testing.assert_array_equal(perms[0], np.arange(8))  # identity first
        assert len({tuple(p) for p in map(tuple, perms)}) == 35

    def test_matches_brute_force_on_8_columns(self):
        """With one stripe pair the window IS the matrix: the search must
        find the global optimum over all 8!-column regroupings."""
        from apex_tpu.contrib.sparsity.permutation import (
            _unique_group_permutations,
            exhaustive_search,
        )

        rngn = np.random.RandomState(3)
        for _ in range(5):
            mat = rngn.randn(6, 8).astype(np.float32)
            perm = exhaustive_search(mat)
            got = _retained_after_perm(mat, perm)
            best = max(
                _retained_after_perm(mat, p)
                for p in _unique_group_permutations(8, 4)
            )
            assert got >= best - 1e-5, (got, best)

    def test_beats_or_ties_greedy_on_adversarial(self):
        """VERDICT r2 missing #4's bar: retained magnitude >= greedy on
        adversarial matrices (clustered large columns, the case channel
        permutation exists for)."""
        rngn = np.random.RandomState(7)
        for cols in (16, 32):
            # adversarial: big columns clustered into aligned groups
            big = rngn.randn(16, cols // 2) * 10.0
            small = rngn.randn(16, cols // 2) * 0.01
            mat = np.concatenate([big, small], axis=1).astype(np.float32)
            g = search_for_good_permutation(mat, max_iters=4000)
            e = exhaustive_search(mat, escape_attempts=10)
            assert _retained_after_perm(mat, e) >= _retained_after_perm(
                mat, g
            ) - 1e-4

    def test_is_permutation_and_improves_or_ties_identity(self):
        rngn = np.random.RandomState(11)
        mat = rngn.randn(12, 24).astype(np.float32)
        perm = exhaustive_search(mat)
        assert sorted(perm.tolist()) == list(range(24))
        assert _retained_after_perm(mat, perm) >= _retained_after_perm(
            mat, np.arange(24)
        ) - 1e-6

    def test_escape_attempts_never_hurt(self):
        rngn = np.random.RandomState(13)
        mat = rngn.randn(8, 16).astype(np.float32)
        base = _retained_after_perm(mat, exhaustive_search(mat))
        esc = _retained_after_perm(
            mat, exhaustive_search(mat, escape_attempts=20)
        )
        assert esc >= base - 1e-6


class TestASPRegression:
    def test_late_bound_masks_reference_call_order(self, rng):
        """Reference order: init model -> init optimizer -> compute masks
        (asp.py:53-55) — the chain must see the masks computed LATER."""
        params = {"dense": {"kernel": jax.random.normal(rng, (32, 16))}}
        asp = ASP()
        asp.init_model_for_pruning(params)
        opt = asp.init_optimizer_for_pruning(optax.sgd(0.1))
        asp.compute_sparse_masks(params)  # after optimizer creation
        params = prune(params, asp.masks)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        k = np.asarray(params["dense"]["kernel"])
        zero_pat = np.asarray(asp.masks["dense"]["kernel"]) == 0
        np.testing.assert_array_equal(k[zero_pat], 0.0)

    def test_masks_recomputed_after_jit_are_seen(self, rng):
        """Masks live in the optimizer STATE, so a step jitted before
        compute_sparse_masks still applies masks pushed in later via
        refresh_opt_state (the round-1 closure-constant hazard)."""
        from apex_tpu.contrib.sparsity import replace_masks

        params = {"dense": {"kernel": jax.random.normal(rng, (32, 16))}}
        asp = ASP()
        asp.init_model_for_pruning(params)
        opt = asp.init_optimizer_for_pruning(optax.sgd(0.1))
        state = opt.init(params)  # masks still all-ones here

        @jax.jit
        def step(params, state):
            grads = jax.tree_util.tree_map(jnp.ones_like, params)
            updates, state = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        params, state = step(params, state)  # trace with all-ones masks
        # late compute MUST take the live opt_state (r2 weak #7: the
        # silent-dense path is unrepresentable, not a warning)
        asp2 = ASP()
        asp2.init_model_for_pruning(params)
        opt2 = asp2.init_optimizer_for_pruning(optax.sgd(0.1))
        state2 = opt2.init(params)
        with pytest.raises(RuntimeError, match="stay dense"):
            asp2.compute_sparse_masks(params)
        # the sanctioned repair: retry with the live state, flag clears,
        # and refresh_opt_state keeps working as the manual form
        _, state2 = asp2.compute_sparse_masks(params, state2)
        asp2.compute_sparse_masks(params)  # no longer raises
        state2b = asp2.refresh_opt_state(state2)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state2b, state2,
        )
        _, state = asp.compute_sparse_masks(params, state)
        params = prune(params, asp.masks)
        params, state = step(params, state)  # same trace, new masks
        k = np.asarray(params["dense"]["kernel"])
        zero_pat = np.asarray(asp.masks["dense"]["kernel"]) == 0
        np.testing.assert_array_equal(k[zero_pat], 0.0)
        # replace_masks is a no-op on states without a MaskedState
        plain = optax.sgd(0.1).init(params)
        assert replace_masks(plain, asp.masks) == plain

    def test_prune_trained_model_after_dense_training(self, rng):
        """The reference one-shot recipe (ref asp.py:292) after a dense run
        whose optimizer was initialized on placeholder masks: passing the
        live opt_state returns (pruned_params, refreshed_state)."""
        params = {"dense": {"kernel": jax.random.normal(rng, (32, 16))}}
        asp = ASP()
        asp.init_model_for_pruning(params)
        opt = asp.init_optimizer_for_pruning(optax.sgd(0.1))
        state = opt.init(params)  # placeholder masks
        pruned, state = asp.prune_trained_model(params, state)
        k = np.asarray(pruned["dense"]["kernel"])
        assert ((np.abs(k).T.reshape(-1, 4) > 0).sum(axis=1) <= 2).all()
        # the refreshed state drives sparse updates from here on
        grads = jax.tree_util.tree_map(jnp.ones_like, pruned)
        updates, state = opt.update(grads, state, pruned)
        after = optax.apply_updates(pruned, updates)
        zero_pat = np.asarray(asp.masks["dense"]["kernel"]) == 0
        np.testing.assert_array_equal(
            np.asarray(after["dense"]["kernel"])[zero_pat], 0.0
        )

    def test_embeddings_never_pruned(self, rng):
        params = {
            "embedding": {"embedding": jax.random.normal(rng, (64, 32))},
            "embed_tokens": {"weight": jax.random.normal(rng, (64, 32))},
            "proj": {"kernel": jax.random.normal(rng, (64, 32))},
        }
        masks = compute_sparse_masks(params)
        assert (np.asarray(masks["embedding"]["embedding"]) == 1).all()
        assert (np.asarray(masks["embed_tokens"]["weight"]) == 1).all()
        k = np.asarray(masks["proj"]["kernel"])
        assert (k.T.reshape(-1, 4).sum(axis=1) == 2).all()
