"""Tensor/sequence-parallel tests on the virtual 8-device mesh.

Mirrors the reference's distributed-in-process tier (tests/L0/run_transformer/
test_layers.py, test_mapping.py, test_cross_entropy.py) — here shard_map over
the 'tp' axis of a real Mesh replaces MultiProcessTestCase, and parity is
checked against single-device dense compositions with identical weights.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P
from apex_tpu.compat import shard_map

from apex_tpu.models import GPTModel, gpt_loss_fn
from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.random import checkpoint_distributed
from apex_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer import TransformerConfig

TP = 8
VOCAB = 64


def tp_mesh():
    return parallel_state.initialize_model_parallel(tensor_model_parallel_size=TP)


def tiny_cfg(**kw):
    defaults = dict(
        num_layers=2,
        hidden_size=32,
        num_attention_heads=8,
        vocab_size=VOCAB,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


class TestTPLinears:
    def test_column_parallel_matches_dense(self, rng):
        mesh = tp_mesh()
        x = jax.random.normal(rng, (4, 16), jnp.float32)
        kernel = jax.random.normal(jax.random.fold_in(rng, 1), (16, 24))
        bias = jax.random.normal(jax.random.fold_in(rng, 2), (24,))
        mod = ColumnParallelLinear(output_size=24, gather_output=True)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(None, "tp"), P("tp")),
            out_specs=P(),
            check_vma=False,
        )
        def run(x, k_local, b_local):
            return mod.apply({"params": {"kernel": k_local, "bias": b_local}}, x)

        np.testing.assert_allclose(
            run(x, kernel, bias), x @ kernel + bias, rtol=1e-5, atol=1e-5
        )

    def test_row_parallel_matches_dense(self, rng):
        mesh = tp_mesh()
        x = jax.random.normal(rng, (4, 16), jnp.float32)
        kernel = jax.random.normal(jax.random.fold_in(rng, 1), (16, 24))
        bias = jax.random.normal(jax.random.fold_in(rng, 2), (24,))
        mod = RowParallelLinear(output_size=24, input_is_parallel=False)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P("tp", None), P()),
            out_specs=P(),
            check_vma=False,
        )
        def run(x, k_local, b):
            return mod.apply({"params": {"kernel": k_local, "bias": b}}, x)

        np.testing.assert_allclose(
            run(x, kernel, bias), x @ kernel + bias, rtol=1e-5, atol=1e-5
        )

    def test_vocab_parallel_embedding_matches_dense(self, rng):
        mesh = tp_mesh()
        table = jax.random.normal(rng, (VOCAB, 8))
        ids = jax.random.randint(jax.random.fold_in(rng, 1), (4, 6), 0, VOCAB)
        mod = VocabParallelEmbedding(num_embeddings=VOCAB, embedding_dim=8)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("tp", None), P()),
            out_specs=P(),
            check_vma=False,
        )
        def run(t_local, ids):
            return mod.apply({"params": {"embedding": t_local}}, ids)

        np.testing.assert_allclose(run(table, ids), table[ids], rtol=1e-6, atol=1e-6)

    def test_vocab_parallel_cross_entropy(self, rng):
        mesh = tp_mesh()
        logits = jax.random.normal(rng, (4, 6, VOCAB))
        target = jax.random.randint(jax.random.fold_in(rng, 1), (4, 6), 0, VOCAB)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(None, None, "tp"), P()),
            out_specs=P(),
            check_vma=False,
        )
        def run(logits_local, target):
            return vocab_parallel_cross_entropy(logits_local, target)

        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        ref = lse - jnp.take_along_axis(logits, target[..., None], -1)[..., 0]
        np.testing.assert_allclose(run(logits, target), ref, rtol=1e-5, atol=1e-5)

    def test_column_row_grads_match_dense(self, rng):
        """d/dx and d/dW of Column→gelu→Row == dense MLP grads."""
        mesh = tp_mesh()
        x = jax.random.normal(rng, (4, 16))
        k1 = jax.random.normal(jax.random.fold_in(rng, 1), (16, 32)) * 0.1
        k2 = jax.random.normal(jax.random.fold_in(rng, 2), (32, 16)) * 0.1
        col = ColumnParallelLinear(output_size=32, use_bias=False)
        row = RowParallelLinear(output_size=16, use_bias=False)

        def dense_loss(x, k1, k2):
            return jnp.sum(jax.nn.gelu(x @ k1, approximate=True) @ k2)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(None, "tp"), P("tp", None)),
            out_specs=(P(), P(None, "tp"), P("tp", None)),
            check_vma=False,
        )
        def tp_grads(x, k1l, k2l):
            def loss(x, k1l, k2l):
                h = col.apply({"params": {"kernel": k1l}}, x)
                h = jax.nn.gelu(h, approximate=True)
                y = row.apply({"params": {"kernel": k2l}}, h)
                return jnp.sum(y)

            return jax.grad(loss, argnums=(0, 1, 2))(x, k1l, k2l)

        gx, gk1, gk2 = tp_grads(x, k1, k2)
        rx, rk1, rk2 = jax.grad(dense_loss, argnums=(0, 1, 2))(x, k1, k2)
        np.testing.assert_allclose(gx, rx, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(gk1, rk1, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(gk2, rk2, rtol=1e-4, atol=1e-5)


class TestGPTTensorParallel:
    def _train_losses(self, cfg, rng, steps=10):
        mesh = tp_mesh()
        tokens = jax.random.randint(rng, (4, 16), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=1)
        model = GPTModel(config=cfg)
        opt = optax.adam(1e-3)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        def train(tokens, labels):
            params = model.init(jax.random.PRNGKey(0), tokens)
            opt_state = opt.init(params)

            def step(carry, _):
                params, opt_state = carry

                def loss_fn(p):
                    losses = model.apply(p, tokens, labels=labels)
                    return gpt_loss_fn(losses)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = opt.update(grads, opt_state)
                return (optax.apply_updates(params, updates), opt_state), loss

            (_, _), losses = jax.lax.scan(step, (params, opt_state), None, length=steps)
            return losses

        return np.asarray(train(tokens, labels))

    def test_tp8_loss_decreases(self, rng):
        losses = self._train_losses(tiny_cfg(), rng)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] - 0.2, losses

    def test_tp8_gqa_loss_decreases(self, rng):
        # REAL GQA under tensor parallelism (groups < heads): 16 q heads
        # share 8 kv heads; over tp=8 each rank holds 2 q heads + 1 kv head
        losses = self._train_losses(
            tiny_cfg(num_attention_heads=16, num_query_groups=8), rng
        )
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] - 0.2, losses

    def test_tp8_sequence_parallel_loss_decreases(self, rng):
        losses = self._train_losses(tiny_cfg(sequence_parallel=True), rng)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] - 0.2, losses

    @pytest.mark.parametrize("gqa", [False, True])
    def test_tp_kv_cache_decode_matches_full_forward(self, rng, gqa):
        """KV-cache decoding with the cache sharded over tp (heads split
        across ranks): per-step decode logits must equal full-forward
        slices on every rank's vocab shard."""
        mesh = tp_mesh()
        kw = dict(num_attention_heads=16, num_query_groups=8) if gqa else {}
        model = GPTModel(config=tiny_cfg(**kw))
        tokens = jax.random.randint(rng, (2, 12), 0, VOCAB)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def run(tokens):
            variables = model.init(jax.random.PRNGKey(0), tokens[:, :1])
            full = model.apply(variables, tokens)  # (b, 12, vocab_local)
            logits, st = model.apply(
                variables, tokens[:, :5], cache_len=12, mutable=["cache"]
            )
            cache = st["cache"]
            err = jnp.max(jnp.abs(logits - full[:, :5]))
            for pos in range(5, 12):
                sl, upd = model.apply(
                    {**variables, "cache": cache},
                    tokens[:, pos : pos + 1],
                    position_ids=jnp.full((1, 1), pos),
                    decode_step=True,
                    mutable=["cache"],
                )
                cache = upd["cache"]
                err = jnp.maximum(
                    err, jnp.max(jnp.abs(sl[:, 0] - full[:, pos]))
                )
            return jax.lax.pmax(err, "tp")

        assert float(run(tokens)) < 2e-5

    def test_sp_kv_cache_decode_matches_full_forward(self, rng):
        """KV-cache decode under sequence parallelism (VERDICT r4 item 8,
        formerly a NotImplementedError guard): prefill keeps full SP — the
        column linears gather the sequence, so the cache holds full-length
        K/V — while each decode step runs in plain-TP layout (a single
        replicated token cannot be sequence-sharded).  Per-step decode
        logits must equal full-forward slices on every rank's vocab
        shard."""
        mesh = tp_mesh()
        model = GPTModel(config=tiny_cfg(sequence_parallel=True))
        tokens = jax.random.randint(rng, (2, 16), 0, VOCAB)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def run(tokens):
            variables = model.init(jax.random.PRNGKey(0), tokens[:, :8])
            full = model.apply(variables, tokens)  # (b, 16, vocab_local)
            logits, st = model.apply(
                variables, tokens[:, :8], cache_len=16, mutable=["cache"]
            )
            cache = st["cache"]
            # the SP head gathers the sequence, so prefill logits are
            # full-length just like the uncached forward's
            err = jnp.max(jnp.abs(logits - full[:, :8]))
            for pos in range(8, 16):
                sl, upd = model.apply(
                    {**variables, "cache": cache},
                    tokens[:, pos : pos + 1],
                    position_ids=jnp.full((1, 1), pos),
                    decode_step=True,
                    mutable=["cache"],
                )
                cache = upd["cache"]
                err = jnp.maximum(
                    err, jnp.max(jnp.abs(sl[:, 0] - full[:, pos]))
                )
            return jax.lax.pmax(err, "tp")

        assert float(run(tokens)) < 2e-5

    def test_sp_matches_non_sp(self, rng):
        """Same per-rank params ⇒ identical losses with/without SP (the SP
        mappings are pure re-partitionings; ref mappings.py:213-272)."""
        mesh = tp_mesh()
        tokens = jax.random.randint(rng, (2, 16), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=1)
        m_sp = GPTModel(config=tiny_cfg(sequence_parallel=True))
        m_np = GPTModel(config=tiny_cfg(sequence_parallel=False))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
            check_vma=False,
        )
        def run(tokens, labels):
            params = m_np.init(jax.random.PRNGKey(0), tokens)
            l_np = gpt_loss_fn(m_np.apply(params, tokens, labels=labels))
            l_sp = gpt_loss_fn(m_sp.apply(params, tokens, labels=labels))
            return l_np, l_sp

        l_np, l_sp = run(tokens, labels)
        np.testing.assert_allclose(l_np, l_sp, rtol=1e-5, atol=1e-6)

    def test_bert_sp_loss_and_grads_match_non_sp(self, rng):
        """BERT post-process heads under SP: loss and grads must equal the
        non-SP path with identical per-rank params (guards the dual-head
        gather backward composition in models/bert.py)."""
        from apex_tpu.models import BertModel

        mesh = tp_mesh()
        tokens = jax.random.randint(rng, (2, 16), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=1)
        amask = jnp.ones_like(tokens)
        m_sp = BertModel(config=tiny_cfg(sequence_parallel=True))
        m_np = BertModel(config=tiny_cfg(sequence_parallel=False))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P(), P(), P()), check_vma=False,
        )
        def run(tokens, labels, amask):
            params = m_np.init(jax.random.PRNGKey(0), tokens, amask)

            def loss_fn(mod, p):
                losses, binary = mod.apply(p, tokens, amask, lm_labels=labels)
                return jnp.mean(losses) + jnp.mean(binary**2)

            l_np, g_np = jax.value_and_grad(lambda p: loss_fn(m_np, p))(params)
            l_sp, g_sp = jax.value_and_grad(lambda p: loss_fn(m_sp, p))(params)

            def gnorm2(g):
                # identical reduction for both paths (psum over tp), so the
                # equality check is valid for sharded and replicated leaves
                total = sum(
                    jnp.sum(x.astype(jnp.float32) ** 2)
                    for x in jax.tree.leaves(g)
                )
                return jax.lax.psum(total, "tp")

            return l_np, l_sp, gnorm2(g_np), gnorm2(g_sp)

        l_np, l_sp, g_np, g_sp = run(tokens, labels, amask)
        np.testing.assert_allclose(l_np, l_sp, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(g_np, g_sp, rtol=1e-4, atol=1e-6)


class TestCheckpointDistributed:
    def test_value_and_grads_match_plain_checkpoint(self, rng):
        """ref random.py:246-266 distribute_saved_activations: partitioning
        the saved boundary activation over tp must not change math."""
        tp = 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp, devices=jax.devices()[:tp]
        )
        w = jax.random.normal(rng, (16, 16)) * 0.3
        x = jax.random.normal(jax.random.fold_in(rng, 1), (8, 16))

        def fn(x, w):
            return jnp.sum(jnp.tanh(x @ w) ** 2)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
            check_vma=False,
        )
        def run(x, w):
            return jax.value_and_grad(
                lambda w_: checkpoint_distributed(fn)(x, w_)
            )(w)

        loss, grads = run(x, w)
        ref_loss, ref_grads = jax.value_and_grad(lambda w_: fn(x, w_))(w)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
        np.testing.assert_allclose(grads, ref_grads, rtol=1e-5, atol=1e-7)

    def test_grad_wrt_boundary_input(self, rng):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, devices=jax.devices()[:2]
        )
        x = jax.random.normal(rng, (8, 16))

        def fn(x):
            return jnp.sum(jnp.sin(x) * x)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def run(x):
            return jax.grad(lambda x_: checkpoint_distributed(fn)(x_))(x)

        np.testing.assert_allclose(
            run(x), jax.grad(lambda x_: fn(x_))(x), rtol=1e-5, atol=1e-7
        )


class TestMeshConstruction:
    def test_default_devices_topology_path(self):
        """Default device list goes through mesh_utils (CPU falls back to
        plain order); axis sizes must match the requested factorization."""
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2
        )
        assert dict(mesh.shape) == {"dp": 2, "pp": 2, "cp": 1, "tp": 2}

    def test_hybrid_requires_dp_divisible_by_slices(self):
        with pytest.raises(RuntimeError, match="num_slices"):
            parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=2, num_slices=3
            )

    def test_initialize_distributed_single_process_noop(self, monkeypatch):
        """No args + no cluster env = deterministic no-op, even with
        backends long since initialized — no exception matching. (The
        cluster vars are scrubbed: this machine's TPU relay exports
        TPU_WORKER_HOSTNAMES without being a multi-host cluster.)"""
        for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                  "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES",
                  "MEGASCALE_COORDINATOR_ADDRESS"):
            monkeypatch.delenv(v, raising=False)
        n, i = parallel_state.initialize_distributed()
        assert (n, i) == (jax.process_count(), jax.process_index())
        # idempotent second call
        assert parallel_state.initialize_distributed() == (n, i)

    def test_hybrid_rejects_explicit_devices(self):
        with pytest.raises(ValueError, match="explicit devices"):
            parallel_state.initialize_model_parallel(
                devices=jax.devices()[:4], num_slices=2
            )


class TestAmaxReduction:
    def test_pmax_over_dp_and_tp(self, rng):
        """Ref parallel_state.py:280-292: the amax group spans tp x dp
        within a pipeline stage — every rank holding a shard of the same
        activations agrees on one scaling statistic."""
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2, pipeline_model_parallel_size=2
        )

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=P("dp", "tp"), out_specs=P("dp", "tp"),
            check_vma=False,
        )
        def reduce(x):
            return parallel_state.amax_reduction(jnp.max(jnp.abs(x)))[
                None, None
            ]

        x = jax.random.normal(rng, (4, 8))
        out = np.asarray(reduce(x))
        # every (dp, tp) shard agrees on the global max over dp x tp shards
        assert (out == out.flat[0]).all()
        np.testing.assert_allclose(out.flat[0], np.abs(np.asarray(x)).max(),
                                   rtol=1e-6)

    def test_trivial_axes_are_noop_outside_shard_map(self):
        """With every amax axis trivial (dp=cp=tp=1, all devices on pp) the
        host-view call is well-defined and passes through."""
        parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=8
        )
        v = jnp.asarray(3.0)
        np.testing.assert_allclose(parallel_state.amax_reduction(v), 3.0)

    def test_misuse_outside_shard_map_raises(self):
        """Outside shard_map over a >1 axis the statistic would silently
        miss the other shards — hardened to raise (VERDICT r3 weak #4)."""
        parallel_state.initialize_model_parallel()  # dp=8
        with pytest.raises(RuntimeError, match="outside shard_map"):
            parallel_state.amax_reduction(jnp.asarray(3.0))


class TestRankAccessorMisuse:
    """Mesh accessors must raise on host-view misuse, not act as rank 0."""

    def test_rank_outside_shard_map_raises(self):
        parallel_state.initialize_model_parallel(tensor_model_parallel_size=8)
        with pytest.raises(RuntimeError, match="outside shard_map"):
            parallel_state.get_tensor_model_parallel_rank()

    def test_trivial_axis_rank_is_zero(self):
        parallel_state.initialize_model_parallel(tensor_model_parallel_size=8)
        assert parallel_state.get_data_parallel_rank() == 0  # dp == 1

    def test_rank_inside_shard_map_still_works(self):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=8
        )

        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P("tp"),
                           out_specs=P("tp"), check_vma=False)
        def ranks(x):
            return x + parallel_state.get_tensor_model_parallel_rank()

        out = np.asarray(ranks(jnp.zeros(8, jnp.int32)))
        np.testing.assert_array_equal(out, np.arange(8))

    def test_tp_rank_init_outside_shard_map_raises(self):
        from apex_tpu.parallel.layers import tp_rank_init

        parallel_state.initialize_model_parallel(tensor_model_parallel_size=8)
        init = tp_rank_init(jax.nn.initializers.normal())
        with pytest.raises(RuntimeError, match="outside shard_map"):
            init(jax.random.PRNGKey(0), (4, 4))
