"""Reference import-surface parity: the names apex user code imports must
exist at the same paths with the package root substituted (ref:
apex/transformer/__init__.py, apex/parallel/__init__.py,
apex/normalization/__init__.py, apex/mlp, apex/fused_dense)."""

import jax


def test_transformer_namespace():
    import apex_tpu.transformer as T

    # ref transformer/__init__.py __all__
    for name in ("amp", "functional", "parallel_state", "pipeline_parallel",
                 "tensor_parallel", "utils", "LayerType", "AttnType",
                 "AttnMaskType"):
        assert hasattr(T, name), name

    from apex_tpu.transformer.tensor_parallel import (  # noqa: F401
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
        broadcast_data,
        checkpoint,
        copy_to_tensor_model_parallel_region,
        gather_from_tensor_model_parallel_region,
        reduce_from_tensor_model_parallel_region,
        scatter_to_tensor_model_parallel_region,
        split_tensor_along_last_dim,
        vocab_parallel_cross_entropy,
    )
    from apex_tpu.transformer.pipeline_parallel import (  # noqa: F401
        build_model,
        get_forward_backward_func,
    )
    from apex_tpu.transformer.functional import (  # noqa: F401
        FusedScaleMaskSoftmax,
        fused_apply_rotary_pos_emb,
        fused_apply_rotary_pos_emb_cached,
    )
    from apex_tpu.transformer.amp import GradScaler  # noqa: F401


def test_parallel_namespace():
    # ref apex/parallel/__init__.py: DDP, SyncBatchNorm family, LARC
    from apex_tpu.parallel import (  # noqa: F401
        LARC,
        DistributedDataParallel,
        Reducer,
        SyncBatchNorm,
        convert_syncbn_model,
    )


def test_module_class_packages():
    from apex_tpu.normalization import (  # noqa: F401
        FusedLayerNorm,
        FusedRMSNorm,
        MixedFusedLayerNorm,
        MixedFusedRMSNorm,
    )
    from apex_tpu.mlp import MLP  # noqa: F401
    from apex_tpu.fused_dense import (  # noqa: F401
        FusedDense,
        FusedDenseGeluDense,
    )


def test_cached_rope_matches_freqs_form(rng):
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops.rope import (
        apply_rotary_pos_emb,
        apply_rotary_pos_emb_cached,
        rope_frequencies,
    )

    t = jax.random.normal(rng, (8, 2, 4, 32))
    freqs = rope_frequencies(16, 8)  # partial rotation, pass-through tail
    ref = apply_rotary_pos_emb(t, freqs)
    out = apply_rotary_pos_emb_cached(t, jnp.cos(freqs), jnp.sin(freqs))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_top_level_package_aliases():
    """Every reference top-level package has a same-named apex_tpu path
    (ref: ls /root/reference/apex — RNN, amp, contrib, fp16_utils,
    fused_dense, mlp, multi_tensor_apply, normalization, optimizers,
    parallel, transformer)."""
    import importlib

    for name in ("RNN", "amp", "contrib", "fp16_utils", "fused_dense",
                 "mlp", "multi_tensor_apply", "normalization", "optimizers",
                 "parallel", "transformer"):
        importlib.import_module(f"apex_tpu.{name}")

    from apex_tpu.RNN import GRU, LSTM, ReLU, Tanh, mLSTM, models  # noqa: F401
    from apex_tpu.multi_tensor_apply import (
        MultiTensorApply,
        multi_tensor_applier,
    )

    # the shim instance forwards to the engine with the ref call contract:
    # applier(op, noop_flag, tensor_lists, *args) -> op's return
    import jax.numpy as jnp
    import numpy as np

    assert multi_tensor_applier.available  # ref gating attribute
    applier = MultiTensorApply(2048 * 32)
    noop = jnp.zeros((), jnp.int32)

    def scale_op(noop_flag, tensor_lists, s):
        return [[t * s for t in tl] for tl in tensor_lists], noop_flag

    out, flag = applier(scale_op, noop, [[jnp.ones(4)]], 2.0)
    np.testing.assert_allclose(np.asarray(out[0][0]), 2.0)


def test_import_apex_tpu_exposes_subpackages():
    """`import apex_tpu; apex_tpu.amp...` works like `import apex`
    (ref apex/__init__.py __all__)."""
    import apex_tpu

    assert callable(apex_tpu.amp.initialize)
    assert callable(apex_tpu.optimizers.FusedAdam)
    assert apex_tpu.normalization.FusedLayerNorm is not None
    assert apex_tpu.parallel.DistributedDataParallel is not None
    assert apex_tpu.transformer.TransformerConfig is not None
    assert apex_tpu.fp16_utils.FP16_Optimizer is not None


def test_contrib_path_parity():
    """Every reference contrib package path resolves under apex_tpu.contrib
    (ref: ls /root/reference/apex/contrib) — each imported EXPLICITLY, not
    via the contrib __init__'s eager imports, so a future lazy __init__
    cannot silently void this guarantee."""
    import importlib

    for name in ("bottleneck", "clip_grad", "conv_bias_relu", "cudnn_gbn",
                 "fmha", "focal_loss", "group_norm", "groupbn",
                 "index_mul_2d", "layer_norm", "multihead_attn",
                 "openfold_triton", "optimizers", "peer_memory", "sparsity",
                 "transducer", "xentropy"):
        importlib.import_module(f"apex_tpu.contrib.{name}")

    from apex_tpu.contrib.clip_grad import clip_grad_norm_  # noqa: F401
    from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d  # noqa: F401
    from apex_tpu.contrib.fmha import fmha  # noqa: F401
    from apex_tpu.contrib.layer_norm import FastLayerNorm  # noqa: F401
    from apex_tpu.contrib.openfold_triton import (  # noqa: F401
        FusedAdamSWA,
        LayerNormSmallShapeOptImpl,
    )
    from apex_tpu.contrib.optimizers import (  # noqa: F401
        DistributedFusedAdam,
        DistributedFusedLAMB,
        FP16_Optimizer,
    )
    from apex_tpu.contrib.peer_memory import halo_exchange_1d  # noqa: F401
