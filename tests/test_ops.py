"""Tests for softmax family, RoPE, xentropy, fused dense, MLP, flash attention.

Mirrors reference tests/L0/run_transformer/test_fused_softmax.py,
test_fused_rope.py, contrib/test/xentropy, contrib/test/fmha,
tests/L0/run_mlp/test_mlp.py — numeric comparison against straightforward
compositions.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    scaled_softmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
    fused_scale_mask_softmax,
    apply_rotary_pos_emb,
    rope_frequencies,
    softmax_cross_entropy_loss,
    fused_dense,
    fused_dense_gelu_dense,
    mlp_init,
    mlp_apply,
    flash_attention,
)


class TestSoftmax:
    def test_scaled_softmax(self, rng):
        x = jax.random.normal(rng, (2, 4, 8, 8))
        out = scaled_softmax(x, 0.5)
        ref = jax.nn.softmax(x * 0.5, axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_scaled_masked_softmax(self, rng):
        k1, k2 = jax.random.split(rng)
        x = jax.random.normal(k1, (2, 4, 8, 8))
        mask = jax.random.bernoulli(k2, 0.3, (2, 1, 8, 8))
        out = scaled_masked_softmax(x, mask, 2.0)
        ref = jax.nn.softmax(jnp.where(mask, -10000.0, x * 2.0), axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_causal_softmax_masks_future(self, rng):
        x = jax.random.normal(rng, (3, 8, 8))
        out = np.asarray(scaled_upper_triang_masked_softmax(x, 1.0))
        # strictly-upper entries must be ~0
        upper = np.triu(np.ones((8, 8)), k=1).astype(bool)
        assert np.all(out[:, upper] < 1e-3)
        np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)

    def test_dispatcher_causal_matches(self, rng):
        x = jax.random.normal(rng, (2, 4, 8, 8))
        out = fused_scale_mask_softmax(x, scale=0.7, causal=True)
        ref = scaled_upper_triang_masked_softmax(x.reshape(8, 8, 8), 0.7).reshape(
            2, 4, 8, 8
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


class TestRope:
    def test_rope_shapes_and_norm_preserved(self, rng):
        t = jax.random.normal(rng, (16, 2, 4, 32))  # (s, b, h, d)
        freqs = rope_frequencies(32, 16)
        out = apply_rotary_pos_emb(t, freqs)
        assert out.shape == t.shape
        # rotation preserves per-pair norms -> total norm preserved
        np.testing.assert_allclose(
            float(jnp.linalg.norm(out)), float(jnp.linalg.norm(t)), rtol=1e-5
        )

    def test_rope_partial_rotation_passthrough(self, rng):
        t = jax.random.normal(rng, (8, 1, 2, 64))
        freqs = rope_frequencies(32, 8)
        out = apply_rotary_pos_emb(t, freqs)
        np.testing.assert_allclose(
            np.asarray(out[..., 32:]), np.asarray(t[..., 32:]), atol=1e-7
        )

    def test_rope_position_zero_identity(self, rng):
        t = jax.random.normal(rng, (4, 1, 1, 16))
        freqs = rope_frequencies(16, 4)
        out = apply_rotary_pos_emb(t, freqs)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(t[0]), atol=1e-6)


class TestXentropy:
    def test_matches_manual_ce(self, rng):
        k1, k2 = jax.random.split(rng)
        logits = jax.random.normal(k1, (10, 50))
        labels = jax.random.randint(k2, (10,), 0, 50)
        loss = softmax_cross_entropy_loss(logits, labels)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ref = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), atol=1e-5)

    def test_label_smoothing(self, rng):
        k1, k2 = jax.random.split(rng)
        logits = jax.random.normal(k1, (10, 50))
        labels = jax.random.randint(k2, (10,), 0, 50)
        s = 0.1
        loss = softmax_cross_entropy_loss(logits, labels, smoothing=s)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        smooth = -jnp.mean(logp, axis=-1)
        ref = (1 - s) * nll + s * smooth
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), atol=1e-5)

    def test_grad_is_softmax_minus_onehot(self, rng):
        logits = jax.random.normal(rng, (4, 10))
        labels = jnp.array([1, 2, 3, 4])
        g = jax.grad(lambda l: softmax_cross_entropy_loss(l, labels).sum())(logits)
        p = jax.nn.softmax(logits, -1)
        onehot = jax.nn.one_hot(labels, 10)
        np.testing.assert_allclose(np.asarray(g), np.asarray(p - onehot), atol=1e-5)


class TestDenseMlp:
    def test_fused_dense(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        x = jax.random.normal(k1, (5, 16))
        w = jax.random.normal(k2, (8, 16))
        b = jax.random.normal(k3, (8,))
        np.testing.assert_allclose(
            np.asarray(fused_dense(x, w, b)), np.asarray(x @ w.T + b), atol=1e-5
        )

    def test_fused_dense_gelu_dense(self, rng):
        ks = jax.random.split(rng, 5)
        x = jax.random.normal(ks[0], (5, 16))
        w1 = jax.random.normal(ks[1], (32, 16))
        b1 = jax.random.normal(ks[2], (32,))
        w2 = jax.random.normal(ks[3], (8, 32))
        b2 = jax.random.normal(ks[4], (8,))
        out = fused_dense_gelu_dense(x, w1, b1, w2, b2)
        ref = jax.nn.gelu(x @ w1.T + b1, approximate=True) @ w2.T + b2
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_mlp_matches_manual(self, rng):
        params = mlp_init(rng, [16, 32, 32, 4])
        x = jax.random.normal(jax.random.PRNGKey(5), (7, 16))
        out = mlp_apply(params, x, activation="relu")
        h = x
        for i, (w, b) in enumerate(zip(params["weights"], params["biases"])):
            h = h @ w.T + b
            if i < 2:
                h = jax.nn.relu(h)
        np.testing.assert_allclose(np.asarray(out), np.asarray(h), atol=1e-5)

    def test_mlp_grad_flows(self, rng):
        params = mlp_init(rng, [8, 16, 4])
        x = jax.random.normal(jax.random.PRNGKey(3), (5, 8))
        g = jax.grad(lambda p: jnp.sum(mlp_apply(p, x) ** 2))(params)
        assert all(
            float(jnp.abs(gw).sum()) > 0 for gw in jax.tree_util.tree_leaves(g)
        )


class TestFlashAttention:
    def _ref(self, q, k, v, causal):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
        if causal:
            sq, sk = s.shape[-2:]
            cm = jnp.arange(sk)[None, :] > jnp.arange(sq)[:, None]
            s = jnp.where(cm, -1e30, s)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_forward(self, rng, causal, impl):
        k1, k2, k3 = jax.random.split(rng, 3)
        q = jax.random.normal(k1, (2, 2, 256, 64))
        k = jax.random.normal(k2, (2, 2, 256, 64))
        v = jax.random.normal(k3, (2, 2, 256, 64))
        out = flash_attention(q, k, v, causal=causal, impl=impl)
        ref = self._ref(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("h_kv", [4, 2, 1])
    def test_blockwise_matches_xla(self, rng, causal, h_kv):
        """Long-context tiled path vs the dense reference: forced via
        impl='blockwise' with small tiles so several (cq, ck) chunks and
        the band bounds are actually exercised."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        q = jax.random.normal(k1, (2, 4, 256, 32))
        k = jax.random.normal(k2, (2, h_kv, 256, 32))
        v = jax.random.normal(k3, (2, h_kv, 256, 32))
        out = flash_attention(q, k, v, causal=causal, impl="blockwise",
                              block_q=8, block_k=8)  # cq = ck = 64
        ref = flash_attention(q, k, v, causal=causal, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

        ct = jax.random.normal(k4, q.shape)

        def loss(impl):
            def f(q, k, v):
                o = flash_attention(q, k, v, causal=causal, impl=impl,
                                    block_q=8, block_k=8)
                return jnp.sum(o * ct)
            return f

        gb = jax.grad(loss("blockwise"), (0, 1, 2))(q, k, v)
        gr = jax.grad(loss("xla"), (0, 1, 2))(q, k, v)
        for a, b in zip(gb, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_blockwise_window_and_kpm(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        q = jax.random.normal(k1, (2, 2, 256, 32))
        k = jax.random.normal(k2, (2, 2, 256, 32))
        v = jax.random.normal(k3, (2, 2, 256, 32))
        out = flash_attention(q, k, v, causal=True, window=100,
                              impl="blockwise", block_q=8, block_k=8)
        ref = flash_attention(q, k, v, causal=True, window=100, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

        kpm = jnp.zeros((2, 256), bool).at[0, 180:].set(True).at[1, :].set(True)
        out = flash_attention(q, k, v, key_padding_mask=kpm,
                              impl="blockwise", block_q=8, block_k=8)
        ref = flash_attention(q, k, v, key_padding_mask=kpm, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        # fully-padded batch row -> exact zeros (kernel-path contract)
        assert not np.any(np.asarray(out)[1])

        ct = jax.random.normal(k4, q.shape)
        gb = jax.grad(lambda q: jnp.sum(ct * flash_attention(
            q, k, v, key_padding_mask=kpm, impl="blockwise",
            block_q=8, block_k=8)))(q)
        gr = jax.grad(lambda q: jnp.sum(ct * flash_attention(
            q, k, v, key_padding_mask=kpm, impl="xla")))(q)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(gr), atol=5e-5)

    def test_blockwise_non_divisible_lengths(self, rng):
        """Prime sequence lengths must run padded full-size tiles, not
        degrade the chunk toward 1 (advisor finding r3): sq=131, sk=257
        have no useful divisors, so this exercises the front-padding path
        (pq, pk > 0) including causal band alignment, window, kpm, grads."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        q = jax.random.normal(k1, (2, 4, 131, 32))
        k = jax.random.normal(k2, (2, 2, 257, 32))
        v = jax.random.normal(k3, (2, 2, 257, 32))
        for kwargs in ({}, {"causal": True}, {"causal": True, "window": 60}):
            out = flash_attention(q, k, v, impl="blockwise",
                                  block_q=8, block_k=8, **kwargs)
            ref = flash_attention(q, k, v, impl="xla", **kwargs)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, err_msg=str(kwargs))

        kpm = jnp.zeros((2, 257), bool).at[0, 200:].set(True)
        out = flash_attention(q, k, v, key_padding_mask=kpm,
                              impl="blockwise", block_q=8, block_k=8)
        ref = flash_attention(q, k, v, key_padding_mask=kpm, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

        ct = jax.random.normal(k4, q.shape)
        gb = jax.grad(lambda q, k, v: jnp.sum(ct * flash_attention(
            q, k, v, causal=True, impl="blockwise", block_q=8, block_k=8)),
            (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(ct * flash_attention(
            q, k, v, causal=True, impl="xla")), (0, 1, 2))(q, k, v)
        for a, b in zip(gb, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_blockwise_rectangular_causal(self, rng):
        # sq != sk causal (bottom-right aligned) — the kernel path refuses
        # this; blockwise covers it exactly
        k1, k2, k3 = jax.random.split(rng, 3)
        q = jax.random.normal(k1, (1, 2, 64, 32))
        k = jax.random.normal(k2, (1, 2, 256, 32))
        v = jax.random.normal(k3, (1, 2, 256, 32))
        out = flash_attention(q, k, v, causal=True, impl="blockwise",
                              block_q=4, block_k=8)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_long_context_autodispatch(self, rng, monkeypatch):
        """Past the VMEM-residency / score-tensor budgets, auto dispatch
        must pick the tiled path (budgets shrunk so the test stays small)."""
        import apex_tpu.ops.attention as attn_mod

        called = {}
        real = attn_mod._attn_blockwise

        def spy(*a, **kw):
            called["yes"] = True
            return real(*a, **kw)

        monkeypatch.setattr(attn_mod, "_attn_blockwise", spy)
        monkeypatch.setattr(attn_mod, "_SCORE_BYTES", 1024)
        k1, k2, k3 = jax.random.split(rng, 3)
        q = jax.random.normal(k1, (1, 2, 128, 32))
        k = jax.random.normal(k2, (1, 2, 128, 32))
        v = jax.random.normal(k3, (1, 2, 128, 32))
        out = flash_attention(q, k, v, causal=True, impl="xla")
        assert called.get("yes"), "oversized XLA case did not tile"
        ref = self._ref(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla(self, rng, causal):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        shape = (1, 2, 128, 64)
        q = jax.random.normal(k1, shape)
        k = jax.random.normal(k2, shape)
        v = jax.random.normal(k3, shape)
        ct = jax.random.normal(k4, shape)

        def loss(impl):
            return lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=causal, impl=impl) * ct
            )

        gp = jax.grad(loss("pallas"), (0, 1, 2))(q, k, v)
        gr = jax.grad(loss("xla"), (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    @pytest.mark.parametrize("window", [1, 64, 200, 1000])
    def test_sliding_window_matches_dense_mask(self, rng, window):
        """Windowed-causal (mistral) vs an explicit band mask through the
        dense reference — windows below, straddling, and beyond the 128
        block size, plus the degenerate window=1 (self-only) and a window
        larger than the sequence (== plain causal)."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        shape = (2, 2, 256, 64)
        q = jax.random.normal(k1, shape)
        k = jax.random.normal(k2, shape)
        v = jax.random.normal(k3, shape)
        ct = jax.random.normal(k4, shape)

        sq = shape[2]
        rows = jnp.arange(sq)[:, None]
        cols = jnp.arange(sq)[None, :]
        band = jnp.logical_or(cols > rows, cols <= rows - window)

        out = flash_attention(q, k, v, causal=True, window=window, impl="pallas")
        ref = flash_attention(q, k, v, mask=band[None, None], impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

        gp = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True, window=window,
                                impl="pallas") * ct
            ),
            (0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, mask=band[None, None], impl="xla") * ct
            ),
            (0, 1, 2),
        )(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("h_kv", [1, 2])
    def test_gqa_matches_broadcast_reference(self, rng, causal, h_kv):
        """Grouped-query attention: kv with h_kv heads through the Pallas
        kernels must equal full attention over explicitly repeated kv heads
        (consecutive llama grouping), fwd and all grads — including the
        group-sum of the per-q-head dk/dv partials."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        h, sq, d = 4, 128, 64
        q = jax.random.normal(k1, (2, h, sq, d))
        k = jax.random.normal(k2, (2, h_kv, sq, d))
        v = jax.random.normal(k3, (2, h_kv, sq, d))
        ct = jax.random.normal(k4, (2, h, sq, d))
        group = h // h_kv
        k_rep = jnp.repeat(k, group, axis=1)
        v_rep = jnp.repeat(v, group, axis=1)

        out = flash_attention(q, k, v, causal=causal, impl="pallas")
        ref = flash_attention(q, k_rep, v_rep, causal=causal, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

        def loss(impl, grouped):
            def f(q, k, v):
                o = flash_attention(q, k, v, causal=causal, impl=impl)
                return jnp.sum(o * ct)

            return f

        gq, gk, gv = jax.grad(loss("pallas", True), (0, 1, 2))(q, k, v)
        rq, rk_rep, rv_rep = jax.grad(loss("xla", False), (0, 1, 2))(
            q, k_rep, v_rep
        )
        # repeated-kv reference grads sum over each group
        rk = rk_rep.reshape(2, h_kv, group, sq, d).sum(axis=2)
        rv = rv_rep.reshape(2, h_kv, group, sq, d).sum(axis=2)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=5e-5)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=5e-5)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=5e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_key_padding_mask_matches_xla(self, rng, causal):
        """Pallas fast path with (b, sk) key padding — the reference fmha's
        variable-seqlen capability. One batch row is fully padded to pin the
        exp(-inf - lse) guard."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        shape = (3, 2, 128, 64)
        q = jax.random.normal(k1, shape)
        k = jax.random.normal(k2, shape)
        v = jax.random.normal(k3, shape)
        ct = jax.random.normal(k4, shape)
        # row 0: valid prefix 70; row 1: no padding; row 2: ALL padded
        kpm = np.zeros((3, 128), bool)
        kpm[0, 70:] = True
        kpm[2, :] = True
        kpm = jnp.asarray(kpm)

        out_p = flash_attention(q, k, v, causal=causal,
                                key_padding_mask=kpm, impl="pallas")
        out_x = flash_attention(q, k, v, causal=causal,
                                key_padding_mask=kpm, impl="xla")
        # fully-padded rows are ZERO in both impls (no uniform-softmax
        # leakage of padded v values), finite everywhere, never nan
        assert bool(jnp.all(jnp.isfinite(out_p)))
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x), atol=5e-5)
        np.testing.assert_allclose(np.asarray(out_p[2]), 0.0, atol=0.0)

        # grads INCLUDE the dead row's output in the loss on purpose: the
        # o=0 convention must be differentiable-consistent (all-zero grads
        # for that row) in BOTH impls, not just when the loss masks it
        def loss(impl):
            def f(q, k, v):
                o = flash_attention(q, k, v, causal=causal,
                                    key_padding_mask=kpm, impl=impl)
                return jnp.sum(o * ct)

            return f

        gp = jax.grad(loss("pallas"), (0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            assert bool(jnp.all(jnp.isfinite(a)))
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
        # the dead batch row's q/k/v receive exactly zero gradient
        for a in gp:
            np.testing.assert_allclose(np.asarray(a[2]), 0.0, atol=0.0)

    def test_bf16_gqa_window_compose(self, rng):
        """All three fast-path features at once — bf16 operands, grouped kv,
        sliding window — against the fp32 repeated-kv dense-band reference."""
        k1, k2, k3 = jax.random.split(rng, 3)
        q = jax.random.normal(k1, (2, 4, 128, 64), jnp.float32)
        k = jax.random.normal(k2, (2, 2, 128, 64), jnp.float32)
        v = jax.random.normal(k3, (2, 2, 128, 64), jnp.float32)

        out_b = flash_attention(
            q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16), causal=True, window=40, impl="pallas",
        )
        rows = jnp.arange(128)[:, None]
        cols = jnp.arange(128)[None, :]
        band = jnp.logical_or(cols > rows, cols <= rows - 40)
        ref = flash_attention(
            q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
            mask=band[None, None], impl="xla",
        )
        assert out_b.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out_b, np.float32), np.asarray(ref), atol=0.08
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_bf16_fwd_bwd_close_to_fp32_ref(self, rng, causal):
        """bf16 path: the kernel keeps dot OPERANDS in bf16 (p and ds are
        cast back down before their dots — the MXU-rate flash recipe) with
        fp32 accumulation/softmax.  Gate: within a few bf16 ulps of the
        all-fp32 reference, fwd and bwd — this is the only test where the
        kernel's bf16 casts are not no-ops."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        shape = (1, 2, 128, 64)
        qf = jax.random.normal(k1, shape)
        kf = jax.random.normal(k2, shape)
        vf = jax.random.normal(k3, shape)
        ct = jax.random.normal(k4, shape)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))

        out_b = flash_attention(qb, kb, vb, causal=causal, impl="pallas")
        ref_f = self._ref(qf, kf, vf, causal)
        # |out| <= max|v| ~ 4; bf16 eps ~ 8e-3 -> a few ulps of headroom
        np.testing.assert_allclose(
            np.asarray(out_b, np.float32), np.asarray(ref_f), atol=0.08
        )

        def loss(impl, dt):
            return lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=causal, impl=impl).astype(
                    jnp.float32
                ) * ct
            )

        gb = jax.grad(loss("pallas", jnp.bfloat16), (0, 1, 2))(qb, kb, vb)
        gf = jax.grad(loss("xla", jnp.float32), (0, 1, 2))(qf, kf, vf)
        for a, b in zip(gb, gf):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b), atol=0.35
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_multiblock(self, rng, causal):
        """seq > block forces the backward kernels' inner block loops (and
        the causal lo/hi bounds) to run over several blocks."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        shape = (1, 2, 256, 32)
        q = jax.random.normal(k1, shape)
        k = jax.random.normal(k2, shape)
        v = jax.random.normal(k3, shape)
        ct = jax.random.normal(k4, shape)

        def loss(impl):
            return lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=causal, impl=impl,
                                block_q=64, block_k=64) * ct
            )

        gp = jax.grad(loss("pallas"), (0, 1, 2))(q, k, v)
        gr = jax.grad(loss("xla"), (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_grads_rectangular_kv(self, rng):
        """sk > sq (cross-attention shape) through the Pallas backward."""
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        q = jax.random.normal(k1, (1, 2, 64, 32))
        k = jax.random.normal(k2, (1, 2, 192, 32))
        v = jax.random.normal(k3, (1, 2, 192, 32))
        ct = jax.random.normal(k4, (1, 2, 64, 32))

        def loss(impl):
            return lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, impl=impl, block_q=64, block_k=64) * ct
            )

        gp = jax.grad(loss("pallas"), (0, 1, 2))(q, k, v)
        gr = jax.grad(loss("xla"), (0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_mask_path(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        q = jax.random.normal(k1, (2, 2, 64, 32))
        k = jax.random.normal(k2, (2, 2, 64, 32))
        v = jax.random.normal(k3, (2, 2, 64, 32))
        mask = jax.random.bernoulli(k4, 0.2, (2, 1, 64, 64))
        out = flash_attention(q, k, v, mask=mask)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(32)
        s = jnp.where(mask, -1e30, s)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestModuleStyleSurfaces:
    """apex.mlp / apex.fused_dense import-surface parity: flax module
    classes over the functional ops (ref mlp/mlp.py:33,
    fused_dense/fused_dense.py:64,82)."""

    def test_mlp_module_matches_functional(self, rng):
        from apex_tpu.mlp import MLP
        from apex_tpu.ops.mlp import mlp_apply

        sizes = [16, 32, 8]
        m = MLP(mlp_sizes=sizes, activation="relu")
        x = jax.random.normal(rng, (4, 16))
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        # rebuild the functional param pytree from the module params
        p = params["params"]
        fparams = {
            "weights": [p["weight_0"], p["weight_1"]],
            "biases": [p["bias_0"], p["bias_1"]],
        }
        ref = mlp_apply(fparams, x, activation="relu")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)
        # init matches reset_parameters (ref mlp/mlp.py:71-79): weights
        # ~ N(0, sqrt(2/(fan_in+fan_out))) — check the std statistically
        w_wide = MLP(mlp_sizes=[256, 256]).init(
            jax.random.PRNGKey(7), jnp.ones((1, 256))
        )["params"]["weight_0"]
        std = float(jnp.std(w_wide))
        expect = (2.0 / 512.0) ** 0.5
        assert abs(std - expect) / expect < 0.1, (std, expect)

    def test_mlp_module_rejects_bad_activation(self, rng):
        from apex_tpu.mlp import MLP

        with pytest.raises(TypeError, match="activation"):
            MLP(mlp_sizes=[4, 4], activation="tanh").init(
                jax.random.PRNGKey(0), jnp.ones((2, 4))
            )

    def test_fused_dense_modules(self, rng):
        from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense

        x = jax.random.normal(rng, (4, 16))
        m = FusedDense(in_features=16, out_features=8)
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        w = params["params"]["weight"]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x @ w.T), atol=1e-5
        )
        m2 = FusedDenseGeluDense(in_features=16, intermediate_features=32,
                                 out_features=8, bias=True)
        p2 = m2.init(jax.random.PRNGKey(1), x)
        out2 = m2.apply(p2, x)
        assert out2.shape == (4, 8) and bool(jnp.all(jnp.isfinite(out2)))
        # reference ctor kwarg: bias=False supported on FusedDense only
        m3 = FusedDense(in_features=16, out_features=8, bias=False)
        p3 = m3.init(jax.random.PRNGKey(2), x)
        assert "bias" not in p3["params"]
