"""Behavioral tests for parallel/ddp.py (the reference's
apex.parallel.DistributedDataParallel semantics, parallel/distributed.py:131).

What the reference's 600 lines of bucketed-NCCL machinery ultimately
guarantee is pinned here directly on the 8-device mesh: DP-averaged grads
equal the full-batch gradient, predivide trades fp16 overflow headroom
exactly as documented (distributed.py:439-455), allreduce_always_fp32
accumulates in fp32 and hands back the original dtype, and the init-time
param broadcast makes rank 0 authoritative.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import HAS_VMA, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import (
    DistributedDataParallel,
    Reducer,
    all_reduce_gradients,
    broadcast_params,
)

_requires_vma = pytest.mark.skipif(
    not HAS_VMA,
    reason="asserts vma-typing semantics (jax.lax.pcast / "
           "varying-vs-unvarying grads) absent on check_rep-era jax",
)


@pytest.fixture
def mesh():
    return Mesh(np.asarray(jax.devices()), ("dp",))


def _loss(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


class TestAllReduceGradients:
    def test_dp_grads_equal_full_batch_grad(self, mesh, rng):
        """mean over equal shards of per-shard grads == full-batch grad —
        THE data-parallel correctness property."""
        k1, k2, k3 = jax.random.split(rng, 3)
        x = jax.random.normal(k1, (32, 8))
        y = jax.random.normal(k2, (32, 1))
        params = {
            "w": jax.random.normal(k3, (8, 1)),
            "b": jnp.zeros((1,)),
        }
        full = jax.grad(_loss)(params, x, y)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P("dp"), P("dp")), out_specs=P(),
        )
        def dp_grads(params, x, y):
            g = jax.grad(_loss)(params, x, y)
            return all_reduce_gradients(g, "dp")

        got = dp_grads(params, x, y)
        for k in full:
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(full[k]), rtol=1e-5, atol=1e-6
            )

    @_requires_vma
    def test_predivide_buys_fp16_overflow_headroom(self, mesh):
        """Per-rank VARYING fp16 grads of 30000: a postdivide sum
        overflows fp16 (8 x 30000 >> 65504 -> inf) while
        predivide_factor=8 keeps every partial in range and lands the
        mean — the reference's stated reason for
        gradient_predivide_factor (distributed.py:439-455)."""

        def reduce(factor):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=P(), out_specs=P()
            )
            def run(g):
                g = jax.lax.pcast(g, "dp", to="varying")
                return all_reduce_gradients(
                    {"g": g}, "dp", gradient_predivide_factor=factor
                )["g"]

            return run(jnp.float16(30000.0))

        assert not np.isfinite(np.asarray(reduce(1.0)))  # postdivide: inf
        np.testing.assert_allclose(
            np.asarray(reduce(8.0)), 30000.0, rtol=1e-3
        )  # predivide: in-range mean (fp16 sequential-sum rounding)

    @_requires_vma
    def test_allreduce_always_fp32_keeps_dtype_and_value(self, mesh):
        """fp32 accumulation around the psum rescues the same overflow case
        WITHOUT predivide, and the result comes back in the grads' dtype."""

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P()
        )
        def run(g):
            g = jax.lax.pcast(g, "dp", to="varying")
            return all_reduce_gradients(
                {"g": g}, "dp", allreduce_always_fp32=True
            )["g"]

        out = run(jnp.float16(30000.0))
        assert out.dtype == jnp.float16
        np.testing.assert_allclose(np.asarray(out), 30000.0)

    @_requires_vma
    def test_pmean_global_loss_grads_are_final_skip_allreduce(self, mesh):
        """The documented pmean'd-GLOBAL-loss regime (the SyncBatchNorm
        pattern): under checked shard_map those grads arrive unvarying and
        ALREADY AVERAGED — they equal the full-batch gradient with NO call
        to all_reduce_gradients, and calling it anyway silently divides by
        N again (the unvarying type cannot tell a sum from a mean).  Pins
        the docstring's 'skip this function' guidance."""
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
        x = jax.random.normal(k1, (32, 8))
        y = jax.random.normal(k2, (32, 1))
        params = {
            "w": jax.random.normal(k3, (8, 1)),
            "b": jnp.zeros((1,)),
        }
        full = jax.grad(_loss)(params, x, y)

        def run(call_allreduce):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(P(), P("dp"), P("dp")), out_specs=P(),
            )
            def dp_grads(params, x, y):
                g = jax.grad(
                    lambda p: jax.lax.pmean(_loss(p, x, y), "dp")
                )(params)
                return all_reduce_gradients(g, "dp") if call_allreduce else g

            return dp_grads(params, x, y)

        got = run(call_allreduce=False)
        for k in full:
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(full[k]), rtol=1e-5, atol=1e-6
            )
        # the trap, pinned so a refactor can't silently change it: the
        # already-reduced branch has no way to know these are means
        wrong = run(call_allreduce=True)
        np.testing.assert_allclose(
            np.asarray(wrong["w"]), np.asarray(full["w"]) / 8.0,
            rtol=1e-5, atol=1e-7,
        )

    def test_sum_mode_when_average_off(self, mesh):
        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
        )
        def run(g):
            return all_reduce_gradients({"g": g}, "dp", gradient_average=False)["g"]

        g = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        np.testing.assert_allclose(np.asarray(run(g)), np.full((8, 1), 28.0))


class TestBroadcastAndReducer:
    def test_broadcast_params_makes_rank0_authoritative(self, mesh):
        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
        )
        def run(p):
            # per-rank distinct params (leading dp dim sliced by shard_map)
            out = broadcast_params({"w": p}, "dp")
            return out["w"]

        p = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 5.0
        np.testing.assert_allclose(np.asarray(run(p)), np.full((8, 1), 5.0))

    def test_reducer_means_tree(self, mesh):
        red = Reducer("dp")

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P()
        )
        def run(x):
            return red.reduce({"x": x})["x"]

        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        np.testing.assert_allclose(np.asarray(run(x)), [[3.5]])

    def test_reducer_passes_replicated_leaves_through(self, mesh):
        """An already-replicated leaf is its own cross-rank mean — a psum
        would return 8x the value."""
        red = Reducer("dp")

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P()
        )
        def run(x):
            return red.reduce({"x": x})["x"]

        np.testing.assert_allclose(float(run(jnp.float32(5.0))), 5.0)


class TestDistributedDataParallel:
    def test_value_and_grad_returns_synced_grads(self, mesh, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        x = jax.random.normal(k1, (32, 8))
        y = jax.random.normal(k2, (32, 1))
        params = {"w": jax.random.normal(k3, (8, 1)), "b": jnp.zeros((1,))}
        ddp = DistributedDataParallel(loss_fn=_loss)
        full = jax.grad(_loss)(params, x, y)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(), P("dp"), P("dp")), out_specs=(P("dp"), P()),
        )
        def step(params, x, y):
            loss, grads = ddp.value_and_grad()(params, x, y)
            return loss[None], grads

        losses, grads = step(params, x, y)
        assert losses.shape == (8,)
        for k in full:
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(full[k]), rtol=1e-5,
                atol=1e-6,
            )
