"""Static-analysis subsystem (apex_tpu.analysis): jaxpr auditors, AST
lint framework, compiled-HLO passes, allowlist machinery, and the repo
self-check.

Every pass gets a hand-built miniature step with ONE known violation
(bad promotion, rejected donation, non-permutation ppermute, mismatched
pipeline edge, host callback, mis-sharded matmul, transpose-synthesized
backward collective, dead psum, oversized replicated entry buffer)
asserting exact Finding fields, plus a clean-function negative test —
the auditors must find exactly what is seeded and nothing else. The HLO
side additionally pins the GPT dp2xtp2 target's hand-counted collective
inventory (per-axis op counts AND bytes, exact). The self-check at the
bottom is the acceptance gate: ``python -m apex_tpu.analysis`` (lint +
jaxpr + HLO passes over the GPT/BERT step targets on the dp2xtp2 CPU
mesh) must exit 0 against the repo as committed.
"""

import functools
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.compat import shard_map
from apex_tpu.monitor.xray import ledger as xlax
from jax.sharding import PartitionSpec as P

from apex_tpu.analysis import (
    Allowlist,
    AllowlistEntry,
    Finding,
    StepTarget,
    merge_findings,
    run_passes,
)
from apex_tpu.analysis.donation import audit_donation
from apex_tpu.analysis.lint import run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THIS_FILE = "tests/test_analysis.py"


def mesh1d(n, name):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), (name,))


def mesh2d(a, b, names):
    return jax.sharding.Mesh(
        np.array(jax.devices()[: a * b]).reshape(a, b), names
    )


# ---------------------------------------------------------------------------
# findings + allowlist machinery


class TestFindingsAndAllowlist:
    def test_bare_allowlist_entry_rejected(self):
        with pytest.raises(ValueError, match="reason"):
            AllowlistEntry(rule="precision.promotion", match="x.py", reason="  ")

    def test_entry_matching_rule_glob_and_site(self):
        e = AllowlistEntry(
            rule="precision.*", match="apex_tpu/ops/", reason="stats in f32"
        )
        hit = Finding(rule="precision.promotion", message="m",
                      site="apex_tpu/ops/layer_norm.py:52")
        miss_rule = Finding(rule="donation.missed", message="m",
                            site="apex_tpu/ops/layer_norm.py:52")
        miss_site = Finding(rule="precision.promotion", message="m",
                            site="apex_tpu/models/gpt.py:1")
        assert e.matches(hit)
        assert not e.matches(miss_rule)
        assert not e.matches(miss_site)

    def test_merge_findings_sums_counts(self):
        a = Finding(rule="r", message="m", site="s", count=2)
        b = Finding(rule="r", message="m", site="s", count=3)
        c = Finding(rule="r", message="m", site="other")
        merged = merge_findings([a, b, c])
        assert sorted(f.count for f in merged) == [1, 5]

    def test_apply_partitions_and_detects_stale(self):
        al = Allowlist([
            AllowlistEntry(rule="r", match="ok.py", reason="fine"),
            AllowlistEntry(rule="r", match="gone.py", reason="was fine",
                           require_hit=True),
        ])
        res = al.apply([Finding(rule="r", message="m", site="ok.py:1"),
                        Finding(rule="r", message="m", site="bad.py:1")])
        assert [f.site for f in res.findings] == ["bad.py:1"]
        assert len(res.suppressed) == 1
        assert [e.match for e in res.stale_entries] == ["gone.py"]
        assert not res.ok

    def test_info_findings_do_not_fail(self):
        res = Allowlist().apply(
            [Finding(rule="r", message="m", site="s", severity="info")]
        )
        assert res.ok

    def test_records_share_router_schema(self):
        from apex_tpu import monitor

        res = Allowlist([
            AllowlistEntry(rule="r", match="b.py", reason="documented why"),
        ]).apply([
            Finding(rule="r", message="kept", site="a.py:1"),
            Finding(rule="r", message="hidden", site="b.py:2"),
        ])
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        for rec in res.to_records(step=7):
            router.emit(rec)
        assert len(mem.records) == 2
        for rec in mem.records:
            assert {"t", "step", "kind", "rule", "site"} <= set(rec)
            assert rec["kind"] == "analysis" and rec["step"] == 7
        allowed = [r for r in mem.records if r["allowed"]]
        assert len(allowed) == 1 and allowed[0]["reason"] == "documented why"

    def test_repo_allowlist_every_entry_carries_a_reason(self):
        from apex_tpu.analysis.allowlist import REPO_ALLOWLIST

        assert len(REPO_ALLOWLIST) > 0
        for e in REPO_ALLOWLIST.entries:
            # a reason must be a sentence someone can review, not a token
            assert len(e.reason.split()) >= 5, (e.rule, e.match)


# ---------------------------------------------------------------------------
# precision auditor


class TestPrecisionPass:
    def test_seeded_promotion_exact_fields(self):
        def step(x):
            return x.astype(jnp.float32).sum()  # the seeded violation

        tgt = StepTarget(
            name="seeded", fn=step,
            args=(jax.ShapeDtypeStruct((4,), jnp.bfloat16),),
        )
        (f,) = run_passes(tgt, passes=["precision"])
        assert f.rule == "precision.promotion"
        assert f.severity == "error"
        assert f.target == "seeded"
        assert f.count == 1
        assert f.data == {"from": "bfloat16", "to": "float32"}
        assert f.site.startswith(THIS_FILE + ":")

    def test_promotion_found_inside_nested_scan(self):
        def step(x):
            def body(c, _):
                return c + x.astype(jnp.float32).sum(), None

            out, _ = jax.lax.scan(body, 0.0, None, length=3)
            return out

        tgt = StepTarget(
            name="t", fn=step, args=(jax.ShapeDtypeStruct((4,), jnp.bfloat16),)
        )
        fins = run_passes(tgt, passes=["precision"])
        assert [f.rule for f in fins] == ["precision.promotion"]

    def test_f64_flagged(self):
        from jax.experimental import enable_x64

        def step(x):
            return x.astype(jnp.float64) * 2

        with enable_x64():
            tgt = StepTarget(
                name="t", fn=step,
                args=(jax.ShapeDtypeStruct((2,), jnp.float32),),
            )
            fins = run_passes(tgt, passes=["precision"])
        rules = {f.rule for f in fins}
        assert rules == {"precision.f64"}
        assert all(f.severity == "error" for f in fins)
        prims = {f.data["primitive"] for f in fins}
        assert "convert_element_type" in prims

    def test_clean_bf16_step_no_findings(self):
        # no reduction on purpose: jnp.sum of a bf16 array upcasts its
        # accumulator to f32 (a REAL promotion the pass would flag)
        def step(x, w):
            return jnp.tanh(x @ w) * 2

        tgt = StepTarget(
            name="t", fn=step,
            args=(jax.ShapeDtypeStruct((4, 4), jnp.bfloat16),
                  jax.ShapeDtypeStruct((4, 4), jnp.bfloat16)),
        )
        assert run_passes(tgt, passes=["precision"]) == []


# ---------------------------------------------------------------------------
# collective-safety validator


class TestCollectivePass:
    def test_unknown_axis_flagged(self):
        mesh_dp = mesh1d(2, "dp")
        mesh_tp = mesh1d(2, "tp")  # the ambient mesh the pass audits against

        @functools.partial(
            shard_map, mesh=mesh_dp, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return xlax.psum(x, "dp")

        tgt = StepTarget(name="t", fn=step, args=(jnp.ones((2,)),),
                         mesh=mesh_tp)
        fins = run_passes(tgt, passes=["collective"])
        (f,) = [f for f in fins if f.rule == "collective.unknown-axis"]
        assert f.severity == "error"
        assert f.data == {"op": "psum", "axis": "dp"}
        assert f.site.startswith(THIS_FILE + ":")

    def test_size1_axis_flagged_as_dead_traffic(self):
        mesh = mesh2d(2, 1, ("dp", "pp"))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return xlax.psum(x, "pp")  # size-1 axis: dead traffic

        # the ledger elides size-1 axes from RECORDING, but the primitive
        # is still in the jaxpr — exactly what this pass exists to flag
        tgt = StepTarget(name="t", fn=step, args=(jnp.ones((2,)),), mesh=mesh)
        (f,) = run_passes(tgt, passes=["collective"])
        assert f.rule == "collective.dead-traffic"
        assert f.severity == "warning"
        assert f.data == {"op": "psum", "axis": "pp"}

    def test_non_permutation_ppermute_flagged(self):
        mesh = mesh1d(4, "pp")

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            # rank 0 sends twice: not a permutation (jax traces it fine,
            # which is why the static check exists)
            return xlax.ppermute(x, "pp", [(0, 1), (0, 2)])

        (f,) = run_passes(StepTarget(name="t", fn=step, args=(jnp.ones((2,)),),
                                     mesh=mesh), passes=["collective"])
        assert f.rule == "collective.non-permutation"
        assert f.severity == "error"
        assert "duplicate source" in f.message
        assert f.data["axis"] == "pp"

    def test_mismatched_pipeline_edge_flagged(self):
        mesh = mesh1d(4, "pp")

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            # stage 1's outgoing edge is missing: stages 2..3 wait on a
            # stream that never crosses the gap
            return xlax.ppermute(x, "pp", [(0, 1), (2, 3)])

        (f,) = run_passes(StepTarget(name="t", fn=step, args=(jnp.ones((2,)),),
                                     mesh=mesh), passes=["collective"])
        assert f.rule == "collective.mismatched-edge"
        assert f.severity == "error"
        assert f.data["gaps"] == "[1]"

    def test_p2p_edge_grammar_is_clean(self):
        """Every edge constructor in parallel/pipeline/p2p.py must pass
        the validator — the schedules build all their edges from these."""
        from apex_tpu.parallel.pipeline import p2p

        mesh = mesh1d(4, "pp")
        for edges in (p2p.forward_edges(4), p2p.backward_edges(4),
                      p2p.ring_edges(4), p2p.last_to_first_edges(4)):

            @functools.partial(
                shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            )
            def step(x, edges=edges):
                return xlax.ppermute(x, "pp", edges)

            fins = run_passes(StepTarget(name="t", fn=step,
                                         args=(jnp.ones((2,)),), mesh=mesh),
                              passes=["collective"])
            assert fins == [], (edges, [f.format() for f in fins])

    def test_real_pipeline_schedule_validates_clean(self):
        """The 1F1B schedule (fwd AND the transposed backward edges jax
        synthesizes through the scan) contains only valid chains."""
        from apex_tpu.parallel.pipeline import schedules

        mesh = mesh1d(4, "pp")

        def stage_fn(p, x):
            return jnp.tanh(x @ p)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False,
        )
        def step(p, mb, tg):
            loss, _, grads = (
                schedules.forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, p, mb, tg, axis_name="pp"
                )
            )
            return loss

        p = jnp.ones((4, 4))
        mb = jnp.ones((4, 2, 4))
        fins = run_passes(StepTarget(name="pp1f1b", fn=step, args=(p, mb, mb),
                                     mesh=mesh), passes=["collective"])
        assert fins == [], [f.format() for f in fins]

    def test_chain_gaps_unit(self):
        from apex_tpu.analysis.collectives import chain_gaps

        assert chain_gaps([(0, 1), (1, 2), (2, 3)], 4) == []
        assert chain_gaps([(1, 0), (2, 1), (3, 2)], 4) == []
        assert chain_gaps([(0, 1), (2, 3)], 4) == [1]
        assert chain_gaps([(0, 1), (3, 4)], 8) == [1, 2]
        # rings / wrap edges / shuffles have no linear-chain semantics
        assert chain_gaps([(0, 1), (1, 2), (2, 3), (3, 0)], 4) is None
        assert chain_gaps([(3, 0)], 4) is None
        assert chain_gaps([(0, 2), (2, 0)], 4) is None


# ---------------------------------------------------------------------------
# host-sync detector


class TestHostSyncPass:
    def test_debug_print_flagged(self):
        def step(x):
            jax.debug.print("loss={l}", l=x.sum())  # the seeded violation
            return x * 2

        (f,) = run_passes(
            StepTarget(name="t", fn=step, args=(jnp.ones((4,)),)),
            passes=["host-sync"],
        )
        assert f.rule == "host-sync.callback"
        assert f.severity == "error"
        assert f.data == {"primitive": "debug_callback"}
        assert f.site.startswith(THIS_FILE + ":")

    def test_pure_callback_flagged(self):
        def step(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v) * 2,
                jax.ShapeDtypeStruct((4,), jnp.float32), x,
            )
            return y.sum()

        (f,) = run_passes(
            StepTarget(name="t", fn=step, args=(jnp.ones((4,)),)),
            passes=["host-sync"],
        )
        assert f.rule == "host-sync.callback"
        assert f.data == {"primitive": "pure_callback"}

    def test_clean_step_no_findings(self):
        def step(x):
            return (x @ x).sum()

        assert run_passes(
            StepTarget(name="t", fn=step, args=(jnp.ones((4, 4)),)),
            passes=["host-sync"],
        ) == []


# ---------------------------------------------------------------------------
# donation auditor


class TestDonationAuditor:
    MiB = 1 << 20

    def test_rejected_donation_exact_fields(self):
        def step(a, b):
            return b * 2.0  # 'a' donated but no output matches it

        a = jax.ShapeDtypeStruct((512, 512), jnp.float32)  # 1 MiB
        b = jax.ShapeDtypeStruct((8,), jnp.float32)
        fins = audit_donation(step, a, b, donate_argnums=(0,),
                              arg_names=("a", "b"), target="seeded")
        (f,) = [f for f in fins if f.rule == "donation.rejected"]
        assert f.severity == "error"
        assert f.data["leaf"] == "a"
        assert f.data["stage"] == "lowering"
        assert f.data["bytes"] == self.MiB
        assert f.target == "seeded"

    def test_missed_donation_flagged(self):
        def step(p, o, x):
            new_p = jax.tree_util.tree_map(lambda l: l - 0.1 * x.sum(), p)
            new_o = jax.tree_util.tree_map(lambda l: l + 1.0, o)
            return new_p, new_o

        p = {"w": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        o = {"m": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        x = jax.ShapeDtypeStruct((4,), jnp.float32)
        # p donated, o forgotten: o matches an un-aliased output
        fins = audit_donation(step, p, o, x, donate_argnums=(0,),
                              arg_names=("params", "opt_state", "x"))
        (f,) = [f for f in fins if f.rule == "donation.missed"]
        assert f.severity == "warning"
        assert f.data["leaf"] == "opt_state['m']"
        assert f.data["bytes"] == self.MiB

    def test_clean_donation_no_findings(self):
        def step(p, o, x):
            new_p = jax.tree_util.tree_map(lambda l: l - 0.1 * x.sum(), p)
            new_o = jax.tree_util.tree_map(lambda l: l + 1.0, o)
            return new_p, new_o

        p = {"w": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        o = {"m": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        x = jax.ShapeDtypeStruct((4,), jnp.float32)
        assert audit_donation(step, p, o, x, donate_argnums=(0, 1)) == []

    def test_prejitted_step_uses_its_own_donation(self):
        def step(p, x):
            return jax.tree_util.tree_map(lambda l: l - x.sum(), p)

        p = {"w": jnp.ones((512, 512))}
        x = jnp.ones((4,))
        jitted = jax.jit(step, donate_argnums=(0,))
        assert audit_donation(jitted, p, x) == []

    def test_pass_skipped_without_donation_intent(self):
        tgt = StepTarget(name="t", fn=lambda x: x * 2,
                         args=(jnp.ones((4,)),), donate_argnums=None)
        assert run_passes(tgt, passes=["donation"]) == []


# ---------------------------------------------------------------------------
# AST lint framework


class TestLintFramework:
    def test_raw_collective_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "from jax import lax\n\n\ndef f(x):\n"
                "    return lax.psum(x, 'tp')\n",
        }
        (f,) = run_lint(rules=["lint.raw-collective"], files=files)
        assert f.rule == "lint.raw-collective"
        assert f.site == "apex_tpu/fake.py:5"
        assert f.data == {"op": "psum"}

    def test_raw_collective_docstring_mention_not_flagged(self):
        files = {
            "apex_tpu/fake.py":
                '"""docs mention jax.lax.psum freely"""\n'
                "# and comments: lax.all_gather\n",
        }
        assert run_lint(rules=["lint.raw-collective"], files=files) == []

    def test_float64_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "import jax.numpy as jnp\nimport numpy as np\nimport numpy\n"
                "x = jnp.float64(3.0)\n"
                "y = np.float64(3.0)  # host-side: fine\n"
                "z = numpy.float64(3.0)  # host-side too: fine\n"
                "w = jax.numpy.float64(3.0)\n",
        }
        fins = run_lint(rules=["lint.float64"], files=files)
        # only the jax spellings: jnp.float64 and jax.numpy.float64
        assert sorted(f.site for f in fins) == [
            "apex_tpu/fake.py:4", "apex_tpu/fake.py:7",
        ]
        assert all(f.rule == "lint.float64" for f in fins)

    def test_rule_scopes_enforced_by_registry(self):
        # raw-collective is scoped to apex_tpu/: the same violation under
        # examples/ is out of scope and must not be flagged
        files = {
            "examples/fake.py":
                "from jax import lax\n\n\ndef f(x):\n"
                "    return lax.psum(x, 'tp')\n",
        }
        assert run_lint(rules=["lint.raw-collective"], files=files) == []

    def test_jit_donate_seeded_and_data_calls_exempt(self):
        files = {
            "examples/fake.py":
                "import functools, jax\n"
                "step = jax.jit(lambda x: x, donate_argnums=(0,))\n"
                "tgt = StepTarget(fn=step, donate_argnums=(0,))\n"
                "part = functools.partial(jax.jit, donate_argnums=(1,))\n",
        }
        fins = run_lint(rules=["lint.jit-donate"], files=files)
        # the jax.jit call and the partial(jax.jit) are flagged; the
        # StepTarget DECLARATION (auditing intent, not a jit) is not
        assert sorted(f.site for f in fins) == [
            "examples/fake.py:2", "examples/fake.py:4",
        ]

    def test_signal_handlers_seeded(self):
        # raw registration in library code (both the plain and the repo's
        # `import signal as _signal` spellings) and the import-hiding
        # `from signal import signal` form are all flagged
        files = {
            "apex_tpu/fake.py":
                "import signal\nimport signal as _signal\n"
                "signal.signal(signal.SIGTERM, lambda *a: None)\n"
                "_signal.signal(_signal.SIGINT, lambda *a: None)\n"
                "from signal import signal\n",
            "examples/fake.py":
                "import signal\n"
                "signal.signal(signal.SIGTERM, lambda *a: None)\n",
        }
        fins = run_lint(rules=["lint.signal-handlers"], files=files)
        assert sorted(f.site for f in fins) == [
            "apex_tpu/fake.py:3", "apex_tpu/fake.py:4",
            "apex_tpu/fake.py:5", "examples/fake.py:2",
        ]
        assert all(f.rule == "lint.signal-handlers" for f in fins)

    def test_signal_handlers_reads_not_flagged(self):
        # getsignal / SIG constants / os.kill are reads or delivery, not
        # registration — the rule polices rewiring only
        files = {
            "apex_tpu/fake.py":
                "import os, signal as _signal\n"
                "h = _signal.getsignal(_signal.SIGTERM)\n"
                "os.kill(os.getpid(), _signal.SIGTERM)\n",
        }
        assert run_lint(rules=["lint.signal-handlers"], files=files) == []

    def test_signal_handlers_blessed_homes_allowlisted(self):
        # the two homes exist, are flagged by the raw rule, and are the
        # ONLY apex_tpu/examples sites (require_hit entries go stale if
        # either registration moves)
        fins = run_lint(rules=["lint.signal-handlers"])
        homes = {f.site.rsplit(":", 1)[0] for f in fins}
        assert homes == {"apex_tpu/utils/autoresume.py",
                         "apex_tpu/monitor/router.py"}

    def test_nondeterminism_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "import random, time\n"
                "import numpy as np\n"
                "a = random.random()\n"
                "b = np.random.rand(3)\n"
                "c = time.time()\n"
                "d = (None or random).uniform(0, 1)\n",
        }
        fins = run_lint(rules=["lint.nondeterminism"], files=files)
        assert sorted(f.site for f in fins) == [
            "apex_tpu/fake.py:3", "apex_tpu/fake.py:4",
            "apex_tpu/fake.py:5", "apex_tpu/fake.py:6",
        ]
        assert {f.data["call"] for f in fins} == {
            "random.random", "np.random.rand", "time.time",
            "random.uniform",
        }

    def test_nondeterminism_seeded_constructs_and_clocks_exempt(self):
        # seeded constructors PIN determinism, jax.random is functional,
        # and monotonic clocks are durations — none of these are the
        # unreproducible inputs the rule polices
        files = {
            "apex_tpu/fake.py":
                "import random, time\n"
                "import numpy as np\n"
                "import jax\n"
                "rng = np.random.RandomState(0)\n"
                "g = np.random.default_rng(7)\n"
                "r = random.Random(3)\n"
                "x = rng.uniform(0, 1)\n"
                "y = random.Random(3).random()\n"
                "z = r.random()\n"
                "random.seed(0)\n"
                "np.random.seed(0)\n"
                "k = jax.random.uniform(jax.random.PRNGKey(0), (2,))\n"
                "t0 = time.monotonic(); t1 = time.perf_counter()\n",
        }
        assert run_lint(rules=["lint.nondeterminism"], files=files) == []

    def test_nondeterminism_repo_scan_fully_explained(self):
        # the ONLY library sites are the two allowlisted homes (retry
        # jitter, record timestamps) — anything new must carry a reason
        fins = run_lint(rules=["lint.nondeterminism"])
        homes = {f.site.rsplit(":", 1)[0] for f in fins}
        assert homes == {"apex_tpu/resilience/retry.py",
                         "apex_tpu/monitor/router.py"}
        from apex_tpu.analysis.allowlist import repo_allowlist as _ral

        res = _ral().apply(fins, check_stale=False)
        assert res.ok

    def test_serving_clock_seeded(self):
        files = {
            "apex_tpu/serving/fake.py":
                "import time\n"
                "import time as _time\n"
                "from time import monotonic\n"
                "a = time.time()\n"
                "b = time.monotonic()\n"
                "c = _time.monotonic_ns()\n",
        }
        fins = run_lint(rules=["lint.serving-clock"], files=files)
        assert sorted(f.site for f in fins) == [
            "apex_tpu/serving/fake.py:3", "apex_tpu/serving/fake.py:4",
            "apex_tpu/serving/fake.py:5", "apex_tpu/serving/fake.py:6",
        ]
        assert {f.data.get("call") for f in fins if "call" in f.data} == {
            "time.time", "time.monotonic", "time.monotonic_ns",
        }

    def test_serving_clock_injection_idiom_exempt(self):
        # the injected-default REFERENCE is the idiom the rule protects;
        # perf_counter is a duration probe and sleep is not a read —
        # none of them feed deadline math off a hidden clock
        files = {
            "apex_tpu/serving/fake.py":
                "import time\n"
                "def f(time_fn=time.monotonic):\n"
                "    now = time_fn()\n"
                "    t0 = time.perf_counter()\n"
                "    time.sleep(0.0)\n"
                "    return now\n",
        }
        assert run_lint(rules=["lint.serving-clock"], files=files) == []
        # scoped to apex_tpu/serving/ only: elsewhere bare clock reads
        # are lint.nondeterminism's business, not this rule's
        outside = {
            "apex_tpu/utils/fake.py": "import time\nt = time.time()\n",
        }
        assert run_lint(rules=["lint.serving-clock"], files=outside) == []

    def test_serving_clock_repo_scan_clean(self):
        # the serving tree speaks injected-clock everywhere, with no
        # allowlist entries needed
        assert run_lint(rules=["lint.serving-clock"]) == []

    def test_registered_taps_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "def mod(self, x):\n"
                "    self.sow('intermediates', 'not_a_real_tap', x)\n",
        }
        fins = run_lint(rules=["lint.registered-taps"], files=files)
        seeded = [f for f in fins if f.data.get("tap") == "not_a_real_tap"]
        assert len(seeded) == 1
        assert seeded[0].site == "apex_tpu/fake.py:2"
        assert not seeded[0].data.get("stale")

    def test_hlo_text_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "def dump(compiled):\n"
                "    return compiled.as_text()\n",
        }
        (f,) = run_lint(rules=["lint.hlo-text"], files=files)
        assert f.rule == "lint.hlo-text"
        assert f.site == "apex_tpu/fake.py:2"
        assert f.severity == "error"

    def test_hlo_text_docstring_mention_not_flagged(self):
        files = {
            "apex_tpu/fake.py":
                '"""docs may say .as_text() freely"""\n'
                "# comments too: compiled.as_text()\n"
                "s = 'as_text'\n",
        }
        assert run_lint(rules=["lint.hlo-text"], files=files) == []

    def test_trace_file_seeded(self):
        # a glob/suffix string is a reader's fingerprint, wherever it
        # appears — docstrings included (unlike hlo-text's NAME tokens,
        # the format marker only ever appears as a string)
        files = {
            "apex_tpu/fake.py":
                "import gzip\n"
                "SUFFIX = '.trace.json.gz'\n",
            "examples/fake2.py":
                '"""reads the *.trace.json export by hand"""\n',
        }
        fins = run_lint(rules=["lint.trace-file"], files=files)
        assert sorted(f.site for f in fins) == [
            "apex_tpu/fake.py:2", "examples/fake2.py:1",
        ]
        assert all(f.rule == "lint.trace-file" for f in fins)
        assert all(f.severity == "error" for f in fins)

    def test_trace_file_fstring_flagged(self):
        # 3.12+ tokenizes f-strings as FSTRING_* (literal text in
        # FSTRING_MIDDLE), not STRING — the rule must catch the reader
        # fingerprint in both spellings on every supported python
        files = {
            "apex_tpu/fake.py": 'p = f"{host}.trace.json.gz"\n',
        }
        (f,) = run_lint(rules=["lint.trace-file"], files=files)
        assert f.site == "apex_tpu/fake.py:1"

    def test_trace_file_comment_mention_not_flagged(self):
        files = {
            "apex_tpu/fake.py":
                "# the parser owns .trace.json reading\n"
                "x = 1\n",
        }
        assert run_lint(rules=["lint.trace-file"], files=files) == []

    def test_unknown_rule_raises(self):
        with pytest.raises(KeyError, match="lint.nope"):
            run_lint(rules=["lint.nope"], files={})


# ---------------------------------------------------------------------------
# compiled-HLO parser (analysis/hlo/parser.py)


SYNTHETIC_HLO = """\
HloModule test_mod, input_output_alias={ {0}: (0, {}, may-alias), {1, 2}: (3, {}, must-alias) }, num_partitions=4

%add.1 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

%while_body.2 (p: (s32[], f32[4])) -> (s32[], f32[4]) {
  %p = (s32[], f32[4]{0}) parameter(0)
  %x = f32[4]{0} get-tuple-element((s32[], f32[4]{0}) %p), index=1
  %ar.1 = f32[4]{0} all-reduce(f32[4]{0} %x), channel_id=1, replica_groups=[2,2]<=[4], use_global_device_ids=true, to_apply=%add.1, metadata={op_name="while/psum" source_file="/repo/a.py" source_line=10}
  %i = s32[] get-tuple-element((s32[], f32[4]{0}) %p), index=0
  ROOT %t = (s32[], f32[4]{0}) tuple(s32[] %i, f32[4]{0} %ar.1)
}

ENTRY %main.9 (p0: f32[4], p1: f32[8,8], p2: f32[2,4]) -> (f32[8], f32[4], f32[4]) {
  %p0 = f32[4]{0} parameter(0), sharding={replicated}, metadata={op_name="params[\\'w\\']"}
  %p1 = f32[8,8]{1,0} parameter(1), sharding={devices=[2,1,2]<=[4] last_tile_dim_replicate}, metadata={op_name="tokens"}
  %p2 = f32[2,4]{1,0} parameter(2), sharding={devices=[1,1,4]<=[4] last_tile_dim_replicate}
  %ags = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %p0), channel_id=2, replica_groups={{0,1},{2,3}}, dimensions={0}, metadata={op_name="jit(f)/all_gather" source_file="/repo/b.py" source_line=20}
  %agd = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ags)
  %cp = f32[4]{0} collective-permute(f32[4]{0} %p0), channel_id=3, source_target_pairs={{0,1},{1,0},{2,3},{3,2}}
  ROOT %r = (f32[8]{0}, f32[4]{0}, f32[4]{0}) tuple(f32[8]{0} %agd, f32[4]{0} %cp, f32[4]{0} %p0)
}
"""


class TestHloParser:
    def test_balanced_is_nesting_safe(self):
        from apex_tpu.analysis.hlo.parser import balanced

        body, end = balanced("x={a={b}, c={d={e}}} tail", 2)
        assert body == "a={b}, c={d={e}}"
        assert end == 19
        with pytest.raises(ValueError):
            balanced("{unclosed", 0)

    def test_balanced_skips_quoted_braces(self):
        # XLA carries a user named_scope verbatim into op_name, so a
        # quoted metadata string may contain braces: an unmatched one
        # must not crash the scan, a matched one must not truncate it
        from apex_tpu.analysis.hlo.parser import balanced

        body, _ = balanced('x={op_name="scope{x" k={v}} tail', 2)
        assert body == 'op_name="scope{x" k={v}'
        body, _ = balanced('x={op_name="a{b}c" k=1} tail', 2)
        assert body == 'op_name="a{b}c" k=1'

    def test_braced_named_scope_in_metadata_parses(self):
        from apex_tpu.analysis.hlo.parser import parse_hlo_module

        hlo = SYNTHETIC_HLO.replace(
            'op_name="while/psum"', 'op_name="while/odd{scope/psum"'
        )
        mod = parse_hlo_module(hlo)
        ar = next(c for c in mod.collectives if c.kind == "all-reduce")
        assert ar.op_name == "while/odd{scope/psum"
        assert ar.source_file == "/repo/a.py" and ar.source_line == 10

    def test_realized_aliases_nested_output_indices(self):
        from apex_tpu.analysis.hlo.parser import realized_aliases

        # tuple output index {1, 2} must map through nesting-safely
        assert realized_aliases(SYNTHETIC_HLO) == {0: 0, 3: 1}

    def test_parse_synthetic_module(self):
        from apex_tpu.analysis.hlo.parser import parse_hlo_module

        mod = parse_hlo_module(SYNTHETIC_HLO)
        assert mod.name == "test_mod"
        assert mod.entry_name == "main.9"
        # collectives everywhere: the while-body all-reduce is found, the
        # -start async form normalizes to its sync kind, -done is skipped
        kinds = sorted(c.kind for c in mod.collectives)
        assert kinds == ["all-gather", "all-reduce", "collective-permute"]
        ar = next(c for c in mod.collectives if c.kind == "all-reduce")
        assert ar.computation == "while_body.2"
        # iota shorthand [2,2]<=[4] expands row-major
        assert ar.replica_groups == ((0, 1), (2, 3))
        assert ar.channel_id == 1
        assert ar.source_file == "/repo/a.py" and ar.source_line == 10
        assert ar.operands[0].elements == 4 and ar.operands[0].nbytes == 16
        ag = next(c for c in mod.collectives if c.kind == "all-gather")
        assert ag.computation == "main.9"
        # ledger convention: the operand (local shard), not the result
        assert ag.elements == 4
        assert ag.op_name == "jit(f)/all_gather"
        # permutes print source_target_pairs, not replica_groups
        cp = next(c for c in mod.collectives
                  if c.kind == "collective-permute")
        assert cp.replica_groups == ()
        assert cp.source_target_pairs == ((0, 1), (1, 0), (2, 3), (3, 2))
        # entry params with shardings and jax's human labels
        assert [p.index for p in mod.entry_params] == [0, 1, 2]
        p0, p1, p2 = mod.entry_params
        assert p0.sharding.fully_replicated and p0.label == "params[\\'w\\']"
        assert not p1.sharding.fully_replicated  # tiled over a real axis
        assert p2.sharding.fully_replicated  # all tile dims 1 + replicate
        assert p1.shape.nbytes == 256
        assert [s.elements for s in mod.entry_root_shapes] == [8, 4, 4]

    def test_module_text_requires_as_text_or_str(self):
        from apex_tpu.analysis.hlo.parser import module_text

        assert module_text("HloModule x") == "HloModule x"
        with pytest.raises(TypeError, match="as_text"):
            module_text(42)


# ---------------------------------------------------------------------------
# replica_groups -> mesh-axis attribution


class TestHloAttribution:
    def test_partitions_and_classify_dp2tp2(self):
        from apex_tpu.analysis.hlo import attribution

        mesh = mesh2d(2, 2, ("dp", "tp"))
        parts = attribution.mesh_axis_partitions(mesh)
        labels = set(parts.values())
        assert labels == {"dp", "tp", "dp,tp"}
        classify = attribution.classify_replica_groups
        assert classify(mesh, ((0, 1), (2, 3))) == "tp"
        assert classify(mesh, ((0, 2), (1, 3))) == "dp"
        assert classify(mesh, ((0, 1, 2, 3),)) == "dp,tp"
        # implicit "everyone" and singleton groups
        assert classify(mesh, ()) == "dp,tp"
        assert classify(mesh, ((0,), (1,), (2,), (3,))) == attribution.AXIS_NONE
        # a partition no axis subset induces
        assert classify(mesh, ((0, 3), (1, 2))) == attribution.AXIS_UNKNOWN

    def test_classify_source_target_pairs(self):
        from apex_tpu.analysis.hlo import attribution

        mesh = mesh2d(2, 2, ("dp", "pp"))
        classify = attribution.classify_source_target_pairs
        # pp ring edges inside each dp group: the SMALLEST subset wins
        assert classify(mesh, ((0, 1), (1, 0), (2, 3), (3, 2))) == "pp"
        assert classify(mesh, ((0, 2), (2, 0), (1, 3), (3, 1))) == "dp"
        # an edge crossing both axes only fits the full-mesh subset
        assert classify(mesh, ((0, 3),)) == "dp,pp"
        assert classify(mesh, ()) == attribution.AXIS_NONE
        assert classify(mesh, ((0, 9),)) == attribution.AXIS_UNKNOWN

    def test_size1_axes_dropped(self):
        from apex_tpu.analysis.hlo import attribution

        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:4]).reshape(2, 1, 1, 2),
            ("dp", "pp", "cp", "tp"),
        )
        parts = attribution.mesh_axis_partitions(mesh)
        assert set(parts.values()) == {"dp", "tp", "dp,tp"}
        # ledger composite keys canonicalize: size-1 names drop, order is
        # mesh order, unknown names stay visible
        canon = attribution.canon_axis_key
        assert canon(mesh, "pp,cp,tp") == "tp"
        assert canon(mesh, "tp,dp") == "dp,tp"
        assert canon(mesh, "pp") == attribution.AXIS_NONE
        assert canon(mesh, "nope") == "nope"


# ---------------------------------------------------------------------------
# ghost-collective differ (analysis/hlo/comms_diff.py)


class TestHloComms:
    def mesh(self):
        return mesh2d(2, 2, ("dp", "tp"))

    def test_misharded_matmul_unpredicted(self):
        # the ISSUE's seeded positive: a matmul whose operands are
        # sharded along the contracting dim forces GSPMD to insert an
        # all-reduce no ledger wrapper ever saw
        from apex_tpu.analysis.hlo import audit_comms
        from jax.sharding import NamedSharding

        mesh = self.mesh()
        xs = jax.ShapeDtypeStruct((8, 64), jnp.float32,
                                  sharding=NamedSharding(mesh, P(None, "tp")))
        ws = jax.ShapeDtypeStruct((64, 8), jnp.float32,
                                  sharding=NamedSharding(mesh, P("tp", None)))
        f = jax.jit(lambda x, w: x @ w,
                    out_shardings=NamedSharding(mesh, P()))
        fins = audit_comms(f, xs, ws, mesh=mesh, target="seeded")
        (f1,) = [f for f in fins if f.rule == "comms.unpredicted"]
        assert f1.severity == "error"
        assert f1.data["op"] == "all-reduce"
        assert f1.data["axis"] == "tp"
        assert f1.data["elements"] == 64  # the (8,8) partial product
        assert f1.data["transpose"] is False
        assert f1.site.startswith(THIS_FILE + ":")  # the matmul's line

    def test_transpose_bwd_unpredicted_and_allowlisted(self):
        # a NON-custom_vjp all_gather under grad: jax's transpose rule
        # synthesizes the reduce-scatter mate, which never runs through
        # the ledger wrappers — the documented blind spot, now loud. The
        # reason-carrying allowlist is the sanctioned way to keep known
        # transpose-derived backward collectives.
        from apex_tpu.analysis.hlo import audit_comms

        mesh = self.mesh()

        # x sharded over BOTH axes: no dp broadcast in the forward, so
        # the only transpose-synthesized collective is the tp
        # reduce-scatter mate of the gather
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp", "tp"), out_specs=P(),
            check_vma=False,
        )
        def gathered_sum(x):
            return jnp.sum(xlax.all_gather(x, "tp"))

        def step(x):
            return jax.value_and_grad(gathered_sum)(x)

        x = jax.ShapeDtypeStruct((2, 8), jnp.float32)
        fins = audit_comms(step, x, mesh=mesh, target="seeded")
        rs = [f for f in fins if f.rule == "comms.unpredicted"
              and f.data["op"] == "reduce-scatter"]
        (f1,) = rs
        assert f1.severity == "error"
        assert f1.data["axis"] == "tp"
        assert f1.data["transpose"] is True
        assert "transpose-synthesized" in f1.message
        # the transposed op inherits the FORWARD call's source info —
        # the ledger wrapper line (the eqn_site quirk, passes.py)
        assert "ledger.py" in f1.site
        allow = Allowlist([AllowlistEntry(
            rule="comms.unpredicted",
            match="ledger.py",
            reason=(
                "transpose-derived backward mate of the forward "
                "all_gather: legitimate mirrored traffic the ledger "
                "cannot see without a custom_vjp pairing"
            ),
        )])
        res = allow.apply(fins, check_stale=False)
        assert not any(
            f.rule == "comms.unpredicted" for f in res.findings
        )
        assert any(
            f.rule == "comms.unpredicted" for f, _ in res.suppressed
        )

    def test_ledgered_ppermute_matches(self):
        # a predicted permute must MATCH its emitted collective-permute —
        # which XLA prints with source_target_pairs, not replica_groups
        # (the attribution goes through the pair graph)
        from apex_tpu.analysis.hlo import audit_comms

        mesh = mesh2d(2, 2, ("dp", "pp"))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return xlax.ppermute(x, "pp", [(0, 1), (1, 0)])

        fins = audit_comms(step, jax.ShapeDtypeStruct((16,), jnp.float32),
                           mesh=mesh, target="seeded")
        assert fins == [], [f.format() for f in fins]

    def test_dead_psum_vanished(self):
        from apex_tpu.analysis.hlo import audit_comms

        mesh = self.mesh()

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            xlax.psum(x, "tp")  # result unused: XLA deletes the traffic
            return x * 2.0

        fins = audit_comms(step, jax.ShapeDtypeStruct((16,), jnp.float32),
                           mesh=mesh, target="seeded")
        (f1,) = [f for f in fins if f.rule == "comms.vanished"]
        assert f1.severity == "warning"
        assert f1.data == {"op": "all-reduce", "axis": "tp", "elements": 16}

    def test_async_start_done_confirmed(self):
        """The overlap proof loop's emitted-HLO leg: a ledger-matched
        collective spelled as an async -start/-done pair yields the
        comms.async positive confirmation with predicted==emitted bytes
        (synthetic text: CPU XLA emits sync collectives, so the
        mechanism is pinned here and fires for real on TPU compiles)."""
        from apex_tpu.analysis.hlo import audit_comms
        from apex_tpu.analysis.hlo.parser import parse_hlo_module

        mesh = mesh1d(4, "dp")

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return xlax.all_gather(x, "dp", tiled=True)

        synthetic = """\
HloModule m

ENTRY %main.1 (p0: f32[8]) -> f32[32] {
  %p0 = f32[8]{0} parameter(0)
  %ags = (f32[8]{0}, f32[32]{0}) all-gather-start(f32[8]{0} %p0), channel_id=1, replica_groups={{0,1,2,3}}, dimensions={0}, use_global_device_ids=true, metadata={op_name="jit(step)/all_gather" source_file="/repo/apex_tpu/monitor/xray/ledger.py" source_line=419}
  ROOT %agd = f32[32]{0} all-gather-done((f32[8]{0}, f32[32]{0}) %ags)
}
"""
        # the parser records the async spelling (and skips the -done)
        mod = parse_hlo_module(synthetic)
        (c,) = mod.collectives
        assert c.kind == "all-gather" and c.is_async

        x = jax.ShapeDtypeStruct((32,), jnp.float32)
        fins = audit_comms(step, x, mesh=mesh, target="t",
                           compiled=synthetic)
        (f1,) = fins
        assert f1.rule == "comms.async"
        assert f1.severity == "info"
        assert f1.data == {"axis": "dp", "op": "all-gather", "ops": 1,
                           "bytes": 32}
        assert "predicted == emitted" in f1.message
        # sync spelling: same match, NO async confirmation
        sync = synthetic.replace(
            "(f32[8]{0}, f32[32]{0}) all-gather-start", "f32[32]{0} all-gather"
        ).replace(
            "ROOT %agd = f32[32]{0} all-gather-done((f32[8]{0}, "
            "f32[32]{0}) %ags)",
            "ROOT %agd = f32[32]{0} add(f32[32]{0} %ags, f32[32]{0} %ags)",
        )
        assert audit_comms(step, x, mesh=mesh, target="t",
                           compiled=sync) == []

    def test_unverifiable_without_mesh(self):
        from apex_tpu.analysis.hlo import audit_comms

        fins = audit_comms(lambda x: x * 2, jnp.ones((4,)), mesh=None,
                           target="t")
        (f1,) = fins
        assert f1.rule == "comms.unverifiable"
        assert f1.severity == "info"

    def test_unparseable_hlo_unverifiable_not_crash(self):
        # malformed module text (truncated alias header) must degrade to
        # the documented comms.unverifiable outcome, not a ValueError
        # that kills the whole gate
        from apex_tpu.analysis.hlo import audit_comms

        fins = audit_comms(
            lambda x: x * 2, jnp.ones((4,)), mesh=self.mesh(), target="t",
            compiled="HloModule m, input_output_alias={ {0",
        )
        (f1,) = fins
        assert f1.rule == "comms.unverifiable"
        assert f1.severity == "info"
        assert "could not be parsed" in f1.message

    def test_batched_reconcile_requires_leading_dim_split(self):
        # stage-2 guard: an emitted op whose size is coincidentally k*e
        # of a predicted bucket but whose operand dims do NOT factor as
        # (batch..., payload...) is a real unpredicted collective, not
        # vmap batching — it must survive to comms.unpredicted instead
        # of silently consuming k predictions
        from apex_tpu.analysis.hlo import audit_comms

        mesh = self.mesh()

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            with xlax.scaled(4):  # 4 predicted tp psums of 16 el
                return xlax.psum(x, "tp")

        x = jax.ShapeDtypeStruct((16,), jnp.float32)
        synthetic = """\
HloModule m

ENTRY %main.1 (p0: f32[{dims}]) -> f32[{dims}] {{
  %p0 = f32[{dims}]{{0}} parameter(0)
  ROOT %ar = f32[{dims}]{{0}} all-reduce(f32[{dims}]{{0}} %p0), channel_id=1, replica_groups={{{{0,1}},{{2,3}}}}, to_apply=%add, metadata={{op_name="jit(step)/mystery" source_file="/repo/c.py" source_line=5}}
}}
"""
        # 48 = 3*16 divides the bucket payload, but f32[48] is not a
        # 3-stack of f32[16] payloads in any leading-dim split
        fins = audit_comms(step, x, mesh=mesh, target="seeded",
                           compiled=synthetic.format(dims="48"))
        (f1,) = [f for f in fins if f.rule == "comms.unpredicted"]
        assert f1.data["op"] == "all-reduce"
        assert f1.data["axis"] == "tp"
        assert f1.data["elements"] == 48
        # the 4 predictions are then genuinely unconsumed -> vanished
        assert [f.rule for f in fins if f is not f1] == ["comms.vanished"]
        # positive control: a true vmap batch IS a leading-dim stack and
        # consumes the whole bucket cleanly
        fins = audit_comms(step, x, mesh=mesh, target="seeded",
                           compiled=synthetic.format(dims="4,16"))
        assert fins == [], [f.format() for f in fins]

    def test_gpt_dp2tp2_inventory_and_clean(self):
        """ACCEPTANCE: the hand-counted collective inventory of the GPT
        dp2xtp2 target's OPTIMIZED HLO, pinned exactly per (op, axis) in
        both counts and operand bytes (f32 on the CPU backend — XLA
        legalizes bf16 collectives to f32 there, which is exactly why
        the differ matches on elements, not bytes).

        The hand count (model: 2 layers, hidden 16, ffn 32, heads 2,
        vocab 32, seq 8, batch 2 over dp2 => per-shard b=1; SP over tp2
        => s/tp=4):

        - all-gather/tp, 10 ops x 64 el (4,1,16): SP activation gathers
          -- fwd qkv + h_to_4h per layer (4) + final pre-logits gather
          (1), and their custom_vjp backward mates at dense + 4h_to_h
          per layer (4) + the tied-embedding attend path (1).
        - reduce-scatter/tp, 9 ops x 128 el (8,1,16): fwd dense +
          4h_to_h per layer (4), bwd qkv + h_to_4h per layer (4), and
          the tied-embedding logits-grad path (1).
        - all-reduce/tp, 19 ops, 1508 B: 14 x 16-el grad psums for the
          tp-replicated LN scales/biases (5 norms x 2 params) and the
          SP dense/4h biases (4); 3 x 8-el vocab-parallel CE stats over
          the (1,8) token rows (pmax + sumexp psum + target-logit psum,
          the 4th predicted psum CSE-folds with the sumexp one); 1 x
          scalar found_inf psum (grad scaler); 1 x 128-el vocab-parallel
          embedding-grad psum.
        - all-reduce/dp, 29 ops, 15172 B: one grad psum per parameter
          leaf (28 leaves: 12 per layer + word/pos embeddings + final
          LN scale/bias) + the scalar loss pmean.
        - all-reduce/none, 1 op: the found_inf psum over the size-1
          pp/cp axes — singleton groups, zero bytes, elided by the
          ledger and skipped by the differ.

        And the differ itself must come back CLEAN on this target: only
        the info-severity comms.folded record for the CSE'd CE-stats
        psum (no unpredicted, no reshard, no vanished).
        """
        from apex_tpu.analysis import StepContext
        from apex_tpu.analysis.hlo import attribution, audit_comms
        from apex_tpu.analysis.hlo.parser import parse_hlo_module
        from apex_tpu.analysis.targets import dp2tp2_mesh, gpt_step_target

        mesh = dp2tp2_mesh()
        tgt = gpt_step_target(mesh)
        ctx = StepContext(tgt)
        _, compiled = ctx.aot()
        mod = parse_hlo_module(compiled)
        parts = attribution.mesh_axis_partitions(mesh)

        inventory = {}
        for c in mod.collectives:
            axis = attribution.classify_replica_groups(
                mesh, c.replica_groups, parts
            )
            count, nbytes = inventory.get((c.kind, axis), (0, 0))
            inventory[(c.kind, axis)] = (count + 1, nbytes + c.nbytes)

        assert inventory == {
            ("all-gather", "tp"): (10, 10 * 64 * 4),
            ("reduce-scatter", "tp"): (9, 9 * 128 * 4),
            ("all-reduce", "tp"): (19, 14 * 16 * 4 + 3 * 8 * 4
                                   + 1 * 4 + 128 * 4),
            ("all-reduce", "dp"): (29, 15172),
            ("all-reduce", "none"): (1, 4),
        }
        # dp bytes cross-check: 28 f32 grad leaves = the full parameter
        # tree (3792 el) + the scalar loss pmean
        assert 15172 == 3792 * 4 + 4

        fins = audit_comms(
            tgt.fn, *tgt.args, mesh=mesh,
            donate_argnums=tgt.donate_argnums, target=tgt.name,
            compiled=compiled,
        )
        assert all(f.severity == "info" for f in fins), [
            f.format() for f in fins
        ]
        (folded,) = [f for f in fins if f.rule == "comms.folded"]
        assert folded.data == {
            "op": "all-reduce", "axis": "tp", "elements": 8,
        }

    def test_bert_clean(self):
        """Clean negative for the second CLI target: no error/warning
        comms findings, and the sharding auditor is silent (every entry
        buffer is tiny)."""
        from apex_tpu.analysis import StepContext
        from apex_tpu.analysis.hlo import audit_comms, audit_entry_shardings
        from apex_tpu.analysis.targets import bert_step_target, dp2tp2_mesh

        mesh = dp2tp2_mesh()
        tgt = bert_step_target(mesh)
        ctx = StepContext(tgt)
        _, compiled = ctx.aot()
        fins = audit_comms(
            tgt.fn, *tgt.args, mesh=mesh,
            donate_argnums=tgt.donate_argnums, target=tgt.name,
            compiled=compiled,
        )
        assert all(f.severity == "info" for f in fins), [
            f.format() for f in fins
        ]
        assert audit_entry_shardings(compiled, mesh, target=tgt.name) == []


# ---------------------------------------------------------------------------
# entry-sharding auditor (analysis/hlo/sharding_audit.py)


class TestHloSharding:
    def test_replicated_param_flagged_sharded_clean(self):
        from apex_tpu.analysis.hlo import audit_entry_shardings
        from jax.sharding import NamedSharding

        mesh = mesh2d(2, 2, ("dp", "tp"))
        big = jax.ShapeDtypeStruct((512, 1024), jnp.float32,
                                   sharding=NamedSharding(mesh, P()))
        small = jax.ShapeDtypeStruct((8,), jnp.float32,
                                     sharding=NamedSharding(mesh, P()))
        compiled = jax.jit(lambda a, b: (a * 2.0, b + 1.0)).lower(
            big, small
        ).compile()
        fins = audit_entry_shardings(compiled, mesh, target="seeded")
        # the small buffer is exempt by the 1 MiB floor
        (f1,) = [f for f in fins if f.severity == "warning"]
        assert f1.rule == "sharding.replicated-param"
        assert f1.data["bytes"] == 512 * 1024 * 4
        assert f1.data["index"] == 0
        # CPU jit leaves the ROOT unannotated and the 2 MiB result is
        # above the floor: the auditor must SAY outputs went unaudited
        # (degrade-loudly) instead of silently skipping them
        (u,) = [f for f in fins if f.rule == "sharding.unverifiable"]
        assert u.severity == "info"
        assert u.data["outputs"] >= 1

        sharded = jax.ShapeDtypeStruct(
            (512, 1024), jnp.float32,
            sharding=NamedSharding(mesh, P("dp", None)),
        )
        compiled2 = jax.jit(lambda a: a * 2.0).lower(sharded).compile()
        fins2 = audit_entry_shardings(compiled2, mesh, target="s")
        assert [f.rule for f in fins2 if f.severity != "info"] == []
        assert {f.rule for f in fins2} <= {"sharding.unverifiable"}

    def test_silent_without_parallel_axes(self):
        from apex_tpu.analysis.hlo import audit_entry_shardings

        mesh = mesh1d(1, "dp")
        assert audit_entry_shardings("HloModule x", mesh) == []
        assert audit_entry_shardings("HloModule x", None) == []


# ---------------------------------------------------------------------------
# the repo self-check: the CLI gate must pass against the tree as committed


class TestRepoSelfCheck:
    def test_hlo_passes_registered(self):
        # the CLI gate runs every registered pass: the HLO family must
        # be in the registry or the gate silently loses its coverage
        from apex_tpu.analysis import JAXPR_PASSES

        assert {"precision", "donation", "collective", "host-sync",
                "hlo-comms", "hlo-sharding"} <= set(JAXPR_PASSES)

    def test_repo_lint_clean(self):
        """All source rules over the real tree, repo allowlist applied:
        zero unallowlisted findings and zero stale entries."""
        from apex_tpu.analysis import Allowlist
        from apex_tpu.analysis.allowlist import REPO_ALLOWLIST

        fins = run_lint()
        lint_entries = [
            e for e in REPO_ALLOWLIST.entries if e.rule.startswith("lint.")
        ]
        res = Allowlist(lint_entries).apply(fins, check_stale=True)
        assert not res.findings, "\n".join(f.format() for f in res.findings)
        assert not res.stale_entries, res.stale_entries

    def test_cli_main_clean(self):
        """ACCEPTANCE: the full gate — AST rules + all four jaxpr passes
        over the GPT dp2xtp2 and BERT step builders — exits 0. Any future
        silent promotion, broken donation, raw collective, or in-step
        host callback fails this test."""
        from apex_tpu.analysis.__main__ import main

        try:
            assert main([]) == 0
        finally:
            # the CLI points parallel_state at a 4-device sub-mesh;
            # restore the full default mesh for whatever test runs next
            from apex_tpu.parallel import parallel_state

            parallel_state.initialize_model_parallel()

    def test_gpt_pp_target_zero_comms_suppressions(self):
        """CI satellite (ISSUE 14): the zero-bubble pp target audits
        with ZERO comms-allowlist suppressions — no unpredicted /
        reshard / vanished findings exist at all, because the schedule
        hand-writes its backward edges through the ledgered p2p wrappers
        and the ZeRO prefetch gathers are ledger-routed. Only the
        broadly-allowlisted positive/bookkeeping rules (comms.folded,
        comms.async, comms.quantized) may appear."""
        from apex_tpu.analysis import targets as targets_mod
        from apex_tpu.analysis.allowlist import repo_allowlist

        try:
            target = targets_mod.gpt_pp_step_target()
            fins = run_passes(target)
        finally:
            from apex_tpu.parallel import parallel_state

            parallel_state.initialize_model_parallel()
        bad = [f for f in fins if f.rule in (
            "comms.unpredicted", "comms.reshard", "comms.vanished",
            "comms.unverifiable",
        )]
        assert bad == [], "\n".join(f.format() for f in bad)
        res = repo_allowlist().apply(fins, check_stale=False)
        assert res.ok, "\n".join(f.format() for f in res.findings)


def test_analysis_cli_subprocess(tmp_path):
    """The real entry point, as CI would run it: ``python -m
    apex_tpu.analysis`` in a fresh process (its own env setup), exit 0,
    and every emitted record an allowlisted finding with a reason."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    out = str(tmp_path / "analysis.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "--json", out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=570,
    )
    assert proc.returncode == 0, (
        f"analysis CLI failed\nstdout tail: {proc.stdout[-2000:]}\n"
        f"stderr tail: {proc.stderr[-800:]}"
    )
    records = [json.loads(l) for l in open(out)]
    assert records, "CLI emitted no analysis records"
    for rec in records:
        assert rec["kind"] == "analysis"
        assert rec["allowed"] is True
        assert rec["reason"].strip()
