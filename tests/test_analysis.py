"""Static-analysis subsystem (apex_tpu.analysis): jaxpr auditors, AST
lint framework, allowlist machinery, and the repo self-check.

Every pass gets a hand-built miniature step with ONE known violation
(bad promotion, rejected donation, non-permutation ppermute, mismatched
pipeline edge, host callback) asserting exact Finding fields, plus a
clean-function negative test — the auditors must find exactly what is
seeded and nothing else. The self-check at the bottom is the acceptance
gate: ``python -m apex_tpu.analysis`` (lint + GPT/BERT step targets on
the dp2xtp2 CPU mesh) must exit 0 against the repo as committed.
"""

import functools
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.compat import shard_map
from apex_tpu.monitor.xray import ledger as xlax
from jax.sharding import PartitionSpec as P

from apex_tpu.analysis import (
    Allowlist,
    AllowlistEntry,
    Finding,
    StepTarget,
    merge_findings,
    run_passes,
)
from apex_tpu.analysis.donation import audit_donation
from apex_tpu.analysis.lint import run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THIS_FILE = "tests/test_analysis.py"


def mesh1d(n, name):
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), (name,))


def mesh2d(a, b, names):
    return jax.sharding.Mesh(
        np.array(jax.devices()[: a * b]).reshape(a, b), names
    )


# ---------------------------------------------------------------------------
# findings + allowlist machinery


class TestFindingsAndAllowlist:
    def test_bare_allowlist_entry_rejected(self):
        with pytest.raises(ValueError, match="reason"):
            AllowlistEntry(rule="precision.promotion", match="x.py", reason="  ")

    def test_entry_matching_rule_glob_and_site(self):
        e = AllowlistEntry(
            rule="precision.*", match="apex_tpu/ops/", reason="stats in f32"
        )
        hit = Finding(rule="precision.promotion", message="m",
                      site="apex_tpu/ops/layer_norm.py:52")
        miss_rule = Finding(rule="donation.missed", message="m",
                            site="apex_tpu/ops/layer_norm.py:52")
        miss_site = Finding(rule="precision.promotion", message="m",
                            site="apex_tpu/models/gpt.py:1")
        assert e.matches(hit)
        assert not e.matches(miss_rule)
        assert not e.matches(miss_site)

    def test_merge_findings_sums_counts(self):
        a = Finding(rule="r", message="m", site="s", count=2)
        b = Finding(rule="r", message="m", site="s", count=3)
        c = Finding(rule="r", message="m", site="other")
        merged = merge_findings([a, b, c])
        assert sorted(f.count for f in merged) == [1, 5]

    def test_apply_partitions_and_detects_stale(self):
        al = Allowlist([
            AllowlistEntry(rule="r", match="ok.py", reason="fine"),
            AllowlistEntry(rule="r", match="gone.py", reason="was fine",
                           require_hit=True),
        ])
        res = al.apply([Finding(rule="r", message="m", site="ok.py:1"),
                        Finding(rule="r", message="m", site="bad.py:1")])
        assert [f.site for f in res.findings] == ["bad.py:1"]
        assert len(res.suppressed) == 1
        assert [e.match for e in res.stale_entries] == ["gone.py"]
        assert not res.ok

    def test_info_findings_do_not_fail(self):
        res = Allowlist().apply(
            [Finding(rule="r", message="m", site="s", severity="info")]
        )
        assert res.ok

    def test_records_share_router_schema(self):
        from apex_tpu import monitor

        res = Allowlist([
            AllowlistEntry(rule="r", match="b.py", reason="documented why"),
        ]).apply([
            Finding(rule="r", message="kept", site="a.py:1"),
            Finding(rule="r", message="hidden", site="b.py:2"),
        ])
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        for rec in res.to_records(step=7):
            router.emit(rec)
        assert len(mem.records) == 2
        for rec in mem.records:
            assert {"t", "step", "kind", "rule", "site"} <= set(rec)
            assert rec["kind"] == "analysis" and rec["step"] == 7
        allowed = [r for r in mem.records if r["allowed"]]
        assert len(allowed) == 1 and allowed[0]["reason"] == "documented why"

    def test_repo_allowlist_every_entry_carries_a_reason(self):
        from apex_tpu.analysis.allowlist import REPO_ALLOWLIST

        assert len(REPO_ALLOWLIST) > 0
        for e in REPO_ALLOWLIST.entries:
            # a reason must be a sentence someone can review, not a token
            assert len(e.reason.split()) >= 5, (e.rule, e.match)


# ---------------------------------------------------------------------------
# precision auditor


class TestPrecisionPass:
    def test_seeded_promotion_exact_fields(self):
        def step(x):
            return x.astype(jnp.float32).sum()  # the seeded violation

        tgt = StepTarget(
            name="seeded", fn=step,
            args=(jax.ShapeDtypeStruct((4,), jnp.bfloat16),),
        )
        (f,) = run_passes(tgt, passes=["precision"])
        assert f.rule == "precision.promotion"
        assert f.severity == "error"
        assert f.target == "seeded"
        assert f.count == 1
        assert f.data == {"from": "bfloat16", "to": "float32"}
        assert f.site.startswith(THIS_FILE + ":")

    def test_promotion_found_inside_nested_scan(self):
        def step(x):
            def body(c, _):
                return c + x.astype(jnp.float32).sum(), None

            out, _ = jax.lax.scan(body, 0.0, None, length=3)
            return out

        tgt = StepTarget(
            name="t", fn=step, args=(jax.ShapeDtypeStruct((4,), jnp.bfloat16),)
        )
        fins = run_passes(tgt, passes=["precision"])
        assert [f.rule for f in fins] == ["precision.promotion"]

    def test_f64_flagged(self):
        from jax.experimental import enable_x64

        def step(x):
            return x.astype(jnp.float64) * 2

        with enable_x64():
            tgt = StepTarget(
                name="t", fn=step,
                args=(jax.ShapeDtypeStruct((2,), jnp.float32),),
            )
            fins = run_passes(tgt, passes=["precision"])
        rules = {f.rule for f in fins}
        assert rules == {"precision.f64"}
        assert all(f.severity == "error" for f in fins)
        prims = {f.data["primitive"] for f in fins}
        assert "convert_element_type" in prims

    def test_clean_bf16_step_no_findings(self):
        # no reduction on purpose: jnp.sum of a bf16 array upcasts its
        # accumulator to f32 (a REAL promotion the pass would flag)
        def step(x, w):
            return jnp.tanh(x @ w) * 2

        tgt = StepTarget(
            name="t", fn=step,
            args=(jax.ShapeDtypeStruct((4, 4), jnp.bfloat16),
                  jax.ShapeDtypeStruct((4, 4), jnp.bfloat16)),
        )
        assert run_passes(tgt, passes=["precision"]) == []


# ---------------------------------------------------------------------------
# collective-safety validator


class TestCollectivePass:
    def test_unknown_axis_flagged(self):
        mesh_dp = mesh1d(2, "dp")
        mesh_tp = mesh1d(2, "tp")  # the ambient mesh the pass audits against

        @functools.partial(
            shard_map, mesh=mesh_dp, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return xlax.psum(x, "dp")

        tgt = StepTarget(name="t", fn=step, args=(jnp.ones((2,)),),
                         mesh=mesh_tp)
        fins = run_passes(tgt, passes=["collective"])
        (f,) = [f for f in fins if f.rule == "collective.unknown-axis"]
        assert f.severity == "error"
        assert f.data == {"op": "psum", "axis": "dp"}
        assert f.site.startswith(THIS_FILE + ":")

    def test_size1_axis_flagged_as_dead_traffic(self):
        mesh = mesh2d(2, 1, ("dp", "pp"))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return xlax.psum(x, "pp")  # size-1 axis: dead traffic

        # the ledger elides size-1 axes from RECORDING, but the primitive
        # is still in the jaxpr — exactly what this pass exists to flag
        tgt = StepTarget(name="t", fn=step, args=(jnp.ones((2,)),), mesh=mesh)
        (f,) = run_passes(tgt, passes=["collective"])
        assert f.rule == "collective.dead-traffic"
        assert f.severity == "warning"
        assert f.data == {"op": "psum", "axis": "pp"}

    def test_non_permutation_ppermute_flagged(self):
        mesh = mesh1d(4, "pp")

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            # rank 0 sends twice: not a permutation (jax traces it fine,
            # which is why the static check exists)
            return xlax.ppermute(x, "pp", [(0, 1), (0, 2)])

        (f,) = run_passes(StepTarget(name="t", fn=step, args=(jnp.ones((2,)),),
                                     mesh=mesh), passes=["collective"])
        assert f.rule == "collective.non-permutation"
        assert f.severity == "error"
        assert "duplicate source" in f.message
        assert f.data["axis"] == "pp"

    def test_mismatched_pipeline_edge_flagged(self):
        mesh = mesh1d(4, "pp")

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            # stage 1's outgoing edge is missing: stages 2..3 wait on a
            # stream that never crosses the gap
            return xlax.ppermute(x, "pp", [(0, 1), (2, 3)])

        (f,) = run_passes(StepTarget(name="t", fn=step, args=(jnp.ones((2,)),),
                                     mesh=mesh), passes=["collective"])
        assert f.rule == "collective.mismatched-edge"
        assert f.severity == "error"
        assert f.data["gaps"] == "[1]"

    def test_p2p_edge_grammar_is_clean(self):
        """Every edge constructor in parallel/pipeline/p2p.py must pass
        the validator — the schedules build all their edges from these."""
        from apex_tpu.parallel.pipeline import p2p

        mesh = mesh1d(4, "pp")
        for edges in (p2p.forward_edges(4), p2p.backward_edges(4),
                      p2p.ring_edges(4), p2p.last_to_first_edges(4)):

            @functools.partial(
                shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            )
            def step(x, edges=edges):
                return xlax.ppermute(x, "pp", edges)

            fins = run_passes(StepTarget(name="t", fn=step,
                                         args=(jnp.ones((2,)),), mesh=mesh),
                              passes=["collective"])
            assert fins == [], (edges, [f.format() for f in fins])

    def test_real_pipeline_schedule_validates_clean(self):
        """The 1F1B schedule (fwd AND the transposed backward edges jax
        synthesizes through the scan) contains only valid chains."""
        from apex_tpu.parallel.pipeline import schedules

        mesh = mesh1d(4, "pp")

        def stage_fn(p, x):
            return jnp.tanh(x @ p)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False,
        )
        def step(p, mb, tg):
            loss, _, grads = (
                schedules.forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, p, mb, tg, axis_name="pp"
                )
            )
            return loss

        p = jnp.ones((4, 4))
        mb = jnp.ones((4, 2, 4))
        fins = run_passes(StepTarget(name="pp1f1b", fn=step, args=(p, mb, mb),
                                     mesh=mesh), passes=["collective"])
        assert fins == [], [f.format() for f in fins]

    def test_chain_gaps_unit(self):
        from apex_tpu.analysis.collectives import chain_gaps

        assert chain_gaps([(0, 1), (1, 2), (2, 3)], 4) == []
        assert chain_gaps([(1, 0), (2, 1), (3, 2)], 4) == []
        assert chain_gaps([(0, 1), (2, 3)], 4) == [1]
        assert chain_gaps([(0, 1), (3, 4)], 8) == [1, 2]
        # rings / wrap edges / shuffles have no linear-chain semantics
        assert chain_gaps([(0, 1), (1, 2), (2, 3), (3, 0)], 4) is None
        assert chain_gaps([(3, 0)], 4) is None
        assert chain_gaps([(0, 2), (2, 0)], 4) is None


# ---------------------------------------------------------------------------
# host-sync detector


class TestHostSyncPass:
    def test_debug_print_flagged(self):
        def step(x):
            jax.debug.print("loss={l}", l=x.sum())  # the seeded violation
            return x * 2

        (f,) = run_passes(
            StepTarget(name="t", fn=step, args=(jnp.ones((4,)),)),
            passes=["host-sync"],
        )
        assert f.rule == "host-sync.callback"
        assert f.severity == "error"
        assert f.data == {"primitive": "debug_callback"}
        assert f.site.startswith(THIS_FILE + ":")

    def test_pure_callback_flagged(self):
        def step(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v) * 2,
                jax.ShapeDtypeStruct((4,), jnp.float32), x,
            )
            return y.sum()

        (f,) = run_passes(
            StepTarget(name="t", fn=step, args=(jnp.ones((4,)),)),
            passes=["host-sync"],
        )
        assert f.rule == "host-sync.callback"
        assert f.data == {"primitive": "pure_callback"}

    def test_clean_step_no_findings(self):
        def step(x):
            return (x @ x).sum()

        assert run_passes(
            StepTarget(name="t", fn=step, args=(jnp.ones((4, 4)),)),
            passes=["host-sync"],
        ) == []


# ---------------------------------------------------------------------------
# donation auditor


class TestDonationAuditor:
    MiB = 1 << 20

    def test_rejected_donation_exact_fields(self):
        def step(a, b):
            return b * 2.0  # 'a' donated but no output matches it

        a = jax.ShapeDtypeStruct((512, 512), jnp.float32)  # 1 MiB
        b = jax.ShapeDtypeStruct((8,), jnp.float32)
        fins = audit_donation(step, a, b, donate_argnums=(0,),
                              arg_names=("a", "b"), target="seeded")
        (f,) = [f for f in fins if f.rule == "donation.rejected"]
        assert f.severity == "error"
        assert f.data["leaf"] == "a"
        assert f.data["stage"] == "lowering"
        assert f.data["bytes"] == self.MiB
        assert f.target == "seeded"

    def test_missed_donation_flagged(self):
        def step(p, o, x):
            new_p = jax.tree_util.tree_map(lambda l: l - 0.1 * x.sum(), p)
            new_o = jax.tree_util.tree_map(lambda l: l + 1.0, o)
            return new_p, new_o

        p = {"w": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        o = {"m": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        x = jax.ShapeDtypeStruct((4,), jnp.float32)
        # p donated, o forgotten: o matches an un-aliased output
        fins = audit_donation(step, p, o, x, donate_argnums=(0,),
                              arg_names=("params", "opt_state", "x"))
        (f,) = [f for f in fins if f.rule == "donation.missed"]
        assert f.severity == "warning"
        assert f.data["leaf"] == "opt_state['m']"
        assert f.data["bytes"] == self.MiB

    def test_clean_donation_no_findings(self):
        def step(p, o, x):
            new_p = jax.tree_util.tree_map(lambda l: l - 0.1 * x.sum(), p)
            new_o = jax.tree_util.tree_map(lambda l: l + 1.0, o)
            return new_p, new_o

        p = {"w": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        o = {"m": jax.ShapeDtypeStruct((512, 512), jnp.float32)}
        x = jax.ShapeDtypeStruct((4,), jnp.float32)
        assert audit_donation(step, p, o, x, donate_argnums=(0, 1)) == []

    def test_prejitted_step_uses_its_own_donation(self):
        def step(p, x):
            return jax.tree_util.tree_map(lambda l: l - x.sum(), p)

        p = {"w": jnp.ones((512, 512))}
        x = jnp.ones((4,))
        jitted = jax.jit(step, donate_argnums=(0,))
        assert audit_donation(jitted, p, x) == []

    def test_pass_skipped_without_donation_intent(self):
        tgt = StepTarget(name="t", fn=lambda x: x * 2,
                         args=(jnp.ones((4,)),), donate_argnums=None)
        assert run_passes(tgt, passes=["donation"]) == []


# ---------------------------------------------------------------------------
# AST lint framework


class TestLintFramework:
    def test_raw_collective_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "from jax import lax\n\n\ndef f(x):\n"
                "    return lax.psum(x, 'tp')\n",
        }
        (f,) = run_lint(rules=["lint.raw-collective"], files=files)
        assert f.rule == "lint.raw-collective"
        assert f.site == "apex_tpu/fake.py:5"
        assert f.data == {"op": "psum"}

    def test_raw_collective_docstring_mention_not_flagged(self):
        files = {
            "apex_tpu/fake.py":
                '"""docs mention jax.lax.psum freely"""\n'
                "# and comments: lax.all_gather\n",
        }
        assert run_lint(rules=["lint.raw-collective"], files=files) == []

    def test_float64_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "import jax.numpy as jnp\nimport numpy as np\nimport numpy\n"
                "x = jnp.float64(3.0)\n"
                "y = np.float64(3.0)  # host-side: fine\n"
                "z = numpy.float64(3.0)  # host-side too: fine\n"
                "w = jax.numpy.float64(3.0)\n",
        }
        fins = run_lint(rules=["lint.float64"], files=files)
        # only the jax spellings: jnp.float64 and jax.numpy.float64
        assert sorted(f.site for f in fins) == [
            "apex_tpu/fake.py:4", "apex_tpu/fake.py:7",
        ]
        assert all(f.rule == "lint.float64" for f in fins)

    def test_rule_scopes_enforced_by_registry(self):
        # raw-collective is scoped to apex_tpu/: the same violation under
        # examples/ is out of scope and must not be flagged
        files = {
            "examples/fake.py":
                "from jax import lax\n\n\ndef f(x):\n"
                "    return lax.psum(x, 'tp')\n",
        }
        assert run_lint(rules=["lint.raw-collective"], files=files) == []

    def test_jit_donate_seeded_and_data_calls_exempt(self):
        files = {
            "examples/fake.py":
                "import functools, jax\n"
                "step = jax.jit(lambda x: x, donate_argnums=(0,))\n"
                "tgt = StepTarget(fn=step, donate_argnums=(0,))\n"
                "part = functools.partial(jax.jit, donate_argnums=(1,))\n",
        }
        fins = run_lint(rules=["lint.jit-donate"], files=files)
        # the jax.jit call and the partial(jax.jit) are flagged; the
        # StepTarget DECLARATION (auditing intent, not a jit) is not
        assert sorted(f.site for f in fins) == [
            "examples/fake.py:2", "examples/fake.py:4",
        ]

    def test_registered_taps_seeded(self):
        files = {
            "apex_tpu/fake.py":
                "def mod(self, x):\n"
                "    self.sow('intermediates', 'not_a_real_tap', x)\n",
        }
        fins = run_lint(rules=["lint.registered-taps"], files=files)
        seeded = [f for f in fins if f.data.get("tap") == "not_a_real_tap"]
        assert len(seeded) == 1
        assert seeded[0].site == "apex_tpu/fake.py:2"
        assert not seeded[0].data.get("stale")

    def test_unknown_rule_raises(self):
        with pytest.raises(KeyError, match="lint.nope"):
            run_lint(rules=["lint.nope"], files={})


# ---------------------------------------------------------------------------
# the repo self-check: the CLI gate must pass against the tree as committed


class TestRepoSelfCheck:
    def test_repo_lint_clean(self):
        """All source rules over the real tree, repo allowlist applied:
        zero unallowlisted findings and zero stale entries."""
        from apex_tpu.analysis import Allowlist
        from apex_tpu.analysis.allowlist import REPO_ALLOWLIST

        fins = run_lint()
        lint_entries = [
            e for e in REPO_ALLOWLIST.entries if e.rule.startswith("lint.")
        ]
        res = Allowlist(lint_entries).apply(fins, check_stale=True)
        assert not res.findings, "\n".join(f.format() for f in res.findings)
        assert not res.stale_entries, res.stale_entries

    def test_cli_main_clean(self):
        """ACCEPTANCE: the full gate — AST rules + all four jaxpr passes
        over the GPT dp2xtp2 and BERT step builders — exits 0. Any future
        silent promotion, broken donation, raw collective, or in-step
        host callback fails this test."""
        from apex_tpu.analysis.__main__ import main

        try:
            assert main([]) == 0
        finally:
            # the CLI points parallel_state at a 4-device sub-mesh;
            # restore the full default mesh for whatever test runs next
            from apex_tpu.parallel import parallel_state

            parallel_state.initialize_model_parallel()


def test_analysis_cli_subprocess(tmp_path):
    """The real entry point, as CI would run it: ``python -m
    apex_tpu.analysis`` in a fresh process (its own env setup), exit 0,
    and every emitted record an allowlisted finding with a reason."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    out = str(tmp_path / "analysis.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "--json", out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=570,
    )
    assert proc.returncode == 0, (
        f"analysis CLI failed\nstdout tail: {proc.stdout[-2000:]}\n"
        f"stderr tail: {proc.stderr[-800:]}"
    )
    records = [json.loads(l) for l in open(out)]
    assert records, "CLI emitted no analysis records"
    for rec in records:
        assert rec["kind"] == "analysis"
        assert rec["allowed"] is True
        assert rec["reason"].strip()
