"""X-ray layer (apex_tpu.monitor.xray): collective-traffic ledger, XLA
memory reports, recompile sentinel.

The load-bearing contracts:

- BYTE EXACTNESS: ledger totals must match hand-computed values digit for
  digit (the per-op formulas are the documentation — a comms report that
  is "roughly right" cannot diff two runs);
- ZERO-COST PASSTHROUGH: the wrappers emit the exact same primitives, so
  numerics are bit-identical with and without an active ledger;
- the memory report gives a non-degenerate args/outputs/temps breakdown
  for a real jitted train step;
- a deliberately shape-polymorphic step triggers exactly ONE post-warmup
  recompile warning record.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.compat import shard_map
from apex_tpu.monitor import xray
from apex_tpu.monitor.xray import ledger as xlax
from jax.sharding import Mesh, PartitionSpec as P


def tp_mesh(n=2):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


def f32b(*shape):
    """Bytes of an f32 array of this shape."""
    return int(np.prod(shape, dtype=np.int64)) * 4


class TestLedgerCore:
    def test_wrappers_are_passthrough(self):
        """Same numerics with and without an active ledger (the wrappers
        emit the identical primitive)."""
        mesh = tp_mesh(4)
        x = jnp.arange(16.0).reshape(4, 4)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("tp"), out_specs=P("tp"),
            check_vma=False,
        )
        def f(x):
            a = xlax.psum(x, "tp")
            b = xlax.all_gather(x, "tp", axis=0, tiled=True)
            c = xlax.psum_scatter(b, "tp", scatter_dimension=0, tiled=True)
            d = xlax.ppermute(x, "tp", [(i, (i + 1) % 4) for i in range(4)])
            return a + c + d

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("tp"), out_specs=P("tp"),
            check_vma=False,
        )
        def f_raw(x):
            a = jax.lax.psum(x, "tp")
            b = jax.lax.all_gather(x, "tp", axis=0, tiled=True)
            c = jax.lax.psum_scatter(b, "tp", scatter_dimension=0, tiled=True)
            d = jax.lax.ppermute(x, "tp", [(i, (i + 1) % 4) for i in range(4)])
            return a + c + d

        np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(f_raw(x)))
        with xlax.comms_ledger() as led:
            y = jax.jit(f)(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(f_raw(x)))
        assert len(led.entries) == 4

    def test_nothing_recorded_without_context(self):
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):
            return xlax.psum(x, "tp")

        with xlax.comms_ledger() as led:
            pass  # closed before any trace
        f(jnp.ones((2,)))
        assert led.entries == []

    def test_hand_counted_bytes_and_ici(self):
        """Every op's bytes/ici against the documented formulas, n=2:
        psum 2(n-1)/n*B = B; all_gather (n-1)*B = B; psum_scatter
        (n-1)/n*B = B/2; all_to_all (n-1)/n*B = B/2; ppermute B."""
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, "tp"), out_specs=P(),
            check_vma=False,
        )
        def f(x):  # x local shard: (4, 4) f32 = 64 B
            g = xlax.all_gather(x, "tp", axis=1, tiled=True)  # 64 B in
            s = xlax.psum(g, "tp")                            # 128 B in
            r = xlax.psum_scatter(s, "tp", scatter_dimension=1, tiled=True)
            p = xlax.ppermute(r, "tp", [(0, 1)])              # 64 B
            a = xlax.all_to_all(
                jnp.broadcast_to(p[:, :, None], (4, 4, 2)), "tp",
                split_axis=2, concat_axis=2, tiled=True,
            )  # 128 B in
            m = xlax.pmax(jnp.sum(a), "tp")                   # 4 B
            return m

        led = xlax.predict_comms(f, jax.ShapeDtypeStruct((4, 8), jnp.float32))
        by_op = {e.op: e for e in led.entries}
        assert by_op["all_gather"].bytes == 64
        assert by_op["all_gather"].ici_bytes == 64
        assert by_op["psum"].bytes == 128
        assert by_op["psum"].ici_bytes == 128
        assert by_op["psum_scatter"].bytes == 128
        assert by_op["psum_scatter"].ici_bytes == 64
        assert by_op["ppermute"].bytes == 64
        assert by_op["ppermute"].ici_bytes == 64
        assert by_op["all_to_all"].bytes == 128
        assert by_op["all_to_all"].ici_bytes == 64
        assert by_op["pmax"].bytes == 4
        assert by_op["pmax"].ici_bytes == 4
        assert led.total_bytes(axis="tp") == 64 + 128 + 128 + 64 + 128 + 4
        assert set(led.per_axis()) == {"tp"}
        assert led.per_axis()["tp"]["axis_size"] == 2

    def test_axis_size_query_records_nothing(self):
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):
            n = xlax.axis_size("tp")
            return x * n

        led = xlax.predict_comms(f, jnp.ones((3,)))
        assert led.entries == []

    def test_scaled_multiplier_and_muted(self):
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):  # x: (4,) f32 = 16 B
            with xlax.scaled(5):
                a = xlax.psum(x, "tp")
            with xlax.muted():
                b = xlax.psum(x, "tp")  # probe: must not count
            return a + b

        led = xlax.predict_comms(f, jnp.ones((4,)))
        assert len(led.entries) == 1
        (e,) = led.entries
        assert e.count == 5 and e.bytes == 16 and e.total_bytes == 80
        assert led.total_bytes() == 80

    def test_predict_comms_sidesteps_jit_cache(self):
        """A compiled-and-cached step records nothing when CALLED, but
        predict_comms (eval_shape) still traces the wrappers."""
        mesh = tp_mesh(2)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):
            return xlax.psum(x, "tp")

        x = jnp.ones((4,))
        f(x)  # compile + cache
        with xlax.comms_ledger() as led_call:
            f(x)
        assert led_call.entries == []  # cache hit: no trace, no record
        led = xlax.predict_comms(f, x)
        assert len(led.entries) == 1 and led.total_bytes() == 16

    def test_to_records_schema_and_roofline(self, monkeypatch):
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):
            return xlax.psum(x, "tp")

        monkeypatch.setenv("APEX_TPU_ICI_BANDWIDTH", "1e6")
        led = xlax.predict_comms(f, jnp.ones((250,)))  # 1000 B, ici 1000 B
        (rec,) = led.to_records(step=7)
        assert rec["kind"] == "comms" and rec["step"] == 7
        assert rec["axis"] == "tp" and rec["axis_size"] == 2
        assert rec["bytes"] == 1000 and rec["ici_bytes"] == 1000
        assert rec["ici_seconds"] == pytest.approx(1000 / 1e6)
        assert led.roofline_seconds() == {"tp": pytest.approx(1e-3)}
        # no bandwidth known (CPU, no env): None — never a fake number
        monkeypatch.delenv("APEX_TPU_ICI_BANDWIDTH")
        assert led.roofline_seconds() == {"tp": None}
        (rec2,) = led.to_records()
        assert rec2["ici_seconds"] is None

    def test_ici_bandwidth_table_and_override(self, monkeypatch):
        class FakeDev:
            device_kind = "TPU v5 lite"

        assert xlax.ici_bandwidth_per_device(FakeDev()) == 200e9
        FakeDev.device_kind = "TPU v6 lite"
        assert xlax.ici_bandwidth_per_device(FakeDev()) == 448e9
        FakeDev.device_kind = "cpu"
        assert xlax.ici_bandwidth_per_device(FakeDev()) is None
        monkeypatch.setenv("APEX_TPU_ICI_BANDWIDTH", "123.5e9")
        assert xlax.ici_bandwidth_per_device(FakeDev()) == 123.5e9

    def test_summary_mentions_axes_and_ops(self):
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):
            return xlax.psum(x, "tp")

        led = xlax.predict_comms(f, jnp.ones((4,)))
        s = led.summary()
        assert "axis 'tp'" in s and "psum" in s
        assert xlax.CommsLedger().summary().startswith("comms ledger: no")


class TestTPMappingsComms:
    """Satellite: hand-counted byte totals for the mappings.py custom-vjp
    pairs in a TP forward+backward — gather fwd => reduce-scatter bwd,
    copy fwd (free) => psum bwd, etc. Because every pair's bwd is a
    custom_vjp rule (Python re-runs at trace time), a grad trace captures
    BOTH directions."""

    def test_tp_forward_backward_hand_counted(self):
        from apex_tpu.parallel import mappings

        mesh = tp_mesh(2)
        s, b, h = 8, 2, 4  # full sequence 8 -> local shard 4 under SP

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def step(x):  # x: (s, b, h) replicated
            def loss(x):
                # SP input shard -> gather fwd (all_gather of the local
                # (s/2, b, h)), reduce-scatter bwd (psum_scatter (s,b,h))
                xs = mappings.scatter_to_sequence_parallel_region(x)
                g = mappings.gather_from_sequence_parallel_region(
                    xs, to_model_parallel=True
                )
                # copy fwd (identity) => psum bwd of the (s, b, h) grad
                c = mappings.copy_to_tensor_model_parallel_region(g)
                # reduce fwd (psum (s, b, h)) => pcast bwd (no collective)
                r = mappings.reduce_from_tensor_model_parallel_region(c)
                return jnp.sum(r)

            l, g = jax.value_and_grad(loss)(x)
            return l

        led = xlax.predict_comms(
            step, jax.ShapeDtypeStruct((s, b, h), jnp.float32)
        )
        per_op = led.per_op(axis="tp")
        # all_gather x2: gather_from_sequence FWD gathers the local
        # (s/2, b, h) shard; scatter_to's BWD gathers the (s/2, b, h)
        # cotangent (via _typed_gather) — 128 B each here.
        assert per_op["all_gather"]["calls"] == 2
        assert per_op["all_gather"]["bytes"] == 2 * f32b(s // 2, b, h)
        # psum x2: reduce_from's FWD psum of (s, b, h) + copy_to's BWD
        # psum of the (s, b, h) grad (reduce_from's bwd is a pcast —
        # no collective).
        assert per_op["psum"]["calls"] == 2
        assert per_op["psum"]["bytes"] == 2 * f32b(s, b, h)
        # psum_scatter x1: gather_from_sequence(to_model_parallel=True)
        # BWD reduce-scatters the full (s, b, h) cotangent — the
        # "gather fwd => reduce-scatter bwd" pair of the SP head gather.
        assert per_op["psum_scatter"]["calls"] == 1
        assert per_op["psum_scatter"]["bytes"] == f32b(s, b, h)
        # the whole step moves exactly these five collectives
        assert sum(d["calls"] for d in per_op.values()) == 5
        assert set(per_op) == {"all_gather", "psum", "psum_scatter"}


class TestPipelineComms:
    """Satellite: one 1F1B pipeline step's ppermute traffic, hand-counted
    under compat.shard_map on the CPU mesh.

    The forward tick scan traces its body ONCE; schedules wrap it in
    ``xray.scaled(T)`` with T = M + P - 1, so the single traced edge
    weighs T executions. (The BACKWARD pipeline's edges come from jax's
    transpose of the scan — no Python, not recorded; they mirror forward
    one-for-one, as documented in the ledger module.)
    """

    PP = 4

    def test_1f1b_ppermute_traffic_hand_counted(self):
        from apex_tpu.parallel.pipeline import (
            forward_backward_pipelining_without_interleaving,
        )

        mesh = Mesh(np.array(jax.devices()[: self.PP]), ("pp",))
        M, micro_b, hid = 8, 2, 4

        def stage_fn(params, x):
            return jnp.tanh(x @ params)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False,
        )
        def step(params, mbs, targets):
            loss, _, _ = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, params, mbs, targets, axis_name="pp",
            )
            return loss

        led = xlax.predict_comms(
            step,
            jax.ShapeDtypeStruct((hid, hid), jnp.float32),
            jax.ShapeDtypeStruct((M, micro_b, hid), jnp.float32),
            jax.ShapeDtypeStruct((M, micro_b, hid), jnp.float32),
        )
        T = M + self.PP - 1
        act_bytes = f32b(micro_b, hid)  # one boundary activation
        # ONE traced ppermute edge, weighted by the T-tick scan
        assert led.total_bytes(op="ppermute", axis="pp") == T * act_bytes
        perms = led.filter(op="ppermute")
        assert len(perms) == 1 and perms[0].count == T
        # loss publication: psum of the per-microbatch losses (M,) plus
        # the scalar mean psum in _last_stage_mean_loss
        assert led.total_bytes(op="psum", axis="pp") == f32b(M) + f32b()
        assert set(led.per_axis()) == {"pp"}

    def test_tick_block_remat_weighs_padding_ticks(self):
        """Blocked remat pads the tick count to a block multiple — the
        padding ticks ship real edges and the ledger must count them."""
        from apex_tpu.parallel.pipeline import pipeline_forward

        mesh = Mesh(np.array(jax.devices()[: self.PP]), ("pp",))
        M, micro_b, hid, B = 6, 2, 4, 4  # T = 9 -> padded to 12

        def stage_fn(params, x):
            return jnp.tanh(x @ params)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
        def fwd(params, mbs):
            outs = pipeline_forward(
                stage_fn, params, mbs, axis_name="pp", tick_block_remat=B
            )
            return jax.tree_util.tree_map(jnp.sum, outs)

        led = xlax.predict_comms(
            fwd,
            jax.ShapeDtypeStruct((hid, hid), jnp.float32),
            jax.ShapeDtypeStruct((M, micro_b, hid), jnp.float32),
        )
        T = M + self.PP - 1  # 9 useful ticks
        padded = -(-T // B) * B  # 12 executed ticks
        assert padded == 12
        assert led.total_bytes(op="ppermute") == padded * f32b(micro_b, hid)


class TestGPTStepComms:
    """ACCEPTANCE: a CPU-mesh GPT train step under the ledger produces
    per-axis byte totals matching hand-computed values exactly.

    Mesh dp=2 x tp=2. Collective inventory of the tiny GPT (tied
    embeddings, learned positions, no SP, fp32 compute), per step:

    tp axis (payload bytes, L layers, batch b, seq s, hidden h):
      forward:
        - VocabParallelEmbedding: reduce_from psum of (b, s, h)
        - per layer: RowParallel attn-out psum (s, b, h)
                   + RowParallel mlp-out psum (s, b, h)
        - vocab-parallel CE: pmax (b, s) + psum sum_exp (b, s)
                           + psum target-logit (b, s) + psum mean-logit (b, s)
      backward (custom_vjp rules):
        - per layer: copy_to bwd psum for the qkv input (s, b, h)
                   + copy_to bwd psum for the mlp input (s, b, h)
        - tied head attend: copy_to bwd psum of (s, b, h)
        - embedding reduce_from bwd: pcast only (no collective)
        - CE bwd: hand-written shard-local rule (no collective)
    dp axis:
        - all_reduce_gradients: one psum per param leaf (classic path
          under check_vma=False) = total param bytes
        - loss pmean: one f32 scalar
    """

    def test_gpt_step_per_axis_totals_exact(self):
        from apex_tpu.models import GPTModel, gpt_loss_fn
        from apex_tpu.parallel import parallel_state
        from apex_tpu.parallel.ddp import all_reduce_gradients
        from apex_tpu.transformer import TransformerConfig

        L, h, heads, vocab, s, b = 2, 8, 2, 32, 4, 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=2
        )
        assert parallel_state.get_data_parallel_world_size() == 4
        cfg = TransformerConfig(
            num_layers=L,
            hidden_size=h,
            num_attention_heads=heads,
            vocab_size=vocab,
            max_position_embeddings=s,
            hidden_dropout=0.0,
            attention_dropout=0.0,
            sequence_parallel=False,
            compute_dtype=jnp.float32,
        )
        model = GPTModel(config=cfg)
        tokens = jnp.zeros((b, s), jnp.int32)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def init(tokens):
            return model.init(jax.random.PRNGKey(0), tokens)

        params = init(tokens)
        param_bytes = sum(
            int(np.prod(l.shape, dtype=np.int64)) * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(params)
        )

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
        def train_step(p, tokens):
            def loss(p):
                return gpt_loss_fn(model.apply(p, tokens, labels=tokens))

            l, grads = jax.value_and_grad(loss)(p)
            all_reduce_gradients(grads, axis_name="dp")
            return xlax.pmean(l, "dp")

        led = xlax.predict_comms(train_step, params, tokens)

        f32 = 4
        hidden_psum = s * b * h * f32  # one (s, b, h)/(b, s, h) fp32 psum
        tok_stat = b * s * f32  # one per-token fp32 statistic
        expected_tp_psum = (
            hidden_psum          # embedding fwd reduce
            + 2 * L * hidden_psum  # per layer fwd: attn-out + mlp-out
            + 3 * tok_stat       # CE: sum_exp, target logit, mean logit
            + 2 * L * hidden_psum  # per layer bwd: qkv + mlp copy_to
            + hidden_psum        # tied head attend copy_to bwd
        )
        per_op_tp = led.per_op(axis="tp")
        assert per_op_tp["psum"]["bytes"] == expected_tp_psum
        assert per_op_tp["pmax"]["bytes"] == tok_stat
        assert set(per_op_tp) == {"psum", "pmax"}

        per_op_dp = led.per_op(axis="dp")
        assert per_op_dp["psum"]["bytes"] == param_bytes
        assert per_op_dp["pmean"]["bytes"] == f32
        assert set(per_op_dp) == {"psum", "pmean"}

        per_axis = led.per_axis()
        assert per_axis["tp"]["bytes"] == expected_tp_psum + tok_stat
        assert per_axis["dp"]["bytes"] == param_bytes + f32
        assert per_axis["tp"]["axis_size"] == 2
        assert per_axis["dp"]["axis_size"] == 4

    def test_records_route_through_router(self):
        """The comms records land in the shared jsonl-compatible stream
        with kind='comms'."""
        mesh = tp_mesh(2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
        def f(x):
            return xlax.psum(x, "tp")

        led = xlax.predict_comms(f, jnp.ones((4,)))
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        for rec in led.to_records(step=3):
            router.emit(rec)
        (got,) = mem.records
        assert got["kind"] == "comms" and got["step"] == 3
        assert got["bytes"] == 16


class TestMemoryReport:
    def test_non_degenerate_breakdown_for_train_step(self):
        """args/outputs/temps all nonzero for a jitted train-ish step
        (the acceptance bar: a real breakdown, not a row of zeros)."""

        def step(w, x):
            y = jnp.tanh(x @ w)
            loss = jnp.sum(y**2)
            g = jax.grad(lambda w: jnp.sum(jnp.tanh(x @ w) ** 2))(w)
            return loss, w - 0.1 * g

        w = jnp.ones((64, 64))
        x = jnp.ones((32, 64))
        rep = xray.memory_report(step, w, x)
        assert rep.argument_bytes > 0
        assert rep.output_bytes > 0
        assert rep.temp_bytes > 0
        assert rep.total_bytes >= (
            rep.argument_bytes + rep.output_bytes + rep.temp_bytes
            + rep.generated_code_bytes - rep.alias_bytes
        )
        # CPU reports no capacity: headroom is honestly None
        assert rep.device_memory_bytes is None
        assert rep.headroom_bytes is None
        fields = rep.fields()
        assert fields["temp_bytes"] == rep.temp_bytes
        assert "MiB" in rep.format()

    def test_accepts_prejitted_function(self):
        jitted = jax.jit(lambda x: (x @ x.T).sum())
        rep = xray.memory_report(jitted, jnp.ones((16, 16)))
        assert rep.argument_bytes == 16 * 16 * 4

    def test_headroom_math(self):
        rep = xray.MemoryReport(
            argument_bytes=100, output_bytes=50, temp_bytes=200,
            generated_code_bytes=25, alias_bytes=50,
            device_memory_bytes=1000,
        )
        assert rep.total_bytes == 325
        assert rep.headroom_bytes == 675
        assert "headroom" in rep.format()

    def test_bench_parity_with_direct_analysis(self):
        """The refactored pipeline-memory benchmark path must report the
        same temp bytes as the raw memory_analysis dance it replaced."""

        def f(x):
            return jnp.tanh(x @ x.T).sum()

        x = jnp.ones((32, 32))
        direct = (
            jax.jit(f).lower(x).compile().memory_analysis().temp_size_in_bytes
        )
        assert xray.memory_report(f, x).temp_bytes == direct


class TestCompileWatcher:
    def test_exactly_one_postwarmup_recompile_record(self):
        """ACCEPTANCE: a deliberately shape-polymorphic step triggers
        exactly one post-warmup recompile warning record."""
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])

        @jax.jit
        def step(x):
            return (x * 2.0 + 1.0).sum()

        watcher = xray.CompileWatcher(router=router)
        if not watcher.available:  # pragma: no cover - jax API drift
            pytest.skip("jax.monitoring not available")

        step(jnp.ones((8,)))  # warmup compile
        rec0 = watcher.on_step(0)
        assert rec0 is not None and rec0["recompile"] is False
        assert rec0["compiles"] >= 1 and rec0["compile_seconds"] > 0

        step(jnp.ones((8,)))  # cached: no compile
        assert watcher.on_step(1) is None

        step(jnp.ones((9,)))  # shape-polymorphic step: recompiles
        rec2 = watcher.on_step(2)
        assert rec2 is not None and rec2["recompile"] is True

        step(jnp.ones((9,)))  # warm again
        assert watcher.on_step(3) is None

        recompiles = [r for r in mem.records
                      if r["kind"] == "compile" and r["recompile"]]
        assert len(recompiles) == 1
        assert rec2["total_compiles"] > rec0["compiles"] - 1

    def test_standalone_records_without_router(self):
        @jax.jit
        def f(x):
            return x + 1

        watcher = xray.CompileWatcher()
        if not watcher.available:  # pragma: no cover
            pytest.skip("jax.monitoring not available")
        f(jnp.ones((3, 3)))
        rec = watcher.on_step(0)
        assert rec is not None and rec["kind"] == "compile"
        assert list(watcher.records) == [rec]  # bounded deque window
        assert watcher.records.maxlen == xray.CompileWatcher.MAX_RECORDS


class TestMoEFlops:
    """Satellite: num_experts/top-k-aware layer FLOPs, hand-counted."""

    def _cfg(self, **kw):
        from apex_tpu.transformer import TransformerConfig

        base = dict(
            num_layers=1, hidden_size=4, num_attention_heads=2,
            ffn_hidden_size=8, vocab_size=32, max_position_embeddings=8,
        )
        base.update(kw)
        return TransformerConfig(**base)

    def test_moe_layer_flops_hand_counted(self):
        h, ffn, s, E, k = 4, 8, 3, 4, 2
        cfg = self._cfg(num_moe_experts=E, moe_top_k=k)
        got = monitor.transformer_layer_flops_per_token(cfg, s)
        qkv = 2 * h * (3 * h)       # dense QKV (no GQA): 96
        attn = 2 * s * h + 2 * s * h  # scores + context: 48
        out = 2 * h * h             # 32
        router = 2 * h * E          # 32
        expert = 2 * h * ffn + 2 * ffn * h  # one ungated FFN pass: 128
        assert got == qkv + attn + out + router + k * expert

    def test_top1_moe_is_dense_plus_router(self):
        """Switch (top-1) runs exactly one expert per token: dense MLP
        FLOPs + the router matmul."""
        s = 5
        dense = monitor.transformer_layer_flops_per_token(self._cfg(), s)
        moe = monitor.transformer_layer_flops_per_token(
            self._cfg(num_moe_experts=4, moe_top_k=1), s
        )
        assert moe == dense + 2 * 4 * 4  # + 2*h*E router

    def test_top2_moe_mfu_would_be_understated_by_dense_count(self):
        """The bug this fixes: a top-2 MoE spends ~2x the dense MLP math;
        counting it as dense understates model FLOPs (overstates nothing
        — MFU computed from the dense count is simply wrong)."""
        s = 5
        cfg2 = self._cfg(num_moe_experts=8, moe_top_k=2)
        dense = monitor.transformer_layer_flops_per_token(self._cfg(), s)
        moe2 = monitor.transformer_layer_flops_per_token(cfg2, s)
        h, ffn = 4, 8
        assert moe2 - dense == 2 * h * 8 + (2 * h * ffn + 2 * ffn * h)

    def test_gpt_flops_compose_with_moe_layers(self):
        cfg = self._cfg(num_moe_experts=4, moe_top_k=2, num_layers=3)
        per_layer = monitor.transformer_layer_flops_per_token(cfg, 8)
        assert monitor.gpt_flops_per_token(cfg, 8) == (
            3 * per_layer + 2 * cfg.hidden_size * cfg.vocab_size
        )


class TestMemorySinkCap:
    def test_eviction_at_cap(self):
        sink = monitor.MemorySink(max_records=3)
        for i in range(5):
            sink.emit(monitor.make_record("metrics", i, i=i))
        assert len(sink.records) == 3
        assert [r["i"] for r in sink.records] == [2, 3, 4]  # oldest evicted

    def test_default_is_bounded(self):
        sink = monitor.MemorySink()
        assert sink.records.maxlen == monitor.MemorySink.DEFAULT_MAX_RECORDS

    def test_none_means_unbounded(self):
        sink = monitor.MemorySink(max_records=None)
        assert sink.records.maxlen is None

    def test_invalid_cap_rejected(self):
        with pytest.raises(ValueError):
            monitor.MemorySink(max_records=0)

    def test_router_integration_keeps_newest(self):
        sink = monitor.MemorySink(max_records=2)
        router = monitor.MetricRouter([sink])
        for i in range(4):
            router.metrics(i, loss=float(i))
        assert [r["step"] for r in sink.records] == [2, 3]
