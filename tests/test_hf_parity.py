"""Functional parity with HuggingFace GPT-2.

The strongest external oracle available offline: a randomly-initialized
``transformers.GPT2LMHeadModel`` (no download — zero-egress safe) is mapped
through ``apex_tpu.models.hf_import`` and must produce the same logits and
per-token loss.  Catches qkv-packing, gelu-flavor, LN-placement, scale, and
tying bugs that self-referential tests cannot see.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.GPT2Config(
        vocab_size=128,
        n_positions=64,
        n_embd=48,
        n_layer=3,
        n_head=4,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    return model


def test_logits_match(hf_model):
    from apex_tpu.models.hf_import import gpt2_from_hf

    model, variables = gpt2_from_hf(hf_model)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 128, size=(2, 32))

    with torch.no_grad():
        ref = hf_model(torch.from_numpy(tokens)).logits.numpy()

    logits = model.apply(variables, jnp.asarray(tokens))  # (b, s, v)
    ours = np.asarray(logits, np.float32)
    # fp32 both sides; atol covers torch-oneDNN vs XLA-CPU matmul rounding
    np.testing.assert_allclose(ours, ref, atol=2e-5)


def test_loss_matches(hf_model):
    from apex_tpu.models.hf_import import gpt2_from_hf

    model, variables = gpt2_from_hf(hf_model)
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, 128, size=(2, 32))

    t = torch.from_numpy(tokens)
    with torch.no_grad():
        # HF shifts internally when labels == input_ids
        ref_loss = float(hf_model(t, labels=t).loss)

    # ours: labels are the NEXT token per position (no internal shift)
    labels = np.roll(tokens, -1, axis=1)
    losses = model.apply(variables, jnp.asarray(tokens), labels=jnp.asarray(labels))
    # HF's shift drops the last position of every row
    ours = float(jnp.mean(losses[:, :-1]))
    np.testing.assert_allclose(ours, ref_loss, rtol=1e-4)


@pytest.fixture(scope="module")
def hf_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=48,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,  # real GQA
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attention_dropout=0.0,
    )
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model


def test_llama_logits_match(hf_llama):
    """Llama family: rmsnorm + rotate-half RoPE + SwiGLU + GQA + no-bias
    linears + untied head, mapped onto GPTModel — logits equal to fp32
    rounding against the HF implementation."""
    from apex_tpu.models.hf_import import llama_from_hf

    model, variables = llama_from_hf(hf_llama)
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, 128, size=(2, 32))

    with torch.no_grad():
        ref = hf_llama(torch.from_numpy(tokens)).logits.numpy()

    logits = model.apply(variables, jnp.asarray(tokens))  # (b, s, v)
    np.testing.assert_allclose(np.asarray(logits, np.float32), ref, atol=3e-5)


def test_mistral_logits_match_with_sliding_window():
    """Mistral = llama schema + sliding window; seq (48) > window (16) so
    the band is genuinely active in both implementations."""
    cfg = transformers.MistralConfig(
        vocab_size=128,
        hidden_size=48,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        sliding_window=16,
        tie_word_embeddings=False,
        attention_dropout=0.0,
    )
    torch.manual_seed(3)
    hf = transformers.MistralForCausalLM(cfg)
    hf.eval()

    from apex_tpu.models.hf_import import mistral_from_hf

    model, variables = mistral_from_hf(hf)
    assert model.config.attention_window == 16
    rng = np.random.RandomState(4)
    tokens = rng.randint(0, 128, size=(2, 48))

    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.numpy()

    logits = model.apply(variables, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(logits, np.float32), ref, atol=3e-5)


def test_llama_export_roundtrip(hf_llama):
    """Train-here -> export-to-HF: params perturbed on our side, loaded
    back into a fresh HF model, logits must track OUR model exactly."""
    from apex_tpu.models.hf_import import llama_from_hf, params_to_hf_llama

    model, variables = llama_from_hf(hf_llama)
    # perturb deterministically so the export isn't trivially the import
    variables = jax.tree_util.tree_map(
        lambda x: x + 0.01 * jnp.sin(jnp.arange(x.size, dtype=jnp.float32)
                                     ).reshape(x.shape),
        variables,
    )
    import copy

    hf2 = copy.deepcopy(hf_llama)
    params_to_hf_llama(variables, hf2)
    hf2.eval()

    rng = np.random.RandomState(5)
    tokens = rng.randint(0, 128, size=(2, 24))
    ours = np.asarray(model.apply(variables, jnp.asarray(tokens)), np.float32)
    with torch.no_grad():
        theirs = hf2(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-5)


def test_gpt2_export_roundtrip(hf_model):
    from apex_tpu.models.hf_import import gpt2_from_hf, params_to_hf_gpt2

    model, variables = gpt2_from_hf(hf_model)
    variables = jax.tree_util.tree_map(
        lambda x: x + 0.01 * jnp.cos(jnp.arange(x.size, dtype=jnp.float32)
                                     ).reshape(x.shape),
        variables,
    )
    import copy

    hf2 = copy.deepcopy(hf_model)
    params_to_hf_gpt2(variables, hf2)
    hf2.eval()

    rng = np.random.RandomState(6)
    tokens = rng.randint(0, 128, size=(2, 24))
    ours = np.asarray(model.apply(variables, jnp.asarray(tokens)), np.float32)
    with torch.no_grad():
        theirs = hf2(torch.from_numpy(tokens)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=3e-5)


def test_greedy_generation_matches_hf(hf_llama):
    """Greedy continuations on the same imported weights must match HF's
    generate(do_sample=False) token-for-token."""
    from apex_tpu.models.generate import generate
    from apex_tpu.models.hf_import import llama_from_hf

    model, variables = llama_from_hf(hf_llama)
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 128, size=(2, 8))

    with torch.no_grad():
        ref = hf_llama.generate(
            torch.from_numpy(prompt), max_new_tokens=12, do_sample=False,
            pad_token_id=0,
        ).numpy()

    out = np.asarray(
        generate(model, variables, jnp.asarray(prompt), max_new_tokens=12)
    )
    np.testing.assert_array_equal(out, ref)

    # the uncached reference path must agree token-for-token too
    out_nc = np.asarray(
        generate(model, variables, jnp.asarray(prompt), max_new_tokens=12,
                 use_cache=False)
    )
    np.testing.assert_array_equal(out_nc, ref)


@pytest.mark.parametrize("kw", [
    dict(position_embedding_type="learned"),
    dict(position_embedding_type="rope", num_query_groups=2),
    dict(position_embedding_type="rope", attention_window=5),
])
def test_kv_cache_decode_logits_match_full_forward(kw):
    """Per-step decode logits through the KV cache == slicing a full
    forward pass at the same position — exact semantics, no argmax (random
    init leaves near-tied logits where fp reassociation flips greedy picks,
    so token-level equality is only asserted on real imported weights
    above)."""
    from apex_tpu.models import GPTModel
    from apex_tpu.transformer import TransformerConfig

    cfg = TransformerConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=97,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0, **kw,
    )
    model = GPTModel(config=cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 97)
    variables = model.init(jax.random.PRNGKey(0), tokens[:, :1])

    full = model.apply(variables, tokens)  # (b, s, vocab)

    s0 = 5
    logits, state = model.apply(
        variables, tokens[:, :s0], cache_len=12, mutable=["cache"]
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :s0]), atol=2e-5
    )
    cache = state["cache"]
    for pos in range(s0, 12):
        step_logits, upd = model.apply(
            {**variables, "cache": cache},
            tokens[:, pos : pos + 1],
            position_ids=jnp.full((1, 1), pos),
            decode_step=True,
            mutable=["cache"],
        )
        cache = upd["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full[:, pos]),
            atol=2e-5,
            err_msg=f"decode step at position {pos} ({kw})",
        )


def test_generate_edge_cases():
    """max_new_tokens=0 returns the prompt untouched (the cached path once
    clamped the first sampled token over the last prompt token), and rope
    models with max_position_embeddings left at its 0 default still decode
    (the rope table is sized from the cache length, not the config)."""
    from apex_tpu.models import GPTModel
    from apex_tpu.models.generate import generate
    from apex_tpu.transformer import TransformerConfig

    cfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=61,
        max_position_embeddings=0, position_embedding_type="rope",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPTModel(config=cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0, 61)
    variables = model.init(jax.random.PRNGKey(0), prompt)

    np.testing.assert_array_equal(
        np.asarray(generate(model, variables, prompt, max_new_tokens=0)),
        np.asarray(prompt),
    )
    out = generate(model, variables, prompt, max_new_tokens=4)
    assert out.shape == (1, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))


def test_topk_topp_filtering():
    """_filter_logits implements the HF conventions: top_k keeps exactly
    the k best logits; top_p keeps the smallest prefix of the sorted
    distribution whose mass reaches p (always at least the best token)."""
    from apex_tpu.models.generate import _filter_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.15, 0.07, 0.03]]))

    k2 = _filter_logits(logits, top_k=2, top_p=None)
    np.testing.assert_array_equal(
        np.isfinite(np.asarray(k2))[0], [True, True, False, False, False]
    )
    # p=0.7: {0.5} has mass .5 < .7, {0.5,.25} reaches .75 -> keep 2
    p7 = _filter_logits(logits, top_k=None, top_p=0.7)
    np.testing.assert_array_equal(
        np.isfinite(np.asarray(p7))[0], [True, True, False, False, False]
    )
    # tiny p still keeps the argmax
    p0 = _filter_logits(logits, top_k=None, top_p=1e-6)
    np.testing.assert_array_equal(
        np.isfinite(np.asarray(p0))[0], [True, False, False, False, False]
    )
    # per-row independence
    two = jnp.stack([logits[0], logits[0][::-1]])
    k1 = _filter_logits(two, top_k=1, top_p=None)
    fin = np.isfinite(np.asarray(k1))
    np.testing.assert_array_equal(fin[0], [True, False, False, False, False])
    np.testing.assert_array_equal(fin[1], [False, False, False, False, True])

    # through generate: sampled continuations stay inside the top-k set of
    # each step (statistical smoke on a real sampling run)
    from apex_tpu.models import GPTModel
    from apex_tpu.models.generate import generate
    from apex_tpu.transformer import TransformerConfig

    cfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=31,
        max_position_embeddings=32, hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPTModel(config=cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0, 31)
    variables = model.init(jax.random.PRNGKey(0), prompt)
    out = generate(model, variables, prompt, max_new_tokens=6,
                   temperature=1.0, rng=jax.random.PRNGKey(9), top_k=1)
    # top_k=1 at any temperature IS greedy
    ref = generate(model, variables, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampling_edges_pinned():
    """The serving-facing sampling edges (ISSUE 13 satellite), pinned:

    - ``top_k >= vocab`` is an exact no-op (not merely equivalent-by-
      accident through the sort);
    - ``top_p = 1.0`` keeps the FULL mass — no token may be lost to
      cumulative-sum rounding at the boundary;
    - ``top_k < 1`` and ``top_p <= 0`` refuse with a reasoned error
      instead of sampling from an empty keep-set;
    - ``temperature = 0`` is deterministic argmax regardless of rng;
    - ``sample_next_token`` (the traced-temperature serving variant)
      agrees with the greedy path at t=0 and stays inside the top-k
      set when sampling.
    """
    from apex_tpu.models.generate import _filter_logits, sample_next_token

    logits = jnp.asarray([[2.0, -1.0, 0.5, -3.0, 1.0]])
    vocab = logits.shape[-1]

    for k in (vocab, vocab + 1, 10 * vocab):
        np.testing.assert_array_equal(
            np.asarray(_filter_logits(logits, top_k=k, top_p=None)),
            np.asarray(logits),
        )
    np.testing.assert_array_equal(
        np.asarray(_filter_logits(logits, top_k=None, top_p=1.0)),
        np.asarray(logits),
    )
    # near-boundary: a distribution whose cumsum rounds to 1.0 before
    # the last slot must still keep every token at top_p=1.0
    tiny = jnp.asarray([[0.0, -20.0, -40.0, -60.0]])
    np.testing.assert_array_equal(
        np.asarray(_filter_logits(tiny, top_k=None, top_p=1.0)),
        np.asarray(tiny),
    )
    with pytest.raises(ValueError, match="top_k must be >= 1"):
        _filter_logits(logits, top_k=0, top_p=None)
    with pytest.raises(ValueError, match="top_p must be in"):
        _filter_logits(logits, top_k=None, top_p=0.0)

    # temperature=0 is argmax, rng-independent
    from apex_tpu.models import GPTModel
    from apex_tpu.models.generate import generate
    from apex_tpu.transformer import TransformerConfig

    cfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=37,
        max_position_embeddings=32, hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    model = GPTModel(config=cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 5), 0, 37)
    variables = model.init(jax.random.PRNGKey(0), prompt)
    a = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=0.0, rng=jax.random.PRNGKey(1))
    b = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=0.0, rng=jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    full = model.apply(variables, a[:, :-1])
    np.testing.assert_array_equal(
        np.asarray(a[:, -1]),
        np.asarray(jnp.argmax(full[:, -1].astype(jnp.float32), -1)),
    )

    # the traced-temperature serving variant: t=0 == argmax; t>0 with
    # top_k=1 is still the argmax (the kept set is a single token)
    row = jnp.asarray([0.1, 3.0, -1.0, 0.2])
    key = jax.random.PRNGKey(7)
    assert int(sample_next_token(row, jnp.float32(0.0), key)) == 1
    assert int(sample_next_token(row, jnp.float32(1.3), key, top_k=1)) == 1
    batched = sample_next_token(
        jnp.stack([row, row[::-1]]),
        jnp.float32(0.0), key,
    )
    np.testing.assert_array_equal(np.asarray(batched), [1, 2])


def test_position_bound_refusal_pinned():
    """``_check_position_bound`` refuses (reasoned error, not clamped
    garbage) when prompt + max_new_tokens exceeds a learned-position
    model's table — through both ``generate`` and ``beam_search``."""
    from apex_tpu.models import GPTModel
    from apex_tpu.models.generate import beam_search, generate
    from apex_tpu.transformer import TransformerConfig

    cfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=37,
        max_position_embeddings=8, hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPTModel(config=cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 6), 0, 37)
    variables = model.init(jax.random.PRNGKey(0), prompt)

    # 6 + 2 == 8 fits; 6 + 3 would gather clamped garbage -> refuse
    out = generate(model, variables, prompt, max_new_tokens=2)
    assert out.shape == (1, 8)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(model, variables, prompt, max_new_tokens=3)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        beam_search(model, variables, prompt, max_new_tokens=3, num_beams=2)


class _MarkovLM(nn.Module):
    """Stub LM whose next-token logits depend only on the current token —
    a lookup table, so beam-search outcomes are analytically known."""

    table: tuple  # (vocab, vocab) row-stochastic log-probs

    @nn.compact
    def __call__(self, tokens, position_ids=None, cache_len=None,
                 decode_step=False, labels=None, loss_mask=None,
                 deterministic=True):
        self.variable(
            "cache", "dummy", lambda: jnp.zeros((tokens.shape[0], 1))
        )
        return jnp.asarray(self.table)[tokens]  # (b, s, vocab)


def test_beam_search():
    from apex_tpu.models.generate import beam_search, generate

    # trap distribution from state 0: token 1 is the greedy pick (p=.5)
    # but dead-ends (uniform continuations); token 2 (p=.4) leads to
    # token 3 with p=.9 — the 2-step optimum is [2, 3]
    import numpy as onp

    V = 4
    tbl = onp.full((V, V), 1.0 / V)
    tbl[0] = [0.05, 0.5, 0.4, 0.05]
    tbl[2] = [0.02, 0.03, 0.05, 0.9]
    table = tuple(map(tuple, onp.log(tbl)))
    model = _MarkovLM(table=table)
    prompt = jnp.zeros((2, 1), jnp.int32)
    variables = {"params": {}}

    toks, scores = beam_search(model, variables, prompt,
                               max_new_tokens=2, num_beams=2)
    assert toks.shape == (2, 2, 3) and scores.shape == (2, 2)
    # best beam took the trap exit, not the greedy dead end
    np.testing.assert_array_equal(np.asarray(toks[:, 0, 1:]), [[2, 3], [2, 3]])
    # normalized by the FULL hypothesis length (prompt 1 + generated 2),
    # HF's BeamHypotheses convention
    np.testing.assert_allclose(
        np.asarray(scores[:, 0]), np.log(0.4 * 0.9) / 3, rtol=1e-5
    )
    # greedy walks into the trap
    g = generate(model, variables, prompt, max_new_tokens=2)
    np.testing.assert_array_equal(np.asarray(g[:, 1]), [1, 1])
    # beams are sorted best-first
    assert np.all(np.asarray(scores[:, 0]) >= np.asarray(scores[:, 1]))


def test_beam_width_one_is_greedy(hf_llama):
    """num_beams=1 must reproduce cached greedy token-for-token — same
    logits through the same cache path, argmax == top-1 of log_softmax."""
    from apex_tpu.models.generate import beam_search, generate
    from apex_tpu.models.hf_import import llama_from_hf

    model, variables = llama_from_hf(hf_llama)
    prompt = jnp.asarray(np.random.RandomState(11).randint(0, 128, (2, 6)))
    greedy = generate(model, variables, prompt, max_new_tokens=8)
    beams, scores = beam_search(model, variables, prompt,
                                max_new_tokens=8, num_beams=1)
    np.testing.assert_array_equal(np.asarray(beams[:, 0]), np.asarray(greedy))
    assert np.isfinite(np.asarray(scores)).all()


def test_qkv_regroup_roundtrip():
    from apex_tpu.models.hf_import import _regroup_qkv

    h, heads = 12, 3
    w = np.arange(3 * h, dtype=np.float32)
    out = _regroup_qkv(w, heads)
    hn = h // heads
    # head 0 block must be [q0.. k0.. v0..] = [0:4, 12:16, 24:28]
    np.testing.assert_array_equal(
        out[: 3 * hn],
        np.concatenate([w[0:hn], w[h : h + hn], w[2 * h : 2 * h + hn]]),
    )
