"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test topology (DistributedTestBase spawns
world_size<=4 single-node processes; apex/transformer/testing/
distributed_test_base.py:36-38) — here a single JAX process with 8 virtual
CPU devices exercises every mesh/collective path, and Pallas kernels run in
interpret mode.
"""

import os

# Must be set before jax initializes its backends. Force-override: the outer
# environment may point JAX_PLATFORMS at the real TPU (axon), and the axon
# plugin's sitecustomize also overrides the jax config — tests always run on
# the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    try:
        from apex_tpu.parallel import parallel_state

        parallel_state.destroy_model_parallel()
    except Exception:
        pass
