"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test topology (DistributedTestBase spawns
world_size<=4 single-node processes; apex/transformer/testing/
distributed_test_base.py:36-38) — here a single JAX process with 8 virtual
CPU devices exercises every mesh/collective path, and Pallas kernels run in
interpret mode. The compiled-HLO analysis passes (donation, the hlo-comms
differ, hlo-sharding) compile against this same virtual topology — their
``replica_groups``/sharding assertions hold digit-for-digit with no TPU
attached, which is what keeps the analysis self-check tier-1.
"""

import os

# Must be set before jax initializes its backends. Force-override: the outer
# environment may point JAX_PLATFORMS at the real TPU (axon), and the axon
# plugin's sitecustomize also overrides the jax config — tests always run on
# the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import pytest  # noqa: E402

# Slow tier (measured >=8 s each on the CPU mesh, ~430 s of the ~750 s
# suite): excluded from the smoke run. Central list instead of per-file
# decorators so the tier stays auditable in one place.
#   smoke: python -m pytest tests/ -q -m "not slow"   (~5 min serial)
#   fast:  python -m pytest tests/ -q -m "not slow" -n 4
#   full:  python -m pytest tests/ -q
_SLOW_TESTS = {
    "test_fwd_bwd_pre_post_checked_matches_unchecked",
    "test_gpt_pp_tp_sp_full_step_checked",
    "test_amp_mlp_example",
    "test_imagenet_example",
    "test_long_context_ring_cp_example",
    "test_gpt_cp_tp_sp_matches_tp_only",
    "test_pp_cp_tp_loss_matches_cp_disabled",
    "test_zero_dp_inside_pp_mesh_trains",
    "test_gpt_pretrain_example",
    "test_gpt_pretrain_resume",
    "test_gpt_pretrain_chaos",
    "test_gpt_compression_parity",
    "test_gpt_compression_resume_migration",
    "test_elastic_selftest_gate",
    "test_replay_selftest_gate",
    "test_serving_selftest_gate",
    "test_remediation_selftest_gate",
    "test_remediation_campaign",
    "test_gpt_remediation_acceptance_drill",
    "test_serving_wedged_decode_bundle",
    "test_serving_overload_drill",
    "test_serving_cancel_and_drain_hardening",
    "test_fleet_selftest_gate",
    "test_fleet_chaos_drill",
    "test_cross_process_determinism",
    "test_gpt_replay_bitflip_drill",
    "test_gpt_elastic_chaos_drill",
    "test_gpt_preemption_skip_budget",
    "test_gpt_hang_incident_drill",
    "test_gpt_slow_host_stall_drill",
    "test_crash_mid_fingerprint_leaves_unverified_dir",
    # subprocess pins: each child pays a fresh jax import (~10 s)
    "test_sigterm_mid_finalize_still_commits",
    "test_kill_mid_async_save_leaves_clean_torn_dir",
    "test_gpt_pretrain_xray",
    "test_gpt_pretrain_profile_analyze",
    "test_analysis_cli_subprocess",
    "test_gpt_pp_target_zero_comms_suppressions",
    "test_sparsity_example",
    "test_llama_finetune_example",
    "test_post_params_stay_replicated_under_sp",
    "test_matches_sequential_composition",
    "test_zero_bubble_matches_fused_pre_post",
    "test_bert_sp_loss_and_grads_match_non_sp",
    "test_tp8_loss_decreases",
    "test_selective_remat_matches_plain",
    "test_tp8_sequence_parallel_loss_decreases",
    "test_loss_decreases",
    "test_gradients_flow_through_halo",
    "test_layer_with_moe_mlp",
    "test_sp_matches_non_sp",
    "test_forward_shapes",
    "test_forward_shape_and_dtype",
    "test_train_updates_batch_stats_and_loss_decreases",
    "test_ep_matches_local",
    "test_pp_tp_sp_training_converges",
    "test_llama_style_pp_tp_sp_training_converges",
    "test_syncbn_dp_matches_single_device_global_batch",
    "test_matches_unsharded",
    "test_gpt_ring_cp_matches_single_device",
    "test_inner_blocking_matches",
    "test_grad_flows",
    "test_remat_matches_plain",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: >=8s on the CPU mesh; excluded by -m 'not slow'"
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection / recovery-path tests (tier-1 unless also slow)",
    )


_COLLECT_ERRORS = False


def pytest_collectreport(report):
    # a module that fails to import must not nuke the whole run through the
    # stale-_SLOW_TESTS guard below: its slow tests are legitimately absent
    global _COLLECT_ERRORS
    if report.failed:
        _COLLECT_ERRORS = True


def pytest_collection_modifyitems(config, items):
    seen = set()
    for item in items:
        if item.originalname in _SLOW_TESTS or item.name in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
            seen.add(item.originalname if item.originalname in _SLOW_TESTS
                     else item.name)
    # name-keyed tiers rot silently: a renamed slow test would drop back
    # into the smoke run with no signal. Fail on stale entries, but only
    # when the FULL suite was collected — any subsetting (node ids, file
    # paths, --ignore, --deselect, -k) legitimately hides entries.
    inv = [str(a) for a in config.invocation_params.args]
    subsetting = any(
        "::" in a or a.endswith(".py") or a.startswith(("-k", "--ignore", "--deselect"))
        for a in inv
    )
    if not subsetting and not _COLLECT_ERRORS:
        stale = _SLOW_TESTS - seen
        if stale:
            raise pytest.UsageError(
                f"_SLOW_TESTS entries matched no collected test (renamed or "
                f"removed?): {sorted(stale)}"
            )


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    yield
    try:
        from apex_tpu.parallel import parallel_state

        parallel_state.destroy_model_parallel()
    except Exception:
        pass
