"""Quantized gradient collectives (parallel/compress.py): quantization
core bounds, EF accumulation invariant, found_inf propagation, the
hand-counted compressed-bytes ledger pin on the dp2xtp2 GPT target, the
hlo-comms differ's positive int8-pattern confirmation, the defer_sync
relaxation, and the lint.compressed-collective home rule.

The acceptance spine (ISSUE 11): predicted dp-axis wire bytes drop
>= 3.5x vs the exact path, the differ CONFIRMS the int8 pattern was
emitted (zero new allowlist suppressions), and convergence/found_inf
parity is pinned by the slow-tier GPT example runs in
tests/test_examples.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import HAS_VMA, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.monitor.xray import ledger as xlax
from apex_tpu.parallel import CompressionConfig, compress
from apex_tpu.parallel.ddp import all_reduce_gradients

DEVS = np.asarray(jax.devices())
pytestmark = pytest.mark.skipif(
    DEVS.size < 8, reason="needs the 8-device CPU mesh (conftest)"
)

CFG = CompressionConfig()


@pytest.fixture
def mesh():
    return Mesh(DEVS, ("dp",))


def _scale_exact(rng, shape, chunk):
    """Integer data that quantizes EXACTLY: every ``chunk``-aligned block
    carries a planted 254 (scale = 254/127 = 2) and even values, so
    ``round(x/2)*2 == x`` digit-for-digit in fp32."""
    x = (rng.randint(-126, 127, size=shape) * 2).astype(np.float32)
    flat = x.reshape(x.shape[0], -1) if x.ndim > 1 else x[None]
    flat[..., ::chunk] = 254.0
    return x


# ---------------------------------------------------------------------------
# quantization core


class TestQuantizeCore:
    def test_round_trip_error_bound(self):
        x = np.random.RandomState(0).randn(1000).astype(np.float32) * 3
        p, s = compress.quantize_blockwise(jnp.asarray(x), CFG)
        assert p.dtype == jnp.int8 and p.shape == (1000,)
        assert s.shape == (8,)  # ceil(1000/128)
        deq = np.asarray(compress.dequantize_blockwise(p, s, CFG))
        # per-element bound: half the block's scale
        bound = np.repeat(np.asarray(s), CFG.block_size)[:1000] / 2
        assert np.all(np.abs(deq - x) <= bound + 1e-7)

    def test_ragged_tail_and_zero_block(self):
        x = np.zeros(130, np.float32)
        x[:3] = [1.0, -2.0, 127.0]
        p, s = compress.quantize_blockwise(jnp.asarray(x), CFG)
        assert s.shape == (2,)
        deq = np.asarray(compress.dequantize_blockwise(p, s, CFG))
        np.testing.assert_array_equal(deq, x)  # scale-1 block + zero block

    @pytest.mark.parametrize("bad", [np.nan, np.inf])
    def test_nonfinite_block_poisons_whole_block_only(self, bad):
        x = np.ones(256, np.float32)
        x[5] = bad
        p, s = compress.quantize_blockwise(jnp.asarray(x), CFG)
        deq = np.asarray(compress.dequantize_blockwise(p, s, CFG))
        assert not np.isfinite(deq[:128]).any()   # poisoned block
        np.testing.assert_array_equal(deq[128:], x[128:])  # clean block

    def test_fp8_config(self):
        if "fp8" not in compress._WIRE_DTYPES:
            with pytest.raises(ValueError, match="not available"):
                CompressionConfig(dtype="fp8")
            return
        cfg = CompressionConfig(dtype="fp8")
        x = np.random.RandomState(1).randn(300).astype(np.float32)
        p, s = compress.quantize_blockwise(jnp.asarray(x), cfg)
        assert p.dtype == cfg.wire_dtype
        deq = np.asarray(compress.dequantize_blockwise(p, s, cfg))
        # e4m3 rounds to ~2^-4 RELATIVE error (3 mantissa bits), plus a
        # subnormal absolute floor near zero
        bound = np.abs(x) / 16 + np.repeat(
            np.asarray(s), cfg.block_size)[:300] / 32
        assert np.all(np.abs(deq - x) <= bound)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="not available|choose"):
            CompressionConfig(dtype="int4")
        with pytest.raises(ValueError, match="block_size"):
            CompressionConfig(block_size=0)


# ---------------------------------------------------------------------------
# quantized collectives on the mesh


class TestQuantizedCollectives:
    def test_quantized_psum_tracks_exact(self, mesh):
        g = np.random.RandomState(1).randn(8, 500).astype(np.float32)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def qsum(x):
            return compress.quantized_psum(x[0], "dp", CFG)

        got = np.asarray(qsum(g))
        exact = g.sum(0)
        # per-element error: 8 phase-1 block errors + 1 phase-2 error,
        # each bounded by the respective block amax / 254
        bound = (np.abs(g).max() * 8 + np.abs(exact).max()) / 254
        assert np.abs(got - exact).max() <= bound

    def test_scale_exact_data_is_exact(self, mesh):
        """All ranks IDENTICAL even-integer data with a planted 254 per
        chunk: phase 1 is exact by scale-2 design, and the phase-2
        reduced chunk is 8x the data — amax 8*254, scale 16, every
        element an exact multiple — so the whole decomposition is
        digit-for-digit equal to the psum."""
        row = _scale_exact(np.random.RandomState(2), (1, 512), 64)[0]
        g = np.broadcast_to(row, (8, 512)).copy()
        # chunk = 512/8 = 64 -> every rank-row block carries a 254

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def qsum(x):
            return compress.quantized_psum(x[0], "dp", CFG)

        np.testing.assert_array_equal(np.asarray(qsum(g)), g.sum(0))

    def test_psum_scatter_phase1_exact_on_scale_exact_data(self, mesh):
        g = _scale_exact(np.random.RandomState(3), (8, 64), 8)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        def qscat(x):
            return compress.quantized_psum_scatter(x[0], "dp", CFG)[None]

        got = np.asarray(qscat(g)).reshape(-1)
        np.testing.assert_array_equal(got, g.sum(0))

    def test_psum_scatter_rejects_indivisible(self, mesh):
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        def qscat(x):
            return compress.quantized_psum_scatter(x[0], "dp", CFG)[None]

        with pytest.raises(ValueError, match="divisible"):
            jax.eval_shape(qscat, jnp.zeros((8, 63)))

    def test_quantized_all_gather(self, mesh):
        g = _scale_exact(np.random.RandomState(4), (8, 64), 64)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def qgat(x):
            return compress.quantized_all_gather(x[0], "dp", CFG)

        np.testing.assert_array_equal(np.asarray(qgat(g)), g.reshape(-1))

    def test_quantized_all_gather_per_rank_scales(self, mesh):
        """Ranks with WILDLY different magnitudes: dequantization must
        apply each rank's OWN scales — a flat dequant of the gathered
        payload would read rank 0's scale across every shard (the
        misalignment quantized_psum's phase 2 also guards against)."""
        rng = np.random.RandomState(13)
        mags = 10.0 ** np.arange(8)  # 1 .. 1e7, one decade per rank
        g = (rng.rand(8, 64).astype(np.float32) + 0.5) * mags[:, None]

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def qgat(x):
            return compress.quantized_all_gather(x[0], "dp", CFG)

        got = np.asarray(qgat(g)).reshape(8, 64)
        # per-rank relative error bounded by that rank's block scale
        for r in range(8):
            bound = np.abs(g[r]).max() / 254 + 1e-6
            assert np.abs(got[r] - g[r]).max() <= bound, r

    def test_min_elements_routes_small_leaves_exact(self, mesh):
        cfg = CompressionConfig(min_elements=32)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def qsum(x):
            return compress.quantized_psum(x[0, :16], "dp", cfg)

        led = xlax.predict_comms(qsum, jnp.zeros((8, 16)))
        # below the threshold: ONE exact f32 psum, no quantized ops
        ops = {(e.op, e.dtype) for e in led.entries}
        assert ops == {("psum", "float32")}

    @pytest.mark.skipif(not HAS_VMA, reason="checked shard_map (vma) only")
    def test_checked_vma_mode_invariant_result(self, mesh):
        """Under jax's default CHECKED shard_map the gathered result must
        type invariant (out_specs P()) exactly like the psum it replaces
        — the _gather_tiled invariant-gather contract."""
        g = np.random.RandomState(5).randn(8, 256).astype(np.float32)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
        )
        def qsum(x):
            x = x.reshape(x.shape[-1])
            x = jax.lax.pcast(x, "dp", to="varying")
            return compress.quantized_psum(x, "dp", CFG)

        got = np.asarray(qsum(g))
        exact = g.sum(0)
        bound = (np.abs(g).max() * 8 + np.abs(exact).max()) / 254
        assert np.abs(got - exact).max() <= bound


# ---------------------------------------------------------------------------
# error feedback


class TestErrorFeedback:
    def test_scatter_ef_invariant_digit_for_digit(self, mesh):
        """ACCEPTANCE (satellite): over T compressed reduce-scatters with
        error feedback, ``sum of applied updates + final residual ==
        sum of true grads`` DIGIT-FOR-DIGIT in fp32 on each rank — the
        telescoping identity e' = acc - C(acc). Data is scale-exact (even
        integers, planted 254 per chunk block) so every fp32 add/sub in
        the telescope is exact; residuals are genuinely nonzero on the
        way (odd intermediate sums quantize lossily)."""
        T, L = 4, 64  # chunk 8 per rank
        rng = np.random.RandomState(6)
        # per-rank grads: even ints with planted 254 -> scale 2 forever;
        # make them ODD sometimes via +1 so residuals become nonzero
        g_steps = []
        for _ in range(T):
            g = _scale_exact(rng, (8, L), 8)
            odd = (rng.rand(8, L) < 0.5) & (g != 254.0) & (np.abs(g) < 126)
            g = g + odd  # odd values: round(x/2)*2 != x -> residual ±1
            g_steps.append(g.astype(np.float32))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp")), check_vma=False,
        )
        def step(g, ef):
            acc = g[0] + ef[0]
            shard, sent = compress.quantized_psum_scatter(
                acc, "dp", CFG, return_transmitted=True
            )
            new_ef = compress.ef_update(acc, sent)
            return shard[None], sent[None], new_ef[None]

        ef = np.zeros((8, L), np.float32)
        sent_total = np.zeros((8, L), np.float32)
        any_resid = False
        for g in g_steps:
            shard, sent, ef = map(np.asarray, step(g, ef))
            sent_total += sent
            any_resid = any_resid or np.asarray(ef).any()
        true_total = sum(g_steps)
        # the per-rank telescope: transmitted + residual == true, exactly
        np.testing.assert_array_equal(sent_total + ef, true_total)
        assert any_resid  # the invariant was not vacuous

    def test_ddp_ef_bounds_accumulated_error(self, mesh):
        """With EF the CUMULATIVE applied-update error stays bounded by
        one step's quantization error instead of growing with T — the
        convergence mechanism the slow-tier parity tests rely on."""
        T, L = 8, 256
        rng = np.random.RandomState(7)
        g_steps = [rng.randn(8, L).astype(np.float32) for _ in range(T)]

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P(), P("dp")), check_vma=False,
        )
        def step(g, ef):
            out, new_ef = all_reduce_gradients(
                {"w": g[0]}, "dp", gradient_average=False,
                compression=CFG, ef_state={"w": ef[0]},
            )
            return out["w"], new_ef["w"][None]

        ef = np.zeros((8, L), np.float32)
        applied = np.zeros(L, np.float32)
        for g in g_steps:
            out, ef = step(g, ef)
            applied += np.asarray(out)
        true_total = sum(g.sum(0) for g in g_steps)
        # phase-1 errors telescope away; what remains is the CURRENT
        # residual + T phase-2 chunk errors (each bounded by amax/254)
        per_step_p2 = max(np.abs(g.sum(0)).max() for g in g_steps) / 254
        bound = np.abs(np.asarray(ef)).sum(0).max() + T * per_step_p2 + 1e-4
        assert np.abs(applied - true_total).max() <= bound
        # sanity: EF beats no-EF accumulation on the same stream
        ef0 = np.zeros((8, L), np.float32)
        applied_no_ef = np.zeros(L, np.float32)
        for g in g_steps:
            out, _ = step(g, ef0 * 0)  # residual always zero
            applied_no_ef += np.asarray(out)
        err_ef = np.abs(applied - true_total).mean()
        err_no = np.abs(applied_no_ef - true_total).mean()
        assert err_ef < err_no

    def test_nonfinite_grads_reach_found_inf_and_reset_residual(self, mesh):
        """ACCEPTANCE (satellite): overflow propagates through the
        compressed path to found_inf — and the residual for the
        poisoned leaf RESETS to zero instead of carrying NaN forever."""
        from apex_tpu.amp import GradScaler

        # no model-parallel axes on this dp-only test mesh; the found_inf
        # CONSENSUS psum itself stays on the exact path by construction
        scaler = GradScaler(loss_scale=128.0, model_parallel_axes=())

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=(P(), P(), P("dp")), check_vma=False,
        )
        def step(g, ef):
            out, new_ef = all_reduce_gradients(
                {"w": g[0]}, "dp", compression=CFG,
                ef_state={"w": ef[0]},
            )
            state = scaler.init()
            _, found_inf = scaler.unscale(state, out)
            return out["w"], found_inf, new_ef["w"][None]

        g = np.random.RandomState(8).randn(8, 256).astype(np.float32)
        ef = np.abs(np.random.RandomState(9).randn(8, 256)).astype(np.float32)
        _, found, _ = step(g, ef)
        assert not bool(found)
        g_bad = g.copy()
        g_bad[2, 7] = np.inf
        out, found, new_ef = step(g_bad, ef)
        assert bool(found)  # the poison crossed the compressed wire
        assert not np.isfinite(np.asarray(out)).all()
        # rank 2's residual covering the poisoned element reset to 0
        assert not np.asarray(new_ef)[2, :].any() or np.isfinite(
            np.asarray(new_ef)).all()

    def test_ef_requires_compression(self, mesh):
        with pytest.raises(ValueError, match="ef_state without"):
            all_reduce_gradients(
                {"w": jnp.zeros(4)}, "dp", ef_state={"w": jnp.zeros(4)}
            )


# ---------------------------------------------------------------------------
# ZeRO integration


class TestZeroCompressed:
    def _updates(self, mesh, compression, grads, params):
        from apex_tpu.optimizers import distributed_fused_adam

        opt = distributed_fused_adam(
            lr=1e-3, axis_name="dp", axis_size=8, average_grads=False,
            compression=compression,
        )

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False,
        )
        def one(p, g):
            st = opt.init(p)
            up, st2 = opt.update(g, st, p)
            return up, st2.ef_residual

        return one(params, grads)

    def test_compressed_update_tracks_exact_and_carries_residual(self, mesh):
        rng = np.random.RandomState(10)
        params = {"w": jnp.asarray(rng.randn(64, 8), jnp.float32)}
        grads = {"w": jnp.asarray(rng.randn(64, 8) * 1e-2, jnp.float32)}
        up_e, ef_e = self._updates(mesh, None, grads, params)
        up_c, ef_c = self._updates(mesh, CFG, grads, params)
        # exact path: scalar placeholder residual; compressed: real buffer
        assert np.asarray(ef_e).shape == ()
        assert np.asarray(ef_c).ndim == 1 and np.asarray(ef_c).any()
        # Adam normalizes the shard to ~±lr; quantization may move any
        # element by at most one lr
        assert float(jnp.max(jnp.abs(up_e["w"] - up_c["w"]))) <= 1e-3 + 1e-9

    def test_overflow_propagates_through_compressed_scatter(self, mesh):
        from apex_tpu.optimizers.distributed_fused_adam import (
            zero_scatter_grads,
        )

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        def scat(g):
            shard, _, _ = zero_scatter_grads(
                {"w": g[0]}, "dp", 8, average=False, compression=CFG
            )
            return shard[None]

        g = np.random.RandomState(11).randn(8, 512).astype(np.float32)
        assert np.isfinite(np.asarray(scat(g))).all()
        g[4, 3] = np.nan
        assert not np.isfinite(np.asarray(scat(g))).all()


# ---------------------------------------------------------------------------
# the ledger pin + the three-referee acceptance


def _dp_totals(led):
    per = led.per_axis()
    return per.get("dp", {"bytes": 0, "ici_bytes": 0, "calls": 0})


class TestLedgerPin:
    """ACCEPTANCE: hand-counted compressed dp-axis bytes on the dp2xtp2
    GPT target, and the >= 3.5x predicted wire-byte drop vs exact."""

    @pytest.fixture(scope="class")
    def ledgers(self):
        from apex_tpu.analysis.targets import (
            dp2tp2_mesh, gpt_compressed_step_target, gpt_step_target,
        )

        mesh = dp2tp2_mesh()
        exact = gpt_step_target(mesh)
        comp = gpt_compressed_step_target(mesh)
        led_e = xlax.predict_comms(exact.fn, *exact.args)
        led_c = xlax.predict_comms(comp.fn, *comp.args)
        return exact, led_e, led_c

    def test_compressed_dp_bytes_hand_counted(self, ledgers):
        """payload + scales at their TRUE dtypes, digit for digit: per
        28-leaf grad tree, each leaf books the four quantized wire
        arrays (predicted_psum_wire_bytes is the documented formula),
        plus the one exact scalar loss pmean."""
        exact, led_e, led_c = ledgers
        n = 2  # dp axis size on the audit mesh
        leaf_sizes = [
            int(np.prod(l.shape, dtype=np.int64))
            for l in jax.tree_util.tree_leaves(exact.args[0])
        ]
        assert len(leaf_sizes) == 28 and sum(leaf_sizes) == 3792
        want_bytes = want_ici = 0
        for size in leaf_sizes:
            b, i = compress.predicted_psum_wire_bytes(size, n, CFG)
            want_bytes += b
            want_ici += i
        # + the scalar loss pmean (exact path, 4 B payload)
        want_bytes += 4
        want_ici += 4  # ceil(2*(n-1)*4/n) with n=2
        got = _dp_totals(led_c)
        assert got["bytes"] == want_bytes
        assert got["ici_bytes"] == want_ici
        # per-leaf op count: 2 all_to_all + 2 all_gather, + 1 pmean
        assert got["calls"] == 28 * 4 + 1
        # the wire dtypes are the TRUE payload dtypes
        dtypes = {e.dtype for e in led_c.entries if e.axis == "dp"}
        assert dtypes == {"int8", "float32"}

    def test_exact_dp_bytes_unchanged_and_drop_at_least_3_5x(self, ledgers):
        _, led_e, led_c = ledgers
        e, c = _dp_totals(led_e), _dp_totals(led_c)
        # the exact target's dp numbers: the PR-3 pin (28 f32 grad
        # psums + loss pmean)
        assert e["bytes"] == 3792 * 4 + 4
        drop = e["ici_bytes"] / c["ici_bytes"]
        assert drop >= 3.5, (e, c)
        # payload-bytes view drops too (all_to_all + gather double-count
        # the payload relative to one psum, so the floor is lower)
        assert e["bytes"] / c["bytes"] >= 2.0

    def test_timeline_join_reads_compressed_prediction(self, ledgers):
        """Mechanism pin for the third referee: the PR-6 bandwidth join
        consumes the COMPRESSED ledger — dp-axis predicted bytes in the
        join report drop by the same factor, so a hardware capture's
        measured seconds divide into achieved bytes/s against the true
        int8 wire bytes (benchmarks/run_all_tpu.py 'comms' section does
        the measuring)."""
        from apex_tpu.analysis.hlo import parse_hlo_module
        from apex_tpu.monitor.xray.timeline import analyze, parse_trace
        from test_timeline import (  # the synthetic-trace seam
            JOIN_HLO, dp2tp2_mesh as join_mesh, ev, step_marker, trace_dict,
        )

        _, led_e, led_c = ledgers
        tl = parse_trace(trace_dict(
            step_marker(0, 0.0, 1000.0),
            ev("all-reduce.1", 100.0, 200.0),  # a measured dp-axis event
        ))
        module = parse_hlo_module(JOIN_HLO)
        mesh = join_mesh()
        rep_e = analyze(tl, module=module, mesh=mesh, ledger=led_e)
        rep_c = analyze(tl, module=module, mesh=mesh, ledger=led_c)

        def dp(rep):
            return next(a for a in rep.axes if a.axis == "dp")

        # identical measured seconds, compressed predicted bytes: the
        # achieved-bytes/s denominator is the TRUE int8 wire bytes
        assert dp(rep_e).measured_us_per_step == 200.0
        assert dp(rep_c).measured_us_per_step == 200.0
        ratio = (dp(rep_e).predicted_ici_bytes_per_step
                 / dp(rep_c).predicted_ici_bytes_per_step)
        assert ratio >= 3.5
        assert (dp(rep_c).achieved_bytes_per_s
                < dp(rep_e).achieved_bytes_per_s)

    def test_differ_confirms_int8_pattern(self, ledgers):
        """ACCEPTANCE: the hlo-comms differ on the compressed target
        reports the quantized pattern MATCHED (comms.quantized, info)
        and nothing unpredicted/resharded/vanished — zero new allowlist
        suppressions needed."""
        from apex_tpu.analysis import StepContext
        from apex_tpu.analysis.hlo import audit_comms
        from apex_tpu.analysis.targets import (
            dp2tp2_mesh, gpt_compressed_step_target,
        )

        mesh = dp2tp2_mesh()
        tgt = gpt_compressed_step_target(mesh)
        ctx = StepContext(tgt)
        _, compiled = ctx.aot()
        fins = audit_comms(
            tgt.fn, *tgt.args, mesh=mesh,
            donate_argnums=tgt.donate_argnums, target=tgt.name,
            compiled=compiled,
        )
        assert all(f.severity == "info" for f in fins), [
            f.format() for f in fins
        ]
        (q,) = [f for f in fins if f.rule == "comms.quantized"]
        assert q.data["axis"] == "dp" and q.data["ops"] == 56
        # the only other finding is the known CSE fold (comms.folded),
        # identical to the exact target — no new suppressions
        others = {f.rule for f in fins} - {"comms.quantized"}
        assert others <= {"comms.folded"}


# ---------------------------------------------------------------------------
# defer_sync (arXiv:2506.19645 relaxation)


class TestDeferSync:
    def test_default_backward_reduce_scatters(self, mesh):
        from apex_tpu.parallel import mappings

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        def f(x):
            return jax.grad(lambda x: (
                mappings.gather_from_sequence_parallel_region(x, "dp") ** 2
            ).sum())(x)

        led = xlax.predict_comms(f, jnp.zeros((8, 4)))
        assert "psum_scatter" in {e.op for e in led.entries}

    def test_defer_sync_skips_backward_collective(self, mesh):
        from apex_tpu.parallel import mappings

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        def f(x):
            return jax.grad(lambda x: (
                mappings.gather_from_sequence_parallel_region(
                    x, "dp", True, True) ** 2
            ).sum())(x)

        led = xlax.predict_comms(f, jnp.zeros((8, 4)))
        # only the forward gather remains on the wire
        assert {e.op for e in led.entries} == {"all_gather"}
        # numerics: the local split of the exact cotangent
        x = np.random.RandomState(12).randn(8, 4).astype(np.float32)
        got = np.asarray(jax.jit(f)(x))
        np.testing.assert_allclose(got, 2 * x, rtol=1e-6)


# ---------------------------------------------------------------------------
# the home rule


class TestCompressedCollectiveLint:
    def test_seeded_composition_flagged(self):
        from apex_tpu.analysis.lint import run_lint

        files = {"apex_tpu/foo.py": (
            "def my_reduce(x, s):\n"
            "    q = quantize_blockwise(x)\n"
            "    return lax_psum(q)\n"  # not a collective name: clean
        )}
        assert run_lint(rules=["lint.compressed-collective"],
                        files=files) == []
        files = {"apex_tpu/foo.py": (
            "def my_reduce(x):\n"
            "    q, s = quantize_blockwise(x)\n"
            "    g = xlax.all_gather(q, 'dp')\n"
            "    return dequantize_blockwise(g, s)\n"
        )}
        (f,) = run_lint(rules=["lint.compressed-collective"], files=files)
        assert f.rule == "lint.compressed-collective"
        assert f.data == {"quant": "quantize_blockwise",
                          "collective": "all_gather",
                          "function": "my_reduce"}

    def test_wrapper_calls_not_flagged(self):
        from apex_tpu.analysis.lint import run_lint

        files = {"apex_tpu/bar.py": (
            "def reduce_grads(g, ef):\n"
            "    out = compress.quantized_psum(g, 'dp')\n"
            "    flag = xlax.psum(jnp.float32(0), 'tp')\n"
            "    return out, flag\n"
        )}
        assert run_lint(rules=["lint.compressed-collective"],
                        files=files) == []

    def test_compress_home_hits_and_is_allowlisted(self):
        from apex_tpu.analysis import REPO_ALLOWLIST
        from apex_tpu.analysis.lint import run_lint

        fins = run_lint(rules=["lint.compressed-collective"])
        assert fins, "the home rule must HIT compress.py (require_hit)"
        assert all("parallel/compress.py" in f.site for f in fins)
        result = REPO_ALLOWLIST.apply(fins, check_stale=False)
        assert result.ok and not result.findings
