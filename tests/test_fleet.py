"""Serving-fleet tests (apex_tpu.serving.fleet, docs/serving.md "Fleet").

Tier-1: the jax-free pieces — the shared-prefix radix index, the KV
handoff ledger, the two-sided debounced autoscaler, the replica health
machine (detect -> restart -> probation -> readmit on the PR-15 policy
table, escalation on exhausted budgets), and fleet-config validation.

Slow tier: the ``--selftest --fleet`` gate wrapper and the ACCEPTANCE
chaos drill — a seeded Poisson load over a disaggregated 3-replica
fleet with a mid-load replica kill: failover re-dispatches the dead
replica's in-flight work, an SLO breach scales the fleet up, p99 TTFT
stays inside the drill budget, every global id reaches exactly one
terminal record (zero silent drops), the handoff ledger closes matched,
and the goodput partition identity holds digit-for-digit fleet-wide.
"""

import numpy as np
import pytest

from apex_tpu.resilience.remediation.policy import (
    TERMINAL_VERDICTS,
    RemediationPolicy,
)
from apex_tpu.serving import lifecycle
from apex_tpu.serving.fleet import (
    FleetAutoscaler,
    FleetConfig,
    HandoffLedger,
    RadixPrefixIndex,
    Replica,
)
from apex_tpu.serving.loadgen import percentile


class _CapRouter:
    """MetricRouter.event-shaped capture: enough surface for the
    jax-free fleet pieces, zero sink machinery."""

    def __init__(self):
        self.records = []

    def event(self, kind, step, **fields):
        rec = {"kind": kind, "step": int(step), **fields}
        self.records.append(rec)
        return rec


# -- shared-prefix radix index ----------------------------------------------


class TestRadixPrefixIndex:
    def test_longest_indexed_prefix_wins(self):
        idx = RadixPrefixIndex(block_size=4)
        toks = list(range(12))
        assert idx.insert(toks[:8], "a") == 2
        # same 8 tokens: full hit at block granularity
        assert idx.lookup(toks[:8]) == ("a", 8)
        # shared 8-token prefix plus a novel tail: the hit is the
        # longest indexed prefix, not all-or-nothing
        assert idx.lookup(toks[:8] + [99, 98, 97, 96]) == ("a", 8)
        s = idx.stats()
        assert s["hits"] == 2 and s["lookups"] == 2
        assert s["hit_tokens"] == 16

    def test_sub_block_prefix_never_indexed(self):
        # the pool hands off whole blocks; a finer match could never be
        # served, so it must not be reported as a hit
        idx = RadixPrefixIndex(block_size=4)
        assert idx.insert([1, 2, 3], "a") == 0
        assert idx.lookup([1, 2, 3]) == (None, 0)
        assert idx.stats()["hit_rate"] == 0.0

    def test_live_filter_falls_back_to_shorter_claim(self):
        idx = RadixPrefixIndex(block_size=4)
        toks = list(range(12))
        idx.insert(toks, "b")        # b claims depths 1..3
        idx.insert(toks[:8], "a")    # a re-claims depths 1..2
        assert idx.lookup(toks, live={"b"}) == ("b", 12)
        # with b inadmissible the best ADMISSIBLE claim is a's, shorter
        assert idx.lookup(toks, live={"a"}) == ("a", 8)
        assert idx.lookup(toks, live={"c"}) == (None, 0)

    def test_evict_replica_drops_its_claims(self):
        idx = RadixPrefixIndex(block_size=4)
        toks = list(range(8))
        idx.insert(toks, "a")
        assert idx.evict_replica("a") == 2
        assert idx.lookup(toks) == (None, 0)

    def test_lru_bound_holds(self):
        idx = RadixPrefixIndex(block_size=4, max_nodes=3)
        for i in range(8):
            idx.insert([i * 10 + d for d in range(4)], "a")
        assert idx.stats()["nodes"] <= 3
        # the most recent insert survived the pruning
        assert idx.lookup([70, 71, 72, 73]) == ("a", 4)

    def test_validation(self):
        with pytest.raises(ValueError, match="block_size"):
            RadixPrefixIndex(block_size=0)
        with pytest.raises(ValueError, match="max_nodes"):
            RadixPrefixIndex(block_size=4, max_nodes=0)


# -- KV handoff ledger ------------------------------------------------------


class TestHandoffLedger:
    def test_matched_roundtrip_books_both_sides(self):
        cap = _CapRouter()
        led = HandoffLedger(router=cap)
        seq = led.book_out(rid=7, src="r0", n_blocks=2, nbytes=4096, tick=3)
        led.book_in(seq, dst="r1", n_blocks=2, nbytes=4096, tick=3)
        audit = led.audit()
        assert audit["matched"] is True
        assert audit["handoffs"] == 1 and audit["abandoned"] == 0
        assert audit["bytes_out"] == audit["bytes_in"] == 4096
        assert audit["open"] == [] and audit["mismatched"] == []
        sides = [r["side"] for r in cap.records if r["kind"] == "handoff"]
        assert sides == ["out", "in"]
        assert all(r["id"] == 7 and r["src"] == "r0" for r in cap.records)

    def test_open_exchange_fails_the_audit(self):
        led = HandoffLedger()
        seq = led.book_out(rid=0, src="r0", n_blocks=1, nbytes=100, tick=0)
        audit = led.audit()
        assert audit["matched"] is False and audit["open"] == [seq]

    def test_byte_mismatch_is_surfaced(self):
        led = HandoffLedger()
        seq = led.book_out(rid=0, src="r0", n_blocks=1, nbytes=100, tick=0)
        led.book_in(seq, dst="r1", n_blocks=1, nbytes=96, tick=0)
        audit = led.audit()
        assert audit["matched"] is False and audit["mismatched"] == [seq]

    def test_abandon_closes_without_matching(self):
        cap = _CapRouter()
        led = HandoffLedger(router=cap)
        seq = led.book_out(rid=1, src="r0", n_blocks=1, nbytes=100, tick=2)
        led.abandon(seq, tick=2, reason="no_adopter")
        audit = led.audit()
        # a deliberate drop is CLOSED, not lost: the audit still matches
        assert audit["matched"] is True and audit["abandoned"] == 1
        assert cap.records[-1]["side"] == "abandoned"
        assert cap.records[-1]["reason"] == "no_adopter"

    def test_double_close_and_unknown_seq_refused(self):
        led = HandoffLedger()
        with pytest.raises(ValueError, match="never booked out"):
            led.book_in(99, dst="r1", n_blocks=1, nbytes=1, tick=0)
        seq = led.book_out(rid=0, src="r0", n_blocks=1, nbytes=1, tick=0)
        led.book_in(seq, dst="r1", n_blocks=1, nbytes=1, tick=0)
        with pytest.raises(ValueError, match="already closed"):
            led.book_in(seq, dst="r2", n_blocks=1, nbytes=1, tick=0)
        with pytest.raises(ValueError, match="already closed"):
            led.abandon(seq, tick=0, reason="late")


# -- autoscaler -------------------------------------------------------------


class TestFleetAutoscaler:
    def _scaler(self, cap=None, **kw):
        kw.setdefault("ttft_budget_s", 1.0)
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("breach_ticks", 2)
        kw.setdefault("clear_ticks", 3)
        return FleetAutoscaler(router=cap, **kw)

    def test_breach_debounce_then_scale_up(self):
        cap = _CapRouter()
        sc = self._scaler(cap)
        assert sc.observe(0, 2.0, 2) is None     # one breach: debounced
        assert sc.observe(1, 2.0, 2) == "scale_up"
        rec = cap.records[-1]
        assert rec["check"] == "autoscale" and rec["action"] == "scale_up"
        assert sc.stats()["scale_ups"] == 1

    def test_none_signal_holds_the_counters(self):
        # a dead spot in the signal is not evidence either way: the
        # breach streak neither grows nor resets
        sc = self._scaler()
        assert sc.observe(0, 2.0, 2) is None
        assert sc.observe(1, None, 2) is None
        assert sc.observe(2, 2.0, 2) == "scale_up"

    def test_hysteresis_band_resets_both_streaks(self):
        sc = self._scaler()
        sc.observe(0, 2.0, 2)                    # breach streak 1
        assert sc.observe(1, 0.5, 2) is None     # in-band: resets
        assert sc.observe(2, 2.0, 2) is None     # streak restarts at 1
        assert sc.observe(3, 2.0, 2) == "scale_up"

    def test_bounds_respected(self):
        sc = self._scaler()
        sc.observe(0, 2.0, 4)
        assert sc.observe(1, 2.0, 4) is None     # already at max
        sc2 = self._scaler()
        for t in range(3):
            sc2.observe(t, 0.01, 1)
        assert sc2.observe(3, 0.01, 1) is None   # already at min

    def test_clear_streak_scales_down(self):
        sc = self._scaler()
        assert sc.observe(0, 0.01, 2) is None
        assert sc.observe(1, 0.01, 2) is None
        assert sc.observe(2, 0.01, 2) == "scale_down"
        assert sc.stats()["scale_downs"] == 1

    def test_validation(self):
        with pytest.raises(ValueError, match="ttft_budget_s"):
            FleetAutoscaler(0.0, 1, 4)
        with pytest.raises(ValueError, match="min_replicas"):
            FleetAutoscaler(1.0, 3, 2)
        with pytest.raises(ValueError, match="breach_ticks"):
            FleetAutoscaler(1.0, 1, 4, breach_ticks=0)
        with pytest.raises(ValueError, match="low_water"):
            FleetAutoscaler(1.0, 1, 4, low_water=1.5)


# -- replica health machine -------------------------------------------------


class _FakeEngine:
    """The slice of the engine surface Replica touches: start() and the
    load signal's queue/lane tables."""

    def __init__(self):
        self.started = False
        self._queue = []
        self._active = {}

    def start(self):
        self.started = True


class TestReplica:
    def _replica(self, cap=None, factory=None, **policy_kw):
        factory = factory or (lambda name, inc: _FakeEngine())
        policy = RemediationPolicy(**policy_kw) if policy_kw else None
        return Replica("r0", factory, policy=policy, router=cap)

    def test_role_validation(self):
        with pytest.raises(ValueError, match="role"):
            Replica("r0", lambda n, i: _FakeEngine(), role="oracle")

    def test_kill_books_nothing_and_stays_dispatchable(self):
        # a silent death has no oracle: the router keeps dispatching to
        # it until the heartbeat watchdog fires — re-dispatch repairs it
        cap = _CapRouter()
        rep = self._replica(cap)
        rep.kill()
        assert not rep.alive and not rep.healthy
        assert rep.dispatchable
        assert cap.records == []

    def test_detect_restart_probation_readmit_walk(self):
        cap = _CapRouter()
        rep = self._replica(cap, probation_steps=2, max_restarts=2)
        rep.kill()
        rep.miss(), rep.miss()
        assert rep.detect(5) == "restart"
        assert rep.case_state == "detected"
        assert rep.restart(5) is True
        assert rep.alive and rep.incarnation == 1 and rep.restarts == 1
        assert rep.case_state == "probation"
        assert rep.dispatchable and not rep.healthy
        rep.probation_tick(6)
        assert rep.case_state == "probation"   # one clean tick of two
        rep.probation_tick(7)
        assert rep.case_state is None and rep.healthy
        actions = [r["action"] for r in cap.records]
        assert actions == ["detected", "restarted", "readmitted"]
        assert cap.records[0]["missed_beats"] == 2
        assert cap.records[-1]["verdict"] == TERMINAL_VERDICTS["recovered"]

    def test_double_detect_refused(self):
        rep = self._replica()
        rep.kill()
        rep.detect(0)
        with pytest.raises(ValueError, match="open case"):
            rep.detect(1)

    def test_quarantine_removes_from_dispatch_set(self):
        rep = self._replica()
        rep.kill()
        rep.detect(0)
        rep.quarantine(0)
        assert rep.case_state == "quarantined"
        assert not rep.dispatchable

    def test_restart_budget_exhaustion_escalates(self):
        cap = _CapRouter()
        rep = self._replica(cap, max_restarts=0)
        rep.kill()
        rep.detect(0)
        assert rep.restart(0) is False
        assert rep.case_state == "escalated"
        assert not rep.alive and not rep.dispatchable
        rec = cap.records[-1]
        assert rec["action"] == "escalated"
        assert rec["verdict"] == TERMINAL_VERDICTS["escalated"]

    def test_failing_relaunch_factory_escalates(self):
        calls = {"n": 0}

        def factory(name, incarnation):
            calls["n"] += 1
            if calls["n"] > 1:      # first build fine, relaunch broken
                raise RuntimeError("broken build")
            return _FakeEngine()

        rep = self._replica(factory=factory)
        rep.kill()
        rep.detect(0)
        # re-running does not fix a broken build: FAILURE, not retry
        assert rep.restart(0) is False
        assert rep.case_state == "escalated" and not rep.alive

    def test_load_signal(self):
        rep = self._replica()
        rep.engine._queue.extend([1, 2])
        rep.engine._active[0] = object()
        assert rep.load == 3
        assert rep.stats()["load"] == 3


# -- fleet config -----------------------------------------------------------


class TestFleetConfig:
    def test_defaults_valid(self):
        cfg = FleetConfig()
        assert cfg.replicas == 2 and cfg.prefill_replicas == 0

    def test_validation(self):
        with pytest.raises(ValueError, match="replicas"):
            FleetConfig(replicas=0)
        with pytest.raises(ValueError, match="decode replica"):
            FleetConfig(replicas=2, prefill_replicas=2)
        with pytest.raises(ValueError, match="min_replicas"):
            FleetConfig(min_replicas=5, max_replicas=4)
        with pytest.raises(ValueError, match="miss_ticks_to_detect"):
            FleetConfig(miss_ticks_to_detect=0)


# -- slow tier: the gate and the ACCEPTANCE chaos drill ---------------------


def test_fleet_selftest_gate():
    """The ``python -m apex_tpu.serving --selftest --fleet`` gate exits
    0 — disaggregated parity through a ledgered KV handoff, then a chaos
    replica kill with failover, restart/readmit and an SLO scale-up."""
    from apex_tpu.serving.__main__ import main

    assert main(["--selftest", "--fleet"]) == 0


def test_fleet_chaos_drill():
    """ISSUE 16 acceptance: a seeded Poisson load pumped into a
    disaggregated 3-replica fleet (the PR-13 generator drives the fleet
    UNCHANGED — drop-in submit/cancel/tick), with a chaos replica kill
    mid-load and the autoscaler armed. Asserts: the kill fired and
    failover re-dispatched the orphans, an SLO scale-up happened, p99
    TTFT of completed requests stays inside the drill budget, every
    global id reaches exactly one terminal record (zero silent drops),
    the handoff ledger closes matched, zero steady-state compiles, and
    the fleet-wide goodput partition identity holds digit-for-digit."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTModel
    from apex_tpu.monitor import MemorySink, MetricRouter
    from apex_tpu.monitor.goodput import account, run_header
    from apex_tpu.resilience.chaos import FaultPlan
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.serving.fleet import FleetRouter
    from apex_tpu.serving.loadgen import PoissonLoadGenerator
    from apex_tpu.transformer import TransformerConfig

    # the p99 bound covers what the drill deliberately pays for: two
    # recovery compile bursts on the CPU mesh (the scale-up engine's
    # warmup and the restarted incarnation's, ~3 s each) plus the
    # standing queue — observed ~6.5 s; the bound catches unbounded
    # stalls, not the booked envelopes
    ttft_drill_budget_s = 15.0
    tcfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4, vocab_size=61,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0, position_embedding_type="rope",
        compute_dtype=jnp.float32,
    )
    model = GPTModel(config=tcfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4), jnp.int32))
    cfg = ServingConfig(lanes=2, block_size=8, num_blocks=16,
                        max_seq_len=32, max_queue_depth=64, seed=0)
    mem = MemorySink(kinds=("request", "run", "span", "fleet", "handoff",
                            "trace", "slo"))
    router = MetricRouter([mem])
    run_header(router, "fleet-chaos-drill")
    fleet = FleetRouter(
        lambda name, inc: ServingEngine(model, variables, cfg,
                                        router=router),
        FleetConfig(
            replicas=3, prefill_replicas=1, miss_ticks_to_detect=2,
            # the AUTOSCALER's budget, not admission's: a micro-budget so
            # the armed estimate provably breaches under load and the
            # scale-up fires inside the drill window
            ttft_budget_s=1e-4, breach_ticks=2,
            min_replicas=1, max_replicas=4,
        ),
        router=router,
        fault_plan=FaultPlan(kill_replica_steps={12}),
    )
    fleet.start()
    gen = PoissonLoadGenerator(
        rate_rps=150.0, vocab=61, n_requests=40,
        prompt_len=(4, 24), max_new=(4, 8), seed=7,
    )
    # inject the seeded Poisson schedule on a virtual clock (explicit
    # ``now``): the whole load is standing when the tick-12 kill fires,
    # so the victim is provably loaded and failover has work to re-home
    gen.pump(fleet, now=0.0)
    gen.pump(fleet, now=1e6)
    assert gen.done and len(gen.submitted) == 40
    n = 0
    while not fleet.idle and n < 800:
        fleet.tick()
        n += 1
    for _ in range(10):     # probation needs clean ticks past idle
        fleet.tick()
    report = fleet.drain(grace_s=10.0)
    router.close()
    assert n < 800, "fleet never went idle under the drill load"
    assert report["timed_out"] == 0

    records = mem.snapshot()
    fleet_records = [r for r in records if r.get("kind") == "fleet"]
    actions = {(r.get("check"), r.get("action")) for r in fleet_records}

    # 1. the kill fired mid-load and failover re-homed the orphans
    assert ("chaos", "kill_replica") in actions
    assert ("replica", "detected") in actions
    assert ("replica", "restarted") in actions
    assert any(r.get("check") == "failover" and r.get("redispatched", 0) > 0
               for r in fleet_records), "failover re-dispatched nothing"
    assert fleet.redispatched > 0

    # 2. the SLO breach scaled the fleet up
    assert ("autoscale", "scale_up") in actions
    assert ("autoscale", "added") in actions

    # 3. exactly one terminal record per global id — no silent drops,
    # through the kill, the re-dispatches and the handoffs
    req_records = [r for r in records if r.get("kind") == "request"]
    terminal = {}
    for r in req_records:
        if r.get("terminal"):
            terminal.setdefault(r["id"], []).append(r["state"])
    assert set(terminal) == set(range(fleet._next_rid))
    assert all(len(v) == 1 for v in terminal.values())
    assert {v[0] for v in terminal.values()} <= lifecycle.TERMINAL_STATES

    # 4. every request completed (the latest attempt's Request — a
    # re-dispatched request terminates on its second-attempt object)
    reqs = fleet.requests()
    assert len(reqs) == 40
    assert all(r.state == "completed" for r in reqs)
    assert any(r.tags.get("attempt", 1) > 1 for r in reqs), \
        "the kill orphaned nothing — the drill never exercised failover"

    # 5. p99 TTFT held through the kill (honest clock: re-dispatched
    # requests keep their ORIGINAL submit time)
    ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
    assert len(ttfts) == 40
    assert percentile(ttfts, 99.0) <= ttft_drill_budget_s

    # 6. every handoff byte is booked both sides and matched
    audit = fleet.ledger.audit()
    assert audit["handoffs"] > 0 and audit["matched"] is True

    # 7. zero steady-state compiles: the restart and scale-up bursts
    # were booked under their own spans, never charged to survivors
    assert fleet.stats()["steady_state_compiles"] == 0

    # 8. recovery time is attributed: failover and handoff are phases
    phases = {r.get("phase") for r in records if r.get("kind") == "span"}
    assert "failover" in phases and "handoff" in phases

    # 9. the goodput partition identity, fleet-wide, with ==
    acct = account(records)
    lhs = acct.productive_s
    for phase in sorted(acct.badput_s):
        lhs = lhs + acct.badput_s[phase]
    assert lhs + acct.unattributed_s == acct.wall_s
    assert acct.productive_s > 0.0

    # 10. ISSUE 17 trace closure: one complete span tree per terminal
    # request — through the kill (attempt > 1) and the handoffs — with
    # the per-request partition identity holding digit-for-digit
    # through a json round trip, and the failover/handoff badput
    # reconciling exactly between the accountant and the gp twins
    from apex_tpu.serving.trace.analyze import analyze as xray

    xr = xray(records)
    assert xr.n_traces > 0 and xr.ok, xr.summary()
    assert not xr.untraced_terminals and not xr.identity_violations
    deco = {d["trace"]: d for d in xr.decompositions}
    assert all(deco[r.rid]["recovery_s"] > 0.0 for r in reqs
               if r.tags.get("attempt", 1) > 1), \
        "failed-over requests must book recovery as its own phase"
    assert all(v["match"] for v in xr.reconcile.values()), xr.summary()

    # 11. the SLO burn monitor saw the micro-budget violations and the
    # fast-burn alert fed the autoscaler (secondary evidence)
    slo_recs = [r for r in records if r.get("kind") == "slo"]
    assert any(r.get("alert") for r in slo_recs)
    assert all(r["n"] >= r["violations"] >= r["sheds"] >= 0
               for r in slo_recs)
