"""Run-level goodput: span ledger, accountant, fleet health, perf gate.

The unit half of the goodput acceptance (the end-to-end half lives in
tests/test_examples.py, which asserts the GPT example's emitted
``kind="goodput"`` record): the partition identity is hand-counted on a
synthetic multi-incarnation, multi-host fixture, the fleet detector is
exercised on synthetic per-host streams, and the perf-regression gate's
exit codes are pinned — 0 on the recorded BENCH trajectory, nonzero on
a seeded 20% tokens/s regression replay.

Everything here is jax-free by design (the goodput package's contract:
a stream is accountable, and the gate runnable, on any box); the
subprocess tests prove it by poisoning jax in the child.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

from apex_tpu.monitor import MemorySink, MetricRouter
from apex_tpu.monitor import goodput
from apex_tpu.monitor.goodput import accountant, fleet, sentinel, spans
from apex_tpu.monitor.goodput.__main__ import main as goodput_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def router():
    mem = MemorySink()
    r = MetricRouter([mem])
    r.mem = mem
    yield r
    goodput.set_router(None)
    r.close()


# ---------------------------------------------------------------------------
# span ledger


class TestSpans:
    def test_span_record_schema(self, router):
        with goodput.span("compile", step=3, router=router):
            pass
        (rec,) = router.mem.records
        assert {"t", "step", "kind", "host", "phase", "start", "dur_s"} <= set(
            rec
        )
        assert rec["kind"] == "span" and rec["phase"] == "compile"
        assert rec["step"] == 3 and rec["host"] == 0
        assert rec["dur_s"] >= 0.0 and "interrupted" not in rec

    def test_taxonomy_is_closed(self, router):
        with pytest.raises(ValueError, match="closed"):
            with goodput.span("coffee_break", router=router):
                pass
        assert list(router.mem.records) == []

    def test_no_router_is_noop(self):
        goodput.set_router(None)
        with goodput.span("init"):
            pass  # measured and dropped; no crash

    def test_global_router_and_override(self, router):
        goodput.set_router(router)
        other = MemorySink()
        with goodput.span("init"):
            pass
        with goodput.span("step", router=MetricRouter([other])):
            pass
        assert [r["phase"] for r in router.mem.records] == ["init"]
        assert [r["phase"] for r in other.records] == ["step"]

    def test_begin_span_close_idempotent(self, router):
        s = goodput.begin_span("data_wait", router=router)
        assert s.close() is not None
        assert s.close() is None  # second close: no second record
        assert len(router.mem.records) == 1

    def test_flush_open_spans_marks_interrupted(self, router):
        s = goodput.begin_span("step", step=7, router=router)
        n = goodput.flush_open_spans()
        assert n == 1
        (rec,) = router.mem.records
        assert rec["interrupted"] is True and rec["phase"] == "step"
        assert s.close() is None  # flushed spans are closed

    def test_run_header_fields(self, router):
        rec = goodput.run_header(router, "run-abc", steps=12)
        assert rec["kind"] == "run" and rec["run_id"] == "run-abc"
        assert rec["pid"] == os.getpid() and rec["steps"] == 12
        assert isinstance(rec["mono"], float)

    def test_derive_run_id_anchored_vs_random(self, tmp_path):
        a = goodput.derive_run_id(str(tmp_path / "ckpt"))
        b = goodput.derive_run_id(str(tmp_path / "ckpt"))
        c = goodput.derive_run_id(str(tmp_path / "other"))
        assert a == b != c  # restartable join key: same --save, same id
        assert goodput.derive_run_id() != goodput.derive_run_id()


# ---------------------------------------------------------------------------
# accountant


def _span(phase, start, dur, host=0, **extra):
    return {"kind": "span", "step": -1, "host": host, "phase": phase,
            "start": float(start), "dur_s": float(dur), **extra}


def _header(mono, host=0, run_id="job1"):
    return {"kind": "run", "step": 0, "host": host, "run_id": run_id,
            "mono": float(mono)}


def _fixture_records():
    """The hand-counted two-incarnation, two-host fixture.

    host 0 / incarnation A (anchor 0, end 10.5 -> wall 10.5):
      init [0,4], ckpt_restore [1,3] nested in it, compile [4,7],
      steps [7,8][8,9][9,10], ckpt_save [9.5,10.5] overlapping the last
      step. Priority attribution: productive 3.0, ckpt_save exposed 0.5,
      ckpt_restore 2.0, compile 3.0, init [0,1]+[3,4] = 2.0.
    host 0 / incarnation B (restart; fresh monotonic clock at 100):
      one step [100,101] -> wall 1.0, productive 1.0.
    host 1 (one incarnation): step [0,2] -> wall 2.0, productive 2.0.

    Totals: wall 13.5, productive 6.0, badput ckpt_save 0.5,
    ckpt_restore 2.0, compile 3.0, init 2.0, unattributed 0.0;
    3 incarnations, hosts (0, 1), 9 spans. All values exact binary
    floats, so the asserts below use ==, never approx.
    """
    recs = [
        _header(0.0, host=0),
        _header(0.0, host=1),
        _span("init", 0.0, 4.0, host=0),
        _span("step", 0.0, 2.0, host=1),
        _span("ckpt_restore", 1.0, 2.0, host=0),
        _span("compile", 4.0, 3.0, host=0),
        _span("step", 7.0, 1.0, host=0),
        _span("step", 8.0, 1.0, host=0),
        _span("step", 9.0, 1.0, host=0),
        _span("ckpt_save", 9.5, 1.0, host=0),
        # the restart: a second header on host 0 re-anchors the clock
        _header(100.0, host=0),
        _span("step", 100.0, 1.0, host=0),
    ]
    # non-span kinds in the same stream are ignored by the accountant
    recs.append({"kind": "metrics", "step": 1, "host": 0, "loss": 1.0})
    return recs


class TestAccountant:
    def test_hand_counted_partition(self):
        rep = accountant.account(_fixture_records())
        assert rep.wall_s == 13.5
        assert rep.productive_s == 6.0
        assert rep.badput_s == {
            "ckpt_save": 0.5, "ckpt_restore": 2.0, "rollback": 0.0,
            "compile": 3.0, "data_wait": 0.0, "stall": 0.0,
            "incident": 0.0, "remediation": 0.0, "drain": 0.0,
            "handoff": 0.0, "failover": 0.0,
            "init": 2.0, "shutdown": 0.0,
        }
        assert rep.unattributed_s == 0.0
        assert rep.incarnations == 3
        assert rep.hosts == (0, 1)
        assert rep.n_spans == 9 and rep.n_interrupted == 0
        assert rep.goodput_fraction == 6.0 / 13.5

    def test_identity_digit_for_digit(self):
        # messy, non-representable durations: the identity must still be
        # EXACT because wall_s is defined as the canonical field sum
        recs = [_header(0.0)]
        t = 0.0
        for i in range(40):
            phase = spans.PHASE_PRIORITY[i % len(spans.PHASE_PRIORITY)]
            dur = 0.1 + 0.013 * i
            recs.append(_span(phase, t, dur))
            t += dur * 0.7  # overlap every successive pair
        rep = accountant.account(recs)
        f = rep.fields()
        total = f["productive_s"]
        for phase in accountant.BADPUT_PHASES:
            total = total + f[f"badput_{phase}_s"]
        total = total + f["unattributed_s"]
        assert total == f["wall_s"]  # ==, never approx
        # and the identity survives a json round trip (the jsonl story)
        g = json.loads(json.dumps(f))
        total = g["productive_s"]
        for phase in accountant.BADPUT_PHASES:
            total = total + g[f"badput_{phase}_s"]
        assert total + g["unattributed_s"] == g["wall_s"]

    def test_overlap_never_double_counts(self):
        # an async ckpt_save fully covered by steps is FREE (off the
        # critical path): zero badput, the TorchTitan design goal
        recs = [
            _header(0.0),
            _span("step", 0.0, 4.0),
            _span("ckpt_save", 1.0, 2.0),
        ]
        rep = accountant.account(recs)
        assert rep.productive_s == 4.0
        assert rep.badput_s["ckpt_save"] == 0.0
        assert rep.wall_s == 4.0

    def test_header_anchors_unattributed(self):
        # wall before the first span (imports, interpreter startup) is
        # unattributed, not silently dropped: the header's mono anchors
        recs = [_header(0.0), _span("step", 5.0, 1.0)]
        rep = accountant.account(recs)
        assert rep.wall_s == 6.0
        assert rep.productive_s == 1.0 and rep.unattributed_s == 5.0

    def test_run_id_filter(self):
        recs = _fixture_records() + [
            _header(0.0, host=0, run_id="other"),
            _span("step", 0.0, 50.0, host=0),
        ]
        rep = accountant.account(recs, run_id="job1")
        assert rep.wall_s == 13.5 and rep.incarnations == 3
        other = accountant.account(recs, run_id="other")
        assert other.wall_s == 50.0 and other.incarnations == 1

    def test_serving_phases_are_productive_and_drain_is_envelope(self):
        # serving taxonomy (PR 13): prefill/decode seconds are the
        # serving analogue of step seconds (PRODUCTIVE_PHASES), and a
        # drain span is an ENVELOPE — the decode ticks inside it stay
        # productive, only the exposed remainder books as drain badput.
        # Hand count: wall [0,10]; prefill [0,2] + decode [2,5]+[6,8]
        # productive = 7.0; drain envelope [5,10] minus the covered
        # [6,8] = 3.0 badput; unattributed [5,6)? no — drain covers it.
        recs = [
            _header(0.0),
            _span("prefill", 0.0, 2.0),
            _span("decode", 2.0, 3.0),
            _span("drain", 5.0, 5.0),
            _span("decode", 6.0, 2.0),
        ]
        rep = accountant.account(recs)
        assert rep.wall_s == 10.0
        assert rep.productive_s == 7.0
        assert rep.badput_s["drain"] == 3.0
        assert rep.unattributed_s == 0.0
        f = rep.fields()
        assert "badput_drain_s" in f and "badput_prefill_s" not in f

    def test_headerless_legacy_stream(self):
        rep = accountant.account([_span("step", 2.0, 3.0)])
        assert rep.incarnations == 1
        assert rep.wall_s == 3.0 and rep.productive_s == 3.0

    def test_interrupted_and_garbage_spans(self):
        recs = [
            _header(0.0),
            _span("step", 0.0, 1.0, interrupted=True),
            _span("step", 1.0, float("nan")),        # skipped
            _span("step", 2.0, -5.0),                # clamped to zero
            {"kind": "span", "host": 0, "phase": "step"},  # no times
            _span("warp_drive", 0.0, 9.0),           # unknown phase
        ]
        rep = accountant.account(recs)
        assert rep.n_interrupted == 1
        assert rep.productive_s == 1.0
        assert rep.wall_s == 2.0  # [0, 2]: the clamped span still anchors

    def test_read_records_skips_torn_lines(self, tmp_path):
        path = tmp_path / "r.jsonl"
        path.write_text(
            json.dumps(_header(0.0)) + "\n"
            + json.dumps(_span("step", 0.0, 1.0)) + "\n"
            + '{"kind": "span", "truncat'  # the killed run's last line
        )
        recs = accountant.read_records([str(path)])
        assert len(recs) == 2
        assert accountant.account(recs).productive_s == 1.0


# ---------------------------------------------------------------------------
# fleet health


def _host_steps(host, durs):
    return [_span("step", i, d, host=host) for i, d in enumerate(durs)]


class TestFleet:
    def test_straggler_flagged_one_sided(self):
        recs = (
            _host_steps(0, [1.0, 1.0, 1.0])
            + _host_steps(1, [1.01, 1.01, 1.01])
            + _host_steps(2, [0.99, 0.99, 0.99])
            + _host_steps(3, [2.0, 2.0, 2.0])     # the straggler
        )
        rep = fleet.detect_divergence(recs)
        assert not rep.ok
        (s,) = rep.stragglers
        assert s["host"] == 3 and s["median_step_s"] == 2.0 and s["z"] > 4
        assert "STRAGGLER host 3" in rep.summary()

    def test_fast_host_not_flagged(self):
        # one-sided: an anomalously FAST host blocks nobody
        recs = (_host_steps(0, [1.0] * 3) + _host_steps(1, [1.01] * 3)
                + _host_steps(2, [0.99] * 3) + _host_steps(3, [0.2] * 3))
        assert fleet.detect_divergence(recs).stragglers == []

    def test_zero_mad_outlier_still_flagged(self):
        # all other hosts identical: MAD is 0, and any slower deviation
        # is infinitely many MADs out — must flag, not divide by zero
        recs = (_host_steps(0, [1.0] * 3) + _host_steps(1, [1.0] * 3)
                + _host_steps(2, [1.0] * 3) + _host_steps(3, [1.2] * 3))
        (s,) = fleet.detect_divergence(recs).stragglers
        assert s["host"] == 3

    def test_two_hosts_cannot_name_a_straggler(self):
        recs = _host_steps(0, [1.0] * 3) + _host_steps(1, [9.0] * 3)
        rep = fleet.detect_divergence(recs)
        assert rep.stragglers == [] and rep.ok

    def test_corruption_suspect(self):
        def metrics(host, step, loss):
            return {"kind": "metrics", "step": step, "host": host,
                    "loss": loss, "grad_norm": 1.0}

        recs = [metrics(h, s, 2.5) for h in range(3) for s in range(4)]
        recs.append(metrics(2, 5, 2.5))
        recs.append(metrics(0, 5, 2.5))
        recs.append(metrics(1, 5, 7.0))  # host 1 diverged at step 5
        rep = fleet.detect_divergence(recs)
        (s,) = rep.suspects
        assert s == {"step": 5, "field": "loss", "host": 1,
                     "value": 7.0, "median": 2.5}
        assert "CORRUPTION SUSPECT host 1" in rep.summary()

    def test_nonfinite_on_one_host_is_suspect(self):
        recs = [
            {"kind": "metrics", "step": 1, "host": 0, "loss": 2.0},
            {"kind": "metrics", "step": 1, "host": 1, "loss": float("nan")},
        ]
        (s,) = fleet.detect_divergence(recs).suspects
        assert s["host"] == 1

    def test_all_hosts_nonfinite_is_not_sdc(self):
        # every host agrees the loss blew up: diverged together (the
        # PR-1 sentinel's job), not silent corruption
        recs = [
            {"kind": "metrics", "step": 1, "host": h, "loss": float("nan")}
            for h in range(3)
        ]
        assert fleet.detect_divergence(recs).suspects == []

    def test_to_records_schema(self):
        recs = (_host_steps(0, [1.0] * 3) + _host_steps(1, [1.01] * 3)
                + _host_steps(2, [0.99] * 3) + _host_steps(3, [2.0] * 3))
        out = fleet.detect_divergence(recs).to_records()
        (rec,) = out
        assert rec["kind"] == "fleet" and rec["check"] == "straggler"
        assert rec["flagged_host"] == 3
        assert {"t", "step", "host"} <= set(rec)


# ---------------------------------------------------------------------------
# perf-regression sentinel


def _meas(metric, value, platform="run", source="test"):
    return {"metric": metric, "value": value, "unit": None,
            "platform": platform, "source": source}


class TestSentinel:
    def test_noise_tolerance_floor_without_repeats(self):
        assert sentinel.noise_tolerance([]) == 0.05
        assert sentinel.noise_tolerance([100.0]) == 0.05

    def test_noise_tolerance_widens_with_repeat_spread(self):
        # best 110; repeats within 15% of it = {100, 110} (90 is 18% off,
        # excluded): med 105, MAD 5, tol = 3 * 5/105 = 1/7 > the 5% floor
        assert sentinel.noise_tolerance([100.0, 110.0, 90.0]) == pytest.approx(
            3.0 * 5.0 / 105.0
        )

    def test_trajectory_progress_is_not_noise(self):
        # rounds 23 -> 2626 -> 2626: the early cpu-era value must not
        # widen the band to "anything goes"
        tol = sentinel.noise_tolerance([23.0, 2626.0, 2626.0])
        assert tol == 0.05  # two identical repeats: MAD 0, floor applies

    def test_regression_and_clean(self):
        history = [_meas("tokens_per_s", 1000.0)]
        (f,) = sentinel.check_regression([_meas("tokens_per_s", 790.0)],
                                         history)
        assert f.rule == "perf.regression" and f.severity == "error"
        assert f.data["baseline"] == 1000.0
        assert sentinel.check_regression([_meas("tokens_per_s", 960.0)],
                                         history) == []

    def test_lower_is_better_direction(self):
        history = [_meas("step_ms", 100.0)]
        (f,) = sentinel.check_regression([_meas("step_ms", 130.0)], history)
        assert f.rule == "perf.regression"
        assert sentinel.check_regression([_meas("step_ms", 95.0)],
                                         history) == []

    def test_no_baseline_is_info_not_error(self):
        (f,) = sentinel.check_regression([_meas("new_metric", 5.0)], [])
        assert f.rule == "perf.no-baseline" and f.severity == "info"

    def test_platform_mismatch_is_no_baseline(self):
        history = [_meas("tokens_per_s", 1000.0, platform="tpu")]
        (f,) = sentinel.check_regression([_meas("tokens_per_s", 10.0,
                                                platform="cpu")], history)
        assert f.rule == "perf.no-baseline"

    def test_platform_aliases_fold(self):
        # a live capture says "tpu"; the recorded rounds say
        # "tpu_harvested" (replayed real-TPU measurements) — same backend
        history = [_meas("imgs", 2626.0, platform="tpu_harvested")]
        (f,) = sentinel.check_regression(
            [_meas("imgs", 2000.0, platform="tpu")], history)
        assert f.rule == "perf.regression"

    def test_measurements_from_records_medians(self):
        recs = [
            {"kind": "metrics", "step": i, "host": 0,
             "tokens_per_s": v, "step_ms": 100.0}
            for i, v in enumerate([900.0, 1000.0, 1100.0])
        ]
        recs.append({"kind": "bench", "step": 0, "host": 0,
                     "metric": "imgs", "value": 42.0, "platform": "tpu"})
        recs.append({"kind": "goodput", "step": 0, "host": 0,
                     "goodput_fraction": 0.9})
        out = {(m["metric"], m["platform"]): m["value"]
               for m in sentinel.measurements_from_records(recs)}
        assert out[("tokens_per_s", "run")] == 1000.0  # median, not mean
        assert out[("step_ms", "run")] == 100.0
        assert out[("imgs", "tpu")] == 42.0
        assert out[("goodput_fraction", "run")] == 0.9

    def test_load_bench_history_reads_recorded_rounds(self):
        history = sentinel.load_bench_history()
        assert len(history) >= 3  # r03 cpu_fallback + r04/r05 tpu_harvested
        newest = history[-1]
        assert newest["source"] == "BENCH_r05.json"
        assert newest["value"] == 2626.48
        assert newest["platform"] == "tpu_harvested"

    def test_allowlist_requires_reason_and_suppresses(self):
        from apex_tpu.analysis.findings import AllowlistEntry

        with pytest.raises(ValueError, match="reason"):
            AllowlistEntry(rule="perf.regression", match="tokens", reason="")
        findings = sentinel.check_regression(
            [_meas("tokens_per_s", 500.0)], [_meas("tokens_per_s", 1000.0)])
        allow = sentinel.goodput_allowlist().extended([AllowlistEntry(
            rule="perf.regression", match="tokens_per_s",
            reason="traded tokens/s for the verified-checkpoint path",
        )])
        res = allow.apply(findings, check_stale=False)
        assert res.ok and len(res.suppressed) == 1

    def test_repo_allowlist_is_empty(self):
        # the recorded trajectory stands un-waived; any entry added here
        # is a reviewable claim, and this pin makes adding one deliberate
        assert len(sentinel.goodput_allowlist()) == 0


# ---------------------------------------------------------------------------
# CLI (in-process; the subprocess/jax-free property is pinned below)


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


class TestCLI:
    def test_account_mode_and_json(self, tmp_path, capsys):
        stream = tmp_path / "run.jsonl"
        _write_jsonl(stream, _fixture_records())
        out_json = tmp_path / "out.jsonl"
        rc = goodput_main([str(stream), "--json", str(out_json)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "goodput: 6.000s productive of 13.500s wall" in out
        (rec,) = [json.loads(l) for l in open(out_json)]
        assert rec["kind"] == "goodput" and rec["wall_s"] == 13.5

    def test_account_no_spans_exits_nonzero(self, tmp_path):
        stream = tmp_path / "empty.jsonl"
        _write_jsonl(stream, [{"kind": "metrics", "step": 0, "loss": 1.0}])
        assert goodput_main([str(stream)]) == 1

    def test_fleet_mode_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        _write_jsonl(bad, _host_steps(0, [1.0] * 3)
                     + _host_steps(1, [1.01] * 3)
                     + _host_steps(2, [0.99] * 3)
                     + _host_steps(3, [2.0] * 3))
        assert goodput_main([str(bad), "--fleet"]) == 1
        ok = tmp_path / "ok.jsonl"
        _write_jsonl(ok, _host_steps(0, [1.0] * 3)
                     + _host_steps(1, [1.0] * 3))
        assert goodput_main([str(ok), "--fleet"]) == 0

    def test_check_recorded_trajectory_passes(self, capsys):
        # ACCEPTANCE: the recorded BENCH_r05 round passes its own gate
        assert goodput_main(["--check"]) == 0
        assert "BENCH_r05.json" in capsys.readouterr().out

    def test_check_seeded_regression_fails(self, tmp_path, capsys):
        # ACCEPTANCE: a 20% tokens/s regression replay exits nonzero
        def run_records(tokens_per_s):
            return [
                {"kind": "metrics", "step": i, "host": 0,
                 "tokens_per_s": tokens_per_s, "mfu": 0.4, "step_ms": 100.0}
                for i in range(3)
            ]

        baseline = tmp_path / "baseline.jsonl"
        _write_jsonl(baseline, run_records(1000.0))
        fresh = tmp_path / "fresh.jsonl"
        _write_jsonl(fresh, run_records(800.0))
        rc = goodput_main([str(fresh), "--check", "--baseline",
                           str(baseline)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "perf.regression" in out and "tokens_per_s" in out
        # control: the same run replayed against itself passes
        same = tmp_path / "same.jsonl"
        _write_jsonl(same, run_records(1000.0))
        assert goodput_main([str(same), "--check", "--baseline",
                             str(baseline)]) == 0


# ---------------------------------------------------------------------------
# teardown + jax-free subprocess pins


_CHILD_PRELUDE = """
import sys
class _Poison:
    def find_module(self, name, path=None):
        if name in ("jax", "jaxlib", "flax"):
            raise ImportError("poisoned: " + name)
sys.meta_path.insert(0, _Poison())
import json, os
from apex_tpu.monitor import JsonlSink, MetricRouter
from apex_tpu.monitor import goodput
"""


def _run_child(code, timeout=60):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return subprocess.run(
        [sys.executable, "-c", _CHILD_PRELUDE + code],
        capture_output=True, text=True, env=env, timeout=timeout,
    )


class TestTeardown:
    def test_atexit_flushes_open_spans_jax_free(self, tmp_path):
        # a run that forgets to close its spans (or dies past the loop)
        # still lands them, marked interrupted — and the whole producer
        # stack imports with jax POISONED (the any-box contract)
        stream = tmp_path / "run.jsonl"
        code = f"""
router = MetricRouter([JsonlSink({str(stream)!r})])
goodput.run_header(router, "run-x")
goodput.set_router(router)
goodput.begin_span("step", step=5)
"""
        proc = _run_child(code)
        assert proc.returncode == 0, proc.stderr
        recs = [json.loads(l) for l in open(stream)]
        assert recs[0]["kind"] == "run"
        (span_rec,) = [r for r in recs if r["kind"] == "span"]
        assert span_rec["interrupted"] is True and span_rec["step"] == 5

    @pytest.mark.skipif(os.name != "posix", reason="posix signals")
    def test_sigterm_flushes_then_dies_by_sigterm(self, tmp_path):
        # the chaos harness's real-SIGTERM drill: the in-flight span
        # must land (interrupted) AND the process must still die by
        # SIGTERM — the flush hook converts nothing into a survival
        stream = tmp_path / "run.jsonl"
        code = f"""
import signal, time
router = MetricRouter([JsonlSink({str(stream)!r})])
goodput.run_header(router, "run-sig")
goodput.set_router(router)
goodput.begin_span("ckpt_save", step=9)
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(30)  # never reached: the handler re-raises SIGTERM
"""
        proc = _run_child(code)
        assert proc.returncode == -signal.SIGTERM, (proc.returncode,
                                                    proc.stderr)
        recs = [json.loads(l) for l in open(stream)]
        (span_rec,) = [r for r in recs if r["kind"] == "span"]
        assert span_rec["phase"] == "ckpt_save"
        assert span_rec["interrupted"] is True
