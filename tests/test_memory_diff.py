"""Compiled-memory differ (analysis/hlo/memory_diff): the confirm leg.

The load-bearing contracts:

- POSITIVE CONFIRMATION: on the real dp2tp2 GPT audit target the differ
  returns ``memory.reconciled`` carrying the exact component table —
  every resident component matches the analytic ledger DIGIT FOR DIGIT
  against XLA's ``memory_analysis()``, and temps sit inside the band;
- SEEDED DEFECTS ARE CAUGHT: a ledger whose weights arithmetic is off
  by four bytes, an unclaimed argument buffer, or a temp band squeezed
  below the real ratio each produce ``memory.unpredicted`` (error) with
  largest-buffer attribution;
- HEADROOM: a capacity just above the measured peak warns
  (``memory.headroom``); ample capacity stays silent;
- HONESty ABOUT LIMITS: no prediction / no parsed module each downgrade
  to ``memory.unverifiable`` (info) — never a silent pass;
- the real findings survive ``repo_allowlist()`` (the gate wiring).

One AOT compile is shared module-wide (the StepContext discipline —
the compile is the only non-tracing cost here).
"""

import dataclasses

import pytest

from apex_tpu.analysis import StepContext
from apex_tpu.analysis.hlo.memory_diff import audit_memory
from apex_tpu.analysis.targets import dp2tp2_mesh, gpt_step_target


@pytest.fixture(scope="module")
def gpt_ctx():
    """(target, compiled, module): ONE shared AOT compile + HLO parse."""
    tgt = gpt_step_target(dp2tp2_mesh())
    ctx = StepContext(tgt)
    _, compiled = ctx.aot()
    return tgt, compiled, ctx.hlo_module()


def _audit(gpt_ctx, **kw):
    tgt, compiled, module = gpt_ctx
    kw.setdefault("predicted", tgt.hbm)
    return audit_memory(
        tgt.fn, *tgt.args,
        donate_argnums=tgt.donate_argnums, target=tgt.name,
        compiled=compiled, module=module, **kw,
    )


def _rules(fins):
    return sorted(f.rule for f in fins)


class TestReconciled:
    def test_real_target_reconciles_exactly(self, gpt_ctx):
        """The tentpole acceptance: the analytic ledger and XLA agree
        on every resident component of the dp2tp2 GPT step, byte for
        byte, and the proof (the component table) rides in the finding
        data — the gate's jsonl carries it."""
        tgt, _, _ = gpt_ctx
        fins = _audit(gpt_ctx)
        assert not [f for f in fins if f.severity == "error"], [
            f.format() for f in fins
        ]
        (rec,) = [f for f in fins if f.rule == "memory.reconciled"]
        table = rec.data["components"]
        for comp, row in table.items():
            assert row["predicted"] == row["measured"], (comp, row)
        # the table's resident rows ARE the ledger's resident components
        assert set(table) == {
            c.name for c in tgt.hbm.components if not c.transient
        }
        assert table["weights"]["measured"] == 15168
        assert table["optimizer_state"]["measured"] == 30340
        assert rec.data["predicted_peak_bytes"] == tgt.hbm.peak_bytes
        assert 0 < rec.data["temp_ratio"] <= 4.0

    def test_real_findings_survive_the_repo_allowlist(self, gpt_ctx):
        from apex_tpu.analysis.allowlist import repo_allowlist

        res = repo_allowlist().apply(_audit(gpt_ctx), check_stale=False)
        assert res.ok, [f.format() for f in res.kept]

    def test_registered_in_the_gate(self):
        from apex_tpu.analysis.passes import JAXPR_PASSES

        assert "hlo-memory" in JAXPR_PASSES


class TestSeededDefects:
    def test_wrong_weights_arithmetic_is_unpredicted(self, gpt_ctx):
        """Four bytes of ledger error -> error finding naming the
        component, the delta, and the largest-buffer attribution."""
        tgt, _, _ = gpt_ctx
        bad_comps = tuple(
            dataclasses.replace(c, bytes=c.bytes + 4)
            if c.name == "weights" else c
            for c in tgt.hbm.components
        )
        bad = dataclasses.replace(tgt.hbm, components=bad_comps)
        fins = _audit(gpt_ctx, predicted=bad)
        bad_fins = [f for f in fins if f.rule == "memory.unpredicted"]
        assert bad_fins and all(f.severity == "error" for f in bad_fins)
        (w,) = [f for f in bad_fins if f.data.get("component") == "weights"]
        assert w.data["predicted"] - w.data["measured"] == 4
        assert w.data["largest_buffers"][0]["bytes"] > 0
        assert "memory.reconciled" not in _rules(fins)

    def test_missing_component_orphans_argument_bytes(self, gpt_ctx):
        """Dropping batch_data from the ledger leaves the token buffers
        attributable (they fall through to nothing) -> unpredicted."""
        tgt, _, _ = gpt_ctx
        slim = dataclasses.replace(
            tgt.hbm,
            components=tuple(
                c for c in tgt.hbm.components if c.name != "batch_data"
            ),
        )
        fins = _audit(gpt_ctx, predicted=slim)
        assert any(
            f.rule == "memory.unpredicted"
            and "unattributed_bytes" in (f.data or {})
            for f in fins
        ), [f.format() for f in fins]

    def test_squeezed_temp_band_breaches(self, gpt_ctx):
        """The band is a DECLARED tolerance: squeezing it below the
        real temp ratio must flip the verdict (proves the band is
        actually enforced, not decorative)."""
        fins = _audit(gpt_ctx, temp_band=0.01)
        (f,) = [
            f for f in fins
            if f.rule == "memory.unpredicted" and "temp_bytes" in f.data
        ]
        assert f.severity == "error"
        assert f.data["temp_bytes"] > 0
        assert "memory.reconciled" not in _rules(fins)


class TestHeadroom:
    def test_tight_capacity_warns(self, gpt_ctx):
        fins = _audit(gpt_ctx, capacity_bytes=70_000)
        (f,) = [f for f in fins if f.rule == "memory.headroom"]
        assert f.severity == "warning"
        assert f.data["capacity_bytes"] == 70_000

    def test_ample_capacity_is_silent(self, gpt_ctx):
        fins = _audit(gpt_ctx, capacity_bytes=2 ** 30)
        assert "memory.headroom" not in _rules(fins)

    def test_breakdown_capacity_is_the_fallback(self, gpt_ctx):
        """A capacity declared on the breakdown itself (virtual-topology
        rehearsal) is honored when the caller and device offer none."""
        tgt, _, _ = gpt_ctx
        virt = dataclasses.replace(tgt.hbm, capacity_bytes=70_000)
        fins = _audit(gpt_ctx, predicted=virt)
        assert "memory.headroom" in _rules(fins)


class TestUnverifiable:
    def test_no_prediction_downgrades_honestly(self, gpt_ctx):
        fins = _audit(gpt_ctx, predicted=None)
        (f,) = [f for f in fins if f.rule == "memory.unverifiable"]
        assert f.severity == "info"
        # the measured breakdown still rides along for the record
        assert f.data["measured"]["total_bytes"] > 0

    def test_no_parsed_module_downgrades_honestly(self, gpt_ctx):
        tgt, compiled, _ = gpt_ctx
        fins = audit_memory(
            tgt.fn, *tgt.args,
            donate_argnums=tgt.donate_argnums, target=tgt.name,
            compiled=compiled, module=None, predicted=tgt.hbm,
        )
        assert any(f.rule == "memory.unverifiable" for f in fins)
        assert not [f for f in fins if f.severity == "error"]
