"""Elastic restart: topology block, ZeRO regroup, resharded restore,
deadline-budgeted preemption saves, and the end-to-end chaos drill.

Fast tier: hand-built sharded state (device_put only — no shard_map
compiles) exercises the reshard/refusal/crc paths; the deadline decision
is a pure function of seeded EMAs + grace, pinned arm by arm; the
AutoResume integration drives real async saves on the 8-device CPU mesh.
Slow tier: ``python -m apex_tpu.resilience.elastic`` (the gate) and the
chaos drill through the real GPT example — SIGTERM at step k on 8
devices, resharded resume on 4 (and 4->8), loss trajectory pinned
against an uninterrupted run, goodput identity across both incarnations
under one run id.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import monitor
from apex_tpu.monitor import goodput
from apex_tpu.optimizers import zero_regroup_flat
from apex_tpu.resilience import integrity
from apex_tpu.resilience.elastic import (
    ElasticRestoreError,
    needs_reshard,
    restore_resharded,
    spec_from_json,
    spec_to_json,
    topology_block,
)
from apex_tpu.utils import AutoResume

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEVS = np.asarray(jax.devices())
pytestmark = pytest.mark.skipif(
    DEVS.size < 8, reason="needs the 8-device CPU mesh (conftest)"
)


def _mesh(n):
    return Mesh(DEVS[:n], ("dp",))


TOTAL = 225  # pad8 -> 232, pad4 -> 228: the dp change changes the length


def _padded(total, dp):
    return ((total + dp - 1) // dp) * dp


def _state(mesh, dp, seed=0, zeros=False):
    """Hand-built elastic-shaped state: replicated params + scalar +
    RNG key, one dp-sharded ZeRO-style flat buffer (padded to dp)."""
    rng = np.random.RandomState(seed)
    rep = NamedSharding(mesh, P())
    flat = np.zeros(_padded(TOTAL, dp), np.float32)
    if not zeros:
        flat[:TOTAL] = rng.randn(TOTAL)
    w = np.zeros((12, 16), np.float32) if zeros else rng.randn(12, 16)
    return {
        "params": {"w": jax.device_put(np.asarray(w, np.float32), rep)},
        "master": jax.device_put(flat, NamedSharding(mesh, P("dp"))),
        "rng": jax.device_put(np.asarray([3, 7], np.uint32), rep),
        "scale": jax.device_put(np.float32(512.0), rep),
    }


# ---------------------------------------------------------------------------
# topology block


class TestTopologyBlock:
    def test_block_records_layout(self):
        topo = topology_block(_state(_mesh(8), 8))
        assert topo["version"] == 1
        assert topo["mesh"] == {"axes": {"dp": 8}, "devices": 8}
        leaves = {l["path"]: l for l in topo["leaves"]}
        assert leaves["['params']['w']"]["shape"] == [12, 16]
        # a replicated leaf's P() serializes to the empty entry list
        assert leaves["['params']['w']"]["spec"] == []
        assert leaves["['params']['w']"]["zero_shard_axis"] is None
        m = leaves["['master']"]
        assert m["shape"] == [232] and m["dtype"] == "float32"
        assert m["spec"] == ["dp"]
        # the flat-shard marker: 1-D + sharded over exactly one axis
        assert m["zero_shard_axis"] == "dp"
        assert leaves["['rng']"]["dtype"] == "uint32"
        assert leaves["['scale']"]["shape"] == []

    def test_spec_json_round_trip(self):
        for spec in (P(), P("dp"), P(None, "tp"), P(("dp", "tp"), None)):
            assert spec_from_json(spec_to_json(spec)) == spec
        assert spec_from_json(None) == P()

    def test_host_arrays_read_replicated(self):
        topo = topology_block({"a": np.ones((3,), np.float32), "b": 2.0})
        assert topo["mesh"] is None
        assert all(l["spec"] is None and l["zero_shard_axis"] is None
                   for l in topo["leaves"])


# ---------------------------------------------------------------------------
# ZeRO flat-buffer regroup


class TestZeroRegroup:
    def test_truncate_drops_only_padding(self):
        arr = np.concatenate([np.arange(1, 6, dtype=np.float32),
                              np.zeros(3, np.float32)])
        out = zero_regroup_flat(arr, 6)
        assert out.shape == (6,)
        np.testing.assert_array_equal(out[:5], arr[:5])
        assert out[5] == 0

    def test_extend_pads_zeros(self):
        arr = np.arange(1, 5, dtype=np.float32)
        out = zero_regroup_flat(arr, 8)
        np.testing.assert_array_equal(out[:4], arr)
        assert not out[4:].any() and out.dtype == np.float32

    def test_identity_when_lengths_match(self):
        arr = np.arange(4, dtype=np.float32)
        np.testing.assert_array_equal(zero_regroup_flat(arr, 4), arr)

    def test_nonzero_truncation_refuses(self):
        arr = np.arange(1, 9, dtype=np.float32)  # no zero tail
        with pytest.raises(ValueError, match="state, not dp padding"):
            zero_regroup_flat(arr, 6)

    def test_non_1d_refuses(self):
        with pytest.raises(ValueError, match="1-D"):
            zero_regroup_flat(np.zeros((2, 2)), 2)


# ---------------------------------------------------------------------------
# resharded restore


class TestRestoreResharded:
    def test_8_to_4_regroups_and_relays(self, tmp_path):
        d = str(tmp_path)
        state8 = _state(_mesh(8), 8, seed=1)
        integrity.save_checkpoint_verified(d, 3, state8)
        target = _state(_mesh(4), 4, zeros=True)
        step, out = restore_resharded(d, target, mesh=_mesh(4))
        assert step == 3
        np.testing.assert_array_equal(
            np.asarray(out["params"]["w"]), np.asarray(state8["params"]["w"]))
        master = np.asarray(out["master"])
        assert master.shape == (228,)  # regrouped 232 -> 228
        np.testing.assert_array_equal(
            master[:TOTAL], np.asarray(state8["master"])[:TOTAL])
        assert not master[TOTAL:].any()
        # the new layout is REAL: dp-sharded on the 4-device mesh
        assert out["master"].sharding.spec == P("dp")
        assert dict(out["master"].sharding.mesh.shape) == {"dp": 4}
        np.testing.assert_array_equal(np.asarray(out["rng"]), [3, 7])
        assert float(out["scale"]) == 512.0

    def test_4_to_8_extends_padding(self, tmp_path):
        d = str(tmp_path)
        state4 = _state(_mesh(4), 4, seed=2)
        integrity.save_checkpoint_verified(d, 1, state4)
        step, out = restore_resharded(
            d, _state(_mesh(8), 8, zeros=True), mesh=_mesh(8))
        assert step == 1
        master = np.asarray(out["master"])
        assert master.shape == (232,)
        np.testing.assert_array_equal(
            master[:TOTAL], np.asarray(state4["master"])[:TOTAL])
        assert not master[TOTAL:].any()

    def test_needs_reshard_tri_state(self, tmp_path):
        d = str(tmp_path)
        assert needs_reshard(d, _mesh(8)) is None  # no checkpoint at all
        integrity.save_checkpoint_verified(d, 1, _state(_mesh(8), 8))
        assert needs_reshard(d, _mesh(8)) is False
        assert needs_reshard(d, _mesh(4)) is True
        # a newest manifest with no topology block is undecidable
        from apex_tpu.utils.checkpoint import save_checkpoint

        path = save_checkpoint(d, 2, _state(_mesh(8), 8))
        integrity.write_manifest(path)  # tree-less: no topology
        assert needs_reshard(d, _mesh(4)) is None

    def test_crc_mismatch_refuses(self, tmp_path):
        """File digests intact but the fingerprint disagrees with the
        restored bytes: the resharded restore must refuse, not ship."""
        d = str(tmp_path)
        integrity.save_checkpoint_verified(d, 1, _state(_mesh(8), 8))
        mpath = integrity.manifest_path(os.path.join(d, "step_1"))
        manifest = json.load(open(mpath))
        for leaf in manifest["fingerprint"]["leaves"]:
            if leaf["path"] == "['master']":
                leaf["crc32"] = (leaf["crc32"] + 1) & 0xFFFFFFFF
        json.dump(manifest, open(mpath, "w"))
        with pytest.raises(ElasticRestoreError, match="crc32 mismatch"):
            restore_resharded(d, _state(_mesh(4), 4, zeros=True),
                              mesh=_mesh(4))

    def test_refuses_non_zero_shape_change(self, tmp_path):
        d = str(tmp_path)
        integrity.save_checkpoint_verified(d, 1, _state(_mesh(8), 8))
        target = _state(_mesh(4), 4, zeros=True)
        target["params"]["w"] = jax.device_put(
            np.zeros((12, 17), np.float32), NamedSharding(_mesh(4), P()))
        with pytest.raises(ElasticRestoreError, match="refusing to guess"):
            restore_resharded(d, target, mesh=_mesh(4))

    def test_refuses_grown_flat_buffer(self, tmp_path):
        """The zero_shard_axis marker is a layout heuristic: a 1-D
        dp-sharded buffer whose target length GREW beyond what dp
        re-padding can explain (a resized table, not ZeRO padding) must
        refuse, not silently zero-extend."""
        d = str(tmp_path)
        integrity.save_checkpoint_verified(d, 1, _state(_mesh(8), 8))
        target = _state(_mesh(4), 4, zeros=True)
        target["master"] = jax.device_put(
            np.zeros(260, np.float32),  # 260 % 4 == 0, but no common T
            NamedSharding(_mesh(4), P("dp")))
        with pytest.raises(ElasticRestoreError,
                           match="migration, not a ZeRO regroup"):
            restore_resharded(d, target, mesh=_mesh(4))

    def test_refuses_dtype_change(self, tmp_path):
        d = str(tmp_path)
        integrity.save_checkpoint_verified(d, 1, _state(_mesh(8), 8))
        target = _state(_mesh(4), 4, zeros=True)
        target["scale"] = jax.device_put(
            np.float64(1.0).astype(np.float16),
            NamedSharding(_mesh(4), P()))
        with pytest.raises(ElasticRestoreError, match="dtype"):
            restore_resharded(d, target, mesh=_mesh(4))

    def test_refuses_absent_axis_and_bad_divisibility(self, tmp_path):
        d = str(tmp_path)
        integrity.save_checkpoint_verified(d, 1, _state(_mesh(8), 8))
        target = _state(_mesh(4), 4, zeros=True)
        specs = jax.tree_util.tree_map(lambda _: P(), target)
        specs["master"] = P("tp")
        with pytest.raises(ElasticRestoreError,
                           match="absent from the restore mesh"):
            restore_resharded(d, target, mesh=_mesh(4), target_specs=specs)
        # 12 x 16 'w' sharded over dp=8 on dim 1: 16 % 8 == 0 is fine,
        # but dim 0 (12) over dp=8 is not
        target8 = _state(_mesh(8), 8, zeros=True)
        specs8 = jax.tree_util.tree_map(lambda _: P(), target8)
        specs8["master"] = P("dp")
        specs8["params"] = {"w": P("dp", None)}
        with pytest.raises(ElasticRestoreError, match="not divisible"):
            restore_resharded(d, target8, mesh=_mesh(8), target_specs=specs8)


# ---------------------------------------------------------------------------
# error-feedback residual state (compressed collectives, PR "quantized
# gradient collectives"): marked advisory in the manifest, regrouped
# where the layout matches, reset-to-zero (never refused) otherwise


class TestErrorFeedbackReshard:
    """The satellite contract (ISSUE 11): EF leaves are marked ``ef`` in
    the topology block; across a topology change they regroup like ZeRO
    flat buffers when the length change is padding-only and otherwise
    reset to zero with a logged warning — a hard refusal is never the
    answer, EF state is advisory."""

    def _ef_state(self, mesh, dp, seed=0, zeros=False, ef_len=None,
                  ef_sharded=False):
        state = _state(mesh, dp, seed=seed, zeros=zeros)
        rng = np.random.RandomState(seed + 100)
        ef_len = _padded(TOTAL, dp) if ef_len is None else ef_len
        ef = np.zeros(ef_len, np.float32)
        if not zeros and ef_sharded:
            # per-rank residuals are nonzero EVERYWHERE (each rank's own
            # error) — truncation can never pass off as padding removal
            ef[:] = rng.randn(ef_len) * 1e-3
        elif not zeros:
            ef[:TOTAL] = rng.randn(TOTAL) * 1e-3
        spec = P("dp") if ef_sharded else P()
        state["ef_residual"] = jax.device_put(
            ef, NamedSharding(mesh, spec))
        return state

    def test_topology_block_marks_ef(self):
        topo = topology_block(self._ef_state(_mesh(8), 8))
        leaves = {l["path"]: l for l in topo["leaves"]}
        assert leaves["['ef_residual']"]["ef"] is True
        assert leaves["['master']"]["ef"] is False

    def test_8_to_4_regroups_padding_only_ef(self, tmp_path):
        """A replicated DDP-style flat residual (padding-only length
        change, zero tail) REGROUPS — the accumulated error survives."""
        d = str(tmp_path)
        state8 = self._ef_state(_mesh(8), 8, seed=3)
        # zero tail: only the padding region beyond TOTAL is zero
        integrity.save_checkpoint_verified(d, 1, state8)
        target = self._ef_state(_mesh(4), 4, zeros=True)
        step, out = restore_resharded(d, target, mesh=_mesh(4))
        assert step == 1
        ef = np.asarray(out["ef_residual"])
        assert ef.shape == (_padded(TOTAL, 4),)
        np.testing.assert_array_equal(
            ef[:TOTAL], np.asarray(state8["ef_residual"])[:TOTAL])

    def test_nonregroupable_ef_resets_to_zero_with_warning(self, tmp_path):
        """A dp-SHARDED per-rank residual concatenates over dp, so the
        global length change is NOT padding-only: reset to zero, warn,
        and restore everything else — never ElasticRestoreError."""
        import logging

        d = str(tmp_path)
        # sharded over dp=8: global length 8 * padded -> nonzero tail
        state8 = self._ef_state(_mesh(8), 8, seed=4, ef_len=8 * 232,
                                ef_sharded=True)
        np.asarray(state8["ef_residual"])  # materialize
        integrity.save_checkpoint_verified(d, 1, state8)
        target = self._ef_state(_mesh(4), 4, zeros=True, ef_len=4 * 228,
                                ef_sharded=True)
        # the elastic logger carries its own handlers; listen directly
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        elog = logging.getLogger("apex_tpu.resilience.elastic")
        elog.addHandler(handler)
        try:
            step, out = restore_resharded(d, target, mesh=_mesh(4))
        finally:
            elog.removeHandler(handler)
        assert step == 1
        ef = np.asarray(out["ef_residual"])
        assert ef.shape == (4 * 228,) and not ef.any()
        assert any("resetting to zero" in r.getMessage() for r in records)
        # the REST of the state still restored with values
        np.testing.assert_array_equal(
            np.asarray(out["master"])[:TOTAL],
            np.asarray(state8["master"])[:TOTAL])

    def test_pre_compression_checkpoint_zero_fills_ef(self, tmp_path):
        """Migration shim: a checkpoint saved BEFORE the compressed
        collectives existed has no EF leaf at all — restoring it into a
        compression-enabled target zero-fills the advisory residual
        (with a warning) instead of refusing on the structure diff."""
        d = str(tmp_path)
        state8 = _state(_mesh(8), 8, seed=7)  # pre-upgrade: no ef leaf
        integrity.save_checkpoint_verified(d, 1, state8)
        target = self._ef_state(_mesh(4), 4, zeros=False)  # nonzero ef
        step, out = restore_resharded(d, target, mesh=_mesh(4))
        assert step == 1
        ef = np.asarray(out["ef_residual"])
        assert ef.shape == (_padded(TOTAL, 4),) and not ef.any()
        np.testing.assert_array_equal(
            np.asarray(out["master"])[:TOTAL],
            np.asarray(state8["master"])[:TOTAL])
        # a NON-advisory structure diff still refuses
        target2 = self._ef_state(_mesh(4), 4, zeros=True)
        target2["stray"] = jax.device_put(
            np.zeros(3, np.float32), NamedSharding(_mesh(4), P()))
        with pytest.raises(ElasticRestoreError, match="migration"):
            restore_resharded(d, target2, mesh=_mesh(4))

    def test_compression_off_drops_saved_ef_with_warning(self, tmp_path):
        """The reverse migration: a checkpoint saved WITH compression
        restores into a compression-off target — the checkpoint-only EF
        leaves are simply not restored (warning), everything else lands;
        and the ef marker is an EXACT segment match, so a leaf merely
        CONTAINING the name still refuses."""
        d = str(tmp_path)
        state8 = self._ef_state(_mesh(8), 8, seed=9)
        integrity.save_checkpoint_verified(d, 1, state8)
        target = _state(_mesh(4), 4, zeros=True)  # no ef leaf at all
        step, out = restore_resharded(d, target, mesh=_mesh(4))
        assert step == 1
        assert "ef_residual" not in out
        np.testing.assert_array_equal(
            np.asarray(out["master"])[:TOTAL],
            np.asarray(state8["master"])[:TOTAL])
        # near-miss name: NOT advisory -> structure diff refuses
        d2 = str(tmp_path / "near")
        state = _state(_mesh(8), 8, seed=10)
        state["chef_residual"] = jax.device_put(
            np.ones(4, np.float32), NamedSharding(_mesh(8), P()))
        topo = topology_block(state)
        assert all(not l["ef"] for l in topo["leaves"])
        integrity.save_checkpoint_verified(d2, 1, state)
        with pytest.raises(ElasticRestoreError, match="migration"):
            restore_resharded(d2, _state(_mesh(4), 4, zeros=True),
                              mesh=_mesh(4))

    def test_8_to_4_resume_with_compression_on(self, tmp_path):
        """ACCEPTANCE (satellite): a REAL compressed-ZeRO optimizer
        state — DistributedFusedAdamState with an error-feedback
        residual — saved on 8 devices resumes on 4: master/moments
        regroup via zero_shard_axis, the per-rank residual resets to
        zero (logged), nothing refuses."""
        import functools

        import jax.numpy as jnp
        from apex_tpu.compat import shard_map
        from apex_tpu.optimizers import (
            distributed_fused_adam, zero_state_specs,
        )
        from apex_tpu.parallel.compress import CompressionConfig

        cfg = CompressionConfig()
        d = str(tmp_path)
        params = {"w": np.arange(225, dtype=np.float32)}

        def make(mesh, dp):
            opt = distributed_fused_adam(
                lr=1e-3, axis_name="dp", axis_size=dp, compression=cfg)
            specs = zero_state_specs("dp", compression=cfg)
            rep = NamedSharding(mesh, P())
            init = functools.partial(
                shard_map, mesh=mesh, in_specs=(P(),), out_specs=specs,
                check_vma=False,
            )(opt.init)
            p = {"w": jax.device_put(jnp.asarray(params["w"]), rep)}
            return {"params": p, "opt": init(p)}

        state8 = make(_mesh(8), 8)
        # make the per-rank residual NONZERO (as after a real compressed
        # step) so the non-regroupable reset is observable: the global
        # view concatenates 8 per-rank buffers
        ef_global = np.asarray(state8["opt"].ef_residual)
        assert ef_global.ndim == 1 and ef_global.shape[0] % 8 == 0
        nonzero_ef = (np.random.RandomState(9)
                      .randn(ef_global.shape[0]).astype(np.float32) * 1e-3)
        state8["opt"] = state8["opt"]._replace(ef_residual=jax.device_put(
            nonzero_ef,
            NamedSharding(_mesh(8), P("dp"))))
        topo = topology_block(state8)
        leaves = {l["path"]: l for l in topo["leaves"]}
        assert leaves["['opt'].ef_residual"]["ef"] is True
        assert leaves["['opt'].ef_residual"]["spec"] == ["dp"]
        integrity.save_checkpoint_verified(d, 2, state8)

        target = make(_mesh(4), 4)
        step, out = restore_resharded(d, target, mesh=_mesh(4))
        assert step == 2
        # master/moments: the flat padded length is CHUNK_SIZE-dominated
        # here, so the global shape is dp-invariant and restores verbatim
        np.testing.assert_array_equal(
            np.asarray(out["opt"].master_shard),
            np.asarray(state8["opt"].master_shard))
        # the per-rank residual could not regroup (nonzero truncation):
        # reset to zero at the NEW dp's global length, not refused
        ef = np.asarray(out["opt"].ef_residual)
        assert ef.shape == (ef_global.shape[0] // 2,) and not ef.any()


# ---------------------------------------------------------------------------
# AutoResume integration: elastic routing + EMA persistence


class TestAutoResumeElastic:
    def test_restore_routes_through_resharder(self, tmp_path):
        d = str(tmp_path)
        ar8 = AutoResume(d, interval=1, install_handlers=False)
        state8 = _state(_mesh(8), 8, seed=5)
        ar8.step(1, state8)
        ar8.close()
        # the finalize folded a real measurement and persisted it
        manifest = integrity.read_manifest(os.path.join(d, "step_1"))
        assert manifest["autoresume"]["save_ema_s"] > 0
        assert manifest["topology"]["mesh"]["axes"] == {"dp": 8}

        ar4 = AutoResume(d, install_handlers=False)
        step0, out = ar4.restore(_state(_mesh(4), 4, zeros=True))
        assert step0 == 1
        master = np.asarray(out["master"])
        assert master.shape == (228,)
        np.testing.assert_array_equal(
            master[:TOTAL], np.asarray(state8["master"])[:TOTAL])
        # the restart inherited the previous incarnation's EMAs
        assert ar4._save_ema == manifest["autoresume"]["save_ema_s"]

    def test_same_mesh_restore_stays_on_normal_path(self, tmp_path):
        d = str(tmp_path)
        ar = AutoResume(d, interval=1, install_handlers=False)
        state = _state(_mesh(8), 8, seed=6)
        ar.step(1, state)
        ar.close()
        step0, out = AutoResume(d, install_handlers=False).restore(
            _state(_mesh(8), 8, zeros=True))
        assert step0 == 1
        np.testing.assert_array_equal(
            np.asarray(out["master"]), np.asarray(state["master"]))


# ---------------------------------------------------------------------------
# deadline-budgeted termination saves


def _tiny_state():
    rep = NamedSharding(_mesh(8), P())
    return {"w": jax.device_put(np.arange(8, dtype=np.float32), rep)}


class TestDeadlineDecision:
    """The decision is a pure function of grace/EMAs/pending — every arm
    pinned with seeded values (no IO)."""

    def _ar(self, tmp_path, **kw):
        return AutoResume(str(tmp_path), install_handlers=False, **kw)

    def test_no_budget_always_saves(self, tmp_path):
        ar = self._ar(tmp_path)
        ar._save_ema = 1e9
        decision, info = ar._emergency_decision()
        assert decision == "save" and info["grace_s"] is None

    def test_no_history_attempts_save(self, tmp_path):
        ar = self._ar(tmp_path, grace_s=0.001)
        decision, info = ar._emergency_decision()
        assert decision == "save" and info["save_ema_s"] is None

    def test_budget_covers_full_save(self, tmp_path):
        ar = self._ar(tmp_path, grace_s=100.0)
        ar._save_ema = 1.0
        ar.request_resume()  # anchors the countdown
        decision, info = ar._emergency_decision()
        assert decision == "save"
        assert info["remaining_s"] == pytest.approx(100.0, abs=1.0)

    def test_finalize_when_only_the_commit_fits(self, tmp_path):
        ar = self._ar(tmp_path, grace_s=1.0)
        ar._save_ema = 50.0
        ar._finalize_ema = 0.01
        ar._pending = {"step": 7, "fingerprint": None, "topology": None,
                       "issue_s": 0.0}
        ar.request_resume()
        decision, info = ar._emergency_decision()
        assert decision == "finalize" and info["pending_step"] == 7
        ar._pending = None  # avoid close() touching the fake

    def test_skip_when_nothing_fits(self, tmp_path):
        ar = self._ar(tmp_path, grace_s=0.001)
        ar._save_ema = 50.0
        ar._finalize_ema = 40.0
        ar._pending = {"step": 7, "fingerprint": None, "topology": None,
                       "issue_s": 0.0}
        ar.request_resume()
        decision, _ = ar._emergency_decision()
        assert decision == "skip"
        ar._pending = None

    def test_without_pending_tight_budget_still_skips(self, tmp_path):
        ar = self._ar(tmp_path, grace_s=0.001)
        ar._save_ema = 50.0
        ar.request_resume()
        assert ar._emergency_decision()[0] == "skip"

    def test_env_default_grace(self, tmp_path, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PREEMPTION_GRACE_S", "12.5")
        assert self._ar(tmp_path).grace_s == 12.5
        monkeypatch.setenv("APEX_TPU_PREEMPTION_GRACE_S", "nope")
        assert self._ar(tmp_path).grace_s is None


class TestDeadlineBehavior:
    """ACCEPTANCE: with a seeded grace budget smaller than the measured
    save EMA, AutoResume provably skips the fresh save and the restart
    restores the last VERIFIED step — no torn manifest ever treated as
    durable. Real saves, real manifests, 8-device mesh."""

    @pytest.fixture
    def router(self):
        sink = monitor.MemorySink()
        r = monitor.MetricRouter([sink])
        goodput.set_router(r)
        try:
            yield sink
        finally:
            goodput.set_router(None)
            r.close()

    def test_skip_abandons_pending_and_restores_last_verified(
            self, tmp_path, router):
        d = str(tmp_path)
        # background_finalize=False: the drill needs step 4's manifest
        # commit DETERMINISTICALLY un-landed when the SIGTERM decision
        # runs; with the default background verify a tiny state's commit
        # wins the race and there is nothing left to abandon (that
        # healthy outcome has its own pin in test_health.py)
        ar = AutoResume(d, interval=2, install_handlers=False,
                        background_finalize=False)
        s2, s4, s5 = (_state(_mesh(8), 8, seed=i) for i in (2, 4, 5))
        assert not ar.step(2, s2)        # interval save of step 2 (pending)
        assert not ar.step(3, s2)        # no-op step
        assert not ar.step(4, s4)        # finalizes step 2, pends step 4
        # seed: grace provably smaller than the measured save EMA
        assert ar._save_ema is not None and ar._save_ema > 0
        ar.grace_s = 1e-9
        ar.request_resume()
        assert ar.step(5, s5) is True
        assert ar.termination_decision == "skip"
        ar.close()
        # step 4's dir may exist (background write), but it is TOMBSTONED
        # — failed verification, not legacy-acceptable — and step 5 was
        # never written; the restart restores verified step 2
        ok, why = integrity.verify_checkpoint(os.path.join(d, "step_4"))
        assert not ok and "abandoned" in why
        assert not os.path.isdir(os.path.join(d, "step_5"))
        assert integrity.verified_latest_step(d) == 2
        step0, out = AutoResume(d, install_handlers=False).restore(
            _state(_mesh(8), 8, zeros=True))
        assert step0 == 2
        np.testing.assert_array_equal(
            np.asarray(out["master"]), np.asarray(s2["master"]))
        # the decision reached the goodput stream: a ckpt_save span slice
        # carrying it plus the preemption event with the inputs
        recs = list(router.records)
        (ev,) = [r for r in recs if r["kind"] == "preemption"]
        assert ev["decision"] == "skip" and ev["saved_step"] is None
        assert ev["grace_s"] == 1e-9 and ev["save_ema_s"] > 0
        assert ev["pending_step"] == 4
        spans = [r for r in recs if r["kind"] == "span"
                 and r.get("decision") == "skip"]
        assert spans and spans[0]["phase"] == "ckpt_save"

    def test_finalize_commits_pending_only(self, tmp_path, router):
        d = str(tmp_path)
        # background_finalize=False for the same determinism reason as
        # the skip drill above: the "finalize" arm needs a genuinely
        # pending step-4 commit at decision time
        ar = AutoResume(d, interval=2, install_handlers=False,
                        background_finalize=False)
        s2, s4, s5 = (_state(_mesh(8), 8, seed=i) for i in (2, 4, 5))
        assert not ar.step(2, s2)        # first save: calibration commit
        assert not ar.step(3, s2)
        assert not ar.step(4, s4)        # pending step 4 (overlapped)
        ar._save_ema = 50.0              # a fresh save "cannot" fit...
        ar._finalize_ema = 1e-6          # ...but the commit can
        ar.grace_s = 5.0
        ar.request_resume()
        assert ar.step(5, s5) is True
        assert ar.termination_decision == "finalize"
        ar.close()
        assert integrity.verified_latest_step(d) == 4
        assert not os.path.isdir(os.path.join(d, "step_5"))
        (ev,) = [r for r in router.records if r["kind"] == "preemption"]
        assert ev["decision"] == "finalize" and ev["saved_step"] == 4

    def test_default_save_decision_emits_event(self, tmp_path, router):
        d = str(tmp_path)
        ar = AutoResume(d, install_handlers=False)
        ar.request_resume()
        assert ar.step(1, _state(_mesh(8), 8)) is True
        assert ar.termination_decision == "save"
        ar.close()
        assert integrity.verified_latest_step(d) == 1
        (ev,) = [r for r in router.records if r["kind"] == "preemption"]
        assert ev["decision"] == "save" and ev["saved_step"] == 1


# ---------------------------------------------------------------------------
# retention: the torn-dir window pin lives in test_resilience.py


# ---------------------------------------------------------------------------
# the gate + the chaos drill (slow tier)


def test_elastic_selftest_gate(tmp_path):
    """The ``python -m apex_tpu.resilience.elastic`` gate exits 0 —
    8->4->8 round trips of a REAL ZeRO state plus every refusal case."""
    from apex_tpu.resilience.elastic.__main__ import main

    assert main(["--dir", str(tmp_path)]) == 0


def _run_gpt(args, devices, extra_env=None):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={devices}",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        **(extra_env or {}),
    )
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.argv={['x'] + args!r}\n"
        f"exec(open('examples/gpt/pretrain_gpt.py').read())\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=600,
    )
    assert proc.returncode == 0, (
        f"pretrain_gpt failed rc={proc.returncode}\nstdout tail: "
        f"{proc.stdout[-1500:]}\nstderr tail: {proc.stderr[-1500:]}"
    )
    return proc.stdout


_DRILL_BASE = ["--layers", "2", "--hidden", "64", "--heads", "4",
               "--seq-len", "32", "--micro-batch", "1",
               "--global-batch", "16", "--log-interval", "1", "--zero"]


def _losses(jsonl_path):
    out = {}
    for line in open(jsonl_path):
        rec = json.loads(line)
        if rec.get("kind") == "metrics":
            out[rec["step"]] = rec["loss"]
    return out


@pytest.mark.chaos
def test_gpt_elastic_chaos_drill(tmp_path):
    """ACCEPTANCE, both directions: deterministic GPT+ZeRO run, SIGTERM
    at step k, restart on a different device count; params + dp-sharded
    ZeRO state + loss scale restore RESHARDED and verified, the loss
    trajectory continues within pinned tolerance of an uninterrupted
    run, and the goodput accountant books both incarnations under one
    run id with the partition identity exact."""
    steps = 8

    # the reference trajectory: uninterrupted 8-device run (the global
    # batch is dp-invariant, so it also references the 4-device runs)
    ref_jsonl = tmp_path / "ref.jsonl"
    _run_gpt(_DRILL_BASE + ["--steps", str(steps),
                            "--metrics-jsonl", str(ref_jsonl)], devices=8)
    ref = _losses(ref_jsonl)
    assert set(ref) == set(range(steps))

    for first_dev, second_dev, tag in ((8, 4, "8to4"), (4, 8, "4to8")):
        save = tmp_path / f"ck_{tag}"
        jsonl = tmp_path / f"m_{tag}.jsonl"
        out = _run_gpt(
            _DRILL_BASE + ["--steps", str(steps), "--save", str(save),
                           "--save-interval", "3",
                           "--chaos-sigterm-step", "4",
                           "--metrics-jsonl", str(jsonl)],
            devices=first_dev)
        assert "termination checkpoint at step 5; exiting" in out
        out = _run_gpt(
            _DRILL_BASE + ["--steps", str(steps), "--save", str(save),
                           "--save-interval", "3",
                           "--metrics-jsonl", str(jsonl)],
            devices=second_dev)
        assert "resumed from step 5" in out, out

        # the combined trajectory (incarnation 1 steps 0-4, incarnation 2
        # steps 5-7) matches the uninterrupted reference within tolerance
        got = _losses(jsonl)
        assert set(got) == set(range(steps))
        for s in range(steps):
            assert got[s] == pytest.approx(ref[s], abs=5e-2), (
                tag, s, got[s], ref[s])

        records = [json.loads(l) for l in open(jsonl)]
        # both incarnations announce themselves under ONE run id (the
        # --save anchor) and the second books real restore badput
        runs = [r for r in records if r["kind"] == "run"]
        assert len(runs) == 2
        assert len({r["run_id"] for r in runs}) == 1
        # the termination save emitted its deadline decision
        pre = [r for r in records if r["kind"] == "preemption"]
        assert pre and pre[0]["decision"] == "save"
        goodputs = [r for r in records if r["kind"] == "goodput"]
        assert len(goodputs) == 2
        assert goodputs[1]["badput_ckpt_restore_s"] > 0
        # replay the FULL two-incarnation stream offline: identity exact
        report = goodput.account(records, run_id=runs[0]["run_id"])
        f = report.fields()
        total = f["productive_s"]
        for phase in ("ckpt_save", "ckpt_restore", "rollback", "compile",
                      "data_wait", "stall", "init", "shutdown"):
            total = total + f[f"badput_{phase}_s"]
        assert total + f["unattributed_s"] == f["wall_s"]
        assert f["incarnations"] == 2
        assert f["badput_ckpt_save_s"] > 0


@pytest.mark.chaos
def test_gpt_preemption_skip_budget(tmp_path):
    """ACCEPTANCE: a grace budget provably smaller than the measured
    save EMA makes the termination SKIP the fresh save (and abandon the
    pending one); the restart restores the last VERIFIED step."""
    save = tmp_path / "ck"
    jsonl = tmp_path / "m.jsonl"
    # --no-background-finalize: the drill's assertions need step 4's
    # manifest commit DETERMINISTICALLY pending when the SIGTERM skip
    # decision runs; with the default background verify a tiny state's
    # commit can win the race and leave nothing to abandon (the healthy
    # outcome — pinned separately in test_health.py)
    out = _run_gpt(
        _DRILL_BASE + ["--steps", "8", "--save", str(save),
                       "--save-interval", "2",
                       "--chaos-sigterm-step", "5",
                       "--no-background-finalize",
                       "--metrics-jsonl", str(jsonl)],
        devices=8,
        extra_env={"APEX_TPU_PREEMPTION_GRACE_S": "0.000001"})
    # interval saves at 2 and 4 measured the EMA; at SIGTERM the pending
    # step-4 commit cannot fit either -> skip, and the example must NOT
    # claim a termination checkpoint
    assert "termination at step 6: skip (grace budget); exiting" in out, out
    assert "termination checkpoint" not in out
    records = [json.loads(l) for l in open(jsonl)]
    (ev,) = [r for r in records if r["kind"] == "preemption"]
    assert ev["decision"] == "skip" and ev["save_ema_s"] > 0
    # the newest VERIFIED step is the finalized interval save (step 2 —
    # step 4's manifest was never committed and is tombstoned)
    assert integrity.verified_latest_step(str(save)) == 2
    out = _run_gpt(
        _DRILL_BASE + ["--steps", "7", "--save", str(save),
                       "--save-interval", "100",
                       "--metrics-jsonl", str(jsonl)],
        devices=8)
    assert "resumed from step 2" in out, out
