"""Tests for apex_tpu.utils.benchmarking (the relay-proof slope timer).

Timing itself can't be asserted tightly in CI; these pin the harness
mechanics — chains really run k times, outputs are returned, and the
escalation loop terminates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.utils.benchmarking import (
    chained_seconds_per_iter,
    fetch,
    seconds_per_iter,
)


def test_fetch_returns_numpy_leaves():
    out = fetch({"a": jnp.ones(3), "b": (jnp.zeros(()),)})
    assert len(out) == 2
    assert all(isinstance(x, np.ndarray) for x in out)


def test_chained_runs_k_iterations_and_returns_output():
    calls = []

    # the body must cost ~ms, not ~ns: a trivial body's slope is below
    # timer noise and correctly trips the non-positive-slope raise
    def build(k):
        calls.append(k)

        def run(x):
            def body(c, _):
                return jnp.tanh(c @ c + 0.1), None  # bounded: no overflow

            c, _ = jax.lax.scan(body, x, None, length=k)
            return c[0, 0]

        return run

    x = jnp.eye(256, dtype=jnp.float32)
    sec, out = chained_seconds_per_iter(
        build, (x,), reps=1, target_signal=0.0, return_output=True,
    )
    assert sec > 0.0
    # first span is 32: [1, 33] and acceptance at the 0.0 target
    assert calls == [1, 33]
    assert np.isfinite(out[0])


def test_chained_escalates_span_until_signal():
    spans = []

    def build(k):
        spans.append(k)

        def run(x):
            def body(c, _):
                return jnp.sin(c), None

            c, _ = jax.lax.scan(body, x, None, length=k)
            return c

        return run

    # unreachable signal target forces escalation to max_span exactly once
    try:
        chained_seconds_per_iter(
            build, (jnp.float32(1.0),), reps=1, target_signal=1e9,
            max_span=128,
        )
    except RuntimeError:
        pass  # slope may be ~0 for this trivial body; the raise is correct
    assert spans[0] == 1 and spans[1] == 33 and spans[-1] == 129


def test_seconds_per_iter_threads_carry():
    a = jnp.eye(256, dtype=jnp.float32) * 0.5
    sec = seconds_per_iter(lambda c: c @ a + 1.0, a, reps=1)
    assert sec > 0.0


def test_nonpositive_slope_raises_instead_of_recording_garbage(monkeypatch):
    import apex_tpu.utils.benchmarking as B

    times = iter([5.0, 5.0])  # t(1) == t(1+span): zero slope at max_span

    def fake_best_of(fn, args, reps):
        return next(times), [np.float32(0.0)]

    monkeypatch.setattr(B, "_best_of", fake_best_of)
    with pytest.raises(RuntimeError, match="non-positive slope"):
        B.chained_seconds_per_iter(
            lambda k: lambda: None, (), target_signal=1e9, max_span=32
        )


class TestHarvestedReplay:
    """bench.py's harvested-TPU replay selection (freshness + recency)."""

    def _bench(self):
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(root, "bench.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _write(self, tmp_path, records):
        import json

        p = tmp_path / "results.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        return str(p)

    def test_fresh_partial_beats_stale_full(self, tmp_path):
        import time

        bench = self._bench()
        now = time.strftime("%Y-%m-%dT%H:%M:%S")
        old = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(time.time() - 48 * 3600)
        )
        p = self._write(tmp_path, [
            {"section": "headline", "ok": True, "metric": "m",
             "value": 1200.0, "unit": "u", "vs_baseline": 2.0, "ts": old},
            {"section": "headline_o2", "ok": True, "metric": "m",
             "value": 4000.0, "unit": "u", "ts": now},
        ])
        rec = bench.harvested_tpu_record(p)
        assert rec["value"] == 4000.0 and rec["vs_baseline"] is None

    def test_stale_records_never_replay(self, tmp_path):
        import time

        bench = self._bench()
        old = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(time.time() - 48 * 3600)
        )
        p = self._write(tmp_path, [
            {"section": "headline", "ok": True, "metric": "m",
             "value": 1200.0, "unit": "u", "vs_baseline": 2.0, "ts": old},
        ])
        assert bench.harvested_tpu_record(p) is None

    def test_full_record_beats_its_own_partial(self, tmp_path):
        import time

        bench = self._bench()
        now = time.strftime("%Y-%m-%dT%H:%M:%S")
        p = self._write(tmp_path, [
            {"section": "headline_o2", "ok": True, "metric": "m",
             "value": 1500.0, "unit": "u", "ts": now},
            {"section": "headline", "ok": True, "metric": "m",
             "value": 1500.0, "unit": "u", "vs_baseline": 2.1, "ts": now},
        ])
        assert bench.harvested_tpu_record(p)["vs_baseline"] == 2.1

    def test_missing_or_failed_records_yield_none(self, tmp_path):
        bench = self._bench()
        assert bench.harvested_tpu_record(str(tmp_path / "nope.jsonl")) is None
        p = self._write(tmp_path, [
            {"section": "headline", "ok": False, "value": 9.0},
            {"section": "micro", "ok": True, "value": 1.0},
        ])
        assert bench.harvested_tpu_record(p) is None


class TestHeadlineSubrecordReuse:
    """run_all_tpu's split-window headline assembly: each half is emitted
    the moment it lands, and retries / the replay path pair fresh halves
    captured in different relay windows instead of re-measuring."""

    def _write(self, tmp_path, records):
        import json

        p = tmp_path / "results.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        return str(p)

    def _run_all(self):
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "run_all_tpu_mod", os.path.join(root, "benchmarks", "run_all_tpu.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_fresh_subrecord_freshness(self, tmp_path):
        import time

        mod = self._run_all()
        now = time.strftime("%Y-%m-%dT%H:%M:%S")
        old = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(time.time() - 48 * 3600)
        )
        p = self._write(tmp_path, [
            {"section": "headline_o2", "ok": True, "value": 1000.0, "ts": old},
            {"section": "headline_o2", "ok": True, "value": 2626.0, "ts": now},
            {"section": "headline_o0", "ok": True, "value": 900.0, "ts": old},
        ])
        assert mod.fresh_subrecord(p, "headline_o2")["value"] == 2626.0
        assert mod.fresh_subrecord(p, "headline_o0") is None  # stale
        assert mod.fresh_subrecord(str(tmp_path / "nope.jsonl"), "headline_o2") is None

    def test_run_headline_reuses_both_halves_without_measuring(self, tmp_path):
        import sys
        import time

        mod = self._run_all()
        now = time.strftime("%Y-%m-%dT%H:%M:%S")
        p = self._write(tmp_path, [
            {"section": "headline_o2", "ok": True, "value": 2626.0, "ts": now},
            {"section": "headline_o0", "ok": True, "value": 800.0, "ts": now},
        ])

        class _NoMeasure:
            def __getattr__(self, name):
                if name == "measure":
                    def boom(*a, **k):
                        raise AssertionError("measure() must not be called")
                    return boom
                if name == "ts_epoch":
                    def ts_epoch(rec, key="ts"):
                        return time.mktime(
                            time.strptime(rec.get(key, ""), "%Y-%m-%dT%H:%M:%S"))
                    return ts_epoch
                raise AttributeError(name)

        saved = sys.modules.get("bench")
        sys.modules["bench"] = _NoMeasure()
        try:
            rec = mod.run_headline(deadline=time.monotonic() + 60, out_path=p)
        finally:
            if saved is not None:
                sys.modules["bench"] = saved
            else:
                del sys.modules["bench"]
        assert rec["value"] == 2626.0
        assert rec["o0_value"] == 800.0
        assert rec["vs_baseline"] == round(2626.0 / 800.0, 3)

    def test_replay_pairs_split_window_halves(self, tmp_path):
        import importlib.util
        import os
        import time

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_mod2", os.path.join(root, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)

        now = time.strftime("%Y-%m-%dT%H:%M:%S")
        old = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(time.time() - 48 * 3600)
        )
        p = self._write(tmp_path, [
            {"section": "headline_o2", "ok": True, "metric": "m",
             "value": 2626.0, "unit": "u", "ts": now},
            {"section": "headline_o0", "ok": True, "value": 800.0, "ts": now},
        ])
        rec = bench.harvested_tpu_record(p)
        assert rec["vs_baseline"] == round(2626.0 / 800.0, 3)
        assert rec["o0_value"] == 800.0

        # a stale O0 never pairs
        p = self._write(tmp_path, [
            {"section": "headline_o2", "ok": True, "metric": "m",
             "value": 2626.0, "unit": "u", "ts": now},
            {"section": "headline_o0", "ok": True, "value": 800.0, "ts": old},
        ])
        assert bench.harvested_tpu_record(p)["vs_baseline"] is None


class TestReuseFreshnessGate:
    def test_reassembled_record_gates_on_original_measurement_ts(self, tmp_path):
        # a reuse-assembled headline record is re-stamped by emit() at
        # assembly time; the replay freshness bound must follow the ORIGINAL
        # capture time in o2_reused_from_ts, not the re-stamp
        import importlib.util
        import json
        import os
        import time

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_mod3", os.path.join(root, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)

        now = time.strftime("%Y-%m-%dT%H:%M:%S")
        old = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(time.time() - 48 * 3600)
        )
        p = tmp_path / "r.jsonl"
        p.write_text(json.dumps(
            {"section": "headline", "ok": True, "metric": "m",
             "value": 2626.0, "unit": "u", "vs_baseline": 3.0,
             "ts": now, "o2_reused_from_ts": old}) + "\n")
        assert bench.harvested_tpu_record(str(p)) is None
