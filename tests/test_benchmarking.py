"""Tests for apex_tpu.utils.benchmarking (the relay-proof slope timer).

Timing itself can't be asserted tightly in CI; these pin the harness
mechanics — chains really run k times, outputs are returned, and the
escalation loop terminates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.utils.benchmarking import (
    chained_seconds_per_iter,
    fetch,
    seconds_per_iter,
)


def test_fetch_returns_numpy_leaves():
    out = fetch({"a": jnp.ones(3), "b": (jnp.zeros(()),)})
    assert len(out) == 2
    assert all(isinstance(x, np.ndarray) for x in out)


def test_chained_runs_k_iterations_and_returns_output():
    calls = []

    # the body must cost ~ms, not ~ns: a trivial body's slope is below
    # timer noise and correctly trips the non-positive-slope raise
    def build(k):
        calls.append(k)

        def run(x):
            def body(c, _):
                return jnp.tanh(c @ c + 0.1), None  # bounded: no overflow

            c, _ = jax.lax.scan(body, x, None, length=k)
            return c[0, 0]

        return run

    x = jnp.eye(256, dtype=jnp.float32)
    sec, out = chained_seconds_per_iter(
        build, (x,), reps=1, target_signal=0.0, return_output=True,
    )
    assert sec > 0.0
    # first span is 32: [1, 33] and acceptance at the 0.0 target
    assert calls == [1, 33]
    assert np.isfinite(out[0])


def test_chained_escalates_span_until_signal():
    spans = []

    def build(k):
        spans.append(k)

        def run(x):
            def body(c, _):
                return jnp.sin(c), None

            c, _ = jax.lax.scan(body, x, None, length=k)
            return c

        return run

    # unreachable signal target forces escalation to max_span exactly once
    try:
        chained_seconds_per_iter(
            build, (jnp.float32(1.0),), reps=1, target_signal=1e9,
            max_span=128,
        )
    except RuntimeError:
        pass  # slope may be ~0 for this trivial body; the raise is correct
    assert spans[0] == 1 and spans[1] == 33 and spans[-1] == 129


def test_seconds_per_iter_threads_carry():
    a = jnp.eye(256, dtype=jnp.float32) * 0.5
    sec = seconds_per_iter(lambda c: c @ a + 1.0, a, reps=1)
    assert sec > 0.0


def test_nonpositive_slope_raises_instead_of_recording_garbage(monkeypatch):
    import apex_tpu.utils.benchmarking as B

    times = iter([5.0, 5.0])  # t(1) == t(1+span): zero slope at max_span

    def fake_best_of(fn, args, reps):
        return next(times), [np.float32(0.0)]

    monkeypatch.setattr(B, "_best_of", fake_best_of)
    with pytest.raises(RuntimeError, match="non-positive slope"):
        B.chained_seconds_per_iter(
            lambda k: lambda: None, (), target_signal=1e9, max_span=32
        )
