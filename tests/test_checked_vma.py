"""Core parallel primitives under jax's CHECKED shard_map (check_vma=True,
the default) — the mode every fresh user hits.

The package's own tests historically ran check_vma=False; probing under
checked mode (2026-07-31) found three latent type failures, all fixed and
pinned here with checked-vs-unchecked numeric parity:

- ring attention's (b, 0) bias placeholder entered the ring scan carry
  unvarying and left varying after ppermute (scan typecheck);
- the pipeline schedules' zero boundary-activation carry had the same
  mismatch (fixed-point vma derived from eval_shape in _varying_zeros);
- the TP mappings' bwd rules produced wrongly-typed cotangents
  (scatter bwds need the invariant all_gather; reduce_from's bwd must
  pvary the invarying cotangent).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.compat import HAS_VMA
from apex_tpu.parallel.ring_attention import ring_attention

# the whole module probes vma typing, which pre-vma (check_rep era) jax
# does not implement — nothing here is meaningful there
pytestmark = pytest.mark.skipif(
    not HAS_VMA, reason="this jax has no vma tracking (check_rep era)"
)


@pytest.fixture
def cp_mesh():
    return Mesh(np.asarray(jax.devices()), ("cp",))


def _ring_loss_grads(mesh, check_vma, **ring_kw):
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 32, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 32, 8))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 32, 8))

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, None, "cp"),) * 3,
        out_specs=P(None, None, "cp"),
        check_vma=check_vma,
    )
    def grads(q, k, v):
        def loss(q, k, v):
            return jnp.sum(jnp.sin(ring_attention(
                q, k, v, axis_name="cp", **ring_kw)))

        return jax.grad(loss)(q, k, v)

    return np.asarray(grads(q, k, v))


@pytest.mark.parametrize("ring_kw", [
    dict(causal=True),
    dict(causal=True, window=8),
    dict(causal=True, zigzag=True),
])
def test_ring_attention_checked_matches_unchecked(cp_mesh, ring_kw):
    got = _ring_loss_grads(cp_mesh, True, **ring_kw)
    want = _ring_loss_grads(cp_mesh, False, **ring_kw)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_pipeline_1f1b_checked_matches_unchecked():
    from apex_tpu.parallel.pipeline.schedules import (
        forward_backward_pipelining_without_interleaving,
    )

    mesh = Mesh(np.asarray(jax.devices()), ("pp",))
    hid, mb, M = 8, 2, 8
    xs = jax.random.normal(jax.random.PRNGKey(0), (M, mb, hid))
    ts = jax.random.normal(jax.random.PRNGKey(3), (M, mb, hid))

    def stage_fn(params, x):
        return jnp.tanh(x @ params)

    def loss_fn(x, t):
        return jnp.mean((x - t) ** 2)

    def run(check_vma):
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(P(), P()),
            out_specs=(P(), P("pp")), check_vma=check_vma,
        )
        def go(xs, ts):
            params = jax.random.normal(
                jax.random.fold_in(jax.random.PRNGKey(1),
                                   jax.lax.axis_index("pp")),
                (hid, hid),
            ) * 0.3
            loss, _, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, params, xs, ts, axis_name="pp"
            )
            return jax.lax.pmean(loss, "pp"), grads[None]

        return go(xs, ts)

    l1, g1 = run(True)
    l0, g0 = run(False)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-6,
                               atol=1e-7)


def test_gpt_pp_tp_sp_full_step_checked():
    """The dryrun-class integration (pipelined parallel transformer with
    SP) must compile AND produce finite loss/grads under default checked
    shard_map — the three latent fixes compose here."""
    from apex_tpu.models.gpt_pipeline import build_gpt_pipeline
    from apex_tpu.parallel import parallel_state
    from apex_tpu.parallel.pipeline import forward_backward_with_pre_post
    from apex_tpu.transformer import TransformerConfig

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2,
    )
    vocab, seq, hidden, mb, num_micro = 64, 16, 32, 2, 2
    cfg = TransformerConfig(
        num_layers=4, hidden_size=hidden, num_attention_heads=4,
        vocab_size=vocab, max_position_embeddings=seq,
        hidden_dropout=0.0, attention_dropout=0.0,
        sequence_parallel=True, compute_dtype=jnp.float32,
    )
    parts = build_gpt_pipeline(cfg, 2)
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (num_micro, mb * 2, seq), 0, vocab)
    labels = jnp.roll(tokens, -1, axis=2)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, "dp"), P(None, "dp")), out_specs=(P(), P()),
    )
    def step(tokens, labels):
        init_key = jax.random.PRNGKey(0)
        pre = parts.embed.init(init_key, tokens[0])["params"]
        h0 = parts.pre_fn(pre, tokens[0])
        r = jax.lax.axis_index("pp")
        stage = parts.chunk.init(
            jax.random.fold_in(jax.random.fold_in(init_key, 7), r), h0
        )["params"]
        params = {"pre": pre, "stages": stage,
                  "post": parts.init_post(jax.random.fold_in(init_key, 9))}
        loss, _, grads = forward_backward_with_pre_post(
            parts.pre_fn, parts.stage_fn, parts.post_loss_fn, params,
            tokens, labels, axis_name="pp",
        )
        gnorm = sum(
            jnp.sum(jnp.square(g))
            for g in jax.tree_util.tree_leaves(grads)
        )
        for ax in ("tp", "cp", "dp", "pp"):
            loss = jax.lax.pmean(loss, ax)
            gnorm = jax.lax.pmean(gnorm, ax)
        return loss, gnorm

    loss, gnorm = step(tokens, labels)
    assert np.isfinite(float(loss))
    assert np.isfinite(float(gnorm)) and float(gnorm) > 0.0
    parallel_state.destroy_model_parallel()


def test_tp_linears_checked_match_unchecked():
    """Column+Row parallel linears (the mappings' bwd rules) produce the
    same grads in both modes."""
    from apex_tpu.parallel import parallel_state
    from apex_tpu.parallel.layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=8,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))

    def run(check_vma):
        col = ColumnParallelLinear(output_size=32, gather_output=False)
        row = RowParallelLinear(output_size=16, input_is_parallel=True)

        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_vma=check_vma,
        )
        def grads(x):
            from apex_tpu.parallel import pvary_params

            kc = jax.random.fold_in(jax.random.PRNGKey(1),
                                    jax.lax.axis_index("tp"))
            # zeros-init SHARDED params read as replicated under checked
            # vma even though each rank holds a distinct slice: mark them
            # varying or grads auto-psum over tp (the failure pinned
            # here). Column kernel+bias both shard the output dim; row
            # kernel shards the input dim but its bias is applied AFTER
            # the reduction — genuinely replicated, so it must stay
            # invarying (pvarying it makes the output spuriously varying)
            pc = pvary_params(col.init(kc, x), "tp")
            h = col.apply(pc, x)
            pr = row.init(jax.random.fold_in(kc, 2), h)
            pr = {"params": {
                "kernel": pvary_params(pr["params"]["kernel"], "tp"),
                "bias": pr["params"]["bias"],
            }}

            def loss(pc, pr):
                out = row.apply(pr, col.apply(pc, x))
                return jnp.sum(jnp.sin(out))

            gc, gr = jax.grad(loss, argnums=(0, 1))(pc, pr)
            total = sum(
                jnp.sum(jnp.abs(l))
                for l in jax.tree_util.tree_leaves((gc, gr))
            )
            return jax.lax.pmean(total, "tp")

        return float(grads(x))

    got, want = run(True), run(False)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("check_vma", [False, True])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_ce_grads_match_dense(check_vma, smoothing):
    """The CE backward is hand-written (custom_vjp): plain autodiff
    through the forward's psums under check_vma=False double-counted
    (tp x the dense gradient, measured 8x on this mesh — the psum
    transposes to a psum, so every rank's redundant loss copy
    contributed). Both modes must produce the DENSE gradient exactly."""
    from apex_tpu.parallel import parallel_state
    from apex_tpu.parallel.cross_entropy import vocab_parallel_cross_entropy

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=8
    )
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 6, 64))
    targets = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, 64)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(None, None, "tp"), P()),
        out_specs=(P(), P(None, None, "tp")),
        check_vma=check_vma,
    )
    def run(lg, tg):
        def loss(lg):
            return jnp.mean(vocab_parallel_cross_entropy(
                lg, tg, label_smoothing=smoothing))

        l, g = jax.value_and_grad(loss)(lg)
        return jax.lax.pmean(l, ("dp", "pp", "cp", "tp")) if check_vma \
            else jax.lax.pmean(l, "tp"), g

    def dense_loss(lg):
        lf = lg.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lf, axis=-1)
        ce = lse - jnp.take_along_axis(lf, targets[..., None], -1)[..., 0]
        if smoothing > 0.0:
            ce = (1 - smoothing) * ce + smoothing * (
                lse - jnp.mean(lf, axis=-1))
        return jnp.mean(ce)

    l, g = run(logits, targets)
    dl, dg = jax.value_and_grad(dense_loss)(logits)
    np.testing.assert_allclose(float(l), float(dl), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g), np.asarray(dg),
                               rtol=1e-5, atol=1e-6)
    parallel_state.destroy_model_parallel()


def test_fwd_bwd_pre_post_checked_matches_unchecked():
    """forward_backward_with_pre_post's replicated pre/post grad combine
    must not double-psum under checked vma (the grad transpose already
    summed them over pp; the explicit tied-embedding psum now dispatches
    on the vma type). Loss AND grads must match the unchecked run."""
    from apex_tpu.models.gpt_pipeline import build_gpt_pipeline
    from apex_tpu.parallel import parallel_state
    from apex_tpu.parallel.pipeline import forward_backward_with_pre_post
    from apex_tpu.transformer import TransformerConfig

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size=2,
    )
    vocab, seq, hidden, mb, num_micro = 64, 16, 32, 2, 2
    cfg = TransformerConfig(
        num_layers=2, hidden_size=hidden, num_attention_heads=4,
        vocab_size=vocab, max_position_embeddings=seq,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )
    parts = build_gpt_pipeline(cfg, 2)
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (num_micro, mb, seq), 0, vocab)
    labels = jnp.roll(tokens, -1, axis=2)

    def run(check_vma):
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(P(), P()),
            out_specs=(P(), P(), P()), check_vma=check_vma,
        )
        def step(tokens, labels):
            init_key = jax.random.PRNGKey(0)
            pre = parts.embed.init(init_key, tokens[0])["params"]
            h0 = parts.pre_fn(pre, tokens[0])
            r = jax.lax.axis_index("pp")
            stage = parts.chunk.init(
                jax.random.fold_in(jax.random.fold_in(init_key, 7), r), h0
            )["params"]
            params = {"pre": pre, "stages": stage,
                      "post": parts.init_post(jax.random.fold_in(init_key, 9))}
            loss, _, grads = forward_backward_with_pre_post(
                parts.pre_fn, parts.stage_fn, parts.post_loss_fn, params,
                tokens, labels, axis_name="pp",
            )
            pre_norm = sum(
                jnp.sum(jnp.abs(g))
                for g in jax.tree_util.tree_leaves(grads["pre"])
            )
            post_norm = sum(
                jnp.sum(jnp.abs(g))
                for g in jax.tree_util.tree_leaves(grads["post"])
            )
            def rep(x):
                for ax in ("dp", "pp", "cp", "tp"):
                    try:
                        if ax in jax.typeof(x).vma:
                            x = jax.lax.pmean(x, ax)
                    except AttributeError:
                        break
                return x
            return rep(loss), rep(pre_norm), rep(post_norm)

        return [float(v) for v in step(tokens, labels)]

    got = run(True)
    want = run(False)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    parallel_state.destroy_model_parallel()


def test_scan_carry_fixed_point_promotes_to_body_type():
    """A scan whose body widens the carry's varying axes (adding an
    axis-varying term to a replicated-zeros accumulator) fails checked
    scan's carry typecheck; scan_carry_fixed_point promotes the initial
    carry to the body's vma fixed point and the result matches the
    direct computation."""
    from apex_tpu.parallel import scan_carry_fixed_point

    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    x = jnp.arange(8.0)

    def run(warm):
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P()
        )
        def f(x):
            def body(c, _):
                return c + jnp.sum(x), None  # x is dp-varying; c starts not

            c0 = jnp.zeros(())
            if warm:
                c0 = scan_carry_fixed_point(body, c0, None)
            out, _ = jax.lax.scan(body, c0, None, length=3)
            return jax.lax.pmean(out, "dp")

        return float(f(x))

    with pytest.raises(TypeError, match="carry"):
        run(warm=False)
    np.testing.assert_allclose(run(warm=True), 3 * float(jnp.mean(x)))


def test_vma_cond_mixed_vma_branches_checked():
    """Branches whose outputs vary over different manual-axis sets fail a
    plain lax.cond typecheck under checked shard_map; parallel.vma_cond
    widens both outputs to their vma join INSIDE each branch and keeps
    cond's single-branch evaluation (the former known limitation in
    docs/parallel.md, VERDICT r4 item 6)."""
    from apex_tpu.parallel import vma_cond

    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    n = len(jax.devices())
    x = jnp.arange(float(n))

    def run(cond_impl, flag):
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh, in_specs=(P("dp"), P()),
            out_specs=P("dp"),
        )
        def f(x, flag):
            # true: dp-INVARIANT (psum); false: dp-varying — mixed types
            return cond_impl(
                flag,
                lambda o: jax.lax.psum(o, "dp"),
                lambda o: 2.0 * o,
                x,
            )

        return np.asarray(f(x, flag))

    with pytest.raises((TypeError, ValueError)):
        run(jax.lax.cond, jnp.bool_(True))
    total = float(jnp.sum(x))
    np.testing.assert_allclose(run(vma_cond, jnp.bool_(True)),
                               np.full(n, total))
    np.testing.assert_allclose(run(vma_cond, jnp.bool_(False)),
                               2.0 * np.asarray(x))


def test_amp_optimizer_skip_step_checked():
    """AmpOptimizer's overflow skip-step under checked shard_map: grads
    arrive dp-varying while the master/inner state is replicated — the
    exact mixed-vma cond vma_cond exists for (previously AmpOptimizer
    required check_vma=False meshes)."""
    import optax

    from apex_tpu import amp

    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    n = len(jax.devices())
    params = {"w": jnp.ones((4,), jnp.float32)}

    def run(bad):
        tx = optax.sgd(0.1)
        casted, amp_opt, _ = amp.initialize(params, tx, opt_level="O2")
        state = amp_opt.init(casted)
        scale = float(amp_opt.scaler.scale(state.scaler, jnp.float32(1.0)))
        data = jnp.arange(1.0, float(n) + 1.0)  # per-rank scalar 1..n

        @jax.jit
        @functools.partial(jax.shard_map, mesh=mesh,
                           in_specs=P("dp"), out_specs=(P(), P()))
        def step(d):
            per_rank = jnp.inf if bad else 1.0
            grads = {"w": jnp.full((4,), scale * per_rank * d[0],
                                   jnp.float32)}
            new_params, new_state, info = amp_opt.step(grads, state, casted)
            w = jax.lax.pmean(new_params["w"].astype(jnp.float32), "dp")
            return w, jax.lax.pmean(
                info["found_inf"].astype(jnp.float32), "dp")

        return step(data)

    w_bad, inf_bad = run(bad=True)
    np.testing.assert_allclose(np.asarray(w_bad), np.ones(4))  # skipped
    assert float(inf_bad) == 1.0
    w_ok, inf_ok = run(bad=False)
    # sgd(0.1) on per-rank grad r (r = 1..n), pmean'd over ranks
    expect = 1.0 - 0.1 * float(np.mean(np.arange(1.0, n + 1.0)))
    # O2 re-materializes model params in the model dtype (bf16) — compare
    # at bf16 resolution
    np.testing.assert_allclose(np.asarray(w_ok), np.full(4, expect),
                               rtol=1e-2)
    assert float(inf_ok) == 0.0
