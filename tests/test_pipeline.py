"""Pipeline-parallel tests on the virtual CPU mesh.

Mirrors the reference's pipeline test tier (tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py, test_microbatches.py, test_p2p_comm.py):
deterministic toy stages with per-stage weights, parity of loss AND grads
against the single-device sequential composition, all three schedules, and
the microbatch calculators (constant + rampup).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.pipeline import (
    ConstantNumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatchesCalculator,
    build_model,
    build_num_microbatches_calculator,
    bubble_fraction_1f1b,
    compare_schedules,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    forward_backward_zero_bubble,
    get_forward_backward_func,
    pipeline_forward,
    ring_send_last_to_first,
    schedule_cost,
    send_backward_recv_backward,
    send_forward_recv_forward,
)

HID = 8
MICRO_B = 2


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def loss_fn(y, target):
    return jnp.mean((y - target) ** 2)


def make_stage_params(key, n_stages):
    kw, kb = jax.random.split(key)
    return {
        "w": 0.5 * jax.random.normal(kw, (n_stages, HID, HID), jnp.float32),
        "b": 0.1 * jax.random.normal(kb, (n_stages, HID), jnp.float32),
    }


def sequential_reference(params, mbs, targets, stage_order):
    """Single-device composition in the given global stage order."""

    def total(p):
        def one(mb, tgt):
            h = mb
            for s in stage_order:
                h = stage_fn({"w": p["w"][s], "b": p["b"][s]}, h)
            return loss_fn(h, tgt)

        return jnp.mean(jax.vmap(one)(mbs, targets))

    return jax.value_and_grad(total)(params)


class TestP2P:
    def test_forward_and_backward_shift(self):
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=8
        )

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("pp"), out_specs=(P("pp"), P("pp")),
            check_vma=False,
        )
        def run(x):
            return (
                send_forward_recv_forward(x, "pp"),
                send_backward_recv_backward(x, "pp"),
            )

        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0
        fwd, bwd = run(x)
        np.testing.assert_array_equal(
            fwd.ravel(), [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0]
        )
        np.testing.assert_array_equal(
            bwd.ravel(), [2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 0.0]
        )

    def test_ring_last_to_first(self):
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=8
        )

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("pp"), out_specs=P("pp"),
            check_vma=False,
        )
        def run(x):
            return ring_send_last_to_first(x, "pp")

        out = run(jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0)
        np.testing.assert_array_equal(
            out.ravel(), [8.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]
        )


class TestPipelineSchedules:
    @pytest.mark.parametrize("num_micro", [4, 8, 5])
    def test_1f1b_matches_sequential(self, rng, num_micro):
        pp = 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )

        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), P(), pspec),
            check_vma=False,
        )
        def run(stacked, mbs, targets):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            loss, losses, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, local, mbs, targets, axis_name="pp"
            )
            return loss, losses, jax.tree_util.tree_map(lambda g: g[None], grads)

        loss, losses, grads = run(params, mbs, targets)
        ref_loss, ref_grads = sequential_reference(params, mbs, targets, range(pp))
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(jnp.mean(losses), ref_loss, rtol=1e-5, atol=1e-6)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                grads[k], ref_grads[k], rtol=1e-4, atol=1e-5
            )

    def test_pipeline_forward_last_stage_outputs(self, rng):
        pp, num_micro = 4, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID))

        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(pspec, P()), out_specs=P("pp"),
            check_vma=False,
        )
        def run(stacked, mbs):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            return pipeline_forward(stage_fn, local, mbs, axis_name="pp")[None]

        outs = run(params, mbs)[-1]  # last stage's buffer
        h = mbs
        for s in range(pp):
            h = jax.vmap(lambda x, _s=s: stage_fn(
                {"w": params["w"][_s], "b": params["b"][_s]}, x
            ))(h)
        np.testing.assert_allclose(outs, h, rtol=1e-5, atol=1e-6)

    def test_interleaved_matches_sequential(self, rng):
        pp, vpp, num_micro = 2, 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        n_global = pp * vpp
        params = make_stage_params(rng, n_global)
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID))
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )

        # rank r holds chunks [v*pp + r for v in range(vpp)] (ref chunk-id
        # mapping): arrange (pp, vpp, ...) so axis0 shards over 'pp'
        def to_rank_chunks(a):
            # a: (n_global, ...) in global stage order v*pp + r
            return jnp.stack(
                [jnp.stack([a[v * pp + r] for v in range(vpp)]) for r in range(pp)]
            )

        stacked = {k: to_rank_chunks(v) for k, v in params.items()}
        pspec = {"w": P("pp", None, None, None), "b": P("pp", None, None)}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec),
            check_vma=False,
        )
        def run(stacked, mbs, targets):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            loss, _, grads = forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, local, mbs, targets,
                num_model_chunks=vpp, axis_name="pp",
            )
            return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

        loss, grads = run(stacked, mbs, targets)
        ref_loss, ref_grads = sequential_reference(
            params, mbs, targets, range(n_global)
        )
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for k in ("w", "b"):
            ref_stacked = to_rank_chunks(ref_grads[k])
            np.testing.assert_allclose(
                grads[k], ref_stacked, rtol=1e-4, atol=1e-5
            )

    def test_interleaved_bubble_shrinks_with_v(self, rng):
        """The point of virtual PP (ref fwd_bwd_pipelining_with_
        interleaving.py:27): bubble ticks stay P-1 while useful ticks grow
        to V*M, so the bubble FRACTION shrinks by 1/V. Assert on the
        compiled scan length: exactly V*M + P - 1 ticks of one-chunk work,
        not the V*(M + P - 1) of V sequential full passes."""
        from apex_tpu.parallel.pipeline.schedules import (
            pipeline_forward_interleaved,
        )

        pp, num_micro = 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )

        def scan_lengths(jaxpr):
            out = []
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "scan":
                    out.append(eqn.params["length"])
                for sub in jax.core.jaxprs_in_params(eqn.params):
                    out.extend(scan_lengths(sub))
            return out

        for vpp in (2, 4):
            params = {
                "w": jax.random.normal(rng, (vpp, HID, HID)),
                "b": jnp.zeros((vpp, HID)),
            }
            mbs = jnp.zeros((num_micro, MICRO_B, HID))

            @functools.partial(
                shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                check_vma=False,
            )
            def run(chunks, mbs, _v=vpp):
                return pipeline_forward_interleaved(
                    stage_fn, chunks, mbs, num_model_chunks=_v,
                    axis_name="pp", remat=False,
                )

            lengths = scan_lengths(jax.make_jaxpr(run)(params, mbs))
            assert lengths == [vpp * num_micro + pp - 1]

    @pytest.mark.parametrize("num_micro", [5, 8])
    def test_tick_block_remat_grads_match_1f1b(self, rng, num_micro):
        """tick_block_remat is a pure memory/recompute trade: loss and
        grads must be bit-comparable to the unblocked scan, including when
        the block size does not divide the tick count (padding ticks)."""
        pp = 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )
        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        def make_run(block):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(pspec, P(), P()),
                out_specs=(P(), pspec), check_vma=False,
            )
            def run(stacked, mbs, targets):
                local = jax.tree_util.tree_map(lambda a: a[0], stacked)
                loss, _, grads = forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, local, mbs, targets,
                    axis_name="pp", tick_block_remat=block,
                )
                return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

            return run

        loss0, grads0 = make_run(0)(params, mbs, targets)
        for block in (3, 16):  # non-dividing (pads) and over-long (one block)
            loss_b, grads_b = make_run(block)(params, mbs, targets)
            np.testing.assert_allclose(loss_b, loss0, rtol=1e-6)
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    grads_b[k], grads0[k], rtol=1e-5, atol=1e-7
                )

    def test_tick_block_remat_grads_match_interleaved(self, rng):
        pp, vpp, num_micro = 2, 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = {
            "w": jax.random.normal(rng, (vpp, HID, HID)) * 0.5,
            "b": jnp.zeros((vpp, HID)),
        }
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )

        def make_run(block):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(P(), P(), P()),
                out_specs=(P(), P()), check_vma=False,
            )
            def run(chunks, mbs, targets):
                loss, _, grads = forward_backward_pipelining_with_interleaving(
                    stage_fn, loss_fn, chunks, mbs, targets,
                    num_model_chunks=vpp, axis_name="pp",
                    tick_block_remat=block,
                )
                return loss, grads

            return run

        loss0, grads0 = make_run(0)(params, mbs, targets)
        loss_b, grads_b = make_run(4)(params, mbs, targets)  # T=9 pads to 12
        np.testing.assert_allclose(loss_b, loss0, rtol=1e-6)
        for k in ("w", "b"):
            np.testing.assert_allclose(grads_b[k], grads0[k], rtol=1e-5, atol=1e-7)

    def test_interleaved_requires_divisible_microbatches(self, rng):
        pp, vpp = 2, 2
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = {
            "w": jax.random.normal(rng, (vpp, HID, HID)),
            "b": jnp.zeros((vpp, HID)),
        }
        mbs = jnp.zeros((3, MICRO_B, HID))  # 3 % 2 != 0
        targets = jnp.zeros((3, MICRO_B, HID))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P(), P()), check_vma=False,
        )
        def run(chunks, mbs, targets):
            return forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, chunks, mbs, targets,
                num_model_chunks=vpp, axis_name="pp",
            )

        with pytest.raises(ValueError, match="interleaved schedule requires"):
            run(params, mbs, targets)

    def test_no_pipelining_grad_accumulation(self, rng):
        params = {"w": jax.random.normal(rng, (HID, HID))}
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (4, MICRO_B, HID))

        def fwd(p, mb):
            return jnp.mean((mb @ p["w"]) ** 2)

        loss, losses, grads = forward_backward_no_pipelining(fwd, params, mbs)
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: jnp.mean(jax.vmap(lambda m: fwd(p, m))(mbs))
        )(params)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
        np.testing.assert_allclose(losses, jax.vmap(lambda m: fwd(params, m))(mbs),
                                   rtol=1e-6)
        np.testing.assert_allclose(grads["w"], ref_grads["w"], rtol=1e-5, atol=1e-6)

    def test_pipeline_training_converges(self, rng):
        """End-to-end: a few SGD steps through the 1F1B schedule reduce the
        loss (ref: test_gpt_minimal.py's loss-decrease assertion)."""
        pp, num_micro = 4, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID))
        targets = jnp.tanh(
            jax.random.normal(jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID))
        )
        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec),
            check_vma=False,
        )
        def train_step(stacked, mbs, targets):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            loss, _, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, local, mbs, targets, axis_name="pp"
            )
            new_local = jax.tree_util.tree_map(
                lambda p, g: p - 0.5 * g, local, grads
            )
            return loss, jax.tree_util.tree_map(lambda a: a[None], new_local)

        losses = []
        for _ in range(10):
            loss, params = train_step(params, mbs, targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses


class TestScheduleAlgebra:
    """Hand-counted tick/bubble pins for every registered schedule —
    the predicted half of the overlap proof loop (algebra.py)."""

    def test_no_pipelining_hand_counted(self):
        c = schedule_cost("no_pipelining", 4, 8)
        assert (c.forward_ticks, c.backward_ticks) == (8, 8)
        assert c.span_units == 24 and c.useful_units == 24
        assert c.bubble_units == 0 and c.bubble_fraction == 0.0

    def test_1f1b_hand_counted(self):
        # P=4, M=8: scans of 11 ticks; fwd 1 unit, bwd (B+W fused) 2 ->
        # span 33; useful 3*8 = 24; bubble 9/33 = (P-1)/(M+P-1) = 3/11
        c = schedule_cost("1f1b", 4, 8)
        assert (c.forward_ticks, c.backward_ticks) == (11, 11)
        assert c.span_units == 33 and c.useful_units == 24
        assert c.bubble_units == 9
        assert c.bubble_fraction == pytest.approx(3 / 11)
        assert c.bubble_fraction == pytest.approx(bubble_fraction_1f1b(4, 8))

    def test_interleaved_hand_counted(self):
        # P=2, M=4, V=2: T = 2*4 + 1 = 9 one-chunk ticks per direction;
        # span 27, useful 3*4*2 = 24, bubble 3/27 = (P-1)/(VM+P-1) = 1/9
        c = schedule_cost("interleaved", 2, 4, 2)
        assert (c.forward_ticks, c.backward_ticks) == (9, 9)
        assert c.span_units == 27 and c.useful_units == 24
        assert c.bubble_fraction == pytest.approx(1 / 9)
        with pytest.raises(ValueError, match="interleaved"):
            schedule_cost("interleaved", 2, 3, 2)
        # V=1 is just 1F1B — silently computing its bubble under the
        # interleaved label would mislabel the prediction
        with pytest.raises(ValueError, match="num_model_chunks"):
            schedule_cost("interleaved", 2, 4, 1)

    def test_zero_bubble_hand_counted(self):
        # P=4, M=8: two 11-tick scans + filler max(0, 8 - 6) = 2 ->
        # span 24 == useful 24: ZERO bubble (M >= 2(P-1))
        c = schedule_cost("zero_bubble", 4, 8)
        assert (c.forward_ticks, c.backward_ticks) == (11, 11)
        assert c.filler_ticks == 2
        assert c.span_units == 24 and c.useful_units == 24
        assert c.bubble_fraction == 0.0
        # P=8, M=4 (M < 2(P-1)): span 2*11 = 22, useful 12, bubble 10
        c = schedule_cost("zero_bubble", 8, 4)
        assert c.filler_ticks == 0
        assert c.span_units == 22 and c.useful_units == 12
        assert c.bubble_fraction == pytest.approx(10 / 22)

    @pytest.mark.parametrize("P", [2, 4, 8])
    @pytest.mark.parametrize("M", [1, 2, 4, 8, 16, 32])
    def test_identity_and_zero_bubble_beats_1f1b(self, P, M):
        """span == useful + bubble for every schedule, and the zero-
        bubble fraction is strictly below 1F1B's (P-1)/(M+P-1) — the
        acceptance inequality, over the whole (P, M) grid."""
        for name in ("no_pipelining", "1f1b", "zero_bubble"):
            c = schedule_cost(name, P, M)
            assert c.span_units == c.useful_units + c.bubble_units
            assert 0.0 <= c.bubble_fraction < 1.0
        if M % P == 0:
            for V in (2, 4):
                c = schedule_cost("interleaved", P, M, V)
                assert c.span_units == c.useful_units + c.bubble_units
                assert c.bubble_fraction == pytest.approx(
                    (P - 1) / (V * M + P - 1)
                )
        zb = schedule_cost("zero_bubble", P, M).bubble_fraction
        assert zb < bubble_fraction_1f1b(P, M)

    def test_compare_sorted_and_skips_invalid_interleaved(self):
        costs = compare_schedules(4, 8, 2)
        assert [c.bubble_fraction for c in costs] == sorted(
            c.bubble_fraction for c in costs
        )
        assert {c.name for c in costs} == {
            "no_pipelining", "1f1b", "interleaved", "zero_bubble"
        }
        # M=5 % P=4 != 0: the interleaved row drops out instead of lying
        assert {c.name for c in compare_schedules(4, 5, 2)} == {
            "no_pipelining", "1f1b", "zero_bubble"
        }

    def test_errors(self):
        with pytest.raises(KeyError):
            schedule_cost("nope", 2, 2)
        with pytest.raises(ValueError):
            schedule_cost("1f1b", 0, 2)


class TestZeroBubble:
    """The B/W-split schedule: gradient parity with the fused jax.grad
    path, and the closed transpose blind spot (backward edges ledgered)."""

    @pytest.mark.parametrize("num_micro", [4, 5, 8])
    def test_matches_1f1b_bitwise(self, rng, num_micro):
        """Split-backward loss AND grads are BITWISE equal to the fused
        1F1B path on the toy stage — the B/W split is a schedule change,
        not a numerics change."""
        pp = 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )
        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        def make(fb):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(pspec, P(), P()),
                out_specs=(P(), P(), pspec), check_vma=False,
            )
            def run(stacked, mbs, targets):
                local = jax.tree_util.tree_map(lambda a: a[0], stacked)
                loss, losses, grads = fb(
                    stage_fn, loss_fn, local, mbs, targets, axis_name="pp"
                )
                return loss, losses, jax.tree_util.tree_map(
                    lambda g: g[None], grads
                )

            return run

        l1, ls1, g1 = make(forward_backward_pipelining_without_interleaving)(
            params, mbs, targets
        )
        lz, lsz, gz = make(forward_backward_zero_bubble)(params, mbs, targets)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(lz))
        np.testing.assert_array_equal(np.asarray(ls1), np.asarray(lsz))
        for k in ("w", "b"):
            np.testing.assert_array_equal(np.asarray(g1[k]), np.asarray(gz[k]))

    def test_checked_vma_matches_unchecked(self, rng):
        """Both shard_map modes produce the same zero-bubble grads (the
        carry fixed-point typing — _varying_zeros on dy AND the grad
        accumulator — holds under checked vma)."""
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()), ("pp",))
        M = 8

        def sfn(p, x):
            return jnp.tanh(x @ p)

        def lfn(x, t):
            return jnp.mean((x - t) ** 2)

        def run(check_vma):
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(P("pp"), P(), P()),
                out_specs=(P(), P("pp")), check_vma=check_vma,
            )
            def f(stacked, xs, ts):
                loss, _, grads = forward_backward_zero_bubble(
                    sfn, lfn, stacked[0], xs, ts, axis_name="pp"
                )
                return jax.lax.pmean(loss, "pp"), grads[None]

            return f

        stacked = 0.5 * jax.random.normal(rng, (8, HID, HID))
        xs = jax.random.normal(jax.random.fold_in(rng, 1), (M, 2, HID))
        ts = jax.random.normal(jax.random.fold_in(rng, 2), (M, 2, HID))
        lu, gu = run(False)(stacked, xs, ts)
        lc, gc = run(True)(stacked, xs, ts)
        np.testing.assert_array_equal(np.asarray(lu), np.asarray(lc))
        np.testing.assert_array_equal(np.asarray(gu), np.asarray(gc))

    def test_backward_edges_are_ledger_predicted(self, rng):
        """The closed blind spot: the fused path's ledger sees only the
        forward ppermutes (transpose edges are invisible); zero-bubble
        predicts BOTH directions — 2 ppermute entries, each weighted by
        the full T = M + P - 1 tick count."""
        from apex_tpu.monitor.xray import ledger as xlax

        pp, M = 4, 8
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jnp.zeros((M, MICRO_B, HID))
        tgts = jnp.zeros((M, MICRO_B, HID))
        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        def make(fb):
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(pspec, P(), P()),
                out_specs=(P(), P(), pspec), check_vma=False,
            )
            def run(stacked, mbs, targets):
                local = jax.tree_util.tree_map(lambda a: a[0], stacked)
                loss, losses, grads = fb(
                    stage_fn, loss_fn, local, mbs, targets, axis_name="pp"
                )
                return loss, losses, jax.tree_util.tree_map(
                    lambda g: g[None], grads
                )

            return run

        T = M + pp - 1
        led = xlax.predict_comms(
            make(forward_backward_zero_bubble), params, mbs, tgts
        )
        perms = led.filter(op="ppermute", axis="pp")
        assert sorted(e.count for e in perms) == [T, T]
        led_1f1b = xlax.predict_comms(
            make(forward_backward_pipelining_without_interleaving),
            params, mbs, tgts,
        )
        # the fused path predicts only the forward scan's edges
        assert [e.count for e in led_1f1b.filter(op="ppermute", axis="pp")] \
            == [T]

    def test_dispatcher_zero_bubble(self):
        assert (
            get_forward_backward_func(None, 4, zero_bubble=True)
            is forward_backward_zero_bubble
        )
        assert (
            get_forward_backward_func(None, 1, zero_bubble=True)
            is forward_backward_no_pipelining
        )
        with pytest.raises(ValueError, match="zero_bubble"):
            get_forward_backward_func(2, 4, zero_bubble=True)


class TestDispatcher:
    def test_get_forward_backward_func(self):
        assert (
            get_forward_backward_func(None, 1) is forward_backward_no_pipelining
        )
        assert (
            get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving
        )
        f = get_forward_backward_func(2, 4)
        assert f.func is forward_backward_pipelining_with_interleaving
        assert f.keywords == {"num_model_chunks": 2}
        with pytest.raises(ValueError):
            get_forward_backward_func(2, 1)


class TestBuildModel:
    def test_pre_post_flags(self):
        def provider(pre_process, post_process):
            return (pre_process, post_process)

        # plain PP=4: stage 0 pre, stage 3 post (ref common.py:83-108)
        assert build_model(provider, 0, 4) == [(True, False)]
        assert build_model(provider, 3, 4) == [(False, True)]
        assert build_model(provider, 1, 4) == [(False, False)]
        # virtual PP=2 on PP=2: rank0 chunk0 is global stage 0 (pre),
        # rank1 chunk1 is global stage 3 (post)
        assert build_model(provider, 0, 2, 2) == [(True, False), (False, False)]
        assert build_model(provider, 1, 2, 2) == [(False, False), (False, True)]


class TestMicrobatchCalculators:
    def test_constant(self):
        c = ConstantNumMicroBatchesCalculator(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=2
        )
        assert c.get() == 8
        assert c.get_current_global_batch_size() == 32
        c.update(10_000, True)  # no-op
        assert c.get() == 8
        with pytest.raises(ValueError):
            ConstantNumMicroBatchesCalculator(30, 2, 4)

    def test_rampup(self):
        # start 8, +8 per increment, over 160 samples to reach 32:
        # 3 increments, one every 160/3 samples (ref microbatches.py:112)
        c = RampupBatchsizeNumMicroBatchesCalculator(
            start_batch_size=8,
            batch_size_increment=8,
            ramup_samples=160,
            global_batch_size=32,
            micro_batch_size=2,
            data_parallel_size=2,
        )
        assert c.get_current_global_batch_size() == 8
        assert c.get() == 2
        c.update(int(160 / 3) + 1, True)
        assert c.get_current_global_batch_size() == 16
        c.update(161, True)
        assert c.get_current_global_batch_size() == 32
        assert c.get() == 8

    def test_build_dispatch(self):
        c = build_num_microbatches_calculator(0, None, 16, 2, 1)
        assert isinstance(c, ConstantNumMicroBatchesCalculator)
        c = build_num_microbatches_calculator(0, [8, 8, 100], 16, 2, 1)
        assert isinstance(c, RampupBatchsizeNumMicroBatchesCalculator)
        with pytest.raises(ValueError):
            build_num_microbatches_calculator(0, [8, 8], 16, 2, 1)
