"""Pipeline-parallel tests on the virtual CPU mesh.

Mirrors the reference's pipeline test tier (tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py, test_microbatches.py, test_p2p_comm.py):
deterministic toy stages with per-stage weights, parity of loss AND grads
against the single-device sequential composition, all three schedules, and
the microbatch calculators (constant + rampup).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.pipeline import (
    ConstantNumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatchesCalculator,
    build_model,
    build_num_microbatches_calculator,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipeline_forward,
    ring_send_last_to_first,
    send_backward_recv_backward,
    send_forward_recv_forward,
)

HID = 8
MICRO_B = 2


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def loss_fn(y, target):
    return jnp.mean((y - target) ** 2)


def make_stage_params(key, n_stages):
    kw, kb = jax.random.split(key)
    return {
        "w": 0.5 * jax.random.normal(kw, (n_stages, HID, HID), jnp.float32),
        "b": 0.1 * jax.random.normal(kb, (n_stages, HID), jnp.float32),
    }


def sequential_reference(params, mbs, targets, stage_order):
    """Single-device composition in the given global stage order."""

    def total(p):
        def one(mb, tgt):
            h = mb
            for s in stage_order:
                h = stage_fn({"w": p["w"][s], "b": p["b"][s]}, h)
            return loss_fn(h, tgt)

        return jnp.mean(jax.vmap(one)(mbs, targets))

    return jax.value_and_grad(total)(params)


class TestP2P:
    def test_forward_and_backward_shift(self):
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=8
        )

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("pp"), out_specs=(P("pp"), P("pp")),
            check_vma=False,
        )
        def run(x):
            return (
                send_forward_recv_forward(x, "pp"),
                send_backward_recv_backward(x, "pp"),
            )

        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0
        fwd, bwd = run(x)
        np.testing.assert_array_equal(
            fwd.ravel(), [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0]
        )
        np.testing.assert_array_equal(
            bwd.ravel(), [2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 0.0]
        )

    def test_ring_last_to_first(self):
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=8
        )

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("pp"), out_specs=P("pp"),
            check_vma=False,
        )
        def run(x):
            return ring_send_last_to_first(x, "pp")

        out = run(jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0)
        np.testing.assert_array_equal(
            out.ravel(), [8.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]
        )


class TestPipelineSchedules:
    @pytest.mark.parametrize("num_micro", [4, 8, 5])
    def test_1f1b_matches_sequential(self, rng, num_micro):
        pp = 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )

        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), P(), pspec),
            check_vma=False,
        )
        def run(stacked, mbs, targets):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            loss, losses, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, local, mbs, targets, axis_name="pp"
            )
            return loss, losses, jax.tree_util.tree_map(lambda g: g[None], grads)

        loss, losses, grads = run(params, mbs, targets)
        ref_loss, ref_grads = sequential_reference(params, mbs, targets, range(pp))
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(jnp.mean(losses), ref_loss, rtol=1e-5, atol=1e-6)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                grads[k], ref_grads[k], rtol=1e-4, atol=1e-5
            )

    def test_pipeline_forward_last_stage_outputs(self, rng):
        pp, num_micro = 4, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID))

        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(pspec, P()), out_specs=P("pp"),
            check_vma=False,
        )
        def run(stacked, mbs):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            return pipeline_forward(stage_fn, local, mbs, axis_name="pp")[None]

        outs = run(params, mbs)[-1]  # last stage's buffer
        h = mbs
        for s in range(pp):
            h = jax.vmap(lambda x, _s=s: stage_fn(
                {"w": params["w"][_s], "b": params["b"][_s]}, x
            ))(h)
        np.testing.assert_allclose(outs, h, rtol=1e-5, atol=1e-6)

    def test_interleaved_matches_sequential(self, rng):
        pp, vpp, num_micro = 2, 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        n_global = pp * vpp
        params = make_stage_params(rng, n_global)
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID))
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )

        # rank r holds chunks [v*pp + r for v in range(vpp)] (ref chunk-id
        # mapping): arrange (pp, vpp, ...) so axis0 shards over 'pp'
        def to_rank_chunks(a):
            # a: (n_global, ...) in global stage order v*pp + r
            return jnp.stack(
                [jnp.stack([a[v * pp + r] for v in range(vpp)]) for r in range(pp)]
            )

        stacked = {k: to_rank_chunks(v) for k, v in params.items()}
        pspec = {"w": P("pp", None, None, None), "b": P("pp", None, None)}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec),
            check_vma=False,
        )
        def run(stacked, mbs, targets):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            loss, _, grads = forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, local, mbs, targets,
                num_model_chunks=vpp, axis_name="pp",
            )
            return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

        loss, grads = run(stacked, mbs, targets)
        ref_loss, ref_grads = sequential_reference(
            params, mbs, targets, range(n_global)
        )
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        for k in ("w", "b"):
            ref_stacked = to_rank_chunks(ref_grads[k])
            np.testing.assert_allclose(
                grads[k], ref_stacked, rtol=1e-4, atol=1e-5
            )

    def test_interleaved_bubble_shrinks_with_v(self, rng):
        """The point of virtual PP (ref fwd_bwd_pipelining_with_
        interleaving.py:27): bubble ticks stay P-1 while useful ticks grow
        to V*M, so the bubble FRACTION shrinks by 1/V. Assert on the
        compiled scan length: exactly V*M + P - 1 ticks of one-chunk work,
        not the V*(M + P - 1) of V sequential full passes."""
        from apex_tpu.parallel.pipeline.schedules import (
            pipeline_forward_interleaved,
        )

        pp, num_micro = 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )

        def scan_lengths(jaxpr):
            out = []
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "scan":
                    out.append(eqn.params["length"])
                for sub in jax.core.jaxprs_in_params(eqn.params):
                    out.extend(scan_lengths(sub))
            return out

        for vpp in (2, 4):
            params = {
                "w": jax.random.normal(rng, (vpp, HID, HID)),
                "b": jnp.zeros((vpp, HID)),
            }
            mbs = jnp.zeros((num_micro, MICRO_B, HID))

            @functools.partial(
                shard_map, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
                check_vma=False,
            )
            def run(chunks, mbs, _v=vpp):
                return pipeline_forward_interleaved(
                    stage_fn, chunks, mbs, num_model_chunks=_v,
                    axis_name="pp", remat=False,
                )

            lengths = scan_lengths(jax.make_jaxpr(run)(params, mbs))
            assert lengths == [vpp * num_micro + pp - 1]

    @pytest.mark.parametrize("num_micro", [5, 8])
    def test_tick_block_remat_grads_match_1f1b(self, rng, num_micro):
        """tick_block_remat is a pure memory/recompute trade: loss and
        grads must be bit-comparable to the unblocked scan, including when
        the block size does not divide the tick count (padding ticks)."""
        pp = 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )
        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        def make_run(block):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(pspec, P(), P()),
                out_specs=(P(), pspec), check_vma=False,
            )
            def run(stacked, mbs, targets):
                local = jax.tree_util.tree_map(lambda a: a[0], stacked)
                loss, _, grads = forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, local, mbs, targets,
                    axis_name="pp", tick_block_remat=block,
                )
                return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

            return run

        loss0, grads0 = make_run(0)(params, mbs, targets)
        for block in (3, 16):  # non-dividing (pads) and over-long (one block)
            loss_b, grads_b = make_run(block)(params, mbs, targets)
            np.testing.assert_allclose(loss_b, loss0, rtol=1e-6)
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    grads_b[k], grads0[k], rtol=1e-5, atol=1e-7
                )

    def test_tick_block_remat_grads_match_interleaved(self, rng):
        pp, vpp, num_micro = 2, 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = {
            "w": jax.random.normal(rng, (vpp, HID, HID)) * 0.5,
            "b": jnp.zeros((vpp, HID)),
        }
        mbs = jax.random.normal(
            jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID)
        )
        targets = jax.random.normal(
            jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID)
        )

        def make_run(block):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(P(), P(), P()),
                out_specs=(P(), P()), check_vma=False,
            )
            def run(chunks, mbs, targets):
                loss, _, grads = forward_backward_pipelining_with_interleaving(
                    stage_fn, loss_fn, chunks, mbs, targets,
                    num_model_chunks=vpp, axis_name="pp",
                    tick_block_remat=block,
                )
                return loss, grads

            return run

        loss0, grads0 = make_run(0)(params, mbs, targets)
        loss_b, grads_b = make_run(4)(params, mbs, targets)  # T=9 pads to 12
        np.testing.assert_allclose(loss_b, loss0, rtol=1e-6)
        for k in ("w", "b"):
            np.testing.assert_allclose(grads_b[k], grads0[k], rtol=1e-5, atol=1e-7)

    def test_interleaved_requires_divisible_microbatches(self, rng):
        pp, vpp = 2, 2
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = {
            "w": jax.random.normal(rng, (vpp, HID, HID)),
            "b": jnp.zeros((vpp, HID)),
        }
        mbs = jnp.zeros((3, MICRO_B, HID))  # 3 % 2 != 0
        targets = jnp.zeros((3, MICRO_B, HID))

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P(), P()), check_vma=False,
        )
        def run(chunks, mbs, targets):
            return forward_backward_pipelining_with_interleaving(
                stage_fn, loss_fn, chunks, mbs, targets,
                num_model_chunks=vpp, axis_name="pp",
            )

        with pytest.raises(ValueError, match="interleaved schedule requires"):
            run(params, mbs, targets)

    def test_no_pipelining_grad_accumulation(self, rng):
        params = {"w": jax.random.normal(rng, (HID, HID))}
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (4, MICRO_B, HID))

        def fwd(p, mb):
            return jnp.mean((mb @ p["w"]) ** 2)

        loss, losses, grads = forward_backward_no_pipelining(fwd, params, mbs)
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: jnp.mean(jax.vmap(lambda m: fwd(p, m))(mbs))
        )(params)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
        np.testing.assert_allclose(losses, jax.vmap(lambda m: fwd(params, m))(mbs),
                                   rtol=1e-6)
        np.testing.assert_allclose(grads["w"], ref_grads["w"], rtol=1e-5, atol=1e-6)

    def test_pipeline_training_converges(self, rng):
        """End-to-end: a few SGD steps through the 1F1B schedule reduce the
        loss (ref: test_gpt_minimal.py's loss-decrease assertion)."""
        pp, num_micro = 4, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        params = make_stage_params(rng, pp)
        mbs = jax.random.normal(jax.random.fold_in(rng, 1), (num_micro, MICRO_B, HID))
        targets = jnp.tanh(
            jax.random.normal(jax.random.fold_in(rng, 2), (num_micro, MICRO_B, HID))
        )
        pspec = {"w": P("pp", None, None), "b": P("pp", None)}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec),
            check_vma=False,
        )
        def train_step(stacked, mbs, targets):
            local = jax.tree_util.tree_map(lambda a: a[0], stacked)
            loss, _, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, local, mbs, targets, axis_name="pp"
            )
            new_local = jax.tree_util.tree_map(
                lambda p, g: p - 0.5 * g, local, grads
            )
            return loss, jax.tree_util.tree_map(lambda a: a[None], new_local)

        losses = []
        for _ in range(10):
            loss, params = train_step(params, mbs, targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses


class TestDispatcher:
    def test_get_forward_backward_func(self):
        assert (
            get_forward_backward_func(None, 1) is forward_backward_no_pipelining
        )
        assert (
            get_forward_backward_func(None, 4)
            is forward_backward_pipelining_without_interleaving
        )
        f = get_forward_backward_func(2, 4)
        assert f.func is forward_backward_pipelining_with_interleaving
        assert f.keywords == {"num_model_chunks": 2}
        with pytest.raises(ValueError):
            get_forward_backward_func(2, 1)


class TestBuildModel:
    def test_pre_post_flags(self):
        def provider(pre_process, post_process):
            return (pre_process, post_process)

        # plain PP=4: stage 0 pre, stage 3 post (ref common.py:83-108)
        assert build_model(provider, 0, 4) == [(True, False)]
        assert build_model(provider, 3, 4) == [(False, True)]
        assert build_model(provider, 1, 4) == [(False, False)]
        # virtual PP=2 on PP=2: rank0 chunk0 is global stage 0 (pre),
        # rank1 chunk1 is global stage 3 (post)
        assert build_model(provider, 0, 2, 2) == [(True, False), (False, False)]
        assert build_model(provider, 1, 2, 2) == [(False, False), (False, True)]


class TestMicrobatchCalculators:
    def test_constant(self):
        c = ConstantNumMicroBatchesCalculator(
            global_batch_size=32, micro_batch_size=2, data_parallel_size=2
        )
        assert c.get() == 8
        assert c.get_current_global_batch_size() == 32
        c.update(10_000, True)  # no-op
        assert c.get() == 8
        with pytest.raises(ValueError):
            ConstantNumMicroBatchesCalculator(30, 2, 4)

    def test_rampup(self):
        # start 8, +8 per increment, over 160 samples to reach 32:
        # 3 increments, one every 160/3 samples (ref microbatches.py:112)
        c = RampupBatchsizeNumMicroBatchesCalculator(
            start_batch_size=8,
            batch_size_increment=8,
            ramup_samples=160,
            global_batch_size=32,
            micro_batch_size=2,
            data_parallel_size=2,
        )
        assert c.get_current_global_batch_size() == 8
        assert c.get() == 2
        c.update(int(160 / 3) + 1, True)
        assert c.get_current_global_batch_size() == 16
        c.update(161, True)
        assert c.get_current_global_batch_size() == 32
        assert c.get() == 8

    def test_build_dispatch(self):
        c = build_num_microbatches_calculator(0, None, 16, 2, 1)
        assert isinstance(c, ConstantNumMicroBatchesCalculator)
        c = build_num_microbatches_calculator(0, [8, 8, 100], 16, 2, 1)
        assert isinstance(c, RampupBatchsizeNumMicroBatchesCalculator)
        with pytest.raises(ValueError):
            build_num_microbatches_calculator(0, [8, 8], 16, 2, 1)
