"""Auto-remediation (apex_tpu.resilience.remediation).

Fast tier: the jax-free halves — the closed policy machine, persisted
state + checkpoint quarantine, the controller with a STUBBED canary
(including the LiveFleetMonitor -> controller hand-off: the seeded
straggler flag a clean canary replay clears, the zero-MAD outlier, the
<3-host refusal), the exit-code supervisor loop, and the campaign's
fault drawing / bipartite invariant matching. Slow tier: the
exit-nonzero selftest gate, the >=20-sequence seeded campaign, and the
ACCEPTANCE bitflip+hang+SIGTERM drill against the real GPT target.
"""

import json
import os

import pytest

from apex_tpu.resilience.exit_codes import (
    ExitCode,
    RESTARTABLE_EXIT_CODES,
)


# ---------------------------------------------------------------------------
# policy machine (jax-free)


class TestPolicy:
    def test_advance_registered_edges(self):
        from apex_tpu.resilience.remediation import advance

        assert advance("detected", "verifying") == "verifying"
        assert advance("verifying", "cleared") == "cleared"
        assert advance("quarantined", "probation") == "probation"
        assert advance("probation", "readmitted") == "readmitted"

    def test_advance_refuses_unregistered(self):
        from apex_tpu.resilience.remediation import advance

        with pytest.raises(ValueError, match="unregistered"):
            advance("detected", "readmitted")
        with pytest.raises(ValueError, match="unknown"):
            advance("nonsense", "cleared")

    def test_terminal_states_absorb(self):
        from apex_tpu.resilience.remediation import TERMINAL_STATES, advance

        for state in TERMINAL_STATES:
            with pytest.raises(ValueError):
                advance(state, "detected")

    def test_policy_validation(self):
        from apex_tpu.resilience.remediation import RemediationPolicy

        with pytest.raises(ValueError, match="probation_steps"):
            RemediationPolicy(probation_steps=0)
        with pytest.raises(ValueError, match="quarantine_fraction"):
            RemediationPolicy(quarantine_fraction=1.0)
        with pytest.raises(ValueError, match="unknown case kind"):
            RemediationPolicy(responses={"warp_core": "verify"})
        with pytest.raises(ValueError, match="unregistered response"):
            RemediationPolicy(responses={"straggler": "improvise"})

    def test_response_table_defaults(self):
        from apex_tpu.resilience.remediation import RemediationPolicy

        p = RemediationPolicy(responses={"straggler": "observe"})
        assert p.response_for("straggler") == "observe"
        # kinds the custom table omits fall back to the default table
        assert p.response_for("sdc") == "quarantine"
        assert p.response_for("halt") == "escalate"


# ---------------------------------------------------------------------------
# exit-code taxonomy (satellite: the ONE home)


class TestExitCodes:
    def test_taxonomy_pins(self):
        assert int(ExitCode.OK) == 0
        assert int(ExitCode.FAILURE) == 1
        assert int(ExitCode.REPLAY_DIVERGENCE) == 2
        assert int(ExitCode.INCIDENT) == 43
        assert int(ExitCode.REMEDIATION_RESTART) == 44
        assert int(ExitCode.REMEDIATION_HALT) == 45
        assert RESTARTABLE_EXIT_CODES == {
            ExitCode.INCIDENT, ExitCode.REMEDIATION_RESTART,
        }

    def test_responder_imports_the_taxonomy(self):
        # the historical import surface must alias the one home, not
        # restate the magic number
        from apex_tpu.resilience.health.responder import INCIDENT_EXIT_CODE

        assert INCIDENT_EXIT_CODE == int(ExitCode.INCIDENT)


# ---------------------------------------------------------------------------
# persisted state + checkpoint quarantine (jax-free)


class TestState:
    def test_save_load_round_trip(self, tmp_path):
        from apex_tpu.resilience.remediation import RemediationState

        s = RemediationState.load(str(tmp_path))
        s.excluded = [4, 5, 6, 7]
        s.restarts = 2
        s.cases = [{"id": "case-1", "kind": "sdc", "state": "quarantined"}]
        s.save()
        s2 = RemediationState.load(str(tmp_path))
        assert s2.excluded == [4, 5, 6, 7]
        assert s2.restarts == 2
        assert s2.cases[0]["kind"] == "sdc"

    def test_case_ids_unique_across_incarnations(self, tmp_path):
        from apex_tpu.resilience.remediation import RemediationState

        s = RemediationState.load(str(tmp_path))
        a = s.next_case_id()
        s.save()
        s2 = RemediationState.load(str(tmp_path))
        assert s2.next_case_id() != a

    def test_torn_state_file_is_loud(self, tmp_path):
        from apex_tpu.resilience.remediation import (
            RemediationState, state_path,
        )

        with open(state_path(str(tmp_path)), "w") as f:
            f.write('{"excluded": [4')
        with pytest.raises(json.JSONDecodeError):
            RemediationState.load(str(tmp_path))

    def test_device_count_ignores_out_of_world_ordinals(self):
        from apex_tpu.resilience.remediation import RemediationState

        s = RemediationState(excluded=[2, 3, 12])
        assert s.device_count(8) == 6
        assert s.device_count(2) == 2

    def test_quarantine_checkpoints_moves_and_preserves(self, tmp_path):
        from apex_tpu.resilience.remediation import quarantine_checkpoints
        from apex_tpu.utils.checkpoint import finalized_steps

        for step in (2, 4, 6):
            d = tmp_path / f"step_{step}"
            d.mkdir()
            (d / "payload.bin").write_bytes(b"x")
        moved = quarantine_checkpoints(str(tmp_path), 2, "case-9")
        assert moved == [4, 6]
        # the restore walk falls back to the clean anchor automatically
        assert finalized_steps(str(tmp_path)) == [2]
        # rename, not delete: the corrupt bytes stay for forensics
        kept = tmp_path / "quarantined-case-9" / "step_4" / "payload.bin"
        assert kept.read_bytes() == b"x"


# ---------------------------------------------------------------------------
# the controller with a stubbed canary (jax-free)


def _stub_canary_clean():
    return {"ok": True, "audited": [[0, 2]],
            "evidence": {"kind": "canary", "audited": [[0, 2]]}}


def _stub_canary_confirm():
    return {"ok": False, "clean_anchor": 2, "dirty_anchor": 4,
            "evidence": {"kind": "canary", "clean_anchor": 2,
                         "first_divergent_step": 3,
                         "leaves": ["['blocks'][0]['w']"]}}


def _straggler_record(step=6, host=2):
    from apex_tpu.monitor.router import make_record

    return make_record("fleet", step, check="straggler", flagged_host=host,
                       median_step_s=9.9, z=11.0)


class TestController:
    def _controller(self, tmp_path=None, canary=None, policy=None,
                    router=None, world=8):
        from apex_tpu.resilience.remediation import (
            RemediationController, RemediationPolicy,
        )

        return RemediationController(
            policy=policy or RemediationPolicy(),
            router=router,
            save_dir=str(tmp_path) if tmp_path is not None else None,
            world_devices=world,
            canary_fn=canary,
        )

    def test_straggler_cleared_by_clean_canary(self):
        """The false-positive pin: a straggler flag whose canary replay
        clears must produce a verdict="cleared" record and NO restart."""
        ctrl = self._controller(canary=_stub_canary_clean)
        case = ctrl.observe(_straggler_record())
        assert case is not None and case["kind"] == "straggler"
        decision = ctrl.process(6)
        assert decision is None                 # zero restarts
        assert ctrl.state.restarts == 0
        assert not ctrl.open_cases and not ctrl.state.excluded
        terminal = [r for r in ctrl.records if r.get("terminal")]
        assert len(terminal) == 1
        assert terminal[0]["verdict"] == "cleared"
        assert terminal[0]["finding"] == "straggler"
        assert terminal[0]["suspect"] == 2
        # the triggering detector record rode along as evidence
        assert terminal[0]["evidence"][0]["check"] == "straggler"

    def test_confirmed_canary_quarantines(self, tmp_path):
        from apex_tpu.resilience.remediation import RemediationState

        ctrl = self._controller(tmp_path, canary=_stub_canary_confirm)
        ctrl.observe(_straggler_record())
        decision = ctrl.process(6)
        assert decision is not None
        assert decision.action == "restart"
        assert decision.exit_code == int(ExitCode.REMEDIATION_RESTART)
        assert decision.device_count == 4       # 8 -> 4, the upper half
        assert decision.restore_step == 2       # the canary's clean anchor
        # the plan survives the process: the next incarnation reads it
        persisted = RemediationState.load(str(tmp_path))
        assert persisted.excluded == [4, 5, 6, 7]
        assert persisted.restarts == 1
        assert persisted.cases and persisted.cases[0]["state"] == "quarantined"
        quarantine = [r for r in ctrl.records
                      if r.get("action") == "quarantine"]
        assert quarantine[0]["excluded"] == [4, 5, 6, 7]
        # the confirming verify record is in the SAME case's trail (what
        # the campaign's false-positive invariant checks for)
        verify = [r for r in ctrl.records if r.get("action") == "verify"]
        assert verify and verify[0]["verdict"] == "confirmed"
        assert verify[0]["case"] == quarantine[0]["case"]

    def test_probation_readmits_after_clean_steps(self, tmp_path):
        from apex_tpu.resilience.remediation import (
            RemediationPolicy, RemediationState,
        )

        policy = RemediationPolicy(probation_steps=2)
        ctrl = self._controller(tmp_path, canary=_stub_canary_confirm,
                                policy=policy)
        ctrl.observe(_straggler_record())
        assert ctrl.process(6) is not None      # the quarantine restart
        # --- the reduced incarnation ---
        ctrl2 = self._controller(tmp_path, policy=policy)
        adopted = ctrl2.adopt_pending(7)
        assert [c["state"] for c in adopted] == ["probation"]
        ctrl2.on_clean_step(7)
        assert ctrl2.poll() is None             # probation not served yet
        ctrl2.on_clean_step(8)
        decision = ctrl2.poll()
        assert decision is not None and decision.action == "restart"
        assert decision.device_count == 8       # readmit 4 -> 8
        assert RemediationState.load(str(tmp_path)).excluded == []
        terminal = [r for r in ctrl2.records if r.get("terminal")]
        assert terminal and terminal[0]["verdict"] == "readmitted"

    def test_no_canary_demotes_verify_to_observe(self):
        from apex_tpu.resilience.remediation import RemediationPolicy

        ctrl = self._controller(
            canary=None, policy=RemediationPolicy(clean_steps_to_close=1),
        )
        ctrl.observe(_straggler_record())
        assert ctrl.process(6) is None
        assert [c["state"] for c in ctrl.open_cases] == ["observing"]
        ctrl.on_clean_step(7)
        terminal = [r for r in ctrl.records if r.get("terminal")]
        assert terminal and terminal[0]["verdict"] == "recovered"

    def test_raising_canary_demotes_not_quarantines(self):
        def boom():
            raise RuntimeError("journal unreadable")

        ctrl = self._controller(canary=boom)
        ctrl.observe(_straggler_record())
        assert ctrl.process(6) is None          # no restart on a broken canary
        assert [c["state"] for c in ctrl.open_cases] == ["observing"]
        assert not ctrl.state.excluded

    def test_skipped_canary_is_not_a_clearance(self):
        """A canary with nothing sound to re-execute must not close the
        case "cleared" — the vacuous pass the machine exists to refuse."""
        ctrl = self._controller(
            canary=lambda: {"ok": True, "skipped": True, "reason": "empty"},
        )
        ctrl.observe(_straggler_record())
        assert ctrl.process(6) is None
        assert [c["state"] for c in ctrl.open_cases] == ["observing"]
        assert not any(r.get("verdict") == "cleared" for r in ctrl.records)

    def test_repeat_flags_attach_not_fan_out(self):
        ctrl = self._controller(canary=None)
        for step in range(10):
            ctrl.observe(_straggler_record(step=step))
        assert len(ctrl.open_cases) == 1
        case = ctrl.open_cases[0]
        assert case["n_evidence"] == 10
        assert len(case["evidence"]) <= 6       # capped verbatim, all counted
        # a DIFFERENT suspect is a different case
        ctrl.observe(_straggler_record(step=10, host=5))
        assert len(ctrl.open_cases) == 2

    def test_restart_budget_escalates_to_halt(self, tmp_path):
        from apex_tpu.resilience.remediation import RemediationPolicy

        ctrl = self._controller(
            tmp_path, canary=_stub_canary_confirm,
            policy=RemediationPolicy(max_restarts=0),
        )
        ctrl.observe(_straggler_record())
        decision = ctrl.process(6)
        assert decision is not None and decision.action == "halt"
        assert decision.exit_code == int(ExitCode.REMEDIATION_HALT)
        terminal = [r for r in ctrl.records if r.get("terminal")]
        assert terminal and terminal[0]["verdict"] == "halted"

    def test_second_quarantine_shrinks_the_remaining_topology(
            self, tmp_path):
        """A second confirmed corruption after an earlier quarantine
        must exclude devices from the REMAINING ordinals (8->4->2), not
        re-exclude the same upper half and relaunch the identical
        topology while claiming action was taken."""
        from apex_tpu.resilience.remediation import (
            RemediationPolicy, RemediationState,
        )

        policy = RemediationPolicy(probation_steps=2, max_restarts=4)
        ctrl = self._controller(tmp_path, canary=_stub_canary_confirm,
                                policy=policy)
        ctrl.observe(_straggler_record())
        first = ctrl.process(6)
        assert first.device_count == 4
        # --- the reduced incarnation confirms ANOTHER corruption ---
        ctrl2 = self._controller(tmp_path, canary=_stub_canary_confirm,
                                 policy=policy)
        ctrl2.adopt_pending(7)
        ctrl2.observe(_straggler_record(step=8, host=1))
        second = ctrl2.process(8)
        assert second is not None and second.action == "restart"
        assert second.device_count == 2          # 4 -> 2, NOT 4 again
        assert RemediationState.load(str(tmp_path)).excluded == [2, 3, 4,
                                                                 5, 6, 7]

    def test_overlapping_readmit_lifts_only_its_own_devices(self, tmp_path):
        """Two quarantine cases in probation at once (the 8->4->2 path):
        the first case's readmit must lift ONLY the ordinals it
        excluded — the second case's devices stay out until its own
        probation completes."""
        from apex_tpu.resilience.remediation import (
            RemediationPolicy, RemediationState,
        )

        policy = RemediationPolicy(probation_steps=2, max_restarts=6)
        ctrl = self._controller(tmp_path, canary=_stub_canary_confirm,
                                policy=policy)
        ctrl.observe(_straggler_record())
        assert ctrl.process(6).device_count == 4        # excluded [4..7]
        ctrl2 = self._controller(tmp_path, canary=_stub_canary_confirm,
                                 policy=policy)
        ctrl2.adopt_pending(7)
        ctrl2.on_clean_step(7)                  # case-1 one step ahead
        ctrl2.observe(_straggler_record(step=8, host=1))
        assert ctrl2.process(8).device_count == 2       # + excluded [2,3]
        # --- both cases in probation in the next incarnation ---
        ctrl3 = self._controller(tmp_path, policy=policy)
        ctrl3.adopt_pending(9)
        ctrl3.on_clean_step(9)                  # case-1 completes first
        first = ctrl3.poll()
        assert first is not None and first.device_count == 6
        assert RemediationState.load(str(tmp_path)).excluded == [2, 3]
        ctrl3.on_clean_step(10)                 # now case-2 completes
        second = ctrl3.poll()
        assert second is not None and second.device_count == 8
        assert RemediationState.load(str(tmp_path)).excluded == []

    def test_supervisor_timeout_is_a_restartable_incident(self, tmp_path):
        """A wedged incarnation killed by the supervisor's own timeout
        must be recorded and treated as a restartable incident, not
        crash the supervisor with TimeoutExpired."""
        import sys

        from apex_tpu.resilience.remediation import supervise

        report = supervise(
            lambda n: [sys.executable, "-c",
                       "import time; time.sleep(30)"],
            str(tmp_path), 8, max_incarnations=1, timeout_s=0.5,
            env_for=lambda n: dict(os.environ),
        )
        assert report.outcome == "exhausted"
        assert report.incarnations[0].exit_code == int(ExitCode.INCIDENT)

    def test_min_devices_floor_escalates(self, tmp_path):
        from apex_tpu.resilience.remediation import RemediationPolicy

        ctrl = self._controller(
            tmp_path, canary=_stub_canary_confirm,
            policy=RemediationPolicy(min_devices=8),
        )
        ctrl.observe(_straggler_record())
        decision = ctrl.process(6)
        assert decision is not None and decision.action == "halt"
        assert not ctrl.state.excluded          # no half-applied quarantine

    def test_controller_sink_taps_the_router(self):
        from apex_tpu.monitor import MemorySink, MetricRouter
        from apex_tpu.resilience.remediation import ControllerSink

        router = MetricRouter([MemorySink()])
        ctrl = self._controller(canary=_stub_canary_clean, router=router)
        router.add_sink(ControllerSink(ctrl))
        router.event("fleet", 6, check="straggler", flagged_host=2,
                     median_step_s=9.9, z=11.0)
        router.event("metrics", 6, loss=1.0)    # not a detector kind
        assert ctrl.process(6) is None
        assert any(r.get("verdict") == "cleared" for r in ctrl.records)
        # the remediation records ALSO went through the tapped router
        # without deadlocking (the sink only enqueues)
        router.close()

    def test_summary_fleet_records_open_no_case(self):
        from apex_tpu.monitor.router import make_record

        ctrl = self._controller(canary=_stub_canary_clean)
        assert ctrl.observe(make_record(
            "fleet", 6, check="summary", ok=True, n_hosts=4,
            stragglers=0, suspects=0)) is None
        assert not ctrl.open_cases

    def test_preemption_restart_and_recovery(self, tmp_path):
        from apex_tpu.resilience.remediation import RemediationPolicy

        policy = RemediationPolicy(probation_steps=1)
        ctrl = self._controller(tmp_path, policy=policy)
        decision = ctrl.on_preemption(5)
        assert decision.action == "restart"
        assert decision.exit_code == int(ExitCode.REMEDIATION_RESTART)
        assert decision.device_count == 8       # same topology
        # --- the rejoined incarnation ---
        ctrl2 = self._controller(tmp_path, policy=policy)
        adopted = ctrl2.adopt_pending(5)
        assert [c["kind"] for c in adopted] == ["preemption"]
        ctrl2.on_clean_step(6)
        terminal = [r for r in ctrl2.records if r.get("terminal")]
        assert terminal and terminal[0]["verdict"] == "recovered"

    def test_observing_case_survives_a_restart(self, tmp_path):
        """The campaign-caught drop: a case mid-observation when an
        UNRELATED restart ends the incarnation must be re-bound by the
        next one and finish its clean-step closure — not vanish with no
        terminal verdict."""
        from apex_tpu.resilience.remediation import RemediationPolicy

        policy = RemediationPolicy(clean_steps_to_close=2)
        ctrl = self._controller(tmp_path, canary=None, policy=policy)
        ctrl.observe(_straggler_record())
        ctrl.process(6)                         # demoted to observing
        ctrl.on_anchor(6)                       # persists open cases
        # --- the next incarnation (restarted for an unrelated reason) ---
        ctrl2 = self._controller(tmp_path, policy=policy)
        adopted = ctrl2.adopt_pending(7)
        assert [c["state"] for c in adopted] == ["observing"]
        ctrl2.on_clean_step(7)
        ctrl2.on_clean_step(8)
        terminal = [r for r in ctrl2.records if r.get("terminal")]
        assert [t["verdict"] for t in terminal] == ["recovered"]
        assert [t["finding"] for t in terminal] == ["straggler"]

    def test_supervisor_pending_adopted_as_incident(self, tmp_path):
        from apex_tpu.resilience.remediation import (
            RemediationPolicy, RemediationState,
        )

        s = RemediationState.load(str(tmp_path))
        s.pending = {"kind": "incident", "exit_code": 43, "incarnation": 0}
        s.save()
        ctrl = self._controller(
            tmp_path, policy=RemediationPolicy(probation_steps=1),
        )
        adopted = ctrl.adopt_pending(4)
        assert [c["kind"] for c in adopted] == ["incident"]
        # the incident restart already happened (we are it): it counts
        # against the bounded budget
        assert ctrl.state.restarts == 1
        assert RemediationState.load(str(tmp_path)).pending is None

    def test_canary_runs_inside_remediation_span(self):
        from apex_tpu.monitor import MemorySink, MetricRouter
        from apex_tpu.monitor import goodput

        mem = MemorySink()
        router = MetricRouter([mem])
        prev = goodput.get_router()
        goodput.set_router(router)
        try:
            ctrl = self._controller(canary=_stub_canary_clean,
                                    router=router)
            ctrl.observe(_straggler_record())
            ctrl.process(6)
        finally:
            goodput.set_router(prev)
            router.close()
        spans = [r for r in mem.snapshot() if r.get("kind") == "span"
                 and r.get("phase") == "remediation"]
        assert spans                            # recovery time is badput

    def test_metrics_fields_gauges_are_tolerated_keys(self):
        from apex_tpu.monitor.router import CsvSink
        from apex_tpu.resilience.remediation import RemediationPolicy

        ctrl = self._controller(
            canary=None, policy=RemediationPolicy(probation_steps=3),
        )
        assert ctrl.metrics_fields() == {
            "probation": 0, "remediation_cases": 0,
        }
        ctrl.on_preemption(5)                   # a case in probation
        ctrl.on_clean_step(6)
        fields = ctrl.metrics_fields()
        assert fields == {"probation": 2, "remediation_cases": 1}
        # frozen-header CSV resumes survive the schema growth
        assert set(fields) <= CsvSink.TOLERATED_EXTRA_KEYS


# ---------------------------------------------------------------------------
# LiveFleetMonitor -> controller hand-off (satellite: edge cases)


def _fleet_window(n_hosts, slow_host=None, n_steps=4):
    """Per-host step spans: identical 0.1s except the slow host's 5s —
    zero MAD by construction, so the outlier's robust z is inf."""
    recs = []
    for h in range(n_hosts):
        for s in range(n_steps):
            recs.append({"kind": "span", "phase": "step", "step": s,
                         "host": h, "start": float(s),
                         "dur_s": 5.0 if h == slow_host else 0.1})
    return recs


class TestFleetHandoff:
    def test_zero_mad_straggler_flows_to_cleared(self):
        """The seeded straggler flag a clean canary replay clears: one
        case, verdict="cleared", zero restarts — through the REAL
        monitor -> observe_fleet -> controller path."""
        import math

        from apex_tpu.monitor import MemorySink, MetricRouter
        from apex_tpu.monitor.goodput import LiveFleetMonitor
        from apex_tpu.resilience.remediation import (
            RemediationController, RemediationPolicy,
        )

        window = MemorySink()
        for r in _fleet_window(4, slow_host=3):
            window.emit(r)
        router = MetricRouter([MemorySink()])
        mon = LiveFleetMonitor(router, window, interval_steps=1)
        assert mon.maybe_check(0) is None       # anchors the cadence
        report = mon.maybe_check(1)
        assert report is not None and not report.ok
        # zero MAD: the deviation is infinitely many MADs out
        assert math.isinf(report.stragglers[0]["z"])
        ctrl = RemediationController(
            policy=RemediationPolicy(), router=router, world_devices=8,
            canary_fn=_stub_canary_clean,
        )
        touched = ctrl.observe_fleet(report, 1)
        assert len(touched) == 1 and touched[0]["kind"] == "straggler"
        assert ctrl.process(1) is None          # cleared, no restart
        assert ctrl.state.restarts == 0
        terminal = [r for r in ctrl.records if r.get("terminal")]
        assert [t["verdict"] for t in terminal] == ["cleared"]
        router.close()

    def test_under_three_hosts_opens_nothing(self):
        """<3 hosts: the straggler math refuses to name an outlier, the
        report is ok, and the controller opens no case."""
        from apex_tpu.monitor import MemorySink, MetricRouter
        from apex_tpu.monitor.goodput import LiveFleetMonitor
        from apex_tpu.resilience.remediation import (
            RemediationController, RemediationPolicy,
        )

        window = MemorySink()
        for r in _fleet_window(2, slow_host=1):
            window.emit(r)
        router = MetricRouter([MemorySink()])
        mon = LiveFleetMonitor(router, window, interval_steps=1)
        mon.maybe_check(0)
        report = mon.maybe_check(1)
        assert report is not None and report.ok
        ctrl = RemediationController(policy=RemediationPolicy(),
                                     world_devices=8)
        assert ctrl.observe_fleet(report, 1) == []
        assert not ctrl.open_cases
        router.close()


# ---------------------------------------------------------------------------
# the false-positive pin against the broken policy (jax-free)


class TestBrokenPolicyPin:
    def test_unverified_quarantine_is_caught(self, tmp_path):
        """A policy that quarantines WITHOUT canary verification is the
        deliberately broken table; the campaign's invariant checker
        must flag its record shape."""
        from apex_tpu.resilience.remediation import (
            RemediationController, RemediationPolicy,
        )
        from apex_tpu.resilience.remediation.campaign import (
            FaultEvent, SequenceResult, check_invariants,
        )

        ctrl = RemediationController(
            policy=RemediationPolicy(verify_before_quarantine=False),
            save_dir=str(tmp_path), world_devices=8,
        )
        ctrl.observe(_straggler_record())
        decision = ctrl.process(6)
        assert decision is not None and decision.action == "restart"
        fake = SequenceResult(
            faults=[FaultEvent("slow", 6)], run_id="broken",
            outcome="completed", incarnations=[], records=ctrl.records,
            remediation=ctrl.records, losses={},
        )
        violations = check_invariants(fake)
        assert any("WITHOUT canary verification" in v for v in violations)

    def test_verified_quarantine_passes_the_same_check(self, tmp_path):
        from apex_tpu.resilience.remediation import (
            RemediationController, RemediationPolicy,
        )
        from apex_tpu.resilience.remediation.campaign import (
            SequenceResult, _quarantine_verified,
        )

        ctrl = RemediationController(
            policy=RemediationPolicy(), save_dir=str(tmp_path),
            world_devices=8, canary_fn=_stub_canary_confirm,
        )
        ctrl.observe(_straggler_record())
        ctrl.process(6)
        fake = SequenceResult(
            faults=[], run_id="ok", outcome="completed", incarnations=[],
            records=ctrl.records, remediation=ctrl.records, losses={},
        )
        case = ctrl.records[0]["case"]
        assert _quarantine_verified(fake, case)


# ---------------------------------------------------------------------------
# the supervisor (jax-free, injected runner)


class TestSupervisor:
    def test_restarts_on_44_stops_on_0(self, tmp_path):
        from apex_tpu.resilience.remediation import supervise

        codes = [int(ExitCode.REMEDIATION_RESTART), int(ExitCode.OK)]
        argvs = []

        def runner(argv, env):
            argvs.append(list(argv))
            return codes.pop(0)

        report = supervise(lambda n: ["train", f"--devices={n}"],
                           str(tmp_path), 8, runner=runner)
        assert report.ok and report.outcome == "completed"
        assert len(report.incarnations) == 2
        assert report.final_exit_code == 0
        assert argvs[0] == ["train", "--devices=8"]

    def test_relaunch_honors_the_persisted_topology(self, tmp_path):
        from apex_tpu.resilience.remediation import (
            RemediationState, supervise,
        )

        s = RemediationState.load(str(tmp_path))
        s.excluded = [4, 5, 6, 7]
        s.save()
        seen = []

        def runner(argv, env):
            seen.append((list(argv), env.get("XLA_FLAGS")))
            return int(ExitCode.OK)

        report = supervise(lambda n: [f"--devices={n}"], str(tmp_path), 8,
                           runner=runner)
        assert report.ok
        assert seen[0][0] == ["--devices=4"]
        assert "device_count=4" in seen[0][1]
        assert report.incarnations[0].device_count == 4

    def test_halt_45_is_terminal(self, tmp_path):
        from apex_tpu.resilience.remediation import supervise

        report = supervise(
            lambda n: ["x"], str(tmp_path), 8,
            runner=lambda a, e: int(ExitCode.REMEDIATION_HALT),
        )
        assert report.outcome == "halted"
        assert len(report.incarnations) == 1
        assert report.final_exit_code == int(ExitCode.REMEDIATION_HALT)

    def test_non_restartable_code_stops(self, tmp_path):
        from apex_tpu.resilience.remediation import supervise

        report = supervise(lambda n: ["x"], str(tmp_path), 8,
                           runner=lambda a, e: 7)
        assert report.outcome == "failed"
        assert len(report.incarnations) == 1

    def test_incarnation_budget_bounds_the_loop(self, tmp_path):
        from apex_tpu.resilience.remediation import supervise

        report = supervise(
            lambda n: ["x"], str(tmp_path), 8, max_incarnations=3,
            runner=lambda a, e: int(ExitCode.REMEDIATION_RESTART),
        )
        assert report.outcome == "exhausted"
        assert len(report.incarnations) == 3

    def test_incident_exit_writes_the_adoption_note(self, tmp_path):
        from apex_tpu.resilience.remediation import (
            RemediationState, supervise,
        )

        codes = [int(ExitCode.INCIDENT), int(ExitCode.REMEDIATION_HALT)]
        pending_seen = []

        def runner(argv, env):
            pending_seen.append(
                RemediationState.load(str(tmp_path)).pending
            )
            return codes.pop(0)

        supervise(lambda n: ["x"], str(tmp_path), 8, runner=runner)
        # the note did not exist for the first launch, and the SECOND
        # incarnation sees the supervisor-written incident evidence
        assert pending_seen[0] is None
        assert pending_seen[1] == {
            "kind": "incident", "exit_code": int(ExitCode.INCIDENT),
            "incarnation": 0,
        }


# ---------------------------------------------------------------------------
# campaign units (jax-free)


class TestCampaignUnits:
    def test_random_sequence_is_seed_deterministic(self):
        from apex_tpu.resilience.remediation.campaign import random_sequence

        assert random_sequence(17) == random_sequence(17)
        assert any(random_sequence(s) != random_sequence(s + 1)
                   for s in range(5))

    def test_random_sequence_shape(self):
        from apex_tpu.resilience.remediation.campaign import (
            FAULT_KINDS, random_sequence,
        )

        for seed in range(40):
            events = random_sequence(seed, steps=8, max_faults=3)
            assert 1 <= len(events) <= 3
            kinds = [e.kind for e in events]
            steps = [e.step for e in events]
            assert len(set(kinds)) == len(kinds)      # distinct kinds
            assert len(set(steps)) == len(steps)      # distinct steps
            assert all(k in FAULT_KINDS for k in kinds)
            assert all(1 <= s <= 6 for s in steps)
            if "bitflip" in kinds:
                # the flip lands last so earlier faults' canary replays
                # re-execute still-clean segments
                assert max(events, key=lambda e: e.step).kind == "bitflip"

    def test_fault_terminal_matching_is_exact(self):
        from apex_tpu.resilience.remediation.campaign import (
            FaultEvent, _match_faults,
        )

        faults = [FaultEvent("nan", 2), FaultEvent("slow", 4)]
        assert _match_faults(faults, [
            {"finding": "stall", "verdict": "cleared"},
            {"finding": "sentinel", "verdict": "recovered"},
        ])
        # a missing terminal, an extra terminal, and a wrong verdict
        # each break the bipartite match
        assert not _match_faults(faults, [
            {"finding": "sentinel", "verdict": "recovered"},
        ])
        assert not _match_faults(faults, [
            {"finding": "stall", "verdict": "cleared"},
            {"finding": "sentinel", "verdict": "recovered"},
            {"finding": "sdc", "verdict": "readmitted"},
        ])
        assert not _match_faults(faults, [
            {"finding": "sentinel", "verdict": "halted"},
            {"finding": "stall", "verdict": "cleared"},
        ])

    def test_minimize_failing_shrinks_to_the_culprit(self):
        from apex_tpu.resilience.remediation.campaign import (
            FaultEvent, minimize_failing,
        )

        faults = [FaultEvent("nan", 2), FaultEvent("slow", 4),
                  FaultEvent("sigterm", 6)]

        def run_and_check(candidate):
            # the failure needs exactly the (nan, sigterm) pair
            kinds = {e.kind for e in candidate}
            return (["boom"] if {"nan", "sigterm"} <= kinds else [])

        minimal, violations = minimize_failing(faults, run_and_check)
        assert {e.kind for e in minimal} == {"nan", "sigterm"}
        assert violations == ["boom"]


# ---------------------------------------------------------------------------
# the gate + the campaign + the acceptance drill (slow tier)


def test_remediation_selftest_gate(tmp_path):
    """``python -m apex_tpu.resilience.remediation --selftest`` exits 0:
    inject SDC -> canary detect+confirm -> quarantine 8->4 -> probation
    -> readmit 4->8, the false-positive clear, the broken-policy catch,
    the fleet edge cases, and the supervisor's exit-code contract."""
    from apex_tpu.resilience.remediation.__main__ import main

    assert main(["--selftest", "--dir", str(tmp_path)]) == 0


def test_remediation_campaign(tmp_path):
    """>= 20 seeded randomized fault sequences pass the invariant
    checker (the acceptance criterion's campaign surface)."""
    from apex_tpu.resilience.remediation.campaign import run_campaign

    report = run_campaign(str(tmp_path), n_sequences=20, seed=0)
    failing = [e for e in report["sequences"] if e["violations"]]
    assert report["failed"] == 0, failing
    assert report["passed"] == 20


def test_gpt_remediation_acceptance_drill(tmp_path):
    """The acceptance drill: bitflip + hang + SIGTERM in ONE run against
    the GPT target completes with zero human intervention — quarantine
    8->4 under the same run id, probation readmit 4->8, final loss
    within 5e-2 of the uninterrupted reference, goodput partition
    identity digit-for-digit across all incarnations, and every fault
    mapped to exactly one terminal remediation verdict."""
    from apex_tpu.data import IndexedTokenDataset, LMDataset
    from apex_tpu.resilience.remediation.campaign import (
        FaultEvent, TrainingCache, campaign_config, check_invariants,
        run_sequence,
    )
    from apex_tpu.resilience.replay.targets import synthetic_corpus

    cfg = campaign_config()
    cache = TrainingCache(cfg)
    prefix = synthetic_corpus(cfg.vocab, n_tokens=20_000)
    lm = LMDataset(IndexedTokenDataset(prefix), seq_len=cfg.seq_len)
    steps = 8

    reference = run_sequence(
        [], str(tmp_path / "reference"), cache, lm, prefix, steps=steps,
    )
    assert reference.outcome == "completed"
    assert not reference.remediation

    faults = [FaultEvent("sigterm", 2), FaultEvent("hang", 4),
              FaultEvent("bitflip", 6)]
    result = run_sequence(
        faults, str(tmp_path / "drill"), cache, lm, prefix, steps=steps,
    )
    assert result.outcome == "completed", result.incarnations
    violations = check_invariants(
        result, reference_losses=reference.losses, final_step=steps - 1,
    )
    assert violations == [], violations
    # quarantine reduced 8->4 and the readmit restored 8, all under the
    # ONE run id (every incarnation's records carry it)
    devices = [i["devices"] for i in result.incarnations]
    assert 4 in devices and devices[0] == 8 and devices[-1] == 8
    run_ids = {r.get("run_id") for r in result.records
               if r.get("kind") == "run"}
    assert run_ids == {result.run_id}
    # exactly one terminal verdict per fault (the bipartite pin also ran
    # inside check_invariants; restated here as the headline)
    assert len(result.terminals) == len(faults)
