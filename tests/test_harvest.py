"""Harvester section-state semantics (benchmarks/harvest.py).

What gets retried across relay windows is a correctness question: a
deterministic kernel failure must count as captured (retrying re-spends a
window on the same answer) while budget-truncated sections must retry.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benchmarks"
))

import harvest


def _write(tmp_path, records):
    p = tmp_path / "r.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    return str(p)


def test_smoke_rc_semantics(tmp_path):
    # rc=0 (all OK) and rc=1 (deterministic FAIL) are captured; rc=2
    # (budget skip) retries
    for rc, captured in [(0, True), (1, True), (2, False)]:
        p = _write(tmp_path, [{"section": "smoke", "ok": True, "rc": rc}])
        assert ("smoke" in harvest.results_state(p)) is captured, rc


def test_incomplete_sections_retry(tmp_path):
    p = _write(tmp_path, [
        {"section": "micro", "ok": True, "adam_step_s": {"flat": 1.0},
         "incomplete": ["layer_norm_s"]},
        {"section": "configs", "ok": True, "configs": {}},
    ])
    state = harvest.results_state(p)
    assert "micro" not in state and "configs" in state


def test_failed_sections_retry_and_partials_count(tmp_path):
    p = _write(tmp_path, [
        {"section": "headline", "ok": False, "error": "relay dropped"},
        {"section": "headline_o2", "ok": True, "value": 100.0},
    ])
    state = harvest.results_state(p)
    assert "headline" not in state and "headline_o2" in state


def test_missing_file_is_empty(tmp_path):
    assert harvest.results_state(str(tmp_path / "none.jsonl")) == set()


def test_headline_without_vs_baseline_retries(tmp_path):
    # O2 landed but O0 didn't (hung relay fetch, 2026-07-31): the headline
    # section must retry so a later window can capture the missing half —
    # run_all_tpu reuses the fresh O2 sub-record, so the retry is cheap.
    p = _write(tmp_path, [
        {"section": "headline", "ok": True, "value": 2626.0,
         "vs_baseline": None, "note": "O0 baseline failed"},
    ])
    assert "headline" not in harvest.results_state(p)
    p = _write(tmp_path, [
        {"section": "headline", "ok": True, "value": 2626.0,
         "vs_baseline": 3.1, "o0_value": 847.0},
    ])
    assert "headline" in harvest.results_state(p)


def test_null_headline_retry_is_capped(tmp_path):
    # a deterministic O0 failure must not re-burn every remaining relay
    # window: after MAX_NULL_HEADLINE_RETRIES null-vs_baseline records the
    # failure counts as the captured answer (the smoke-rc=1 principle)
    rec = {"section": "headline", "ok": True, "value": 2626.0,
           "vs_baseline": None, "note": "O0 baseline failed: ValueError"}
    p = _write(tmp_path, [rec] * harvest.MAX_NULL_HEADLINE_RETRIES)
    assert "headline" not in harvest.results_state(p)
    p = _write(tmp_path, [rec] * (harvest.MAX_NULL_HEADLINE_RETRIES + 1))
    assert "headline" in harvest.results_state(p)


def test_sweep_budget_exhaustion_marks_incomplete(tmp_path):
    # run_sweep with an already-expired deadline must skip every batch and
    # flag the section incomplete (so harvest retries it next window)
    # without touching the backend.
    import run_all_tpu

    out = str(tmp_path / "r.jsonl")
    rec = run_all_tpu.run_sweep(deadline=0.0, out_path=out)
    assert rec["incomplete"] == ["rn50_ampO2_b384", "rn50_ampO2_b512"]
    assert all("skipped" in rec[n] for n in rec["incomplete"])


def test_sweep_reuses_fresh_subrecords(tmp_path):
    # a batch measured by an earlier attempt is reused, not re-measured
    # (the headline halves' protocol), and only the missing batch retries
    import json
    import time

    import run_all_tpu

    out = str(tmp_path / "r.jsonl")
    with open(out, "w") as f:
        f.write(json.dumps({
            "section": "sweep_b384", "ok": True, "value": 2700.5,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }) + "\n")
    rec = run_all_tpu.run_sweep(deadline=0.0, out_path=out)
    assert rec["rn50_ampO2_b384"]["imgs_per_sec_per_chip"] == 2700.5
    assert rec["incomplete"] == ["rn50_ampO2_b512"]


def test_transient_error_classification():
    # relay-infrastructure failures retry; deterministic answers don't
    import run_all_tpu as r

    assert r.transient_error(RuntimeError(
        "UNAVAILABLE: http://127.0.0.1:8113/remote_compile: transport: ..."))
    assert r.transient_error(RuntimeError("measurement budget exhausted"))
    assert r.transient_error(RuntimeError("Connection reset by peer"))
    assert not r.transient_error(AssertionError("max abs err 0.5 > 0.01"))
    assert not r.transient_error(ValueError("non-positive slope"))


def test_poisoned_all_transient_sections_retry(tmp_path):
    # a relay-down window's all-error micro/configs record (written by a
    # capture predating transient classification) must not count as
    # captured; one real measurement anywhere keeps the record
    err = "error: UNAVAILABLE: http://127.0.0.1:8113/remote_compile: transport"
    p = _write(tmp_path, [
        {"section": "micro", "ok": True, "adam_step_s": err,
         "l2norm_s": err},
        {"section": "configs", "ok": True,
         "configs": {"mlp": {"error": err, "elapsed_s": 3.0},
                     "bert": {"error": err, "elapsed_s": 2.0}}},
    ])
    state = harvest.results_state(p)
    assert "micro" not in state and "configs" not in state
    p2 = _write(tmp_path, [
        {"section": "sweep", "ok": True, "rn50_ampO2_b384": err,
         "rn50_ampO2_b512": err},
    ])
    assert "sweep" not in harvest.results_state(p2)
    p = _write(tmp_path, [
        {"section": "micro", "ok": True,
         "adam_step_s": {"flat": 1.0, "tree": 2.0}, "l2norm_s": err},
        {"section": "configs", "ok": True,
         "configs": {"mlp": {"config": "mlp", "value": 3.0,
                             "elapsed_s": 1.0},
                     "bert": {"error": err, "elapsed_s": 2.0}}},
    ])
    state = harvest.results_state(p)
    assert "micro" in state and "configs" in state


def test_completed_flag_semantics(tmp_path):
    # round-5 records: `ok` = produced data, `completed` = harness health.
    # A completed section whose failures were all deterministic is a
    # captured answer even with ok=false; relay-dead and incomplete
    # sections retry.
    p = _write(tmp_path, [
        # all-deterministic-failure micro: captured (the rc=1 principle)
        {"section": "micro", "ok": False, "completed": True,
         "adam_step_s": "error: non-positive slope", "measured_n": 0},
        # relay died before the section ran: retry
        {"section": "configs", "ok": False, "completed": False,
         "relay_dead": True},
        # measured some items but others transiently failed: retry
        {"section": "sweep", "ok": True, "completed": True,
         "measured_n": 1, "incomplete": ["rn50_ampO2_b512"]},
        # fully measured: captured
        {"section": "profile", "ok": True, "completed": True,
         "measured_n": 3, "fwd_s_per_step": 0.01},
    ])
    state = harvest.results_state(p)
    assert "micro" in state and "profile" in state
    assert "configs" not in state and "sweep" not in state


def test_completed_smoke_rc_semantics(tmp_path):
    # rc semantics carry over to round-5 records: rc=2 (budget/relay)
    # retries even when checks streamed to the sidecar made ok=true
    for rc, captured in [(0, True), (1, True), (2, False)]:
        p = _write(tmp_path, [{"section": "smoke", "ok": True,
                               "completed": True, "rc": rc,
                               "measured_n": 5}])
        assert ("smoke" in harvest.results_state(p)) is captured, rc


def test_micro_reuses_fresh_subrecords(tmp_path):
    # an item measured by an earlier window is reused, not re-measured;
    # the remaining items retry (and with an expired deadline they skip
    # without touching the backend)
    import json
    import time

    import run_all_tpu

    out = str(tmp_path / "r.jsonl")
    with open(out, "w") as f:
        f.write(json.dumps({
            "section": "micro_adam_step_s", "ok": True, "completed": True,
            "value": {"tree": 0.004, "flat": 0.005},
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }) + "\n")
    rec = run_all_tpu.run_micro(deadline=0.0, out_path=out)
    assert rec["adam_step_s"] == {"tree": 0.004, "flat": 0.005}
    assert rec["measured_n"] == 1
    assert "adam_step_s" not in rec["incomplete"]
    assert "l2norm_s" in rec["incomplete"]


def test_configs_reuses_fresh_subrecords(tmp_path):
    import json
    import time

    import run_all_tpu

    out = str(tmp_path / "r.jsonl")
    with open(out, "w") as f:
        f.write(json.dumps({
            "section": "config_gpt", "ok": True, "completed": True,
            "value": {"tokens_per_sec": 1000.0, "elapsed_s": 9.0},
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }) + "\n")
    rec = run_all_tpu.run_configs(deadline=0.0, out_path=out)
    assert rec["configs"]["gpt"]["tokens_per_sec"] == 1000.0
    assert rec["measured_n"] == 1
    assert "gpt" not in rec["incomplete"] and "bert" in rec["incomplete"]


def test_profile_budget_exhaustion_marks_incomplete(tmp_path):
    import run_all_tpu

    out = str(tmp_path / "r.jsonl")
    rec = run_all_tpu.run_profile(deadline=0.0, out_path=out)
    assert rec["incomplete"] == ["fwd", "fwd_bwd", "step"]
    assert rec["measured_n"] == 0


def test_deterministic_all_error_sections_count_as_captured(tmp_path):
    # every item failed, but deterministically (numerics/shape bugs):
    # retrying re-burns a window on the same answer — captured
    p = _write(tmp_path, [
        {"section": "micro", "ok": True,
         "adam_step_s": "error: non-positive slope",
         "l2norm_s": "error: max abs err 0.5"},
    ])
    assert "micro" in harvest.results_state(p)


def test_smoke_later_fail_invalidates_prior_ok(tmp_path):
    # a check that FAILed under the same source fingerprint after an
    # earlier ok must re-run, not be skipped as clean forever
    import tpu_kernel_smoke as s

    p = tmp_path / "progress.log"
    fp = "ab" * 8
    p.write_text(
        f"t === smoke attempt start (pid 1, fp={fp}) ===\n"
        "t ok   layer_norm fwd 512x1024 float32\n"
        "t ok   adam_flat\n"
        f"t === smoke attempt start (pid 2, fp={fp}) ===\n"
        "t FAIL adam_flat: max abs err 0.5 > 1e-06\n"
        "t ok   l2norm_flat\n"
    )
    got = s.prior_ok_checks(str(p), fp)
    assert got == {"layer_norm fwd 512x1024 float32", "l2norm_flat"}


def test_run_items_reuses_deterministic_failures(tmp_path):
    # an item that failed DETERMINISTICALLY in an earlier window is a
    # captured answer: the retry must not re-buy it (and it is neither
    # measured nor incomplete)
    import json
    import time

    import run_all_tpu

    out = str(tmp_path / "r.jsonl")
    with open(out, "w") as f:
        f.write(json.dumps({
            "section": "micro_adam_step_s", "ok": False, "completed": True,
            "error": "error: non-positive slope",
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }) + "\n")
    calls = []

    def fn(d):
        calls.append(1)
        return 1.0

    results, measured, incomplete = run_all_tpu.run_items(
        [("adam_step_s", fn)], time.monotonic() + 300, out, "micro")
    assert calls == []  # not re-run
    assert results["adam_step_s"] == "error: non-positive slope"
    assert measured == 0 and incomplete == []


def test_run_items_emits_failure_subrecords(tmp_path):
    # a deterministic in-window failure is persisted so the NEXT window
    # can reuse it; transient failures are not (they must retry)
    import json
    import time

    import run_all_tpu

    out = str(tmp_path / "r.jsonl")

    def det(d):
        raise ValueError("non-positive slope")

    def trans(d):
        raise RuntimeError("UNAVAILABLE: transport: connection refused")

    results, measured, incomplete = run_all_tpu.run_items(
        [("a", det), ("b", trans)], time.monotonic() + 300, out, "micro")
    assert incomplete == ["b"]
    recs = [json.loads(l) for l in open(out)]
    fails = [r for r in recs if r["section"] == "micro_a"]
    assert len(fails) == 1 and fails[0]["completed"] and not fails[0]["ok"]
    assert not [r for r in recs if r["section"] == "micro_b"]
