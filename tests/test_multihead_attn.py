"""Fused MHA module tests (ref style: apex/contrib/test/multihead_attn —
fused module vs a plain composition oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib import EncdecMultiheadAttn, SelfMultiheadAttn

S, B, E, H = 8, 2, 32, 4


def naive_self_attn(params, x, key_padding_mask=None, additive=None):
    w = np.asarray(params["in_proj_weight"])
    qkv = np.asarray(x) @ w
    q, k, v = np.split(qkv, 3, axis=-1)
    hd = E // H

    def heads(t):  # (s,b,e)->(b,h,s,hd)
        return t.reshape(S, B, H, hd).transpose(1, 2, 0, 3)

    qb, kb, vb = heads(q), heads(k), heads(v)
    s = np.einsum("bhqd,bhkd->bhqk", qb, kb) / np.sqrt(hd)
    if additive is not None:
        s = s + additive
    if key_padding_mask is not None:
        s = np.where(key_padding_mask[:, None, None, :], -1e30, s)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", p, vb)
    out = ctx.transpose(2, 0, 1, 3).reshape(S, B, E)
    return out @ np.asarray(params["out_proj_weight"])


class TestSelfMHA:
    def test_matches_naive(self, rng):
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H)
        params = mod.init(rng, x)["params"]
        got = mod.apply({"params": params}, x)
        want = naive_self_attn(params, x)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_key_padding_mask(self, rng):
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        kpm = jnp.zeros((B, S), bool).at[:, -2:].set(True)
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H)
        params = mod.init(rng, x)["params"]
        got = mod.apply({"params": params}, x, key_padding_mask=kpm)
        want = naive_self_attn(params, x, key_padding_mask=np.asarray(kpm))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_mask_additive(self, rng):
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        am = jax.random.normal(jax.random.fold_in(rng, 1), (S, S)) * 2.0
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, mask_additive=True)
        params = mod.init(rng, x)["params"]
        got = mod.apply({"params": params}, x, attn_mask=am)
        want = naive_self_attn(params, x, additive=np.asarray(am)[None, None])
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_norm_add_variant(self, rng):
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
        params = mod.init(rng, x)["params"]
        got = mod.apply({"params": params}, x)
        # residual + attn(LN(x))
        xn = np.asarray(x, np.float64)
        mu = xn.mean(-1, keepdims=True)
        var = xn.var(-1, keepdims=True)
        ln = ((xn - mu) / np.sqrt(var + 1e-5)).astype(np.float32)
        want = np.asarray(x) + naive_self_attn(params, jnp.asarray(ln))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    def test_separate_qkv_and_bias(self, rng):
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        mod = SelfMultiheadAttn(
            embed_dim=E, num_heads=H, separate_qkv_params=True, bias=True
        )
        params = mod.init(rng, x)["params"]
        assert set(params) >= {"q_weight", "k_weight", "v_weight", "q_bias"}
        out = mod.apply({"params": params}, x)
        assert out.shape == (S, B, E)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_causal_matches_flash(self, rng):
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, causal=True)
        params = mod.init(rng, x)["params"]
        got = mod.apply({"params": params}, x)
        tri = np.triu(np.ones((S, S)), 1) * -1e30
        want = naive_self_attn(params, x, additive=tri[None, None])
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


class TestEncdecMHA:
    def test_shapes_and_mask(self, rng):
        q = jax.random.normal(rng, (S, B, E), jnp.float32)
        kv = jax.random.normal(jax.random.fold_in(rng, 1), (S + 4, B, E))
        mod = EncdecMultiheadAttn(embed_dim=E, num_heads=H, bias=True)
        params = mod.init(rng, q, kv)["params"]
        out = mod.apply({"params": params}, q, kv)
        assert out.shape == (S, B, E)
        kpm = jnp.zeros((B, S + 4), bool).at[:, -1:].set(True)
        out_m = mod.apply({"params": params}, q, kv, key_padding_mask=kpm)
        assert bool(jnp.all(jnp.isfinite(out_m)))
        assert not np.allclose(out, out_m)

    def test_norm_add(self, rng):
        q = jax.random.normal(rng, (S, B, E), jnp.float32)
        kv = jax.random.normal(jax.random.fold_in(rng, 1), (S, B, E))
        mod = EncdecMultiheadAttn(embed_dim=E, num_heads=H, include_norm_add=True)
        params = mod.init(rng, q, kv)["params"]
        out = mod.apply({"params": params}, q, kv)
        assert out.shape == (S, B, E)


class TestCausalWithPadding:
    def test_causal_plus_key_padding_mask(self, rng):
        """Causal decoder with padded batch: both masks compose."""
        x = jax.random.normal(rng, (S, B, E), jnp.float32)
        kpm = jnp.zeros((B, S), bool).at[:, -2:].set(True)
        mod = SelfMultiheadAttn(embed_dim=E, num_heads=H, causal=True)
        params = mod.init(rng, x)["params"]
        got = mod.apply({"params": params}, x, key_padding_mask=kpm)
        tri = np.triu(np.ones((S, S)), 1) * -1e30
        want = naive_self_attn(
            params, x, key_padding_mask=np.asarray(kpm),
            additive=tri[None, None],
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
