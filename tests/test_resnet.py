"""ResNet model tests (ref flow: examples/imagenet/main_amp.py + L1 tier).

Uses a tiny ResNet (BasicBlock, few filters, small images) so the suite
stays fast; ResNet-50 itself differs only in stage sizes/block type.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models import ResNet, cross_entropy_loss
from apex_tpu.models.resnet import BasicBlock
from apex_tpu.parallel import parallel_state


def tiny_resnet(**kw):
    defaults = dict(
        stage_sizes=[1, 1],
        block_cls=BasicBlock,
        num_classes=10,
        num_filters=8,
    )
    defaults.update(kw)
    return ResNet(**defaults)


class TestResNet:
    def test_forward_shapes(self, rng):
        model = tiny_resnet()
        x = jax.random.normal(rng, (2, 32, 32, 3))
        variables = model.init(rng, x)
        logits = model.apply(variables, x)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_bf16_compute_fp32_params(self, rng):
        model = tiny_resnet(dtype=jnp.bfloat16)
        x = jax.random.normal(rng, (2, 32, 32, 3))
        variables = model.init(rng, x)
        for leaf in jax.tree.leaves(variables["params"]):
            assert leaf.dtype == jnp.float32
        logits = model.apply(variables, x)
        assert logits.dtype == jnp.float32

    def test_train_updates_batch_stats_and_loss_decreases(self, rng):
        model = tiny_resnet()
        x = jax.random.normal(rng, (8, 32, 32, 3))
        labels = jax.random.randint(jax.random.fold_in(rng, 1), (8,), 0, 10)
        variables = model.init(rng, x)
        params, batch_stats = variables["params"], variables["batch_stats"]
        opt = optax.sgd(0.1, momentum=0.9)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, batch_stats, opt_state):
            def loss_fn(p):
                logits, mutated = model.apply(
                    {"params": p, "batch_stats": batch_stats},
                    x,
                    train=True,
                    mutable=["batch_stats"],
                )
                return cross_entropy_loss(logits, labels), mutated["batch_stats"]

            (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), bs, opt_state, loss

        losses = []
        for _ in range(10):
            params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        # running stats must have moved off their init
        assert float(jnp.abs(batch_stats["bn_init"]["mean"]).sum()) > 0

    def test_syncbn_dp_matches_single_device_global_batch(self, rng):
        """DP training with bn_axes=('dp',) must compute the same normalized
        activations as single-device training on the concatenated batch
        (ref: tests/distributed/synced_batchnorm parity)."""
        mesh = parallel_state.initialize_model_parallel()  # dp=8
        model_sync = tiny_resnet(bn_axes=("dp",))
        model_local = tiny_resnet()
        x = jax.random.normal(rng, (16, 16, 16, 3))

        variables = model_local.init(rng, x)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P("dp"),),
            out_specs=P("dp"),
            check_vma=False,
        )
        def fwd_sync(v, x_local):
            y, _ = model_sync.apply(
                v, x_local, train=True, mutable=["batch_stats"]
            )
            return y

        y_dp = fwd_sync(variables, x)
        y_ref, _ = model_local.apply(variables, x, train=True, mutable=["batch_stats"])
        np.testing.assert_allclose(y_dp, y_ref, rtol=2e-3, atol=2e-3)


class TestConvertSyncbnModel:
    """Ref apex.parallel.convert_syncbn_model (parallel/__init__.py:21-44):
    post-hoc BN -> SyncBN surgery with state carried across unchanged."""

    def test_repoints_bn_axes_and_preserves_variables(self, rng):
        from apex_tpu.parallel import convert_syncbn_model

        model = tiny_resnet()  # local BN (bn_axes=())
        converted = convert_syncbn_model(model, axis_names=("dp",))
        assert converted.bn_axes == ("dp",)
        assert model.bn_axes == ()  # original untouched (frozen dataclass)

        # same variable structure: the torch version moves state dicts over;
        # here the SAME variables apply to both models
        x = jax.random.normal(rng, (4, 16, 16, 3))
        variables = model.init(rng, x)
        mesh = parallel_state.initialize_model_parallel()  # dp=8

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P("dp")),
            out_specs=P("dp"), check_vma=False,
        )
        def fwd(v, xl):
            y, _ = converted.apply(v, xl, train=True, mutable=["batch_stats"])
            return y

        x8 = jax.random.normal(rng, (16, 16, 16, 3))
        y_conv = fwd(variables, x8)
        y_ref, _ = model.apply(variables, x8, train=True, mutable=["batch_stats"])
        np.testing.assert_allclose(y_conv, y_ref, rtol=2e-3, atol=2e-3)

    def test_converts_flax_batchnorm_field(self):
        import flax.linen as nn

        from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

        class WithBN(nn.Module):
            norm: nn.Module = None

            @nn.compact
            def __call__(self, x):
                return self.norm(x)

        m = WithBN(norm=nn.BatchNorm(use_running_average=False, momentum=0.9))
        c = convert_syncbn_model(m, axis_names=("dp",))
        assert isinstance(c.norm, SyncBatchNorm)
        assert c.norm.axis_names == ("dp",)
        # flax momentum 0.9 (new = 0.9*old + 0.1*batch) -> torch-convention 0.1
        np.testing.assert_allclose(c.norm.momentum, 0.1)

    def test_identity_when_nothing_to_convert(self):
        import flax.linen as nn

        from apex_tpu.parallel import convert_syncbn_model

        m = nn.Dense(4)
        assert convert_syncbn_model(m) is m
