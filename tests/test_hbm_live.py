"""Live HBM watermarks + OOM forensics (monitor.xray.hbm.live / .oom).

The load-bearing contracts:

- NONE IS NEVER FORGED: a backend with no allocator stats (CPU) yields
  None watermarks, None utilization, and EMPTY metric gauges — records
  still flow so the join's absence is visible in the stream;
- the breach detector fires exactly when the watermark crosses the
  ``(1 - headroom_fraction) * capacity`` guard band, the record carries
  ``headroom_breach=True``, and the remediation controller opens ONE
  ``memory`` case on it (plain watermark rows open nothing);
- ``oom_guard`` emits exactly ONE ``kind="oom"`` incident bundle per
  exhaustion and ALWAYS re-raises — it explains failures, never
  swallows them; non-OOM exceptions pass through untouched;
- KV-pool occupancy/fragmentation arithmetic is pinned by hand, the
  serving engine emits pool rows on its tick cadence, and the
  allocator's high-water mark survives frees;
- the router schema holds: StdoutSink skips the memory/oom firehose,
  CsvSink tolerates the watermark gauges.
"""

import io

import numpy as np
import pytest

from apex_tpu.monitor import MemorySink, MetricRouter, StdoutSink
from apex_tpu.monitor.router import CsvSink
from apex_tpu.monitor.xray.hbm.live import (
    HbmWatermarkMonitor,
    device_memory_limit,
    device_watermarks,
    kv_pool_fields,
)
from apex_tpu.monitor.xray.hbm.model import Component, HbmBreakdown
from apex_tpu.monitor.xray.hbm.oom import oom_guard, read_oom_records


class _TpuLikeDevice:
    """A device whose allocator reports stats (the TPU/GPU shape)."""

    def __init__(self, in_use=800, peak=900, limit=1000):
        self.stats = {
            "bytes_in_use": in_use, "peak_bytes_in_use": peak,
            "bytes_limit": limit,
        }

    def memory_stats(self):
        return self.stats


class _CpuLikeDevice:
    """Host backends report no stats at all."""

    def memory_stats(self):
        return None


class _LegacyDevice:
    """Backends predating the stats API raise NotImplementedError."""

    def memory_stats(self):
        raise NotImplementedError


def _bd(n, capacity=None):
    return HbmBreakdown(
        components=(Component("weights", n),), capacity_bytes=capacity
    )


# ---------------------------------------------------------------------------
# watermark probes


class TestDeviceWatermarks:
    def test_stats_pass_through(self):
        wm = device_watermarks(_TpuLikeDevice())
        assert wm == {
            "bytes_in_use": 800, "peak_bytes_in_use": 900,
            "bytes_limit": 1000,
        }

    def test_cpu_reports_none_not_zeros(self):
        assert device_watermarks(_CpuLikeDevice()) is None
        assert device_watermarks(_LegacyDevice()) is None

    def test_memory_limit(self):
        assert device_memory_limit(_TpuLikeDevice()) == 1000
        assert device_memory_limit(_CpuLikeDevice()) is None


# ---------------------------------------------------------------------------
# the watermark monitor


class TestWatermarkMonitor:
    def _mon(self, device, **kw):
        mem = MemorySink()
        router = MetricRouter([mem])
        mon = HbmWatermarkMonitor(router, device=device, **kw)
        return mon, mem

    def test_sample_joins_against_prediction(self):
        mon, mem = self._mon(_TpuLikeDevice(), predicted=_bd(1000))
        fields = mon.sample(5)
        assert fields["scope"] == "device"
        assert fields["peak_bytes_in_use"] == 900
        assert fields["predicted_peak_bytes"] == 1000
        assert fields["utilization"] == 0.9
        (rec,) = mem.records
        assert rec["kind"] == "memory" and rec["step"] == 5
        assert rec["utilization"] == 0.9

    def test_cpu_path_is_none_not_fake(self):
        """The docs/observability.md caveat: records still flow, every
        watermark field is None, and the metric gauges stay EMPTY —
        a forged 0.0 would poison the sentinel's baselines."""
        mon, mem = self._mon(_CpuLikeDevice(), predicted=_bd(1000))
        fields = mon.sample(1)
        assert fields["peak_bytes_in_use"] is None
        assert fields["utilization"] is None
        assert fields["headroom_breach"] is False
        assert len(mem.records) == 1
        assert mon.metrics_fields() == {}
        s = mon.summary()
        assert s["achieved_peak_bytes"] is None
        assert s["utilization"] is None
        assert s["predicted_peak_bytes"] == 1000

    def test_breach_fires_inside_the_guard_band(self):
        # watermark 900 vs capacity 1000 at 10% headroom: 900 > 900 is
        # False — exactly ON the band is NOT a breach
        mon, mem = self._mon(_TpuLikeDevice(peak=900), capacity_bytes=1000)
        assert not mon.sample(1)["headroom_breach"]
        assert mon.breaches == 0
        # one byte past the band breaches
        mon2, mem2 = self._mon(_TpuLikeDevice(peak=901), capacity_bytes=1000)
        fields = mon2.sample(2)
        assert fields["headroom_breach"] is True
        assert mon2.breaches == 1
        (rec,) = mem2.records
        assert rec["headroom_breach"] is True

    def test_allocator_limit_is_the_default_capacity(self):
        mon, _ = self._mon(_TpuLikeDevice(peak=950, limit=1000))
        assert mon.sample(1)["headroom_breach"] is True

    def test_metrics_fields_expose_the_csv_gauges(self):
        mon, _ = self._mon(_TpuLikeDevice(), predicted=_bd(1000))
        mon.sample(1)
        assert mon.metrics_fields() == {
            "peak_hbm_bytes": 900, "hbm_utilization": 0.9,
        }

    def test_maybe_sample_anchors_then_paces(self):
        mon, mem = self._mon(_TpuLikeDevice(), interval_steps=10)
        assert mon.maybe_sample(0) is None      # anchor, no sample
        assert mon.maybe_sample(5) is None      # inside the interval
        assert mon.maybe_sample(10) is not None
        assert mon.maybe_sample(11) is None     # re-anchored at 10
        assert len(mem.records) == 1

    def test_validation(self):
        router = MetricRouter([MemorySink()])
        with pytest.raises(ValueError, match="interval_steps"):
            HbmWatermarkMonitor(router, interval_steps=0)
        with pytest.raises(ValueError, match="headroom_fraction"):
            HbmWatermarkMonitor(router, headroom_fraction=1.0)


# ---------------------------------------------------------------------------
# KV-pool occupancy arithmetic


class TestKvPoolFields:
    def test_pins(self):
        # 6 of 8 blocks used, 4 slots each = 24 reserved token slots;
        # 18 live -> fragmentation 6/24 = 0.25
        f = kv_pool_fields(num_blocks=8, free_blocks=2, block_size=4,
                           live_tokens=18)
        assert f["scope"] == "kv_pool"
        assert f["used_blocks"] == 6 and f["occupancy"] == 0.75
        assert abs(f["fragmentation"] - 0.25) < 1e-12
        assert "kv_pool_peak_blocks" not in f

    def test_empty_pool_is_zero_not_nan(self):
        f = kv_pool_fields(num_blocks=8, free_blocks=8, block_size=4,
                           live_tokens=0)
        assert f["occupancy"] == 0.0 and f["fragmentation"] == 0.0

    def test_peak_rides_when_given(self):
        f = kv_pool_fields(num_blocks=8, free_blocks=4, block_size=4,
                           live_tokens=16, peak_used_blocks=7)
        assert f["kv_pool_peak_blocks"] == 7
        # fully-packed blocks: zero tail waste
        assert f["fragmentation"] == 0.0

    def test_overfull_free_list_refused(self):
        with pytest.raises(ValueError, match="exceeds"):
            kv_pool_fields(num_blocks=4, free_blocks=5, block_size=4,
                           live_tokens=0)


class TestAllocatorPeak:
    def test_high_water_mark_survives_frees(self):
        from apex_tpu.serving.kvcache import BlockAllocator

        alloc = BlockAllocator(8)
        assert alloc.peak_used_blocks == 0
        a = alloc.alloc(5)
        assert alloc.peak_used_blocks == 5
        alloc.free(a)
        assert alloc.used_blocks == 0
        assert alloc.peak_used_blocks == 5     # the mark does not recede
        alloc.alloc(3)
        assert alloc.peak_used_blocks == 5     # below the mark: unchanged


# ---------------------------------------------------------------------------
# OOM forensics at the boundary


class TestOomGuard:
    def test_exactly_one_record_and_reraise(self):
        mem = MemorySink()
        router = MetricRouter([mem])
        bd = _bd(500, capacity=400)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            with oom_guard(router, 9, breakdown=bd, capacity_bytes=400):
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory allocating 640 bytes"
                )
        ooms = [r for r in mem.records if r["kind"] == "oom"]
        assert len(ooms) == 1
        (inc,) = read_oom_records(mem.records)
        assert inc.step == 9 and inc.phase == "execute"
        assert inc.predicted_peak_bytes == 500
        assert inc.capacity_bytes == 400
        assert inc.components == {"weights": 500}
        # every suggestion names a REAL repo knob
        knobs = inc.suggested_knobs()
        assert "--micro-batch" in knobs and "num_blocks" in knobs
        # the dominant component's knob ranks first
        assert inc.suggestions[0]["component"] == "weights"

    def test_non_oom_exceptions_pass_untouched(self):
        mem = MemorySink()
        router = MetricRouter([mem])
        with pytest.raises(KeyError):
            with oom_guard(router, 1):
                raise KeyError("not a memory problem")
        assert not mem.records

    def test_clean_body_emits_nothing(self):
        mem = MemorySink()
        router = MetricRouter([mem])
        with oom_guard(router, 1):
            pass
        assert not mem.records


# ---------------------------------------------------------------------------
# router schema: the new kinds and gauges


class TestRouterSchema:
    def test_stdout_sink_skips_the_firehose(self):
        buf = io.StringIO()
        router = MetricRouter([StdoutSink(stream=buf)])
        router.event("memory", 1, scope="device", bytes_in_use=5)
        router.event("oom", 1, phase="execute", error="x")
        router.metrics(1, loss=0.5)
        out = buf.getvalue()
        assert "memory" not in out and "oom" not in out
        assert "step     1" in out

    def test_csv_sink_tolerates_the_watermark_gauges(self, tmp_path, caplog):
        """A CSV whose header froze before the x-ray existed must
        resume cleanly when the schema grows the gauges — silently
        dropped, not surfaced through the router's isolation log."""
        import logging

        path = tmp_path / "m.csv"
        sink = CsvSink(str(path))
        router = MetricRouter([sink])
        with caplog.at_level(logging.WARNING, "apex_tpu.monitor.router"):
            router.metrics(1, loss=0.5)        # header frozen: t,step,loss
            router.metrics(
                2, loss=0.4, peak_hbm_bytes=900, hbm_utilization=0.9
            )
        router.close()
        rows = path.read_text().strip().splitlines()
        assert len(rows) == 3                   # header + 2 records
        assert "peak_hbm_bytes" not in rows[0]
        assert not caplog.records                # dropped, not isolated


# ---------------------------------------------------------------------------
# remediation: the memory case


class TestRemediationMemoryCase:
    def _controller(self):
        from apex_tpu.resilience.remediation import (
            RemediationController, RemediationPolicy,
        )

        return RemediationController(
            policy=RemediationPolicy(), router=None, save_dir=None,
            world_devices=8,
        )

    def test_plain_watermark_rows_open_nothing(self):
        from apex_tpu.monitor.router import make_record

        ctrl = self._controller()
        rec = make_record("memory", 5, scope="device",
                          headroom_breach=False)
        assert ctrl.observe(rec) is None
        assert not ctrl.open_cases

    def test_breach_opens_one_observe_case(self):
        from apex_tpu.monitor.router import make_record
        from apex_tpu.resilience.remediation import RemediationPolicy

        ctrl = self._controller()
        rec = make_record("memory", 5, scope="device", headroom_breach=True,
                          bytes_in_use=901, capacity_bytes=1000)
        case = ctrl.observe(rec)
        assert case is not None and case["kind"] == "memory"
        # a repeat breach attaches as evidence, not a second case
        ctrl.observe(make_record("memory", 6, headroom_breach=True))
        assert len(ctrl.open_cases) == 1
        assert len(case["evidence"]) == 2
        # restarting cannot shrink a footprint: the response is observe
        assert RemediationPolicy().response_for("memory") == "observe"


# ---------------------------------------------------------------------------
# the serving engine's pool rows (tick-cadence integration)


def test_engine_emits_kv_pool_rows_and_peak():
    """End to end through a REAL engine: ``memory_interval_ticks=1``
    lands one scope="kv_pool" record per tick, the occupancy matches
    the allocator, and ``stats()`` exposes the pool high-water mark."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTModel
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer import TransformerConfig

    tcfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4,
        vocab_size=37, max_position_embeddings=0,
        position_embedding_type="rope", hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    model = GPTModel(config=tcfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4), jnp.int32))
    mem = MemorySink()
    router = MetricRouter([mem])
    cfg = ServingConfig(lanes=2, block_size=8, num_blocks=4,
                        max_seq_len=16, prefill_buckets=(8,), seed=0,
                        memory_interval_ticks=1)
    eng = ServingEngine(model, variables, cfg, router=router)
    eng.start()
    try:
        eng.submit(np.array([1, 2, 3], np.int32), max_new_tokens=6)
        n = 0
        while not eng.idle and n < 60:
            eng.tick()
            n += 1
    finally:
        router.close()
    rows = [r for r in mem.records
            if r["kind"] == "memory" and r.get("scope") == "kv_pool"]
    assert rows, "no kv_pool rows on a 1-tick cadence"
    for r in rows:
        assert r["used_blocks"] + r["free_blocks"] == cfg.num_blocks
        assert 0.0 <= r["fragmentation"] <= 1.0
        assert r["kv_pool_peak_blocks"] >= r["used_blocks"]
    # the request reserved blocks at some point, and stats carries the mark
    stats = eng.stats()
    assert stats["kv_pool_peak_blocks"] >= 1
    assert max(r["used_blocks"] for r in rows) >= 1


def test_memory_interval_validation():
    from apex_tpu.serving import ServingConfig

    with pytest.raises(ValueError, match="memory_interval_ticks"):
        ServingConfig(lanes=1, block_size=8, num_blocks=4, max_seq_len=16,
                      prefill_buckets=(8,), memory_interval_ticks=0)
    # None disables the cadence entirely
    cfg = ServingConfig(lanes=1, block_size=8, num_blocks=4, max_seq_len=16,
                        prefill_buckets=(8,), memory_interval_ticks=None)
    assert cfg.memory_interval_ticks is None
