"""Spatial-parallel bottleneck tests.

Mirrors the reference's bottleneck/halo tests (apex/contrib/test/bottleneck,
peer_memory halo-exchange tests): the spatially-split block must reproduce
the unsharded block exactly, including BN batch statistics and strides.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib import Bottleneck, SpatialBottleneck, halo_exchange_1d
from apex_tpu.parallel import parallel_state

N, H, W, C = 2, 16, 8, 8
SP = 4  # spatial shards


def spatial_mesh():
    return parallel_state.initialize_model_parallel(
        context_parallel_size=SP, devices=jax.devices()[:SP]
    )


class TestHaloExchange:
    def test_halo_rows(self, rng):
        mesh = spatial_mesh()
        x = jax.random.normal(rng, (N, H, W, C), jnp.float32)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp"),
            check_vma=False,
        )
        def run(x):
            h = halo_exchange_1d(x, "cp", halo=1)
            # drop the halos again so output shape matches the input spec;
            # return the halos folded into rows for checking
            return h[:, 1:-1] + 0.0 * h[:, :1] + 0.0 * h[:, -1:]

        np.testing.assert_allclose(run(x), x, rtol=1e-6)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P(None, "cp"), out_specs=P(None, "cp"),
            check_vma=False,
        )
        def halos(x):
            h = halo_exchange_1d(x, "cp", halo=1)
            return jnp.concatenate([h[:, :1], h[:, -1:]], axis=1)

        got = np.asarray(halos(x))  # per shard: (N, 2, W, C) stacked on H
        h_local = H // SP
        for r in range(SP):
            top, bot = got[:, 2 * r], got[:, 2 * r + 1]
            want_top = (
                np.zeros_like(top) if r == 0 else np.asarray(x)[:, r * h_local - 1]
            )
            want_bot = (
                np.zeros_like(bot)
                if r == SP - 1
                else np.asarray(x)[:, (r + 1) * h_local]
            )
            np.testing.assert_allclose(top, want_top, rtol=1e-6)
            np.testing.assert_allclose(bot, want_bot, rtol=1e-6)


class TestSpatialBottleneck:
    @pytest.mark.parametrize("stride", [1, 2])
    @pytest.mark.parametrize("train", [False, True])
    def test_matches_unsharded(self, rng, stride, train):
        mesh = spatial_mesh()
        x = jax.random.normal(rng, (N, H, W, C), jnp.float32)
        ref_mod = Bottleneck(
            in_channels=C, bottleneck_channels=4, out_channels=16, stride=stride
        )
        variables = ref_mod.init(rng, x, train=True)
        ref_out = ref_mod.apply(
            variables, x, train=train, mutable=["batch_stats"] if train else False
        )
        if train:
            ref_out, ref_stats = ref_out

        sp_mod = SpatialBottleneck(
            in_channels=C, bottleneck_channels=4, out_channels=16, stride=stride
        )

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(None, "cp")),
            out_specs=P(None, "cp") if not train else (P(None, "cp"), P()),
            check_vma=False,
        )
        def run(variables, x):
            if train:
                out, mut = sp_mod.apply(
                    variables, x, train=True, mutable=["batch_stats"]
                )
                return out, mut["batch_stats"]
            return sp_mod.apply(variables, x, train=False)

        got = run(variables, x)
        if train:
            got, got_stats = got
            # synced BN batch stats must equal the global-batch stats
            for k in ref_stats["batch_stats"]:
                for s in ("mean", "var"):
                    np.testing.assert_allclose(
                        got_stats[k][s],
                        ref_stats["batch_stats"][k][s],
                        rtol=1e-4,
                        atol=1e-5,
                    )
        np.testing.assert_allclose(got, ref_out, rtol=2e-4, atol=2e-5)

    def test_gradients_flow_through_halo(self, rng):
        mesh = spatial_mesh()
        x = jax.random.normal(rng, (N, H, W, C), jnp.float32)
        sp_mod = SpatialBottleneck(
            in_channels=C, bottleneck_channels=4, out_channels=16
        )
        ref_mod = Bottleneck(
            in_channels=C, bottleneck_channels=4, out_channels=16
        )
        variables = ref_mod.init(rng, x, train=True)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P(None, "cp")),
            out_specs=P(None, "cp"), check_vma=False,
        )
        def grad_x(variables, x):
            def loss(x):
                o = sp_mod.apply(variables, x, train=False)
                l = jnp.sum(o**2)
                return l + jax.lax.stop_gradient(jax.lax.psum(l, "cp") - l)

            return jax.grad(loss)(x)

        def ref_loss(x):
            return jnp.sum(ref_mod.apply(variables, x, train=False) ** 2)

        np.testing.assert_allclose(
            grad_x(variables, x), jax.grad(ref_loss)(x), rtol=2e-3, atol=1e-4
        )
