"""Contrib zoo tests vs naive reference compositions.

Mirrors the reference's contrib test style (apex/contrib/test/*: fused op
vs a plain composition oracle): each fused TPU op is checked against an
independent numpy/jnp implementation, including gradients where the
reference hand-writes a backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib import (
    GroupNorm,
    SoftmaxCrossEntropyLoss,
    TransducerJoint,
    TransducerLoss,
    focal_loss,
    group_norm,
    index_mul_2d,
    transducer_joint,
    transducer_loss,
)


class TestFocalLoss:
    def naive(self, logits, targets, num_pos, num_real, alpha, gamma, smoothing):
        """Straight per-cell loop of the published sigmoid focal loss."""
        n, k = logits.shape
        total = 0.0
        for i in range(n):
            y = int(targets[i])
            if y == -2:
                continue
            for j in range(min(k, num_real)):
                p = float(logits[i, j])
                sigma = 1.0 / (1.0 + np.exp(-p))
                pos = y >= 0 and j == y
                # binary-cell smoothing with K=2 (focal_loss_cuda_kernel.cu:29)
                t = (1.0 - smoothing / 2) if pos else smoothing / 2
                bce = -t * np.log(sigma) - (1.0 - t) * np.log(1.0 - sigma)
                w = alpha * (1 - sigma) ** gamma if pos else (1 - alpha) * sigma**gamma
                total += w * bce
        return total / num_pos

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_naive(self, rng, smoothing):
        n, k, num_real = 16, 8, 6
        logits = jax.random.normal(rng, (n, k), jnp.float32) * 2.0
        targets = jax.random.randint(
            jax.random.fold_in(rng, 1), (n,), -2, num_real
        )
        num_pos = float(jnp.sum(targets >= 0).clip(1))
        got = focal_loss(logits, targets, num_pos, num_real, 0.25, 2.0, smoothing)
        want = self.naive(
            np.asarray(logits), np.asarray(targets), num_pos, num_real,
            0.25, 2.0, smoothing,
        )
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_grad_is_finite_and_ignores_masked(self, rng):
        n, k = 8, 4
        logits = jax.random.normal(rng, (n, k))
        targets = jnp.array([0, 1, -1, -2, 2, -1, 3, -2])
        g = jax.grad(
            lambda l: focal_loss(l, targets, 4.0, k, 0.25, 2.0)
        )(logits)
        assert bool(jnp.all(jnp.isfinite(g)))
        # ignored anchors (-2) receive exactly zero gradient
        np.testing.assert_array_equal(g[3], 0.0)
        np.testing.assert_array_equal(g[7], 0.0)


class TestGroupNorm:
    def test_matches_manual(self, rng):
        x = jax.random.normal(rng, (2, 4, 4, 8), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(rng, 1), (8,)) + 1.0
        b = jax.random.normal(jax.random.fold_in(rng, 2), (8,))
        got = group_norm(x, num_groups=2, weight=w, bias=b)
        # manual: normalize over (H, W, C/G) per group
        xr = np.asarray(x).reshape(2, 4 * 4, 2, 4)
        mean = xr.mean(axis=(1, 3), keepdims=True)
        var = xr.var(axis=(1, 3), keepdims=True)
        normed = ((xr - mean) / np.sqrt(var + 1e-5)).reshape(2, 4, 4, 8)
        want = normed * np.asarray(w) + np.asarray(b)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_swish_fusion_and_module(self, rng):
        x = jax.random.normal(rng, (2, 4, 4, 8), jnp.float32)
        mod = GroupNorm(num_groups=4, num_channels=8, act="swish")
        params = mod.init(rng, x)
        got = mod.apply(params, x)
        base = group_norm(x, 4)  # fresh params are identity affine
        want = base * jax.nn.sigmoid(base)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_bf16_stats_in_fp32(self, rng):
        x = (jax.random.normal(rng, (2, 8, 8, 16)) * 100).astype(jnp.bfloat16)
        y = group_norm(x, num_groups=4)
        assert y.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.isfinite(y.astype(jnp.float32))))


class TestIndexMul2d:
    def test_forward_backward(self, rng):
        in1 = jax.random.normal(rng, (5, 16))
        in2 = jax.random.normal(jax.random.fold_in(rng, 1), (12, 16))
        idx = jax.random.randint(jax.random.fold_in(rng, 2), (12,), 0, 5)
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(out, np.asarray(in1)[np.asarray(idx)] * in2)

        def loss(a, b):
            return jnp.sum(index_mul_2d(a, b, idx) ** 2)

        da, db = jax.grad(loss, argnums=(0, 1))(in1, in2)
        # scatter-add check: d_in1[r] = sum over i with idx[i]==r of 2*out*in2
        ref_da = np.zeros_like(np.asarray(in1))
        o = np.asarray(out)
        for i, r in enumerate(np.asarray(idx)):
            ref_da[r] += 2 * o[i] * np.asarray(in2)[i]
        np.testing.assert_allclose(da, ref_da, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            db, 2 * o * np.asarray(in1)[np.asarray(idx)], rtol=1e-5, atol=1e-5
        )


def naive_transducer_loss(x, label, f_len, y_len, blank_idx):
    """Direct port of the Graves alpha recursion (independent loop impl)."""
    x = np.asarray(x, np.float64)
    lp = x - np.log(np.sum(np.exp(x - x.max(-1, keepdims=True)), -1, keepdims=True)) \
        - x.max(-1, keepdims=True)
    B = x.shape[0]
    losses = []
    for bi in range(B):
        T, U = int(f_len[bi]), int(y_len[bi]) + 1
        alpha = np.full((T, U), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(1, T):
            alpha[t, 0] = alpha[t - 1, 0] + lp[bi, t - 1, 0, blank_idx]
        for u in range(1, U):
            alpha[0, u] = alpha[0, u - 1] + lp[bi, 0, u - 1, label[bi, u - 1]]
        for t in range(1, T):
            for u in range(1, U):
                a = alpha[t - 1, u] + lp[bi, t - 1, u, blank_idx]
                c = alpha[t, u - 1] + lp[bi, t, u - 1, label[bi, u - 1]]
                alpha[t, u] = np.logaddexp(a, c)
        losses.append(-(alpha[T - 1, U - 1] + lp[bi, T - 1, U - 1, blank_idx]))
    return np.array(losses)


class TestTransducer:
    def test_joint_matches_broadcast(self, rng):
        f = jax.random.normal(rng, (2, 5, 8))
        g = jax.random.normal(jax.random.fold_in(rng, 1), (2, 3, 8))
        h = transducer_joint(f, g)
        want = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
        np.testing.assert_allclose(h, want, rtol=1e-6)
        hr = transducer_joint(f, g, relu=True)
        np.testing.assert_allclose(hr, np.maximum(want, 0), rtol=1e-6)

    def test_joint_masks_dont_care(self, rng):
        f = jax.random.normal(rng, (2, 5, 8))
        g = jax.random.normal(jax.random.fold_in(rng, 1), (2, 3, 8))
        f_len = jnp.array([3, 5])
        g_len = jnp.array([2, 3])
        h = transducer_joint(f, g, f_len=f_len, g_len=g_len)
        np.testing.assert_array_equal(np.asarray(h)[0, 3:], 0.0)
        np.testing.assert_array_equal(np.asarray(h)[0, :, 2:], 0.0)
        assert np.abs(np.asarray(h)[1]).min() > 0.0  # full lengths untouched

    def test_loss_matches_naive(self, rng):
        B, T, U, V = 3, 7, 5, 6
        blank = V - 1
        x = jax.random.normal(rng, (B, T, U, V), jnp.float32)
        label = jax.random.randint(jax.random.fold_in(rng, 1), (B, U - 1), 0, blank)
        f_len = jnp.array([7, 5, 6])
        y_len = jnp.array([4, 2, 3])
        got = transducer_loss(x, label, f_len, y_len, blank)
        want = naive_transducer_loss(
            np.asarray(x), np.asarray(label), np.asarray(f_len),
            np.asarray(y_len), blank,
        )
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_loss_grad_finite_and_localized(self, rng):
        B, T, U, V = 2, 5, 4, 5
        x = jax.random.normal(rng, (B, T, U, V), jnp.float32)
        label = jax.random.randint(jax.random.fold_in(rng, 1), (B, U - 1), 0, 4)
        f_len = jnp.array([5, 4])
        y_len = jnp.array([3, 2])

        g = jax.grad(lambda x: jnp.mean(transducer_loss(x, label, f_len, y_len, 4)))(x)
        assert bool(jnp.all(jnp.isfinite(g)))
        # cells outside (f_len, y_len+1) must have zero gradient
        np.testing.assert_array_equal(np.asarray(g)[1, 4:], 0.0)
        np.testing.assert_array_equal(np.asarray(g)[1, :, 3:], 0.0)

    def test_module_forms(self, rng):
        with pytest.raises(NotImplementedError):
            TransducerJoint(pack_output=True)
        with pytest.raises(NotImplementedError):
            TransducerLoss(packed_input=True)
        f = jax.random.normal(rng, (1, 3, 4))
        g = jax.random.normal(rng, (1, 2, 4))
        assert TransducerJoint()(f, g).shape == (1, 3, 2, 4)


class TestContribXentropy:
    def test_padding_zeroed(self, rng):
        logits = jax.random.normal(rng, (6, 10))
        labels = jnp.array([0, 3, 5, 0, 2, 9])
        losses = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1, padding_idx=0)
        assert float(losses[0]) == 0.0 and float(losses[3]) == 0.0
        assert float(losses[1]) > 0.0


class TestConvBiasRelu:
    def test_variants_match_composition(self, rng):
        from apex_tpu.contrib import (
            conv_bias,
            conv_bias_mask_relu,
            conv_bias_relu,
            conv_frozen_scale_bias_relu,
        )

        x = jax.random.normal(rng, (2, 8, 8, 4), jnp.float32)
        w = jax.random.normal(jax.random.fold_in(rng, 1), (3, 3, 4, 6)) * 0.3
        b = jax.random.normal(jax.random.fold_in(rng, 2), (6,))
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + b
        np.testing.assert_allclose(
            conv_bias(x, w, b, padding=1), ref, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            conv_bias_relu(x, w, b, padding=1), np.maximum(ref, 0),
            rtol=1e-4, atol=1e-5,
        )
        mask = (jax.random.uniform(jax.random.fold_in(rng, 3), ref.shape) > 0.5)
        np.testing.assert_allclose(
            conv_bias_mask_relu(x, w, b, mask, padding=1),
            np.maximum(np.asarray(ref) * np.asarray(mask), 0),
            rtol=1e-4, atol=1e-5,
        )
        scale = jnp.ones((6,)) * 2.0
        got = conv_frozen_scale_bias_relu(x, w, scale, b, padding=1)
        np.testing.assert_allclose(
            got, np.maximum((np.asarray(ref) - b) * 2.0 + np.asarray(b), 0),
            rtol=1e-4, atol=1e-5,
        )
        # frozen scale/bias receive no gradient
        g = jax.grad(
            lambda s: jnp.sum(conv_frozen_scale_bias_relu(x, w, s, b, padding=1))
        )(scale)
        np.testing.assert_array_equal(g, 0.0)


class TestGroupBatchNorm2d:
    def test_local_bn_and_fused_relu(self, rng):
        from apex_tpu.contrib import GroupBatchNorm2d

        x = jax.random.normal(rng, (4, 6, 6, 8), jnp.float32)
        mod = GroupBatchNorm2d(num_features=8, fuse_relu=True, axis_names=())
        variables = mod.init(rng, x, train=True)
        y, _ = mod.apply(variables, x, train=True, mutable=["batch_stats"])
        assert float(jnp.min(y)) >= 0.0
        # normalized pre-relu: per-channel mean ~0
        mod2 = GroupBatchNorm2d(num_features=8, axis_names=())
        v2 = mod2.init(rng, x, train=True)
        y2, _ = mod2.apply(v2, x, train=True, mutable=["batch_stats"])
        np.testing.assert_allclose(
            np.asarray(y2).mean(axis=(0, 1, 2)), 0.0, atol=1e-5
        )

    def test_add_relu_residual(self, rng):
        from apex_tpu.contrib import GroupBatchNorm2d

        x = jax.random.normal(rng, (2, 4, 4, 8), jnp.float32)
        z = jax.random.normal(jax.random.fold_in(rng, 1), (2, 4, 4, 8))
        mod = GroupBatchNorm2d(num_features=8, fuse_relu=True, axis_names=())
        variables = mod.init(rng, x, train=True)
        y, _ = mod.apply(variables, x, z=z, train=True, mutable=["batch_stats"])
        plain = GroupBatchNorm2d(num_features=8, axis_names=())
        base, _ = plain.apply(variables, x, train=True, mutable=["batch_stats"])
        np.testing.assert_allclose(y, np.maximum(np.asarray(base) + z, 0),
                                   rtol=1e-5, atol=1e-6)
        # residual without fuse_relu is rejected (ref: batch_norm.py:197)
        bad = GroupBatchNorm2d(num_features=8, axis_names=())
        with pytest.raises(AssertionError):
            bad.apply(variables, x, z=z, train=True, mutable=["batch_stats"])
