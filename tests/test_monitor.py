"""Telemetry subsystem (apex_tpu.monitor): in-step MetricBag, router
fan-out, FLOPs/MFU arithmetic, stall watchdog, profiler trigger, and the
registered-taps lint that keeps ``sow`` names from drifting.

The load-bearing contract is the fetch cadence: metrics cross
device->host ONCE per log interval (through the relay each crossing is a
~73 ms round-trip, utils/benchmarking.py), so the bag tests count actual
fetches via ``monitor.host_fetch_count`` instead of trusting comments.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P



class TestMetricBag:
    SPEC = {"loss": "mean", "skips": "sum", "scale": "last", "peak": "max"}

    def _filled(self):
        bag = monitor.metric_bag(self.SPEC)
        for v in (1.0, 2.0, 6.0):
            bag = bag.add(
                loss=v, skips=float(v > 1), scale=2 * v, peak=v
            )
        return bag

    def test_mode_math(self):
        vals = monitor.read_bag(self._filled())
        assert vals == {"loss": 3.0, "skips": 2.0, "scale": 12.0, "peak": 6.0}

    def test_unknown_metric_raises(self):
        bag = monitor.metric_bag(self.SPEC)
        with pytest.raises(KeyError, match="lss"):
            bag.add(lss=1.0)

    def test_non_scalar_raises(self):
        bag = monitor.metric_bag(self.SPEC)
        with pytest.raises(ValueError, match="scalar"):
            bag.add(loss=jnp.ones((2,)))

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="modes"):
            monitor.metric_bag({"x": "median"})

    def test_empty_bag_reads_none(self):
        # mean of zero add() calls is 0/0 and max of none is -inf: both
        # must surface as None (json null), never as a fake number
        vals = monitor.read_bag(monitor.metric_bag(self.SPEC))
        assert vals["loss"] is None and vals["peak"] is None

    def test_omitted_metric_semantics(self):
        bag = monitor.metric_bag(self.SPEC).add(scale=4.0)
        vals = monitor.read_bag(bag)
        assert vals["scale"] == 4.0
        # per-metric fold counts: metrics this add() omitted read None
        # (no folds), not a diluted or fake number
        assert vals["loss"] is None
        assert vals["peak"] is None

    def test_non_finite_values_excluded(self):
        """A NaN-poisoned step must not null the whole interval: the
        non-finite fold is dropped and the mean covers the finite steps
        (the anomaly itself is the sentinel's/skip-counter's story)."""
        bag = monitor.metric_bag(self.SPEC)
        bag = bag.add(loss=1.0, scale=2.0, peak=1.0, skips=0.0)
        bag = bag.add(loss=jnp.float32(jnp.nan), scale=jnp.float32(jnp.inf),
                      peak=jnp.float32(jnp.nan), skips=1.0)
        bag = bag.add(loss=3.0, scale=4.0, peak=2.0, skips=0.0)
        vals = monitor.read_bag(bag)
        assert vals["loss"] == 2.0      # mean of the two finite folds
        assert vals["scale"] == 4.0     # inf did not overwrite the gauge
        assert vals["peak"] == 2.0
        assert vals["skips"] == 1.0
        # all-non-finite still reads None, not 0
        nan_only = monitor.metric_bag(self.SPEC).add(
            loss=jnp.float32(jnp.nan)
        )
        assert monitor.read_bag(nan_only)["loss"] is None

    def test_reset_and_reuse(self):
        bag = monitor.reset_bag(self._filled())
        assert int(bag.count) == 0
        vals = monitor.read_bag(bag.add(loss=5.0))
        assert vals["loss"] == 5.0  # no leakage from before the reset

    def test_merge(self):
        a = monitor.metric_bag(self.SPEC).add(loss=1.0, peak=1.0)
        b = monitor.metric_bag(self.SPEC).add(loss=3.0, peak=9.0, scale=7.0)
        vals = monitor.read_bag(a.merge(b))
        # skips got zero folds in either bag -> None, same as unmerged
        assert vals == {"loss": 2.0, "skips": None, "scale": 7.0, "peak": 9.0}

    def test_merge_spec_mismatch_raises(self):
        a = monitor.metric_bag({"x": "mean"})
        b = monitor.metric_bag({"y": "mean"})
        with pytest.raises(ValueError, match="specs"):
            a.merge(b)

    def test_one_fetch_per_interval_under_jit(self):
        """The acceptance contract: a donated bag threads through a jitted
        step for N steps with exactly N/interval host fetches."""

        @jax.jit
        def step(bag, x):
            return bag.add(loss=x, skips=0.0, scale=1.0, peak=x)

        bag = monitor.metric_bag(self.SPEC)
        interval, steps, reads = 4, 12, []
        before = monitor.host_fetch_count()
        for i in range(steps):
            bag = step(bag, jnp.float32(i))
            if (i + 1) % interval == 0:
                reads.append(monitor.read_bag(bag))
                bag = monitor.reset_bag(bag)
        assert monitor.host_fetch_count() - before == steps // interval
        assert [r["loss"] for r in reads] == [1.5, 5.5, 9.5]

    def test_fresh_bag_survives_donation(self):
        """Regression: metric_bag/reset_bag must create DISTINCT buffers
        per metric — a shared zero leaf donated under jit trips XLA's
        'donate the same buffer twice' check (and wedged collectives in
        the GPT example before the fix)."""
        import functools

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))
        replicated = jax.sharding.NamedSharding(mesh, P())

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(bag, x):
            return bag.add(loss=x, skips=0.0, scale=1.0, peak=x)

        bag = jax.device_put(monitor.metric_bag(self.SPEC), replicated)
        bag = step(bag, jnp.float32(1.0))  # raised before the fix
        bag = jax.device_put(monitor.reset_bag(bag), replicated)
        bag = step(bag, jnp.float32(3.0))
        assert monitor.read_bag(bag)["loss"] == 3.0

    def test_bag_inside_shard_map(self):
        """The example wiring: the bag crosses a compat.shard_map boundary
        with replicated specs while the data is dp-sharded."""
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))

        @jax.jit
        @lambda f: shard_map(
            f, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False,
        )
        def step(bag, xs):
            loss = jax.lax.pmean(jnp.mean(xs), "dp")
            return bag.add(loss=loss, skips=0.0, scale=1.0, peak=loss)

        bag = monitor.metric_bag(self.SPEC)
        xs = jnp.arange(16, dtype=jnp.float32)
        bag = step(bag, xs)
        assert monitor.read_bag(bag)["loss"] == pytest.approx(7.5)


class TestGradNormTaps:
    def test_global_grad_norm_matches_numpy(self):
        grads = {"a": jnp.asarray([3.0, 4.0]), "b": {"c": jnp.full((2, 2), 1.0)}}
        flat = np.concatenate([np.array([3.0, 4.0]), np.ones(4)])
        assert float(monitor.global_grad_norm(grads)) == pytest.approx(
            np.linalg.norm(flat)
        )

    def test_empty_tree_is_zero(self):
        assert float(monitor.global_grad_norm({})) == 0.0

    def test_per_layer_norms_key_per_top_level_entry(self):
        grads = {
            "params": {
                "layer_0": {"w": jnp.asarray([3.0, 4.0])},
                "layer_1": {"w": jnp.asarray([6.0, 8.0])},
            }
        }
        norms = monitor.per_layer_grad_norms(grads)
        assert set(norms) == {"grad_norm/layer_0", "grad_norm/layer_1"}
        assert float(norms["grad_norm/layer_0"]) == pytest.approx(5.0)
        assert float(norms["grad_norm/layer_1"]) == pytest.approx(10.0)


class TestRouter:
    def test_fan_out_one_schema(self, tmp_path, capsys):
        jsonl = str(tmp_path / "m.jsonl")
        csvp = str(tmp_path / "m.csv")
        mem = monitor.MemorySink()
        router = monitor.MetricRouter(
            [monitor.JsonlSink(jsonl), monitor.CsvSink(csvp),
             monitor.StdoutSink(), mem]
        )
        router.metrics(4, loss=1.2345678, grad_norm=0.5)
        router.event("skip", 5, loss=99.0, lr_scale=1.0)
        router.close()

        lines = [json.loads(l) for l in open(jsonl)]
        assert [l["kind"] for l in lines] == ["metrics", "skip"]
        assert all({"t", "step", "kind"} <= set(l) for l in lines)
        assert lines == list(mem.records)  # deque-backed (bounded) sink
        csv_rows = open(csvp).read().splitlines()
        assert csv_rows[0].startswith("t,step,kind")
        out = capsys.readouterr().out
        assert "step     4 loss   1.2346" in out
        assert "[skip] step 5" in out

    def test_sink_failure_is_isolated(self, caplog):
        class Bomb(monitor.Sink):
            def emit(self, record):
                raise OSError("disk full")

        mem = monitor.MemorySink()
        router = monitor.MetricRouter([Bomb(), mem])
        router.metrics(1, loss=1.0)  # must not raise
        assert len(mem.records) == 1  # later sinks still served

    def test_csv_header_is_frozen(self, tmp_path):
        csvp = str(tmp_path / "m.csv")
        router = monitor.MetricRouter([monitor.CsvSink(csvp)])
        router.metrics(0, loss=1.0)
        router.metrics(1, loss=2.0, surprise=3.0)  # new column: dropped row
        router.metrics(2, loss=4.0)
        router.close()
        rows = open(csvp).read().splitlines()
        assert len(rows) == 3  # header + steps 0 and 2
        assert "surprise" not in rows[0]

    def test_csv_filters_to_metrics_kind(self, tmp_path):
        csvp = str(tmp_path / "m.csv")
        router = monitor.MetricRouter([monitor.CsvSink(csvp)])
        router.event("timer", 0, name="step-time", seconds=0.1)
        router.metrics(0, loss=1.0)
        router.event("skip", 1, loss=9.0)  # anomaly kinds jsonl-only
        router.metrics(2, loss=2.0)
        router.close()
        rows = open(csvp).read().splitlines()
        # header froze on the first METRICS record, not the timer event
        assert rows[0] == "t,step,kind,host,loss" and len(rows) == 3

    def test_csv_resume_keeps_single_header(self, tmp_path):
        csvp = str(tmp_path / "m.csv")
        first = monitor.CsvSink(csvp)
        first.emit(monitor.make_record("metrics", 0, loss=1.0))
        first.close()
        second = monitor.CsvSink(csvp)  # process restart, same path
        second.emit(monitor.make_record("metrics", 1, loss=2.0))
        second.close()
        rows = open(csvp).read().splitlines()
        assert len(rows) == 3  # ONE header + two data rows
        assert sum(r.startswith("t,step,kind") for r in rows) == 1

    def test_timers_plug_into_router(self):
        from apex_tpu.utils import Timers

        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        timers = Timers(write_fn=router.timer_write_fn)
        timers("fwd").start()
        timers("fwd").stop()
        timers.write(["fwd"], iteration=3)
        (rec,) = mem.records
        assert rec["kind"] == "timer" and rec["step"] == 3
        assert rec["name"] == "fwd-time" and rec["seconds"] >= 0.0

    def test_tensorboard_sink_gated_not_raising(self, tmp_path):
        # whichever way the import probe goes on this box, the gate must
        # return (sink or None) rather than raise
        sink = monitor.try_tensorboard_sink(str(tmp_path / "tb"))
        if sink is not None:
            sink.emit(monitor.make_record("metrics", 1, loss=2.0))
            sink.close()


class TestTimersWriteParity:
    """The reference-parity fix: ``Timers.write`` resets by default, so
    successive writes report per-interval times, not a growing total."""

    def _timer_with(self, timers, name, seconds):
        t = timers(name)
        t.start()
        t.elapsed_ += seconds  # deterministic elapsed; stop() adds ~0
        t.stop()

    def test_write_resets_by_default(self):
        from apex_tpu.utils import Timers

        seen = []
        timers = Timers(write_fn=lambda n, v, it: seen.append(v))
        self._timer_with(timers, "x", 1.0)
        timers.write(["x"], iteration=0)
        self._timer_with(timers, "x", 1.0)
        timers.write(["x"], iteration=1)
        assert seen[0] == pytest.approx(1.0, abs=0.05)
        # the old hard-coded reset=False accumulated: ~2.0 here
        assert seen[1] == pytest.approx(1.0, abs=0.05)

    def test_write_reset_false_accumulates(self):
        from apex_tpu.utils import Timers

        seen = []
        timers = Timers(write_fn=lambda n, v, it: seen.append(v))
        self._timer_with(timers, "x", 1.0)
        timers.write(["x"], iteration=0, reset=False)
        self._timer_with(timers, "x", 1.0)
        timers.write(["x"], iteration=1, reset=False)
        assert seen[1] == pytest.approx(2.0, abs=0.1)

    def test_write_normalizer(self):
        from apex_tpu.utils import Timers

        seen = []
        timers = Timers(write_fn=lambda n, v, it: seen.append(v))
        self._timer_with(timers, "x", 1.0)
        timers.write(["x"], iteration=0, normalizer=4.0)
        assert seen[0] == pytest.approx(0.25, abs=0.05)


def _tiny_cfg(**kw):
    from apex_tpu.transformer import TransformerConfig

    base = dict(
        num_layers=1, hidden_size=4, num_attention_heads=2, vocab_size=8,
        max_position_embeddings=6, ffn_hidden_size=8,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    base.update(kw)
    return TransformerConfig(**base)


class TestFlops:
    """MFU math against FULLY hand-counted tiny configs (2*m*n*k per
    matmul, per token): any change to the counters must re-derive these
    numbers, not nudge them until green."""

    def test_layer_flops_hand_counted(self):
        cfg = _tiny_cfg()
        # h=4, heads=2, head_dim=2, q=kv=4, ffn=8, s=6:
        #   qkv   2*4*(4+2*4) = 96
        #   attn  2*6*4 + 2*6*4 = 96   (scores + context)
        #   out   2*4*4 = 32
        #   mlp   2*(2*4*8) = 128
        assert monitor.transformer_layer_flops_per_token(cfg, 6) == 352.0

    def test_gqa_shrinks_kv_projection(self):
        cfg = _tiny_cfg(num_query_groups=1)
        # kv = 1 group * head_dim 2 = 2: qkv = 2*4*(4+2*2) = 64 (was 96)
        assert monitor.transformer_layer_flops_per_token(cfg, 6) == 320.0

    def test_gated_mlp_costs_third_matmul(self):
        cfg = _tiny_cfg(activation="swiglu", add_bias_linear=False)
        # mlp 2 mats -> 3 mats: 128 -> 192
        assert monitor.transformer_layer_flops_per_token(cfg, 6) == 416.0

    def test_gpt_adds_logit_head(self):
        cfg = _tiny_cfg()
        # layers + 2*h*vocab = 352 + 2*4*8 = 416
        assert monitor.gpt_flops_per_token(cfg, 6) == 416.0
        # seq_len defaults to max_position_embeddings
        assert monitor.gpt_flops_per_token(cfg) == 416.0

    def test_bert_adds_lm_head(self):
        cfg = _tiny_cfg()
        # layers + dense h*h + vocab proj = 352 + 32 + 64 = 448
        assert monitor.bert_flops_per_token(cfg, 6) == 448.0

    def test_training_is_3x_forward(self):
        assert monitor.training_flops_per_step(416.0, 10) == 3 * 4160.0

    def test_tokens_per_second(self):
        assert monitor.tokens_per_second(100, 2.0) == 50.0
        with pytest.raises(ValueError):
            monitor.tokens_per_second(100, 0.0)

    def test_mfu_math_and_unknown_peak(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_PEAK_FLOPS", raising=False)
        assert monitor.mfu(1e12, 1.0, 1, peak_flops=2e12) == pytest.approx(0.5)
        assert monitor.mfu(1e12, 0.5, 4, peak_flops=1e12) == pytest.approx(0.5)
        # CPU devices have no peak entry: None, never a made-up number
        assert monitor.mfu(1e12, 1.0, 1) is None

    def test_peak_env_override(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_PEAK_FLOPS", "123e9")
        assert monitor.peak_flops_per_device() == pytest.approx(123e9)
        assert monitor.mfu(123e9, 1.0, 1) == pytest.approx(1.0)


class TestStallWatchdog:
    def test_fires_once_and_rearms_on_beat(self):
        fired = []
        dog = monitor.StallWatchdog(
            0.1, on_stall=fired.append, poll_s=0.02
        ).start()
        try:
            dog.beat(7)
            time.sleep(0.35)
            assert len(fired) == 1  # one stall, not one per poll
            assert fired[0]["step"] == 7
            assert fired[0]["overdue_s"] > 0.1
            dog.beat(8)  # recovery re-arms
            time.sleep(0.35)
            assert len(fired) == 2 and fired[1]["step"] == 8
        finally:
            dog.stop()

    def test_no_fire_while_beating(self):
        dog = monitor.StallWatchdog(0.3, poll_s=0.02)
        with dog:
            for i in range(8):
                dog.beat(i)
                time.sleep(0.05)
        assert dog.stalls == []

    def test_handler_exception_does_not_kill_dog(self):
        def boom(info):
            raise RuntimeError("handler bug")

        dog = monitor.StallWatchdog(0.05, on_stall=boom, poll_s=0.02).start()
        try:
            time.sleep(0.15)
            dog.beat(1)
            time.sleep(0.15)
            assert len(dog.stalls) == 2  # survived the first handler crash
        finally:
            dog.stop()

    def test_bad_deadline_rejected(self):
        with pytest.raises(ValueError):
            monitor.StallWatchdog(0.0)

    def test_restart_after_stop(self):
        """Regression: stop() left the _stop event set, so a restarted
        watchdog's thread exited immediately and stalls went unflagged."""
        dog = monitor.StallWatchdog(0.08, poll_s=0.02)
        dog.start()
        dog.stop()
        dog.start()  # e.g. pause around a known-slow restore, then resume
        try:
            time.sleep(0.3)
            assert dog.stalls  # the restarted dog is actually alive
        finally:
            dog.stop()


class TestProfilerTrigger:
    def _drive(self, trigger, steps, verdicts=None):
        @jax.jit
        def work(x):
            return (x @ x).sum()

        for i in range(steps):
            trigger.maybe_start(i)
            out = work(jnp.ones((8, 8)))
            jax.block_until_ready(out)
            if verdicts and i in verdicts:
                trigger.on_verdict(i, verdicts[i])
            trigger.maybe_stop(i)
        trigger.close()

    def test_requested_step_writes_capture_dir(self, tmp_path):
        trigger = monitor.ProfilerTrigger(str(tmp_path), window_steps=2)
        trigger.request(step=2, reason="requested")
        self._drive(trigger, 6)
        (cap,) = trigger.captures
        assert cap["start_step"] == 2 and cap["end_step"] == 3
        assert os.path.isdir(cap["path"])
        # a real capture lands files under the dir (plugins/profile/...)
        assert any(files for _, _, files in os.walk(cap["path"]))

    def test_verdict_escalation_triggers_capture(self, tmp_path):
        from apex_tpu.resilience.sentinel import VERDICT_ROLLBACK, VERDICT_SKIP

        trigger = monitor.ProfilerTrigger(str(tmp_path), window_steps=1)
        self._drive(trigger, 6, verdicts={1: VERDICT_SKIP, 3: VERDICT_ROLLBACK})
        (cap,) = trigger.captures  # SKIP must not trigger; ROLLBACK must
        assert cap["start_step"] == 4 and "verdict" in cap["reason"]

    def test_one_capture_at_a_time(self, tmp_path):
        trigger = monitor.ProfilerTrigger(str(tmp_path), window_steps=4)
        trigger.request(step=0)
        trigger.request(step=1)  # ignored: a request is already pending
        self._drive(trigger, 6)
        assert len(trigger.captures) == 1

    def test_anomaly_outranks_scheduled_request(self, tmp_path):
        """Regression: a far-future --profile-step request must not block
        the on-anomaly capture — the blowup happening NOW wins."""
        from apex_tpu.resilience.sentinel import VERDICT_ROLLBACK

        trigger = monitor.ProfilerTrigger(str(tmp_path), window_steps=1)
        trigger.request(step=1000, reason="requested")
        self._drive(trigger, 5, verdicts={2: VERDICT_ROLLBACK})
        (cap,) = trigger.captures
        assert cap["start_step"] == 3 and "verdict" in cap["reason"]


class TestResilienceRouting:
    def test_anomaly_stream_shares_schema_and_old_path(self, tmp_path):
        from apex_tpu import resilience

        log = str(tmp_path / "anomalies.jsonl")
        mem = monitor.MemorySink()
        mgr = resilience.ResilienceManager(
            log_path=log, router=monitor.MetricRouter([mem])
        )
        mgr.resolve(3, resilience.VERDICT_SKIP, loss=9.9)
        mgr.resolve(4, resilience.VERDICT_HALT, loss=11.0)

        # the legacy jsonl path still works, byte-for-byte schema
        lines = [json.loads(l) for l in open(log)]
        assert lines == list(mem.records) == mgr.events
        assert [l["kind"] for l in lines] == ["skip", "halt"]
        assert all({"t", "step", "kind"} <= set(l) for l in lines)


class TestAmpOptimizerMetrics:
    def test_collect_metrics_exposes_grad_norm(self):
        import optax

        from apex_tpu import amp

        params = {"w": jnp.ones((4,), jnp.float32)}
        params, amp_opt, _ = amp.initialize(
            params, optax.sgd(0.1), opt_level="O2"
        )
        state = amp_opt.init(params)
        scale = float(state.scaler.scale)
        grads = {"w": jnp.full((4,), 3.0 * scale, jnp.float16)}
        _, _, info = amp_opt.step(
            grads, state, params, collect_metrics=True
        )
        # norm of the UNSCALED fp32 grads: ||(3,3,3,3)|| = 6
        assert float(info["grad_norm"]) == pytest.approx(6.0, rel=1e-3)

    def test_metrics_off_by_default(self):
        import optax

        from apex_tpu import amp

        params = {"w": jnp.ones((4,), jnp.float32)}
        params, amp_opt, _ = amp.initialize(
            params, optax.sgd(0.1), opt_level="O2"
        )
        state = amp_opt.init(params)
        _, _, info = amp_opt.step(
            {"w": jnp.ones((4,), jnp.float16)}, state, params
        )
        assert "grad_norm" not in info


class TestLayerMetricsTap:
    def test_layer_out_rms_sown_and_readable(self, rng):
        from apex_tpu.transformer.layer import ParallelTransformer

        cfg = _tiny_cfg(num_layers=2, collect_layer_metrics=True)
        model = ParallelTransformer(config=cfg)
        x = jnp.ones((6, 2, 4), cfg.compute_dtype)  # (s, b, h)
        params = model.init(rng, x)
        y, col = model.apply(params, x, mutable=["intermediates"])
        taps = monitor.taps_from_intermediates(col["intermediates"])
        assert "layer_out_rms" in taps
        assert np.isfinite(float(taps["layer_out_rms"]))
        assert float(taps["layer_out_rms"]) > 0.0

    def test_tap_off_by_default(self, rng):
        from apex_tpu.transformer.layer import ParallelTransformer

        cfg = _tiny_cfg(num_layers=1)
        model = ParallelTransformer(config=cfg)
        x = jnp.ones((6, 2, 4), cfg.compute_dtype)
        params = model.init(rng, x)
        _, col = model.apply(params, x, mutable=["intermediates"])
        assert monitor.taps_from_intermediates(col.get("intermediates", {})) == {}


class TestRegisteredTapsLint:
    """Tier-1 drift guard: every ``sow("intermediates", <name>, ...)`` in
    apex_tpu/ must be registered in monitor/taps.py, and every registry
    row must still have a live sow site. THIN WRAPPER: the rule logic
    migrated to the unified AST lint framework
    (apex_tpu.analysis.lint, rule ``lint.registered-taps``); these test
    names are kept so the tier-1 history stays legible."""

    def _findings(self):
        from apex_tpu.analysis import lint

        return lint.run_lint(rules=["lint.registered-taps"])

    def test_every_sown_tap_is_registered(self):
        unregistered = [
            f for f in self._findings() if not f.data.get("stale")
        ]
        assert not unregistered, (
            "sow taps missing from monitor/taps.py REGISTERED_TAPS: "
            + "; ".join(f.format() for f in unregistered)
        )

    def test_every_registered_tap_is_still_sown(self):
        stale = [f for f in self._findings() if f.data.get("stale")]
        assert not stale, (
            "REGISTERED_TAPS entries with no sow site left: "
            + "; ".join(f.format() for f in stale)
        )


class TestRawCollectiveLint:
    """Tier-1 drift guard (the REGISTERED_TAPS pattern, for comms): no
    call site in apex_tpu/ may invoke ``lax.{psum,all_gather,...}``
    directly — every collective goes through the xray ledger wrappers so
    the comms ledger sees ALL of apex_tpu's traffic. THIN WRAPPER over
    apex_tpu.analysis.lint rule ``lint.raw-collective``; the allowlist
    (ledger.py itself) now lives in apex_tpu/analysis/allowlist.py with
    its reason, and staleness is the framework's require_hit check."""

    def _result(self):
        from apex_tpu.analysis import Allowlist, lint
        from apex_tpu.analysis.allowlist import REPO_ALLOWLIST

        fins = lint.run_lint(rules=["lint.raw-collective"])
        rule_entries = [
            e for e in REPO_ALLOWLIST.entries
            if e.rule == "lint.raw-collective"
        ]
        return Allowlist(rule_entries).apply(fins, check_stale=True)

    def test_no_raw_collective_bypasses_the_ledger(self):
        res = self._result()
        assert not res.findings, (
            "raw jax.lax collective call sites bypass the xray comms "
            "ledger (use apex_tpu.monitor.xray.ledger wrappers, or add "
            "an allowlist entry with a reason): "
            + "; ".join(f.format() for f in res.findings)
        )

    def test_allowlist_is_not_stale(self):
        """Every allowlist entry for this rule must still suppress a live
        raw-collective site — otherwise remove it."""
        res = self._result()
        assert not res.stale_entries, (
            "stale lint.raw-collective allowlist entries: "
            + ", ".join(e.match for e in res.stale_entries)
        )


class TestRecordSchemaHost:
    """The ``host`` field (PR 7): every record carries the producing
    process's fleet index so merged multi-host streams stay
    attributable, resolved without importing (or initializing) jax."""

    def test_make_record_defaults_host_zero(self):
        rec = monitor.make_record("metrics", 3, loss=1.0)
        assert set(rec) == {"t", "step", "kind", "host", "loss"}
        assert rec["host"] == 0  # single-process runs are host 0

    def test_env_override_and_explicit_kwarg(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_HOST", "5")
        assert monitor.make_record("span", 0)["host"] == 5
        # an explicit host= (replaying another host's stream) wins
        assert monitor.make_record("span", 0, host=2)["host"] == 2
        monkeypatch.setenv("APEX_TPU_HOST", "not-an-int")
        assert monitor.make_record("span", 0)["host"] == 0

    def test_csv_resume_tolerates_pre_host_header(self, tmp_path):
        """A CSV written before the schema grew ``host`` must resume
        cleanly: the adopted old header lacks the column and the sink
        drops the field instead of rejecting every record."""
        csvp = tmp_path / "m.csv"
        csvp.write_text("t,step,kind,loss\n1.0,0,metrics,1.5\n")
        sink = monitor.CsvSink(str(csvp))
        sink.emit(monitor.make_record("metrics", 1, loss=2.5))
        # a genuinely NEW data column is still rejected (header frozen)
        with pytest.raises(ValueError):
            sink.emit(monitor.make_record("metrics", 2, loss=1.0,
                                          surprise=9.0))
        sink.close()
        rows = open(csvp).read().splitlines()
        assert len(rows) == 3 and "host" not in rows[0]
        assert rows[2].endswith(",2.5")

    def test_stdout_sink_hides_plumbing(self, capsys):
        sink = monitor.StdoutSink()
        sink.emit(monitor.make_record("metrics", 1, loss=1.0))
        # span/run records fire per loop iteration for the accountant,
        # not the console; host is schema plumbing on every kind
        sink.emit(monitor.make_record("span", 1, phase="step", start=0.0,
                                      dur_s=0.1))
        sink.emit(monitor.make_record("run", 0, run_id="r"))
        out = capsys.readouterr().out
        assert "step     1" in out and "host" not in out
        assert "span" not in out and "run_id" not in out

    def test_tensorboard_sink_skips_host_scalar(self, tmp_path):
        tb = monitor.try_tensorboard_sink(str(tmp_path))
        if tb is None:
            pytest.skip("no TensorBoard writer importable")
        calls = []
        tb._writer.add_scalar = lambda *a: calls.append(a)
        tb.emit(monitor.make_record("metrics", 1, loss=1.0))
        assert [c[0] for c in calls] == ["metrics/loss"]


class TestRouterLifecycle:
    """PR 7 satellite: MetricRouter is a context manager with idempotent
    close and a best-effort exit flush, so an abnormal termination can't
    tear buffered records off the stream."""

    def test_context_manager_closes_sinks(self, tmp_path):
        closed = []

        class Tracker(monitor.MemorySink):
            def close(self):
                closed.append(True)

        with monitor.MetricRouter([Tracker()]) as router:
            router.metrics(0, loss=1.0)
        assert closed == [True]

    def test_close_is_idempotent(self):
        closed = []

        class Tracker(monitor.MemorySink):
            def close(self):
                closed.append(True)

        router = monitor.MetricRouter([Tracker()])
        router.close()
        router.close()  # the exit teardown re-closing is a no-op
        assert closed == [True]

    def test_emit_after_close_drops_with_one_warning(self, monkeypatch):
        from apex_tpu.monitor import router as router_mod

        warnings = []
        monkeypatch.setattr(
            router_mod.logger, "warning",
            lambda msg, *args: warnings.append(msg % args),
        )
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        router.close()
        router.metrics(1, loss=1.0)  # daemon thread racing shutdown
        router.metrics(2, loss=2.0)
        assert len(mem.records) == 0
        assert sum("after router close" in w for w in warnings) == 1

    def test_flush_hooks_run_before_routers_close(self):
        from apex_tpu.monitor import router as router_mod

        order = []
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        router_mod.register_flush_hook(
            lambda: order.append("hook") or router.event("span", 0,
                                                         phase="stall"))
        try:
            router_mod._flush_all_routers()
            # the hook's record landed BEFORE the router closed
            assert order == ["hook"]
            assert [r["kind"] for r in mem.records] == ["span"]
            assert router._closed
        finally:
            router_mod._FLUSH_HOOKS.clear()


class TestStallRouting:
    """PR 7 satellite: stalls land in the record stream (kind='stall' +
    a phase='stall' span the goodput accountant books as badput), not
    only in logger.warning and the in-memory list."""

    def test_stall_emits_event_and_span(self):
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        dog = monitor.StallWatchdog(0.08, poll_s=0.02, router=router).start()
        try:
            dog.beat(4)
            time.sleep(0.3)
        finally:
            dog.stop()
        by_kind = {}
        for rec in mem.records:
            by_kind.setdefault(rec["kind"], []).append(rec)
        (stall,) = by_kind["stall"]
        assert stall["step"] == 4 and stall["overdue_s"] > 0.08
        (span_rec,) = by_kind["span"]
        assert span_rec["phase"] == "stall" and span_rec["step"] == 4
        # the span covers the dead time measured from the LAST heartbeat
        assert span_rec["dur_s"] == pytest.approx(stall["overdue_s"])

    def test_profiler_trigger_router_records_capture(self, tmp_path):
        mem = monitor.MemorySink()
        router = monitor.MetricRouter([mem])
        trigger = monitor.ProfilerTrigger(str(tmp_path), window_steps=2,
                                          router=router)
        trigger.request(step=1, reason="requested")

        @jax.jit
        def work(x):
            return (x @ x).sum()

        for i in range(4):
            trigger.maybe_start(i)
            jax.block_until_ready(work(jnp.ones((8, 8))))
            trigger.maybe_stop(i)
        trigger.close()
        (rec,) = [r for r in mem.records if r["kind"] == "profile"]
        assert rec["step"] == 1 and rec["end_step"] == 2
        assert rec["reason"] == "requested" and os.path.isdir(rec["path"])


class TestMemorySinkKinds:
    def test_kinds_filter_keeps_window_for_the_consumer(self):
        # the examples' goodput window: metrics/timer traffic must not
        # evict the run header and spans the accountant needs
        mem = monitor.MemorySink(max_records=4, kinds=("run", "span"))
        mem.emit(monitor.make_record("run", 0, run_id="r"))
        for i in range(100):
            mem.emit(monitor.make_record("metrics", i, loss=1.0))
        mem.emit(monitor.make_record("span", 1, phase="step"))
        assert [r["kind"] for r in mem.records] == ["run", "span"]

    def test_default_keeps_everything(self):
        mem = monitor.MemorySink()
        mem.emit(monitor.make_record("metrics", 0, loss=1.0))
        mem.emit(monitor.make_record("span", 0, phase="step"))
        assert len(mem.records) == 2
