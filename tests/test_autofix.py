"""Autofix subsystem (apex_tpu.analysis.autofix): prescriptions derived
from pass findings, applied to library step builders, audited to a
fixpoint.

The seeded fixture is ``targets.gpt_zero_naive_step_target()`` — the
arXiv:2004.13336 baseline anti-pattern (fully replicated flat Adam
state, full-payload grad allreduce, defensive param-resync allreduce,
nothing donated). The pins here are the PR's acceptance criteria:

- derived PartitionSpecs leaf-for-leaf on the seeded target,
- ``apply_fixes`` reaches a clean fixpoint in one round and applying
  twice changes nothing (idempotence),
- the clean gpt target derives ZERO prescriptions (negative control),
- the predict_comms dp-axis ledger numbers digit-for-digit: the naive
  weight-update wire bytes drop by exactly the dp (ZeRO) factor,
- the CLI ``--fix`` wrapper (exit 0, allowlisted prescription records
  with machine-applicable fix= payloads, sentinel-gated bench twin).
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import pytest

from jax.sharding import PartitionSpec as P

from apex_tpu.analysis.allowlist import repo_allowlist
from apex_tpu.analysis.autofix import (
    KIND_CONSTRAINT,
    KIND_DONATE,
    KIND_SPEC,
    Patch,
    apply_fixes,
    derive_patches,
    render_user_diff,
    update_axis,
)
from apex_tpu.analysis.autofix.apply import _merge_overrides, _run_suite
from apex_tpu.analysis.targets import (
    FIXABLE_TARGETS,
    dp2tp2_mesh,
    gpt_step_target,
    gpt_zero_naive_step_target,
)
from apex_tpu.monitor.xray.ledger import predict_comms

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the seeded target's flat Adam buffers: 65536 f32 elements (the
# flatten_pytree chunk multiple), 262144 B each — every ledger pin below
# is arithmetic over this one number plus the 4-byte loss pmean
FLAT_BYTES = 65536 * 4
LOSS_BYTES = 4
DP = 2


@pytest.fixture(autouse=True)
def _dp2tp2_parallel_state():
    """conftest's autouse reset destroys the global parallel_state after
    EVERY test, but the module-scoped cached targets' flax modules read
    tp sizes from it at trace time — re-establish the audit topology
    before each test (cheap: no compile, just the mesh bookkeeping)."""
    dp2tp2_mesh()
    yield


@pytest.fixture(scope="module")
def naive_audit():
    """One audited seeded target, shared: (target, findings, ledger)."""
    target = gpt_zero_naive_step_target(dp2tp2_mesh())
    kept, _ctx, ledger = _run_suite(target, None, repo_allowlist())
    return target, kept, ledger


@pytest.fixture(scope="module")
def naive_report(naive_audit):
    # module-scoped fixtures instantiate BEFORE the function-scoped
    # autouse topology fixture, i.e. right after the previous test's
    # parallel_state teardown — re-establish it here too
    dp2tp2_mesh()
    target, _, _ = naive_audit
    return apply_fixes(target, allowlist=repo_allowlist())


# ---------------------------------------------------------------------------
# derivation: findings -> Patches, leaf for leaf


class TestDerivation:
    def test_seeded_target_prescriptions_leaf_for_leaf(self, naive_audit):
        target, kept, ledger = naive_audit
        patches = derive_patches(
            target, kept, mesh=target.mesh, ledger=ledger
        )
        by_key = {(p.kind, p.argnum): p for p in patches}
        # exactly m and v, each flagged twice (replication + donation):
        # nothing else in the target derives a prescription
        assert set(by_key) == {
            (KIND_SPEC, 1), (KIND_SPEC, 2),
            (KIND_DONATE, 1), (KIND_DONATE, 2),
        }
        for argnum, leaf in ((1, "m"), (2, "v")):
            sp = by_key[(KIND_SPEC, argnum)]
            assert sp.leaf == leaf
            assert tuple(sp.spec) == tuple(P("dp"))
            assert sp.axis == "dp"
            assert sp.slot == "state_spec"
            assert sp.auto
            # ici convention: allreduce 2(n-1)B/n -> reduce-scatter
            # (n-1)B/n, n=2 -> the saving is B/2 per buffer
            assert sp.wire_delta == FLAT_BYTES // 2 == 131072
            assert sp.hbm_delta == FLAT_BYTES - FLAT_BYTES // DP
            dn = by_key[(KIND_DONATE, argnum)]
            assert dn.leaf == leaf
            assert dn.slot == "donate_argnums"
            assert dn.hbm_delta == FLAT_BYTES
            assert dn.auto

    def test_clean_target_zero_prescriptions(self):
        """Negative control: the properly sharded gpt target derives
        nothing — no prescription may exist without a finding."""
        target = gpt_step_target(dp2tp2_mesh())
        kept, _ctx, ledger = _run_suite(target, None, repo_allowlist())
        assert derive_patches(
            target, kept, mesh=target.mesh, ledger=ledger
        ) == []

    def test_update_axis_prefers_reduction_traffic(self, naive_audit):
        target, _, ledger = naive_audit
        # dp carries the grad allreduce + resync; tp is bigger traffic-
        # free axes must not win on size alone when the ledger speaks
        assert update_axis(target.mesh, ledger) == "dp"
        assert update_axis(None) is None

    def test_patch_kind_validated(self):
        with pytest.raises(ValueError, match="kind"):
            Patch(kind="rewrite-everything", target="t", argnum=0, leaf="x")

    def test_prescription_finding_carries_fix_payload(self):
        p = Patch(
            kind=KIND_SPEC, target="t", argnum=1, leaf="m", spec=P("dp"),
            site="<builder:state_spec>", axis="dp", wire_delta=131072,
            hbm_delta=131072, slot="state_spec", reason="seeded",
        )
        f = p.to_finding()
        assert f.rule == "autofix.prescription"
        assert f.severity == "info"
        assert f.fix["spec"] == "PartitionSpec('dp')"
        assert f.fix["wire_delta_bytes"] == 131072
        assert f.fix["auto"] is True
        # the fix payload participates in the finding identity: two
        # different prescriptions at one site must not merge
        assert str(f.fix) in f.key[-1]


# ---------------------------------------------------------------------------
# apply: fixpoint, idempotence, refusal


class TestApplyFixpoint:
    def test_one_round_clean_idempotent(self, naive_report):
        rep = naive_report
        assert rep.rounds == 1
        assert rep.idempotent and not rep.refused
        assert rep.clean and rep.ok
        assert [f for f in rep.findings_after if f.severity != "info"] == []
        assert rep.manual == []

    def test_final_overrides_are_the_prescription(self, naive_report):
        ov = naive_report.final_target.build_overrides
        assert tuple(ov["state_spec"]) == tuple(P("dp"))
        assert tuple(ov["donate_argnums"]) == (1, 2)

    def test_apply_twice_is_noop(self, naive_report):
        """The idempotence gate: autofixing the already-fixed target
        derives nothing, rebuilds nothing, and stays clean."""
        rep2 = apply_fixes(
            naive_report.final_target, allowlist=repo_allowlist()
        )
        assert rep2.applied == [] and rep2.rounds == 0
        assert rep2.idempotent and rep2.ok
        assert rep2.final_target is naive_report.final_target

    def test_conflicting_specs_refuse(self, naive_audit):
        target, _, _ = naive_audit
        mk = lambda spec: Patch(
            kind=KIND_SPEC, target=target.name, argnum=1, leaf="m",
            spec=spec, slot="state_spec",
        )
        _, applied, conflict = _merge_overrides(target, [mk(P("dp")),
                                                         mk(P("tp"))])
        assert applied == []
        assert "conflicting specs" in conflict

    def test_no_progress_patches_refuse(self, naive_audit):
        """A prescription equal to what the target was already built
        with changes no override — the applier must refuse rather than
        rebuild-and-rederive forever."""
        target, _, _ = naive_audit
        fixed = dataclasses.replace(
            target,
            build_overrides={"state_spec": P("dp"),
                             "donate_argnums": (1, 2)},
        )
        p = Patch(kind=KIND_SPEC, target=target.name, argnum=1, leaf="m",
                  spec=P("dp"), slot="state_spec")
        _, applied, conflict = _merge_overrides(fixed, [p])
        assert applied == [] and conflict == ""


# ---------------------------------------------------------------------------
# the ledger pins: the ZeRO byte-drop arithmetic, digit for digit


class TestLedgerPins:
    def _dp(self, target):
        return predict_comms(target.fn, *target.args).per_axis()["dp"]

    def test_naive_dp_totals(self):
        """Seeded: grad pmean (262144) + defensive param-resync pmean
        (262144) + loss pmean (4), every byte on the wire (allreduce
        ici = 2(n-1)B/n = B at n=2)."""
        t = gpt_zero_naive_step_target(dp2tp2_mesh())
        assert self._dp(t) == {
            "bytes": 2 * FLAT_BYTES + LOSS_BYTES,      # 524292
            "ici_bytes": 2 * FLAT_BYTES + LOSS_BYTES,  # 524292
            "calls": 3,
            "axis_size": DP,
        }

    def test_fixed_dp_totals(self):
        """Fixed (state_spec=P('dp')): reduce-scatter the grads
        (payload 262144, ici 131072), all-gather the updated shard
        (payload = the 131072 local shard, ici 131072), loss pmean."""
        t = gpt_zero_naive_step_target(
            dp2tp2_mesh(), state_spec=P("dp"), donate_argnums=(1, 2)
        )
        assert self._dp(t) == {
            "bytes": FLAT_BYTES + FLAT_BYTES // DP + LOSS_BYTES,  # 393220
            "ici_bytes": FLAT_BYTES + LOSS_BYTES,                 # 262148
            "calls": 3,
            "axis_size": DP,
        }

    def test_weight_update_wire_bytes_drop_by_dp_factor(self, naive_report):
        """THE acceptance pin: subtract the (identical) 4-byte loss
        telemetry and the predicted dp-axis weight-update wire bytes
        drop by exactly the dp (ZeRO) factor — 524288 == 2 * 262144."""
        before = naive_report.ledger_before
        after = naive_report.ledger_after
        assert before["ici_bytes"] == 524292
        assert after["ici_bytes"] == 262148
        assert (before["ici_bytes"] - LOSS_BYTES) == DP * (
            after["ici_bytes"] - LOSS_BYTES
        )
        assert (before["ici_bytes"] - LOSS_BYTES) == 524288
        assert DP * (after["ici_bytes"] - LOSS_BYTES) == 2 * 262144


# ---------------------------------------------------------------------------
# user-code prescriptions render as diffs, never edits


class TestUserDiff:
    def test_constraint_patch_renders_unified_diff(self, tmp_path):
        src = tmp_path / "user_step.py"
        src.write_text(
            "def step(params, grads):\n"
            "    grads = psum(grads, 'dp')\n"
            "    return params - grads\n"
        )
        p = Patch(
            kind=KIND_CONSTRAINT, target="user", argnum=None,
            leaf="(entry param)", spec=P("dp"), site="user_step.py:2",
            axis="dp", reason="reshard at the grad sync",
        )
        diff = render_user_diff([p], root=str(tmp_path))
        assert "--- a/user_step.py" in diff
        assert "+++ b/user_step.py" in diff
        assert "with_sharding_constraint" in diff
        assert "PartitionSpec('dp')" in diff
        # render only — the user's file is untouched
        assert "with_sharding_constraint" not in src.read_text()

    def test_siteless_patch_prints_prescription(self):
        p = Patch(kind=KIND_CONSTRAINT, target="user", argnum=None,
                  leaf="x", spec=P("dp"), site="<hlo:user>", axis="dp")
        out = render_user_diff([p])
        assert "unapplied prescription" in out

    def test_auto_patches_render_no_diff(self):
        p = Patch(kind=KIND_SPEC, target="t", argnum=1, leaf="m",
                  spec=P("dp"), slot="state_spec")
        assert render_user_diff([p]) == ""


# ---------------------------------------------------------------------------
# the CLI wrapper: python -m apex_tpu.analysis --fix (tier-1)


def test_fix_cli_subprocess(tmp_path):
    """``--fix`` as CI runs it: fresh process, exit 0 (clean fixpoint +
    idempotence proven), every analysis record an allowlisted
    prescription carrying its machine-applicable fix= payload, plus the
    sentinel-gated bench twin of the fixed dp-axis wire bytes."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    out = str(tmp_path / "fix.jsonl")
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.analysis", "--fix", "--json", out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=570,
    )
    assert proc.returncode == 0, (
        f"--fix CLI failed\nstdout tail: {proc.stdout[-2000:]}\n"
        f"stderr tail: {proc.stderr[-800:]}"
    )
    assert "idempotent" in proc.stdout
    records = [json.loads(l) for l in open(out)]
    analysis = [r for r in records if r["kind"] == "analysis"]
    bench = [r for r in records if r["kind"] == "bench"]
    assert analysis, "--fix emitted no prescription records"
    for rec in analysis:
        assert rec["rule"] == "autofix.prescription"
        assert rec["allowed"] is True
        assert rec["reason"].strip()
        assert rec["fix"]["kind"] in ("shard-spec", "donate", "constraint")
    (tw,) = bench
    assert tw["metric"] == "autofix_gpt_zero_naive_dp_ici_bytes"
    assert tw["value"] == 262148.0
    assert tw["unit"] == "B"


def test_fixable_targets_registry():
    # the CLI iterates exactly this registry; every entry must be a
    # builder producing a target that knows how to rebuild itself
    assert "gpt-zero-naive" in FIXABLE_TARGETS
    t = FIXABLE_TARGETS["gpt-zero-naive"](dp2tp2_mesh())
    assert t.builder is not None
    assert t.spec_slots and t.donate_slot
