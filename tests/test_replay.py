"""Deterministic replay & divergence forensics (apex_tpu.resilience.replay).

Fast tier: journal round trips, batch crc, chaos bit-flip mechanics,
journal diffing, the incident-bundle journal tail, and the AutoResume
anchor/flush wiring. Slow tier: the exit-nonzero selftest gate
(record -> replay -> inject-bitflip -> bisect on a tiny GPT target),
the cross-process determinism subprocess pin, and the ACCEPTANCE chaos
drill through the real GPT example (a single in-memory bit flip the
sentinel misses, pinned by ``replay --bisect`` to the exact step and
leaf; the clean control replays bitwise-identical).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# journal (jax-free)


class TestJournal:
    def _recorder(self, tmp_path, router=None):
        from apex_tpu.resilience.replay import FlightRecorder

        return FlightRecorder(str(tmp_path / "j.jsonl"), router=router)

    def test_round_trip(self, tmp_path):
        from apex_tpu.monitor import MemorySink, MetricRouter
        from apex_tpu.resilience.replay import load_journal

        mem = MemorySink()
        router = MetricRouter([mem])
        rec = self._recorder(tmp_path, router)
        rec.header("run-x", "gpt", config={"layers": 2}, devices=8,
                   platform="cpu")
        rec.anchor(0, init=True)
        rec.step(0, batch=[0, 16], batch_crc=123, loss=1.5, verdict=0,
                 layer_rms=np.asarray([0.5, 0.25], np.float32))
        rec.step(1, batch=[16, 32], batch_crc=456, loss=1.25, verdict=0)
        rec.anchor(2)
        rec.event(1, "bitflip_injected", path="['w']", bit=12)
        rec.close()

        j = load_journal(str(tmp_path / "j.jsonl"))
        assert j.header["target"] == "gpt"
        assert j.header["config"] == {"layers": 2}
        assert sorted(j.steps) == [0, 1]
        assert sorted(j.anchors) == [0, 2]
        assert j.anchors[0]["init"] is True
        assert j.steps[0]["layer_rms"] == [0.5, 0.25]
        assert j.steps[0]["loss"] == 1.5
        assert [e["event"] for e in j.events] == ["bitflip_injected"]
        # every record also reached the router as kind="journal"
        kinds = [r["kind"] for r in mem.records]
        assert kinds == ["journal"] * 6
        router.close()

    def test_float_fingerprints_round_trip_bitwise(self, tmp_path):
        """A float32 loss survives json EXACTLY (the bitwise-compare
        basis): widening to float64 is exact and repr round-trips."""
        from apex_tpu.resilience.replay import load_journal

        ugly = float(np.float32(1.0) / np.float32(3.0))
        rec = self._recorder(tmp_path)
        rec.header("r", "gpt")
        rec.step(0, loss=np.float32(1.0) / np.float32(3.0))
        rec.close()
        j = load_journal(str(tmp_path / "j.jsonl"))
        assert j.steps[0]["loss"] == ugly  # == , not isclose

    def test_last_wins_across_incarnations(self, tmp_path):
        from apex_tpu.resilience.replay import load_journal

        rec = self._recorder(tmp_path)
        rec.header("r", "gpt")
        rec.step(3, loss=1.0)
        rec.step(4, loss=2.0)
        rec.close()
        # restart: new header, step 3 re-executed from a restore
        from apex_tpu.resilience.replay import FlightRecorder

        rec2 = FlightRecorder(str(tmp_path / "j.jsonl"))
        rec2.header("r", "gpt")
        rec2.step(3, loss=9.0)
        rec2.close()
        j = load_journal(str(tmp_path / "j.jsonl"))
        assert len(j.headers) == 2
        assert j.steps[3]["loss"] == 9.0  # the newer incarnation wins
        assert j.steps[4]["loss"] == 2.0

    def test_torn_trailing_line_tolerated(self, tmp_path):
        from apex_tpu.resilience.replay import load_journal

        rec = self._recorder(tmp_path)
        rec.header("r", "gpt")
        rec.step(0, loss=1.0)
        rec.close()
        with open(tmp_path / "j.jsonl", "a") as f:
            f.write('{"kind": "journal", "event": "st')  # torn write
        j = load_journal(str(tmp_path / "j.jsonl"))
        assert sorted(j.steps) == [0]

    def test_journal_path_and_dir_loading(self, tmp_path):
        from apex_tpu.resilience.replay import journal_path, load_journal

        p = journal_path(str(tmp_path))
        assert p == str(tmp_path / "replay-journal.jsonl")
        from apex_tpu.resilience.replay import FlightRecorder

        rec = FlightRecorder(p)
        rec.header("r", "gpt")
        rec.close()
        # a checkpoint DIR is accepted and resolves to the sidecar
        assert load_journal(str(tmp_path)).header["target"] == "gpt"

    def test_breaks_in(self, tmp_path):
        from apex_tpu.resilience.replay import load_journal

        rec = self._recorder(tmp_path)
        rec.header("r", "gpt")
        rec.step(0, loss=1.0)
        rec.event(3, "rollback", to_step=2)
        rec.close()
        j = load_journal(str(tmp_path / "j.jsonl"))
        assert j.breaks_in(0, 5) and not j.breaks_in(3, 5)

    def test_needs_path_or_router(self):
        from apex_tpu.resilience.replay import FlightRecorder

        with pytest.raises(ValueError):
            FlightRecorder(None, router=None)

    def test_batch_crc(self):
        from apex_tpu.resilience.replay import batch_crc

        a = np.arange(64, dtype=np.int32)
        b = np.arange(64, dtype=np.int32)
        assert batch_crc(a) == batch_crc(b)
        assert batch_crc(a, b) != batch_crc(a)          # order/arity
        b[7] += 1
        assert batch_crc(a) != batch_crc(b)             # content
        # a non-contiguous view fingerprints its CONTENT, not its strides
        c = np.arange(128, dtype=np.int32)[::2]
        assert batch_crc(c) == batch_crc(np.ascontiguousarray(c))


# ---------------------------------------------------------------------------
# chaos bit flip


class TestBitflip:
    def _tree(self):
        import jax.numpy as jnp

        return {"w": jnp.ones((4, 4), jnp.float32),
                "b": jnp.zeros((3,), jnp.float32),
                "n": jnp.zeros((2,), jnp.int32)}

    def test_flips_exactly_one_bit(self):
        from apex_tpu.resilience import chaos

        tree = self._tree()
        flipped, info = chaos.bitflip_leaf(tree, bit=12, seed=0)
        # exactly one element of one leaf changed, by exactly one bit
        changed = []
        for (pa, a), (pb, b) in zip(
            _flat(tree), _flat(flipped)
        ):
            diff = np.asarray(a) != np.asarray(b)
            if diff.any():
                changed.append((pa, int(diff.sum())))
        assert changed == [(info["path"], 1)]
        before = np.float32(info["before"]).view(np.uint32)
        after = np.float32(info["after"]).view(np.uint32)
        assert bin(int(before ^ after)).count("1") == 1

    def test_deterministic_and_filtered(self):
        from apex_tpu.resilience import chaos

        tree = self._tree()
        _, i1 = chaos.bitflip_leaf(tree, seed=5)
        _, i2 = chaos.bitflip_leaf(tree, seed=5)
        assert i1 == i2
        _, i3 = chaos.bitflip_leaf(tree, seed=5, path_filter="['b']")
        assert "['b']" in i3["path"]
        with pytest.raises(ValueError):
            chaos.bitflip_leaf({"n": self._tree()["n"]})  # no float leaf

    def test_low_mantissa_bit_is_tiny(self):
        from apex_tpu.resilience import chaos

        _, info = chaos.bitflip_leaf(self._tree(), bit=12, seed=0)
        assert info["before"] != info["after"]
        assert abs(info["after"] - info["before"]) < 1e-3 * max(
            abs(info["before"]), 1.0
        )

    def test_faultplan_consumed_once(self):
        from apex_tpu.resilience import chaos

        plan = chaos.FaultPlan(bitflip_steps={3}, bitflip_seed=1)
        tree = self._tree()
        t1, info = plan.maybe_bitflip(2, tree)
        assert info is None and t1 is tree
        t2, info = plan.maybe_bitflip(3, tree)
        assert info is not None
        t3, info = plan.maybe_bitflip(3, t2)
        assert info is None and t3 is t2  # fired once

    def test_sharding_preserved(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from apex_tpu.resilience import chaos

        mesh = Mesh(np.asarray(jax.devices())[:4], ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        tree = {"w": jax.device_put(np.ones((8, 2), np.float32), sh)}
        flipped, _ = chaos.bitflip_leaf(tree, seed=0)
        assert flipped["w"].sharding == sh


def _flat(tree):
    import jax

    return [(jax.tree_util.keystr(p), l)
            for p, l in jax.tree_util.tree_flatten_with_path(tree)[0]]


# ---------------------------------------------------------------------------
# determinism guard + journal diff


class TestGuardAndDiff:
    def test_determinism_guard_pins_and_reports(self):
        import jax

        from apex_tpu.resilience.replay import determinism_guard

        flags = determinism_guard()
        assert flags["matmul_precision"] == "highest"
        assert flags["x64"] is False
        assert flags["platform"] == jax.default_backend()
        # the replaying side applies the HEADER's flags, not defaults —
        # including a recorded unpinned None precision (the examples'
        # journaling-on-by-default mode must not alter run numerics)
        flags2 = determinism_guard({"matmul_precision": None,
                                    "x64": False})
        assert flags2["matmul_precision"] is None
        # pin=False records without mutating: the flag stays whatever
        # the header application above left it at
        flags3 = determinism_guard(pin=False)
        assert flags3["matmul_precision"] is None
        # restore the conftest default for later tests in this process
        jax.config.update("jax_default_matmul_precision", None)

    def _journal(self, records):
        from apex_tpu.resilience.replay import Journal

        base = [{"kind": "journal", "event": "header", "step": 0,
                 "target": "llama-scan"}]
        return Journal(base + records)

    def _step(self, s, **f):
        return {"kind": "journal", "event": "step", "step": s, **f}

    def test_identical_journals_diff_clean(self):
        from apex_tpu.resilience.replay import compare_journals

        a = self._journal([self._step(0, loss=1.5), self._step(1, loss=1.2)])
        rep = compare_journals(a, a)
        assert rep.ok and rep.steps_replayed == 2

    def test_diff_flags_first_divergent_step(self):
        from apex_tpu.resilience.replay import compare_journals

        a = self._journal([self._step(0, loss=1.5), self._step(1, loss=1.2)])
        b = self._journal([self._step(0, loss=1.5),
                           self._step(1, loss=1.2000001)])
        rep = compare_journals(a, b)
        assert not rep.ok and rep.first_divergent_step == 1

    def test_diff_localizes_layer(self):
        from apex_tpu.resilience.replay import compare_journals

        a = self._journal([self._step(0, layer_rms=[0.5, 0.25, 0.125])])
        b = self._journal([self._step(0, layer_rms=[0.5, 0.25001, 0.13])])
        rep = compare_journals(a, b)
        (d,) = rep.divergences
        assert d["first_divergent_layer"] == 1
        assert d["divergent_layers"] == [1, 2]

    def test_nan_agrees_with_nan(self):
        from apex_tpu.resilience.replay import compare_journals

        a = self._journal([self._step(0, loss=float("nan"))])
        assert compare_journals(a, a).ok


# ---------------------------------------------------------------------------
# incident bundle carries the journal tail


class TestIncidentJournalTail:
    def test_bundle_includes_journal_tail(self):
        from apex_tpu.monitor.router import MemorySink, make_record
        from apex_tpu.resilience.health import capture_incident

        window = MemorySink()
        window.emit(make_record("metrics", 1, loss=1.0))
        window.emit(make_record("journal", 1, event="step", loss=1.0))
        window.emit(make_record("journal", 2, event="anchor"))
        rec = capture_incident(None, step=2, window=window)
        assert [r["event"] for r in rec["journal_tail"]] == [
            "step", "anchor"
        ]
        # the journal records ALSO stay in the full record tail
        assert any(r["kind"] == "journal" for r in rec["record_tail"])


# ---------------------------------------------------------------------------
# AutoResume anchor/flush wiring


class _JournalStub:
    def __init__(self):
        self.anchors = []
        self.events = []
        self.flushes = 0

    def anchor(self, step, **f):
        self.anchors.append(step)

    def event(self, step, event, **f):
        self.events.append((step, event))

    def flush(self):
        self.flushes += 1


class TestAutoResumeJournal:
    def test_save_anchors_and_commit_flushes(self, tmp_path):
        import jax.numpy as jnp

        from apex_tpu.utils import AutoResume

        stub = _JournalStub()
        ar = AutoResume(str(tmp_path), interval=1, install_handlers=False,
                        journal=stub)
        state = {"w": jnp.ones((4,), jnp.float32)}
        ar.step(1, state)
        ar.finalize()
        assert stub.anchors == [1]
        assert stub.flushes >= 1  # the manifest commit made it durable
        ar.close()

    def test_incident_exit_flushes_even_with_nothing_pending(self, tmp_path):
        from apex_tpu.utils import AutoResume

        stub = _JournalStub()
        ar = AutoResume(str(tmp_path), install_handlers=False, journal=stub)
        assert ar.prepare_incident_exit() is None
        assert stub.flushes == 1
        ar.close()

    def test_abandon_notes_the_anchor(self, tmp_path):
        import jax.numpy as jnp

        from apex_tpu.utils import AutoResume

        stub = _JournalStub()
        ar = AutoResume(str(tmp_path), interval=1, install_handlers=False,
                        journal=stub, background_finalize=False)
        # issue an async save but don't finalize; then abandon it
        ar._save(2, {"w": jnp.ones((4,), jnp.float32)}, durable=False)
        # first save is a calibration (finalizes immediately) — issue a
        # second to leave a genuinely pending one
        ar._save(3, {"w": jnp.ones((4,), jnp.float32)}, durable=False)
        if ar._pending is not None:
            ar._abandon_pending()
            assert (3, "anchor_abandoned") in stub.events
            assert stub.flushes >= 1
        ar.close()


# ---------------------------------------------------------------------------
# the gate + the subprocess pins (slow tier)


def test_replay_selftest_gate(tmp_path):
    """``python -m apex_tpu.resilience.replay --selftest`` exits 0:
    record -> bitwise replay -> inject-bitflip -> bisect pins the exact
    step and leaf on a tiny GPT target."""
    from apex_tpu.resilience.replay.__main__ import main

    assert main(["--selftest", "--dir", str(tmp_path)]) == 0


_DETERMINISM_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from apex_tpu.data import IndexedTokenDataset, LMDataset
from apex_tpu.resilience.replay.replayer import determinism_guard
from apex_tpu.resilience.replay.targets import (
    GPTTargetConfig, build_gpt_training, synthetic_corpus)

determinism_guard()
cfg = GPTTargetConfig(vocab=64, seq_len=16, layers=2, hidden=32, heads=4,
                      tp=1, micro_batch=1, global_batch=8, spike_warmup=4)
corpus = sys.argv[1]
training = build_gpt_training(cfg)
lm = LMDataset(IndexedTokenDataset(corpus), seq_len=cfg.seq_len)
state = training.init_state()
bag = training.init_bag()
import jax.numpy as jnp
fingerprints = []
for step in range(5):
    ids = list(range(step * cfg.global_batch, (step + 1) * cfg.global_batch))
    x, y = training.reshape_batch(*lm.batch(ids))
    out = training.train_step(*state, bag, jnp.asarray(x), jnp.asarray(y),
                              jnp.asarray(0.0, jnp.float32),
                              jnp.asarray(1.0, jnp.float32))
    (*state, bag, loss, verdict) = out
    state = tuple(state)
    fingerprints.append([float(np.asarray(loss)), int(np.asarray(verdict))])
from apex_tpu.resilience import integrity
fp = integrity.tree_fingerprint(state)
print("FINGERPRINTS " + json.dumps(
    {"steps": fingerprints, "state": fp["structure_hash"],
     "crcs": [l["crc32"] for l in fp["leaves"]]}))
"""


def test_cross_process_determinism(tmp_path):
    """Two FRESH processes running the same journaled 5-step CPU segment
    produce bitwise-identical per-step fingerprints AND per-leaf state
    crc32s — the foundation the replay referee stands on, pinned with
    the blessed ``determinism_guard`` the CLI and recorder share."""
    # one shared corpus so the pin isolates the COMPUTE, not the data gen
    from apex_tpu.resilience.replay.targets import synthetic_corpus

    corpus = synthetic_corpus(64, n_tokens=4_000)
    results = []
    for _ in range(2):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        proc = subprocess.run(
            [sys.executable, "-c", _DETERMINISM_CHILD, corpus],
            capture_output=True, text=True, cwd=REPO, env=env, timeout=600,
        )
        assert proc.returncode == 0, (
            f"child failed\nstdout: {proc.stdout[-1500:]}\n"
            f"stderr: {proc.stderr[-1500:]}"
        )
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("FINGERPRINTS ")][0]
        results.append(json.loads(line[len("FINGERPRINTS "):]))
    assert results[0] == results[1]  # bitwise: == on exact json values


# ---------------------------------------------------------------------------
# ACCEPTANCE: the chaos drill through the real GPT example (slow tier)


def _run_gpt(args, devices=8):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={devices}",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.argv={['x'] + args!r}\n"
        f"exec(open('examples/gpt/pretrain_gpt.py').read())\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=600,
    )
    assert proc.returncode == 0, (
        f"pretrain_gpt failed rc={proc.returncode}\nstdout tail: "
        f"{proc.stdout[-1500:]}\nstderr tail: {proc.stderr[-1500:]}"
    )
    return proc.stdout


_DRILL = ["--steps", "8", "--layers", "2", "--hidden", "64", "--heads", "4",
          "--seq-len", "32", "--micro-batch", "1", "--global-batch", "16",
          "--log-interval", "2", "--save-interval", "2"]


@pytest.mark.chaos
def test_gpt_replay_bitflip_drill(tmp_path):
    """ACCEPTANCE (ISSUE 12): a single in-memory bit flip injected into
    the params at step 3 of a GPT run passes the sentinel and the run
    completes — but ``replay --bisect`` from the journal + checkpoint
    dir identifies the step and the exact flipped leaf. The clean-run
    control replays bitwise-identical with zero divergence records."""
    from apex_tpu.resilience.replay import load_journal
    from apex_tpu.resilience.replay.__main__ import main as replay_main

    clean = str(tmp_path / "clean")
    flip = str(tmp_path / "flip")
    out_clean = _run_gpt(_DRILL + ["--save", clean])
    out_flip = _run_gpt(
        _DRILL + ["--save", flip, "--chaos-bitflip-step", "3"]
    )
    assert "[chaos] bit-flipped" in out_flip

    # the sentinel MISSED it: no anomalies, no skips, the run completed
    fj = load_journal(flip)
    assert all(r.get("verdict") == 0 for r in fj.steps.values())
    assert "anomalies this run" not in out_flip
    (flip_event,) = [e for e in fj.events
                     if e["event"] == "bitflip_injected"]
    assert flip_event["step"] == 3

    # clean control: bitwise-identical replay, zero divergence (exit 0)
    assert replay_main([clean]) == 0

    # corrupted run: plain verification replay FINDS divergence (exit 2)
    assert replay_main([flip]) == 2

    # the bisector pins the step and the exact flipped leaf, and emits
    # the kind="divergence" forensic record into --json
    forensics = str(tmp_path / "forensics.jsonl")
    assert replay_main([flip, "--bisect", "--json", forensics]) == 0
    records = [json.loads(l) for l in open(forensics)]
    (div,) = [r for r in records if r["kind"] == "divergence"]
    assert div["found"] is True
    # flip applied after step 3 -> the step-4 checkpoint carries it ->
    # first divergent step is 4 and the leaf set is EXACT
    assert div["step"] == 4
    assert div["exact_leaves"] is True
    assert div["leaves"] == ["[0]" + flip_event["path"]]
    assert div["clean_anchor"] == 2 and div["dirty_anchor"] == 4
    # replay booked its own machine time as goodput spans
    span_phases = {r["phase"] for r in records if r["kind"] == "span"}
    assert {"ckpt_restore", "step"} <= span_phases
