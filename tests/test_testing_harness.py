"""Megatron argument-system and globals tests.

Mirrors how the reference's L0 transformer tests drive
apex/transformer/testing (arguments.py parse_args + global_vars
set_global_variables) — reference launch flags must parse verbatim and
derive the same quantities.
"""

import jax.numpy as jnp
import pytest

from apex_tpu.transformer import testing
from apex_tpu.transformer.testing import global_vars

BASE = [
    "--num-layers", "8",
    "--hidden-size", "64",
    "--num-attention-heads", "8",
    "--max-position-embeddings", "128",
    "--seq-length", "128",
    "--micro-batch-size", "2",
]


@pytest.fixture(autouse=True)
def _clean_globals():
    global_vars.destroy_global_variables()
    yield
    global_vars.destroy_global_variables()


class TestParseArgs:
    def test_reference_launch_command_parses(self):
        """A realistic reference launch line (standalone_gpt.py style)."""
        args = testing.parse_args(args=BASE + [
            "--global-batch-size", "16",
            "--tensor-model-parallel-size", "2",
            "--pipeline-model-parallel-size", "2",
            "--lr", "1e-4", "--min-lr", "1e-5",
            "--train-iters", "100",
            "--bf16",
            "--sequence-parallel",
        ])
        assert args.num_layers == 8 and args.global_batch_size == 16
        assert args.tensor_model_parallel_size == 2
        assert args.params_dtype == jnp.bfloat16

    def test_world_size_derivations(self):
        args = testing.parse_args(
            args=BASE + ["--tensor-model-parallel-size", "2",
                         "--pipeline-model-parallel-size", "2"],
            override_args={"world_size": 8},
        )
        assert args.data_parallel_size == 2
        # global batch defaults to micro * dp (ref :146-150)
        assert args.global_batch_size == 2 * 2

    def test_ffn_and_kv_defaults(self):
        args = testing.parse_args(args=BASE)
        assert args.ffn_hidden_size == 4 * 64  # ref :242
        assert args.kv_channels == 64 // 8  # ref :246

    def test_virtual_pipeline_derivation(self):
        args = testing.parse_args(
            args=BASE + ["--pipeline-model-parallel-size", "4",
                         "--num-layers-per-virtual-pipeline-stage", "1"],
            override_args={"world_size": 4},
        )
        # V = (L / P) / layers_per_vstage = (8/4)/1 = 2 (ref :152-162)
        assert args.virtual_pipeline_model_parallel_size == 2

    def test_fp16_bf16_exclusive(self):
        with pytest.raises(AssertionError):
            testing.parse_args(args=BASE + ["--fp16", "--bf16"])

    def test_deprecated_flags_rejected(self):
        with pytest.raises(AssertionError, match="micro-batch-size"):
            testing.parse_args(args=BASE + ["--batch-size", "4"])
        with pytest.raises(AssertionError, match="tensor-model-parallel-size"):
            testing.parse_args(args=BASE + ["--model-parallel-size", "2"])

    def test_checkpoint_activations_maps_to_recompute(self):
        args = testing.parse_args(args=BASE + ["--checkpoint-activations"])
        assert args.recompute_granularity == "full"
        assert args.recompute_method == "uniform"
        assert not hasattr(args, "checkpoint_activations")

    def test_sequence_parallel_requires_tp(self):
        with pytest.raises(AssertionError, match="tensor parallelism"):
            testing.parse_args(args=BASE + ["--sequence-parallel"],
                               override_args={"world_size": 1})

    def test_iteration_vs_sample_based_exclusive(self):
        with pytest.raises(AssertionError):
            testing.parse_args(args=BASE + ["--train-iters", "10",
                                            "--train-samples", "100"])

    def test_extra_args_provider_and_defaults(self):
        def extra(parser):
            parser.add_argument("--my-flag", type=int, default=None)
            return parser

        args = testing.parse_args(
            extra_args_provider=extra,
            args=BASE,
            defaults={"my_flag": 7, "lr": 3e-4},
        )
        assert args.my_flag == 7 and args.lr == 3e-4

    def test_bf16_forces_fp32_grad_accumulation(self):
        args = testing.parse_args(args=BASE + ["--bf16"])
        assert args.accumulate_allreduce_grads_in_fp32  # ref :174-180

    def test_transformer_config_from_args(self):
        args = testing.parse_args(args=BASE + ["--bf16"])
        cfg = testing.transformer_config_from_args(args)
        assert cfg.num_layers == 8 and cfg.hidden_size == 64
        assert cfg.compute_dtype == jnp.bfloat16


class TestGlobalVars:
    def test_lifecycle(self):
        testing.set_global_variables(
            args=BASE + ["--global-batch-size", "8"],
            override_args={"world_size": 2},
        )
        args = testing.get_args()
        assert args.micro_batch_size == 2 and args.data_parallel_size == 2
        assert testing.get_num_microbatches() == 8 // (2 * 2)
        assert testing.get_current_global_batch_size() == 8
        assert testing.get_timers() is not None
        assert testing.get_tensorboard_writer() is None
        with pytest.raises(AssertionError, match="already initialized"):
            testing.set_global_variables(args=BASE)

    def test_get_args_before_init_raises(self):
        with pytest.raises(AssertionError, match="not initialized"):
            testing.get_args()

    def test_rampup_microbatch_updates(self):
        testing.set_global_variables(
            args=BASE + ["--global-batch-size", "16",
                         "--rampup-batch-size", "4", "4", "32",
                         "--train-samples", "64"],
            override_args={"world_size": 1, "data_parallel_size": 1},
        )
        assert testing.get_current_global_batch_size() == 4
        testing.update_num_microbatches(32, consistency_check=False)
        assert testing.get_current_global_batch_size() > 4


class TestStandaloneModels:
    """The runnable standalone LMs (ref standalone_gpt.py /
    standalone_bert.py): args in, finite decreasing losses out."""

    STANDARD = [
        "--num-layers", "4", "--hidden-size", "64",
        "--num-attention-heads", "4", "--seq-length", "32",
        "--max-position-embeddings", "32", "--micro-batch-size", "2",
        "--global-batch-size", "8", "--train-iters", "3", "--lr", "1e-3",
    ]

    @pytest.mark.slow
    def test_standalone_gpt_pp2_tp2_sp(self):
        from apex_tpu.transformer.testing.standalone_gpt import main

        losses = main(self.STANDARD + [
            "--pipeline-model-parallel-size", "2",
            "--tensor-model-parallel-size", "2", "--sequence-parallel",
        ])
        assert len(losses) == 3
        assert all(l == l and l < 20 for l in losses)  # finite, sane
        assert losses[-1] < losses[0]
        # published loss must be the true token mean regardless of SP:
        # vocab=128 => initial CE ~= log(128) ~= 4.85 (a tp-duplicated
        # psum would report ~2x that)
        import math

        assert abs(losses[0] - math.log(128)) < 1.0, losses[0]

    @pytest.mark.slow
    def test_standalone_gpt_tp2_no_sp_loss_not_duplicated(self):
        import math

        from apex_tpu.transformer.testing.standalone_gpt import main

        losses = main(self.STANDARD + ["--tensor-model-parallel-size", "2"])
        assert abs(losses[0] - math.log(128)) < 1.0, losses[0]

    @pytest.mark.slow
    def test_standalone_bert_tp2(self):
        from apex_tpu.transformer.testing.standalone_bert import main

        # later occurrences win in argparse: shrink the stack to 2 layers
        losses = main(self.STANDARD + [
            "--num-layers", "2",
            "--tensor-model-parallel-size", "2",
        ])
        assert len(losses) == 3 and all(l == l for l in losses)

    @pytest.mark.slow
    def test_standalone_gpt_xray_flags(self, tmp_path):
        """--xray-comms / --xray-report: the startup banners print and
        the kind='comms'/'memory' records join the same jsonl stream as
        the metrics (one schema, one tailer)."""
        import json

        from apex_tpu.transformer.testing.standalone_gpt import main

        jsonl = tmp_path / "m.jsonl"
        lines = []
        # tiny single-step config keeps this in the fast tier
        args = [
            "--num-layers", "1", "--hidden-size", "32",
            "--num-attention-heads", "2", "--seq-length", "16",
            "--max-position-embeddings", "16", "--micro-batch-size", "1",
            "--global-batch-size", "8", "--train-iters", "1",
            "--tensor-model-parallel-size", "2",
            "--metrics-jsonl", str(jsonl),
            "--xray-comms", "--xray-report",
        ]
        from apex_tpu.transformer.testing import standalone_gpt

        losses = standalone_gpt.run_gpt(
            standalone_gpt.parse_args(args=args), log=lines.append
        )
        assert len(losses) == 1
        text = "\n".join(str(l) for l in lines)
        assert "comms ledger (per step):" in text
        assert "axis 'tp'" in text
        assert "memory report (per device):" in text
        records = [json.loads(l) for l in jsonl.read_text().splitlines()]
        kinds = {r["kind"] for r in records}
        assert {"comms", "memory", "metrics"} <= kinds
        comms = [r for r in records if r["kind"] == "comms"]
        assert all(r["bytes"] > 0 for r in comms)
        assert {"tp", "dp"} <= {r["axis"] for r in comms}
        (mem_rec,) = [r for r in records if r["kind"] == "memory"]
        assert mem_rec["temp_bytes"] > 0 and mem_rec["argument_bytes"] > 0
