"""Mixed-precision tests.

Mirrors reference tests/L0/run_amp: opt-level properties, cast behavior,
dynamic scaler schedule (incl. overflow), checkpoint round-trip, skip-step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam


class TestPolicies:
    def test_opt_level_properties(self):
        p0 = amp.O0()
        assert p0.cast_model_type == jnp.float32 and not p0.master_weights
        p1 = amp.O1()
        assert p1.cast_model_type is None and p1.compute_dtype == jnp.bfloat16
        p2 = amp.O2(jnp.float16)
        assert p2.cast_model_type == jnp.float16
        assert p2.master_weights and p2.keep_batchnorm_fp32
        assert p2.loss_scale == "dynamic"
        p3 = amp.O3(jnp.float16)
        assert not p3.master_weights and not p3.keep_batchnorm_fp32

    def test_bf16_o2_has_no_loss_scaling(self):
        assert amp.O2(jnp.bfloat16).loss_scale == 1.0

    def test_cast_params_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4))},
            "LayerNorm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
            "step": jnp.asarray(3),  # int leaf untouched
        }
        out = amp.O2(jnp.bfloat16).cast_params(params)
        assert out["dense"]["kernel"].dtype == jnp.bfloat16
        assert out["LayerNorm_0"]["scale"].dtype == jnp.float32
        assert out["step"].dtype == jnp.int32

    def test_o3_casts_everything(self):
        params = {"LayerNorm_0": {"scale": jnp.ones((4,))}}
        out = amp.O3(jnp.bfloat16).cast_params(params)
        assert out["LayerNorm_0"]["scale"].dtype == jnp.bfloat16

    def test_wrap_apply_casts_args_and_kwargs(self):
        policy = amp.O1(jnp.bfloat16)
        seen = {}

        def apply_fn(params, x, y=None):
            seen["x"] = x.dtype
            seen["y"] = y.dtype
            return x

        out = policy.wrap_apply(apply_fn)({}, jnp.ones((2,)), y=jnp.ones((2,)))
        assert seen["x"] == jnp.bfloat16 and seen["y"] == jnp.bfloat16
        assert out.dtype == jnp.float32  # outputs come back fp32

    def test_initialize_bad_level_raises(self):
        with pytest.raises(ValueError):
            amp.initialize(opt_level="O4")


class TestLossScaler:
    def test_dynamic_schedule(self):
        s = amp.LossScaler(loss_scale="dynamic", init_scale=16.0, growth_interval=3)
        st = s.init()
        # 3 clean steps -> growth
        for _ in range(3):
            st = s.update(st, jnp.asarray(False))
        assert float(st.scale) == 32.0
        # overflow -> halve + reset tracker
        st = s.update(st, jnp.asarray(True))
        assert float(st.scale) == 16.0
        assert int(st.growth_tracker) == 0
        assert int(st.skipped) == 1

    def test_min_scale_clamp(self):
        s = amp.LossScaler(loss_scale="dynamic", init_scale=2.0, min_loss_scale=1.0)
        st = s.init()
        for _ in range(5):
            st = s.update(st, jnp.asarray(True))
        assert float(st.scale) == 1.0

    def test_static_scale_never_changes(self):
        s = amp.LossScaler(loss_scale=128.0)
        st = s.init()
        st = s.update(st, jnp.asarray(True))
        assert float(st.scale) == 128.0

    def test_unscale_and_overflow_flag(self):
        s = amp.LossScaler(loss_scale=4.0)
        st = s.init()
        grads = {"w": jnp.asarray([4.0, 8.0])}
        out, inf = s.unscale(st, grads)
        np.testing.assert_allclose(np.asarray(out["w"]), [1.0, 2.0])
        assert not bool(inf)
        grads = {"w": jnp.asarray([jnp.inf, 1.0])}
        _, inf = s.unscale(st, grads)
        assert bool(inf)

    def test_state_dict_roundtrip(self):
        s = amp.LossScaler(loss_scale="dynamic")
        st = s.init()
        st = s.update(st, jnp.asarray(True))
        d = s.state_dict(st)
        st2 = s.load_state_dict(d)
        assert float(st2.scale) == float(st.scale)
        assert int(st2.skipped) == int(st.skipped)


class TestAmpOptimizer:
    def _setup(self, opt_level="O2", half=jnp.float16):
        params = {"w": jnp.ones((4,), jnp.float32), "b": jnp.zeros((2,), jnp.float32)}
        tx = fused_adam(lr=0.1)
        params, amp_opt, policy = amp.initialize(
            params, tx, opt_level=opt_level, half_dtype=half
        )
        return params, amp_opt, policy

    def test_o2_master_weights_fp32(self):
        params, amp_opt, _ = self._setup()
        assert params["w"].dtype == jnp.float16
        state = amp_opt.init(params)
        assert state.master["w"].dtype == jnp.float32

    def test_step_updates_params(self):
        params, amp_opt, _ = self._setup()
        state = amp_opt.init(params)
        # scaled grads must stay representable in fp16 (scale is 2**16)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1024.0, p.dtype), params
        )
        new_params, new_state, info = amp_opt.step(grads, state, params)
        assert not bool(info["found_inf"])
        assert float(new_params["w"][0]) < 1.0  # moved against the gradient
        assert new_params["w"].dtype == jnp.float16

    def test_overflow_skips_step_and_halves_scale(self):
        params, amp_opt, _ = self._setup()
        state = amp_opt.init(params)
        scale0 = float(state.scaler.scale)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, jnp.inf, p.dtype), params
        )
        new_params, new_state, info = amp_opt.step(grads, state, params)
        assert bool(info["found_inf"])
        np.testing.assert_array_equal(
            np.asarray(new_params["w"], np.float32), np.asarray(params["w"], np.float32)
        )
        assert float(new_state.scaler.scale) == scale0 / 2

    def test_jitted_training_decreases_loss(self):
        k = jax.random.PRNGKey(0)
        params = {"w": jax.random.normal(k, (8, 1), jnp.float32)}
        tx = fused_adam(lr=0.05)
        params, amp_opt, policy = amp.initialize(params, tx, opt_level="O2")
        state = amp_opt.init(params)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 8))
        y = x @ jnp.arange(8.0)[:, None]

        def loss_fn(p):
            pred = policy.cast_inputs(x) @ p["w"]
            return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(
                lambda p: amp_opt.scale_loss(loss_fn(p), state)
            )(params)
            params, state, _ = amp_opt.step(grads, state, params)
            return params, state, loss

        losses = []
        for _ in range(60):
            params, state, loss = step(params, state)
            losses.append(float(loss) / float(state.scaler.scale))
        assert losses[-1] < losses[0] * 0.5


class TestHysteresis:
    """Ref csrc/update_scale_hysteresis.cu: overflows decrement a tracker;
    the scale backs off only at zero; clean steps refill the allowance."""

    def test_hysteresis_tolerates_transient_overflows(self):
        from apex_tpu.amp import LossScaler

        s = LossScaler(loss_scale="dynamic", init_scale=1024.0, hysteresis=3)
        st = s.init()
        st = s.update(st, True)   # 1st overflow: tolerated
        assert float(st.scale) == 1024.0
        st = s.update(st, True)   # 2nd: tolerated
        assert float(st.scale) == 1024.0
        st = s.update(st, True)   # 3rd: allowance exhausted -> backoff
        assert float(st.scale) == 512.0
        # consecutive overflows past exhaustion keep backing off (kernel
        # :44-46 refills the tracker only on a clean step)
        st = s.update(st, True)
        assert float(st.scale) == 256.0
        st = s.update(st, False)  # clean -> refill
        st = s.update(st, True)
        assert float(st.scale) == 256.0  # tolerated again

    def test_clean_step_refills_allowance(self):
        from apex_tpu.amp import LossScaler

        s = LossScaler(loss_scale="dynamic", init_scale=1024.0, hysteresis=2)
        st = s.init()
        st = s.update(st, True)    # one down
        st = s.update(st, False)   # clean -> refill
        st = s.update(st, True)    # one down again (not two)
        assert float(st.scale) == 1024.0
        st = s.update(st, True)    # exhausted -> backoff
        assert float(st.scale) == 512.0

    def test_default_hysteresis_matches_plain_schedule(self):
        from apex_tpu.amp import LossScaler

        s = LossScaler(loss_scale="dynamic", init_scale=1024.0)
        st = s.init()
        st = s.update(st, True)
        assert float(st.scale) == 512.0  # hysteresis=1: every overflow backs off

    def test_state_dict_round_trips_hysteresis(self):
        from apex_tpu.amp import LossScaler

        s = LossScaler(loss_scale="dynamic", hysteresis=2)
        st = s.update(s.init(), True)
        st2 = s.load_state_dict(s.state_dict(st))
        assert int(st2.hysteresis_tracker) == int(st.hysteresis_tracker) == 1


class TestMultiLossAmpOptimizer:
    """num_losses > 1: one scaler per loss_id (ref _initialize.py:229-233;
    exercised by examples/dcgan/main_amp.py — D-real and D-fake losses back
    off independently, the step skips if ANY contributing loss overflows)."""

    def _setup(self, num_losses=2):
        params = {"w": jnp.ones((4,), jnp.float32)}
        tx = fused_adam(lr=0.1)
        params, amp_opt, policy = amp.initialize(
            params, tx, opt_level="O2", half_dtype=jnp.float16,
            num_losses=num_losses,
        )
        return params, amp_opt

    def test_state_holds_one_scaler_per_loss(self):
        params, amp_opt = self._setup(3)
        state = amp_opt.init(params)
        assert isinstance(state.scaler, tuple) and len(state.scaler) == 3

    def test_loss_id_out_of_range_on_single_loss_raises(self):
        params, amp_opt = self._setup(1)
        state = amp_opt.init(params)
        with pytest.raises(ValueError, match="num_losses"):
            amp_opt.scale_loss(jnp.float32(1.0), state, loss_id=1)

    def test_overflow_in_one_loss_backs_off_only_its_scaler(self):
        params, amp_opt = self._setup(2)
        state = amp_opt.init(params)
        s0 = float(state.scaler[0].scale)
        clean = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1024.0, p.dtype), params)
        bad = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, jnp.inf, p.dtype), params)
        g0, inf0 = amp_opt.unscale_grads(clean, state, loss_id=0)
        g1, inf1 = amp_opt.unscale_grads(bad, state, loss_id=1)
        total = jax.tree_util.tree_map(jnp.add, g0, g1)
        new_params, new_state, info = amp_opt.step_unscaled(
            total, state, params, {0: inf0, 1: inf1})
        # step skipped (loss 1 overflowed) ...
        assert bool(info["found_inf"])
        np.testing.assert_array_equal(
            np.asarray(new_params["w"], np.float32),
            np.asarray(params["w"], np.float32))
        # ... scaler 1 backed off, scaler 0 advanced its clean streak
        assert float(new_state.scaler[1].scale) == s0 / 2
        assert float(new_state.scaler[0].scale) == s0
        assert int(new_state.scaler[0].growth_tracker) == 1
        assert int(new_state.scaler[1].skipped) == 1

    def test_noncontributing_scaler_untouched(self):
        params, amp_opt = self._setup(3)
        state = amp_opt.init(params)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1024.0, p.dtype), params)
        new_params, new_state, info = amp_opt.step(
            grads, state, params, loss_id=1)
        assert not bool(info["found_inf"])
        assert float(new_params["w"][0]) < 1.0
        # only scaler 1 saw a step
        assert int(new_state.scaler[1].growth_tracker) == 1
        assert int(new_state.scaler[0].growth_tracker) == 0
        assert int(new_state.scaler[2].growth_tracker) == 0

    def test_state_dict_roundtrip_tuple(self):
        params, amp_opt = self._setup(2)
        state = amp_opt.init(params)
        bad = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, jnp.inf, p.dtype), params)
        _, state, _ = amp_opt.step(bad, state, params, loss_id=1)
        d = amp_opt.state_dict(state)
        assert len(d["scalers"]) == 2
        restored = amp_opt.load_state_dict(amp_opt.init(params), d)
        assert float(restored.scaler[1].scale) == float(state.scaler[1].scale)
        assert int(restored.scaler[1].skipped) == 1

    def test_invalid_loss_ids_fail_fast(self):
        params, amp_opt = self._setup(2)
        state = amp_opt.init(params)
        with pytest.raises(ValueError, match="out of range"):
            amp_opt.scale_loss(jnp.float32(1.0), state, loss_id=-1)
        grads = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, 1.0, p.dtype), params)
        flag = jnp.asarray(False)
        with pytest.raises(ValueError, match="invalid"):
            amp_opt.step_unscaled(grads, state, params, {0: flag, 2: flag})
        with pytest.raises(ValueError, match="invalid"):
            amp_opt.step_unscaled(grads, state, params, {})

    def test_load_state_dict_rejects_num_losses_mismatch(self):
        params2, amp_opt2 = self._setup(2)
        params3, amp_opt3 = self._setup(3)
        d3 = amp_opt3.state_dict(amp_opt3.init(params3))
        with pytest.raises(ValueError, match="3 scalers"):
            amp_opt2.load_state_dict(amp_opt2.init(params2), d3)
        params1, amp_opt1 = self._setup(1)
        d1 = amp_opt1.state_dict(amp_opt1.init(params1))
        with pytest.raises(ValueError, match="single-scaler"):
            amp_opt2.load_state_dict(amp_opt2.init(params2), d1)
