"""GPT through the compiled pipeline schedule.

Mirrors the reference's end-to-end pipeline tests
(test_pipeline_parallel_fwd_bwd.py + test_gpt_minimal.py): a real
transformer stack split into pipeline chunks must reproduce the
single-device composition (loss AND grads incl. the replicated
embedding/head psum), and pp x tp (+SP) training must converge.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt_pipeline import build_gpt_pipeline
from apex_tpu.parallel import parallel_state
from apex_tpu.parallel.pipeline import (
    forward_backward_with_pre_post,
    forward_backward_zero_bubble_with_pre_post,
)
from apex_tpu.transformer import TransformerConfig

VOCAB, SEQ, MB = 32, 8, 2


def tiny_cfg(**kw):
    d = dict(
        num_layers=4,
        hidden_size=16,
        num_attention_heads=4,
        vocab_size=VOCAB,
        max_position_embeddings=SEQ,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )
    d.update(kw)
    return TransformerConfig(**d)


def init_all(parts, pp, key, tokens_mb):
    pre = parts.embed.init(key, tokens_mb)["params"]
    h = parts.pre_fn(pre, tokens_mb)
    stages = [
        parts.chunk.init(jax.random.fold_in(key, 100 + r), h)["params"]
        for r in range(pp)
    ]
    stacked = jax.tree_util.tree_map(lambda *a: jnp.stack(a), *stages)
    post = parts.init_post(jax.random.fold_in(key, 999))
    return {"pre": pre, "stages": stacked, "post": post}


class TestPipelinedGPT:
    def test_matches_sequential_composition(self, rng):
        pp, num_micro = 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        cfg = tiny_cfg()
        parts = build_gpt_pipeline(cfg, pp)

        tokens = jax.random.randint(rng, (num_micro, MB, SEQ), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=2)
        params = init_all(parts, pp, jax.random.fold_in(rng, 1), tokens[0])

        pspec = jax.tree_util.tree_map(lambda _: P("pp"), params["stages"])
        io_spec = {"pre": P(), "stages": pspec, "post": P()}

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(io_spec, P(), P()),
            out_specs=(P(), io_spec),
            check_vma=False,
        )
        def run(params, tokens, labels):
            local = dict(params)
            local["stages"] = jax.tree_util.tree_map(
                lambda a: a[0], params["stages"]
            )
            loss, _, grads = forward_backward_with_pre_post(
                parts.pre_fn, parts.stage_fn, parts.post_loss_fn, local,
                tokens, labels, axis_name="pp",
            )
            grads = dict(grads)
            grads["stages"] = jax.tree_util.tree_map(
                lambda g: g[None], grads["stages"]
            )
            return loss, grads

        loss, grads = run(params, tokens, labels)

        def ref_total(params):
            def one(tok, lab):
                h = parts.pre_fn(params["pre"], tok)
                for r in range(pp):
                    h = parts.stage_fn(
                        jax.tree_util.tree_map(
                            lambda a, _r=r: a[_r], params["stages"]
                        ),
                        h,
                    )
                return parts.post_loss_fn(params["post"], h, lab)

            return jnp.mean(jax.vmap(one)(tokens, labels))

        ref_loss, ref_grads = jax.value_and_grad(ref_total)(params)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, atol=1e-6)
        flat_want = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(ref_grads)
        )
        for k, v in jax.tree_util.tree_leaves_with_path(grads):
            np.testing.assert_allclose(
                v, flat_want[jax.tree_util.keystr(k)],
                rtol=5e-4, atol=5e-5, err_msg=jax.tree_util.keystr(k),
            )

    def test_zero_bubble_matches_fused_pre_post(self, rng):
        """The B/W-split equivalence on the tiny GPT target: the zero-
        bubble schedule's loss is BITWISE the fused path's and every
        grad leaf (embedding, stages, norm/head) matches digit-for-digit
        at f32 resolution — the split re-orders the weight-grad
        contractions (hand vjp vs transpose), so the comparison allows
        only the last-ulp reassociation wiggle."""
        pp, num_micro = 2, 4
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size=pp, devices=jax.devices()[:pp]
        )
        cfg = tiny_cfg()
        parts = build_gpt_pipeline(cfg, pp)
        tokens = jax.random.randint(rng, (num_micro, MB, SEQ), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=2)
        params = init_all(parts, pp, jax.random.fold_in(rng, 1), tokens[0])
        pspec = jax.tree_util.tree_map(lambda _: P("pp"), params["stages"])
        io_spec = {"pre": P(), "stages": pspec, "post": P()}

        def make(fb):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(io_spec, P(), P()),
                out_specs=(P(), io_spec), check_vma=False,
            )
            def run(params, tokens, labels):
                local = dict(params)
                local["stages"] = jax.tree_util.tree_map(
                    lambda a: a[0], params["stages"]
                )
                loss, _, grads = fb(
                    parts.pre_fn, parts.stage_fn, parts.post_loss_fn,
                    local, tokens, labels, axis_name="pp",
                )
                grads = dict(grads)
                grads["stages"] = jax.tree_util.tree_map(
                    lambda g: g[None], grads["stages"]
                )
                return loss, grads

            return run

        l1, g1 = make(forward_backward_with_pre_post)(params, tokens, labels)
        lz, gz = make(forward_backward_zero_bubble_with_pre_post)(
            params, tokens, labels
        )
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(lz))
        flat_want = dict(
            (jax.tree_util.keystr(k), v)
            for k, v in jax.tree_util.tree_leaves_with_path(g1)
        )
        for k, v in jax.tree_util.tree_leaves_with_path(gz):
            np.testing.assert_allclose(
                v, flat_want[jax.tree_util.keystr(k)],
                rtol=2e-6, atol=2e-7, err_msg=jax.tree_util.keystr(k),
            )

    def test_pp_tp_sp_training_converges(self, rng):
        """pp=2 x tp=2 mesh with sequence parallelism: the full pipelined
        train step reduces the loss (ref: test_gpt_minimal.py TPxPP grid)."""
        pp = tp = 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp,
            pipeline_model_parallel_size=pp,
            devices=jax.devices()[: pp * tp],
        )
        cfg = tiny_cfg(sequence_parallel=True)
        parts = build_gpt_pipeline(cfg, pp)

        num_micro = 2
        tokens = jax.random.randint(rng, (num_micro, MB, SEQ), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=2)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        def train(tokens, labels):
            key = jax.random.PRNGKey(0)
            pre = parts.embed.init(key, tokens[0])["params"]
            h = parts.pre_fn(pre, tokens[0])
            r = jax.lax.axis_index("pp")
            stage = parts.chunk.init(
                jax.random.fold_in(jax.random.fold_in(key, 7), r), h
            )["params"]
            params = {
                "pre": pre,
                "stages": stage,
                "post": parts.init_post(jax.random.fold_in(key, 9)),
            }

            def step(params, _):
                loss, _, grads = forward_backward_with_pre_post(
                    parts.pre_fn, parts.stage_fn, parts.post_loss_fn,
                    params, tokens, labels, axis_name="pp",
                )
                params = jax.tree_util.tree_map(
                    lambda p, g: p - 0.5 * g.astype(p.dtype), params, grads
                )
                # under SP the loss is tp-local: publish the global mean
                return params, jax.lax.psum(loss, "tp")

            _, losses = jax.lax.scan(step, params, None, length=8)
            return losses

        losses = np.asarray(train(tokens, labels))
        assert losses[-1] < losses[0] * 0.9, losses

    def test_llama_style_pp_tp_sp_training_converges(self, rng):
        """The modern-architecture stack (RMSNorm + rotate-half RoPE +
        SwiGLU + GQA + sliding window + bias-free linears + untied head)
        through the same pp=2 x tp=2 (+SP) compiled pipeline."""
        pp = tp = 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp,
            pipeline_model_parallel_size=pp,
            devices=jax.devices()[: pp * tp],
        )
        cfg = tiny_cfg(
            sequence_parallel=True,
            normalization="rmsnorm",
            activation="swiglu",
            add_bias_linear=False,
            position_embedding_type="rope",
            num_query_groups=2,
            attention_window=4,
            share_embeddings_and_output_weights=False,
        )
        parts = build_gpt_pipeline(cfg, pp)

        num_micro = 2
        tokens = jax.random.randint(rng, (num_micro, MB, SEQ), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=2)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        def train(tokens, labels):
            key = jax.random.PRNGKey(0)
            pre = parts.embed.init(key, tokens[0])["params"]
            h = parts.pre_fn(pre, tokens[0])
            r = jax.lax.axis_index("pp")
            stage = parts.chunk.init(
                jax.random.fold_in(jax.random.fold_in(key, 7), r), h
            )["params"]
            params = {
                "pre": pre,
                "stages": stage,
                "post": parts.init_post(jax.random.fold_in(key, 9)),
            }

            def step(params, _):
                loss, _, grads = forward_backward_with_pre_post(
                    parts.pre_fn, parts.stage_fn, parts.post_loss_fn,
                    params, tokens, labels, axis_name="pp",
                )
                params = jax.tree_util.tree_map(
                    lambda p, g: p - 0.5 * g.astype(p.dtype), params, grads
                )
                return params, jax.lax.psum(loss, "tp")

            _, losses = jax.lax.scan(step, params, None, length=8)
            return losses

        losses = np.asarray(train(tokens, labels))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, losses

    def test_post_params_stay_replicated_under_sp(self, rng):
        """The SP copy_to routing must produce IDENTICAL post grads on all
        tp ranks (review regression: tp-partial head grads)."""
        pp = tp = 2
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=tp,
            pipeline_model_parallel_size=pp,
            devices=jax.devices()[: pp * tp],
        )
        cfg = tiny_cfg(sequence_parallel=True)
        parts = build_gpt_pipeline(cfg, pp)
        tokens = jax.random.randint(rng, (2, MB, SEQ), 0, VOCAB)
        labels = jnp.roll(tokens, -1, axis=2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P()),
            out_specs=P("tp"), check_vma=False,
        )
        def head_grads(tokens, labels):
            key = jax.random.PRNGKey(0)
            pre = parts.embed.init(key, tokens[0])["params"]
            h = parts.pre_fn(pre, tokens[0])
            r = jax.lax.axis_index("pp")
            stage = parts.chunk.init(
                jax.random.fold_in(jax.random.fold_in(key, 7), r), h
            )["params"]
            params = {
                "pre": pre,
                "stages": stage,
                "post": parts.init_post(jax.random.fold_in(key, 9)),
            }
            _, _, grads = forward_backward_with_pre_post(
                parts.pre_fn, parts.stage_fn, parts.post_loss_fn,
                params, tokens, labels, axis_name="pp",
            )
            return grads["post"]["head"][None]

        per_rank = np.asarray(head_grads(tokens, labels))  # (tp, h, v)
        np.testing.assert_allclose(per_rank[0], per_rank[1], rtol=1e-5, atol=1e-6)
        assert np.abs(per_rank[0]).sum() > 0


class TestPipelineWithContextParallel:
    def test_pp_cp_tp_loss_matches_cp_disabled(self, rng):
        """pp x cp x tp in ONE program: the pipelined GPT with its sequence
        sharded over cp (ring attention, GQA) produces the same loss as the
        identical model with cp off — same params (stage init keys depend
        only on the pp rank), same tokens, so the only difference is the
        sequence sharding + ring collectives."""
        pp, cp, tp = 2, 2, 2
        num_micro = 2
        seq = 16

        def run(cp_mode):
            parallel_state.destroy_model_parallel()
            mesh = parallel_state.initialize_model_parallel(
                tensor_model_parallel_size=tp,
                pipeline_model_parallel_size=pp,
                context_parallel_size=2 if cp_mode else 1,
                devices=jax.devices()[: pp * tp * (2 if cp_mode else 1)],
            )
            cfg = tiny_cfg(
                num_layers=2 * pp,
                num_attention_heads=4,
                num_query_groups=2,
                max_position_embeddings=seq,
                context_parallel_mode="ring" if cp_mode else None,
            )
            parts = build_gpt_pipeline(cfg, pp)
            key = jax.random.PRNGKey(0)
            tokens = jax.random.randint(key, (num_micro, MB, seq), 0, VOCAB)
            labels = jnp.roll(tokens, -1, axis=2)
            seq_in = P(None, None, "cp") if cp_mode else P()

            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(seq_in, seq_in),
                out_specs=(P(), P()), check_vma=False,
            )
            def step(tokens, labels):
                init_key = jax.random.PRNGKey(0)
                pre = parts.embed.init(init_key, tokens[0])["params"]
                h0 = parts.pre_fn(pre, tokens[0])
                r = jax.lax.axis_index("pp")
                stage = parts.chunk.init(
                    jax.random.fold_in(jax.random.fold_in(init_key, 7), r),
                    h0,
                )["params"]
                params = {
                    "pre": pre,
                    "stages": stage,
                    "post": parts.init_post(jax.random.fold_in(init_key, 9)),
                }
                loss, _, grads = forward_backward_with_pre_post(
                    parts.pre_fn, parts.stage_fn, parts.post_loss_fn,
                    params, tokens, labels, axis_name="pp",
                )
                gnorm = sum(
                    jnp.sum(jnp.square(g))
                    for g in jax.tree_util.tree_leaves(grads)
                )
                for ax in ("tp", "cp", "dp"):
                    loss = jax.lax.pmean(loss, ax)
                    gnorm = jax.lax.pmean(gnorm, ax)
                return loss, gnorm

            return step(tokens, labels)

        loss_cp, gnorm_cp = run(True)
        loss_ref, _ = run(False)  # cp grads are shard-partial; no norm parity
        np.testing.assert_allclose(float(loss_cp), float(loss_ref),
                                   rtol=2e-5)
        assert float(gnorm_cp) > 0 and np.isfinite(float(gnorm_cp))
