"""Timers, checkpointing, training-loop utils, batch samplers.

Ref style: pipeline_parallel/utils.py + _timers.py + _batchsampler.py
consumers; checkpoint round-trip mirrors the amp state_dict tests
(tests/L0/run_amp/test_checkpointing.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
from apex_tpu.parallel import parallel_state
from apex_tpu.transformer import (
    average_losses_across_data_parallel_group,
    calc_params_l2_norm,
    get_ltor_masks_and_position_ids,
    print_params_min_max_norm,
    report_memory,
)
from apex_tpu.utils import (
    AutoResume,
    Timers,
    annotate,
    latest_step,
    load_checkpoint,
    save_checkpoint,
    AsyncCheckpointWriter,
)


class TestTimers:
    def test_elapsed_and_log(self):
        timers = Timers()
        timers("fwd").start()
        timers("fwd").stop()
        e = timers("fwd").elapsed(reset=False)
        assert e >= 0.0
        out = timers.log(["fwd"])
        assert "fwd" in out and "time (ms)" in out

    def test_write_callback(self):
        seen = []
        timers = Timers(write_fn=lambda name, v, it: seen.append((name, it)))
        timers("x").start()
        timers("x").stop()
        timers.write(["x"], iteration=7)
        assert seen == [("x-time", 7)]

    def test_annotate_context(self):
        with annotate("test-region"):
            jnp.ones(4).sum()

    def test_trace_capture_writes_profile(self, tmp_path):
        from apex_tpu.utils import trace

        with trace(str(tmp_path)):
            out = (jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum()
            jax.block_until_ready(out)
        # the capture lands as plugins/profile/<run>/ under the log dir
        runs = list((tmp_path / "plugins" / "profile").iterdir())
        assert runs, "no profiler capture written"


class TestCheckpoint:
    def test_round_trip_and_latest(self, tmp_path, rng):
        tree = {
            "params": {"w": jax.random.normal(rng, (4, 4))},
            "step": jnp.asarray(3, jnp.int32),
            "scale": jnp.asarray(2.0**16, jnp.float32),
        }
        save_checkpoint(str(tmp_path), 1, tree)
        tree2 = jax.tree_util.tree_map(lambda x: x + 1, tree)
        save_checkpoint(str(tmp_path), 5, tree2)
        assert latest_step(str(tmp_path)) == 5
        restored = load_checkpoint(str(tmp_path))
        np.testing.assert_allclose(restored["params"]["w"], tree2["params"]["w"])
        assert int(restored["step"]) == 4
        old = load_checkpoint(str(tmp_path), step=1, target=tree)
        np.testing.assert_allclose(old["params"]["w"], tree["params"]["w"])
        assert old["step"].dtype == jnp.int32


    def test_latest_step_ignores_torn_and_tmp_dirs(self, tmp_path, rng):
        """Regression pin: a crash during an async save leaves an orbax
        tmp directory (and a non-atomic backend can leave an empty final
        name); neither may be offered for restore."""
        import os

        save_checkpoint(str(tmp_path), 3, {"w": jax.random.normal(rng, (4,))})
        os.makedirs(tmp_path / "step_9")  # torn: final name, no content
        os.makedirs(tmp_path / "step_7.orbax-checkpoint-tmp-0")  # in-progress
        assert latest_step(str(tmp_path)) == 3
        from apex_tpu.utils.checkpoint import finalized_steps

        assert finalized_steps(str(tmp_path)) == [3]

    def test_structure_migration_old_scaler_state(self, tmp_path):
        """The documented migration path (utils/checkpoint.py docstring):
        a checkpoint from before LossScalerState gained
        ``hysteresis_tracker`` resumes through the scaler's
        state_dict/load_state_dict pair (tolerant of missing keys), while
        a raw-pytree restore into the new structure fails fast."""
        from apex_tpu.amp.scaler import LossScaler

        scaler = LossScaler(hysteresis=2)
        old = scaler.state_dict(scaler.init())
        del old["hysteresis_tracker"]  # the pre-hysteresis era on disk
        save_checkpoint(str(tmp_path), 1, {"scaler": old})

        # raw restore into the NEW dataclass structure cannot line up
        with pytest.raises(Exception):
            load_checkpoint(
                str(tmp_path), 1, target={"scaler": scaler.init()}
            )

        # the supported path: raw dict out, load_state_dict in — missing
        # key falls back to the constructor's hysteresis
        raw = load_checkpoint(str(tmp_path), 1)
        state = scaler.load_state_dict(raw["scaler"])
        assert int(state.hysteresis_tracker) == 2
        assert float(state.scale) == float(raw["scaler"]["loss_scale"])
        # and the migrated state round-trips with the new field pinned
        again = scaler.load_state_dict(scaler.state_dict(state))
        assert int(again.hysteresis_tracker) == 2

    def test_async_writer_round_trip_and_mutation_safety(self, tmp_path, rng):
        from apex_tpu.utils.checkpoint import AsyncCheckpointWriter

        tree = {
            "params": {"w": jax.random.normal(rng, (64, 64))},
            "step": jnp.asarray(7, jnp.int32),
        }
        want = np.asarray(tree["params"]["w"])
        with AsyncCheckpointWriter() as writer:
            writer.save(str(tmp_path), 7, tree)
            # mutating (donating) the source right after save() returns must
            # not corrupt the in-flight write: orbax snapshots to host first
            tree["params"]["w"] = tree["params"]["w"] * 0.0 - 5.0
            writer.wait()
            restored = load_checkpoint(str(tmp_path), step=7)
            np.testing.assert_allclose(restored["params"]["w"], want)
            # back-to-back saves from one writer serialize, never interleave
            for step in (8, 9):
                writer.save(str(tmp_path), step,
                            {"params": {"w": jnp.full((8,), float(step))}})
            writer.wait()
        assert latest_step(str(tmp_path)) == 9
        np.testing.assert_allclose(
            load_checkpoint(str(tmp_path), step=9)["params"]["w"], 9.0)


class TestAutoResume:
    """Preemption-safe save/exit/resume (utils/autoresume.py; ref contract:
    the polled ADLR autoresume object, testing/global_vars.py:75)."""

    @staticmethod
    def _train(state, steps, ar=None, kill_after=None):
        """counter/array toy loop; optionally SIGTERM itself mid-run."""
        import os
        import signal

        step0 = 0
        if ar is not None:
            step0, state = ar.restore(state)
        for i in range(step0, steps):
            state = {
                "w": state["w"] * 1.01 + 1.0,
                "n": state["n"] + 1,
            }
            if kill_after is not None and i + 1 == kill_after:
                os.kill(os.getpid(), signal.SIGTERM)
            if ar is not None and ar.step(i + 1, state):
                return state, i + 1, True
        return state, steps, False

    def _init(self):
        return {
            "w": jnp.ones((4,), jnp.float32),
            "n": jnp.asarray(0, jnp.int32),
        }

    def test_preempt_resume_matches_uninterrupted(self, tmp_path):
        straight, _, _ = self._train(self._init(), 10)

        ar = AutoResume(str(tmp_path))
        try:
            state, stopped_at, exited = self._train(
                self._init(), 10, ar, kill_after=4
            )
        finally:
            ar.close()
        assert exited and stopped_at == 4
        assert latest_step(str(tmp_path)) == 4

        ar2 = AutoResume(str(tmp_path), install_handlers=False)
        resumed, end, exited2 = self._train(self._init(), 10, ar2)
        assert not exited2 and end == 10
        np.testing.assert_allclose(resumed["w"], straight["w"], rtol=1e-6)
        assert int(resumed["n"]) == int(straight["n"]) == 10

    def test_interval_saves_and_fresh_restore(self, tmp_path):
        ar = AutoResume(str(tmp_path), interval=2, install_handlers=False)
        state, end, exited = self._train(self._init(), 5, ar)
        # interval saves are async; finalize() is the durability point
        ar.finalize()
        assert not exited and latest_step(str(tmp_path)) == 4

        step0, restored = ar.restore(self._init())
        assert step0 == 4 and int(restored["n"]) == 4

        fresh = AutoResume(str(tmp_path / "empty"), install_handlers=False)
        step0, restored = fresh.restore(self._init())
        assert step0 == 0 and int(restored["n"]) == 0

    def test_consensus_runs_on_mesh_and_request_resume(self, tmp_path):
        # 8 virtual devices: termination_requested takes the collective path
        ar = AutoResume(str(tmp_path), install_handlers=False)
        assert jax.device_count() > 1
        assert ar.termination_requested() is False
        ar.request_resume()  # ref ADLR programmatic request
        assert ar.termination_requested() is True
        # one termination save, then stay-exited without re-saving
        assert ar.step(3, self._init()) is True
        assert ar.step(4, self._init()) is True
        assert latest_step(str(tmp_path)) == 3

    def test_handler_install_and_close_restores(self):
        import signal

        prev = signal.getsignal(signal.SIGTERM)
        ar = AutoResume("/tmp/unused-autoresume")
        assert signal.getsignal(signal.SIGTERM) == ar._on_signal
        ar.close()
        assert signal.getsignal(signal.SIGTERM) == prev


class TestTrainUtils:
    def test_average_losses_across_dp(self):
        mesh = parallel_state.initialize_model_parallel()  # dp=8

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(),
            check_vma=False,
        )
        def run(x):
            return average_losses_across_data_parallel_group([x[0, 0]])

        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        np.testing.assert_allclose(run(x), [3.5])

    def test_calc_params_l2_norm(self, rng):
        params = {"a": jnp.ones((3, 3)), "b": 2.0 * jnp.ones((4,))}
        want = float(np.sqrt(9 + 4 * 2.0**2))
        np.testing.assert_allclose(calc_params_l2_norm(params), want, rtol=1e-6)

    def test_ltor_masks_basic(self):
        data = jnp.array([[5, 1, 7, 1, 9, 2]])  # eod_token = 1
        att, loss_mask, pos = get_ltor_masks_and_position_ids(
            data, eod_token=1, eod_mask_loss=True
        )
        assert att.shape == (1, 1, 6, 6)
        assert bool(att[0, 0, 0, 1])  # future masked
        assert not bool(att[0, 0, 1, 0])  # past visible
        np.testing.assert_array_equal(loss_mask[0], [1, 0, 1, 0, 1, 1])
        np.testing.assert_array_equal(pos[0], np.arange(6))

    def test_ltor_masks_reset(self):
        data = jnp.array([[5, 1, 7, 8, 1, 9]])
        att, _, pos = get_ltor_masks_and_position_ids(
            data, eod_token=1, reset_position_ids=True,
            reset_attention_mask=True,
        )
        # positions restart after each eod
        np.testing.assert_array_equal(pos[0], [0, 1, 0, 1, 2, 0])
        # token 2 (doc 2) cannot attend token 0 (doc 1)
        assert bool(att[0, 0, 2, 0])
        # within doc it can attend backward
        assert not bool(att[0, 0, 3, 2])

    def test_report_and_print(self, rng, capsys):
        report_memory("test")
        print_params_min_max_norm({"w": jnp.ones((2, 2))}, iteration=1)
        out = capsys.readouterr().out
        assert "memory (MB)" in out and "iteration" in out


class TestBatchSamplers:
    def test_sequential_shards_and_resume(self):
        s = MegatronPretrainingSampler(
            total_samples=20, consumed_samples=4, local_minibatch_size=2,
            data_parallel_rank=1, data_parallel_size=2,
        )
        batches = list(s)
        # first global batch covers samples 4..7; rank1 gets [6, 7]
        assert batches[0] == [6, 7]
        assert all(len(b) == 2 for b in batches)
        flat = [i for b in batches for i in b]
        assert max(flat) < 20 and min(flat) >= 4

    def test_sequential_validations(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(0, 0, 2, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(10, 10, 2, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(10, 0, 2, 3, 2)

    def test_random_is_permutation_and_disjoint(self):
        ranks = []
        for r in range(2):
            s = MegatronPretrainingRandomSampler(
                total_samples=16, consumed_samples=0, local_minibatch_size=2,
                data_parallel_rank=r, data_parallel_size=2, seed=3,
            )
            ranks.append([i for b in s for i in b])
        assert len(set(ranks[0]) & set(ranks[1])) == 0
        assert sorted(ranks[0] + ranks[1]) == list(range(16))

    def test_random_epoch_reshuffles(self):
        def epoch_indices(consumed):
            s = MegatronPretrainingRandomSampler(
                total_samples=16, consumed_samples=consumed,
                local_minibatch_size=2, data_parallel_rank=0,
                data_parallel_size=2, seed=3,
            )
            return [i for b in s for i in b]

        assert epoch_indices(0) != epoch_indices(16)

    def test_random_rampup_resume(self):
        """Resume after a batch-size rampup: consumed not a multiple of the
        new global batch must not crash (the reference's commented assert)."""
        s = MegatronPretrainingRandomSampler(
            total_samples=16, consumed_samples=6, local_minibatch_size=2,
            data_parallel_rank=0, data_parallel_size=2, seed=3,
        )
        batches = list(s)
        assert all(len(b) == 2 for b in batches)

    def test_random_too_few_samples_rejected(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingRandomSampler(
                total_samples=3, consumed_samples=0, local_minibatch_size=2,
                data_parallel_rank=0, data_parallel_size=2,
            )

    def test_sequential_zero_batch_rejected(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(10, 0, 0, 0, 1)
