"""Tier-1 tests for the concurrency x-ray (apex_tpu.analysis.concurrency).

Four seeded synthetic defects — an unguarded two-thread counter, an
A/B–B/A lock-order inversion, a router fan-out under a lock, and a
lock-taking SIGTERM handler — each pinned down to exact Finding fields,
with the guarded/safe counterpart asserted clean. Plus the
lint.thread-create rule, and the repo-wide no-rot contract: every
concurrency finding over the real tree is either fixed or carries a
reason-bearing allowlist entry, and no entry is stale.

Everything here is pure AST — no jax import, no thread is ever started.
"""

import textwrap

import pytest

from apex_tpu.analysis.concurrency import (
    CONCURRENCY_PASSES,
    build_model,
    run_concurrency,
)
from apex_tpu.analysis.findings import (
    Allowlist,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
)
from apex_tpu.analysis.lint import run_lint


def _src(body):
    return textwrap.dedent(body)


def _noninfo(findings):
    return [f for f in findings if f.severity != SEV_INFO]


class TestUnguardedWrite:
    def test_two_thread_counter_detected(self):
        # the canonical lost-update: __init__ spawns a poller thread
        # that increments self.count while the public surface (the main
        # root) also increments it, no lock anywhere
        files = {"apex_tpu/fake_counter.py": _src("""\
            import threading

            class Poller:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._work, daemon=True)

                def _work(self):
                    self.count += 1

                def bump(self):
                    self.count += 1
        """)}
        (f,) = _noninfo(run_concurrency(files=files))
        assert f.rule == "concurrency.unguarded-write"
        assert f.severity == SEV_ERROR
        assert f.site == "apex_tpu/fake_counter.py:10"
        assert f.target == "apex_tpu/fake_counter.py::Poller.count"
        assert f.data["state"] == "apex_tpu/fake_counter.py::Poller.count"
        assert f.data["roots"] == (
            "main,thread:apex_tpu/fake_counter.py:7"
        )
        assert f.data["writes"] == 2

    def test_guarded_counter_clean(self):
        # same two roots, every write under the same lock: the must-hold
        # intersection proves the guard and nothing fires
        files = {"apex_tpu/fake_counter.py": _src("""\
            import threading

            class Poller:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._work, daemon=True)

                def _work(self):
                    with self._lock:
                        self.count += 1

                def bump(self):
                    with self._lock:
                        self.count += 1
        """)}
        assert run_concurrency(files=files) == []

    def test_branch_only_lock_still_flagged(self):
        # a lock taken on ONE write path proves nothing — intersection
        # semantics: the unguarded bump() keeps the error alive
        files = {"apex_tpu/fake_counter.py": _src("""\
            import threading

            class Poller:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._work, daemon=True)

                def _work(self):
                    with self._lock:
                        self.count += 1

                def bump(self):
                    self.count += 1
        """)}
        fins = _noninfo(run_concurrency(files=files))
        assert [f.rule for f in fins] == ["concurrency.unguarded-write"]

    def test_init_writes_exempt(self):
        # construction happens-before the thread exists: __init__-only
        # stores never count as a second writer
        files = {"apex_tpu/fake_counter.py": _src("""\
            import threading

            class Poller:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._work, daemon=True)

                def _work(self):
                    self.count += 1
        """)}
        assert _noninfo(run_concurrency(files=files)) == []


class TestLockCycle:
    def test_ab_ba_inversion_detected(self):
        files = {"apex_tpu/fake_locks.py": _src("""\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def fwd():
                with A:
                    with B:
                        pass

            def rev():
                with B:
                    with A:
                        pass
        """)}
        fins = [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.lock-cycle"]
        (f,) = fins
        assert f.severity == SEV_ERROR
        # witness: the acquisition that closes the cycle (A inside B)
        assert f.site == "apex_tpu/fake_locks.py:13"
        assert f.target == "apex_tpu/fake_locks.py::A"
        assert f.data["cycle"] == (
            "apex_tpu/fake_locks.py::A -> apex_tpu/fake_locks.py::B "
            "-> apex_tpu/fake_locks.py::A"
        )

    def test_consistent_order_clean(self):
        # both call sites take A then B: a DAG, no finding
        files = {"apex_tpu/fake_locks.py": _src("""\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with A:
                    with B:
                        pass
        """)}
        assert run_concurrency(files=files) == []

    def test_nonreentrant_self_acquire_is_cycle(self):
        # Lock (not RLock) re-acquired through an internal call:
        # single-thread self-deadlock
        files = {"apex_tpu/fake_self.py": _src("""\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """)}
        fins = [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.lock-cycle"]
        assert len(fins) == 1
        assert "non-reentrant" in fins[0].message

    def test_reentrant_self_acquire_clean(self):
        # the router's design: RLock self-reentry is legal
        files = {"apex_tpu/fake_self.py": _src("""\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
        """)}
        assert [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.lock-cycle"] == []


class TestBlockingUnderLock:
    def test_router_fanout_under_lock_detected(self):
        files = {"apex_tpu/fake_rec.py": _src("""\
            import threading

            class Recorder:
                def __init__(self, router):
                    self._lock = threading.RLock()
                    self.router = router

                def record(self, step):
                    with self._lock:
                        self.router.event("m", step)
        """)}
        (f,) = _noninfo(run_concurrency(files=files))
        assert f.rule == "concurrency.blocking-under-lock"
        assert f.severity == SEV_WARNING
        assert f.site == "apex_tpu/fake_rec.py:10"
        assert f.target == "apex_tpu/fake_rec.py::Recorder._lock"
        assert f.data["op"] == "self.router.event(...) [router fan-out]"
        assert f.data["locks"] == "apex_tpu/fake_rec.py::Recorder._lock"

    def test_fanout_outside_lock_clean(self):
        # claim-under-lock / emit-outside-lock (the ProfilerTrigger
        # shape): nothing fires
        files = {"apex_tpu/fake_rec.py": _src("""\
            import threading

            class Recorder:
                def __init__(self, router):
                    self._lock = threading.RLock()
                    self.router = router
                    self._n = 0

                def record(self, step):
                    with self._lock:
                        self._n += 1
                    self.router.event("m", step)
        """)}
        assert _noninfo(run_concurrency(files=files)) == []

    def test_sleep_and_import_under_lock_detected(self):
        files = {"apex_tpu/fake_slow.py": _src("""\
            import threading
            import time

            _LOCK = threading.Lock()

            def slow():
                with _LOCK:
                    import json
                    time.sleep(1.0)
        """)}
        fins = _noninfo(run_concurrency(files=files))
        ops = sorted(f.data["op"] for f in fins)
        assert ops == ["import json", "time.sleep"]
        assert all(f.rule == "concurrency.blocking-under-lock"
                   for f in fins)

    def test_inline_event_wait_is_unbounded(self):
        # the chaos wedge() shape: an Event nobody holds can never be
        # set — flagged even with no lock held
        files = {"apex_tpu/fake_wedge.py": _src("""\
            import threading

            def wedge(timeout_s=None):
                threading.Event().wait(timeout_s)
        """)}
        (f,) = _noninfo(run_concurrency(files=files))
        assert f.rule == "concurrency.unbounded-wait"
        assert f.severity == SEV_WARNING
        assert f.site == "apex_tpu/fake_wedge.py:4"
        assert f.data["op"] == "Event.wait"


class TestHandlerSafety:
    def test_lock_taking_sigterm_handler_detected(self):
        files = {"apex_tpu/fake_sig.py": _src("""\
            import signal
            import threading

            _LOCK = threading.Lock()
            _STATE = {}

            def _on_term(signum, frame):
                with _LOCK:
                    _STATE["t"] = 1

            signal.signal(signal.SIGTERM, _on_term)
        """)}
        fins = [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.handler-unsafe"]
        (f,) = fins
        assert f.severity == SEV_ERROR
        assert f.site == "apex_tpu/fake_sig.py:8"
        assert f.target == "signal:apex_tpu/fake_sig.py:11"
        assert f.data == {
            "handler": "apex_tpu/fake_sig.py::_on_term",
            "cause": "lock",
            "detail": "apex_tpu/fake_sig.py::_LOCK",
        }

    def test_flag_only_handler_clean(self):
        # the async-signal-safe vocabulary: GIL-atomic stores + a
        # monotonic timestamp
        files = {"apex_tpu/fake_sig.py": _src("""\
            import signal
            import time

            _FLAG = {"signaled": False, "t": None}

            def _on_term(signum, frame):
                _FLAG["signaled"] = True
                _FLAG["t"] = time.monotonic()

            signal.signal(signal.SIGTERM, _on_term)
        """)}
        assert [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.handler-unsafe"] == []

    def test_atexit_hook_blocking_detected(self):
        files = {"apex_tpu/fake_exit.py": _src("""\
            import atexit
            import time

            def _teardown():
                time.sleep(0.5)

            atexit.register(_teardown)
        """)}
        fins = [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.handler-unsafe"]
        (f,) = fins
        assert f.data["cause"] == "blocking"
        assert f.data["detail"] == "time.sleep"


class TestRootsInventory:
    def test_root_kinds(self):
        files = {"apex_tpu/fake_roots.py": _src("""\
            import atexit
            import signal
            import threading

            def _work():
                pass

            def _tick():
                pass

            def _on_term(signum, frame):
                pass

            def _bye():
                pass

            t = threading.Thread(target=_work)
            threading.Timer(1.0, _tick)
            signal.signal(signal.SIGTERM, _on_term)
            atexit.register(_bye)
        """)}
        model = build_model(files)
        kinds = sorted(r.kind for r in model.roots)
        assert kinds == ["atexit", "main", "signal", "thread", "timer"]
        by_kind = {r.kind: r for r in model.roots}
        assert by_kind["thread"].targets == (
            "apex_tpu/fake_roots.py::_work",)
        assert by_kind["timer"].targets == (
            "apex_tpu/fake_roots.py::_tick",)

    def test_dynamic_call_from_thread_reported_unresolved(self):
        # the honesty contract: a call the resolver cannot follow from a
        # thread root surfaces as info, never silently dropped
        files = {"apex_tpu/fake_dyn.py": _src("""\
            import threading

            class Runner:
                def __init__(self, fn):
                    self._fn = fn
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self._fn()
        """)}
        fins = [f for f in run_concurrency(files=files)
                if f.rule == "concurrency.unresolved"]
        (f,) = fins
        assert f.severity == SEV_INFO
        assert f.site == "apex_tpu/fake_dyn.py:9"
        assert f.data["callee"] == "self._fn"

    def test_pass_registry(self):
        assert set(CONCURRENCY_PASSES) == {
            "roots", "shared", "lock-order", "blocking", "handlers"}


class TestThreadCreateLint:
    def test_raw_thread_and_timer_flagged(self):
        files = {
            "apex_tpu/fake.py":
                "import threading\nimport threading as _threading\n"
                "t = threading.Thread(target=print)\n"
                "u = _threading.Timer(1.0, print)\n"
                "from threading import Thread\n",
        }
        fins = run_lint(rules=["lint.thread-create"], files=files)
        assert sorted(f.site for f in fins) == [
            "apex_tpu/fake.py:3", "apex_tpu/fake.py:4",
            "apex_tpu/fake.py:5",
        ]
        assert all(f.rule == "lint.thread-create" for f in fins)
        assert all(f.severity == SEV_ERROR for f in fins)

    def test_coordination_primitives_not_flagged(self):
        # locks/events/current_thread are coordination, not roots
        files = {
            "apex_tpu/fake.py":
                "import threading\n"
                "lk = threading.Lock()\n"
                "rl = threading.RLock()\n"
                "ev = threading.Event()\n"
                "name = threading.current_thread().name\n"
                "from threading import Event, Lock\n",
        }
        assert run_lint(rules=["lint.thread-create"], files=files) == []

    def test_blessed_homes_are_the_only_sites(self):
        # the three homes exist, are flagged by the raw rule, and are
        # the ONLY apex_tpu sites (require_hit entries go stale if a
        # thread construction moves)
        fins = run_lint(rules=["lint.thread-create"])
        homes = {f.site.rsplit(":", 1)[0] for f in fins}
        assert homes == {
            "apex_tpu/monitor/watchdog.py",
            "apex_tpu/resilience/health/responder.py",
            "apex_tpu/utils/checkpoint.py",
        }


class TestRepoScan:
    def test_repo_concurrency_fully_explained(self):
        """No-rot contract over the real tree: every concurrency finding
        is suppressed by a reason-carrying entry and no entry is stale —
        a new thread, a new unguarded write, or a removed hand-proof
        breaks this test, not production."""
        from apex_tpu.analysis.allowlist import REPO_ALLOWLIST

        fins = run_concurrency()
        entries = [e for e in REPO_ALLOWLIST.entries
                   if e.rule.startswith("concurrency.")]
        res = Allowlist(entries).apply(fins, check_stale=True)
        unexplained = _noninfo(res.findings)
        assert not unexplained, "\n".join(
            f.format() for f in unexplained)
        assert not res.stale_entries, res.stale_entries

    def test_repo_scan_is_pure_ast(self):
        """The concurrency passes must never initialize jax (the gate
        runs them before the jaxpr half so host-runtime races report
        even when tracing fails)."""
        import subprocess
        import sys

        code = (
            "import sys\n"
            "from apex_tpu.analysis.concurrency import run_concurrency\n"
            "run_concurrency()\n"
            "assert 'jax' not in sys.modules, 'concurrency scan "
            "imported jax'\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr

    @pytest.mark.slow
    def test_gate_skip_concurrency_not_stale(self):
        """--skip-concurrency must also disable stale checking, or the
        concurrency require_hit entries would fail every skipped run."""
        from apex_tpu.analysis.__main__ import main

        try:
            assert main(["--skip-jaxpr", "--skip-timeline",
                         "--skip-concurrency"]) == 0
        finally:
            from apex_tpu.parallel import parallel_state

            parallel_state.initialize_model_parallel()
