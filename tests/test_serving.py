"""Serving-core tests (apex_tpu.serving, docs/serving.md).

Tier-1: the jax-free pieces — the closed request state machine, the
block allocator, the cache-spec bridge, the serving chaos faults, the
Poisson load generator, the taxonomy/router integration, and the
termination-notice latch.

Slow tier: the selftest gate wrapper, the wedged-decode forensic
bundle, and the ACCEPTANCE overload drill — a Poisson burst at >2x the
sustainable rate with slow-decode and client-abandon faults plus a
mid-load SIGTERM, audited from the example's jsonl stream: every
submitted request reaches exactly one terminal state, p99 TTFT of
admitted requests stays inside the configured budget (excess load is
shed, not queued), the drain completes within the grace budget, the
goodput partition identity holds digit-for-digit, and zero post-warmup
recompiles.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from apex_tpu.monitor import MemorySink, MetricRouter, StdoutSink
from apex_tpu.monitor.goodput import accountant, spans
from apex_tpu.resilience.chaos import FaultPlan
from apex_tpu.serving import kvcache, lifecycle
from apex_tpu.serving.loadgen import PoissonLoadGenerator, percentile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- lifecycle state machine ------------------------------------------------


class TestLifecycle:
    def _req(self, **kw):
        kw.setdefault("rid", 0)
        kw.setdefault("prompt", np.array([1, 2, 3], np.int32))
        kw.setdefault("max_new_tokens", 4)
        kw.setdefault("submit_t", 100.0)
        return lifecycle.Request(**kw)

    def test_happy_path_walk(self):
        r = self._req()
        for state in ("queued", "admitted", "prefill", "decode",
                      "completed"):
            lifecycle.transition(r, state, now=101.0)
        assert r.terminal and r.state == "completed"
        assert r.admit_t == 101.0 and r.end_t == 101.0

    def test_machine_is_closed(self):
        r = self._req()
        with pytest.raises(ValueError, match="machine is closed"):
            lifecycle.transition(r, "warp_drive")
        lifecycle.transition(r, "queued")
        # queued cannot jump straight to decode
        with pytest.raises(ValueError, match="illegal transition"):
            lifecycle.transition(r, "decode")

    def test_terminal_states_absorb(self):
        r = self._req()
        lifecycle.transition(r, "rejected", reason="queue_full")
        with pytest.raises(ValueError, match="absorbing"):
            lifecycle.transition(r, "queued")

    def test_every_live_state_can_time_out(self):
        for path in (("queued",), ("queued", "admitted"),
                     ("queued", "admitted", "prefill"),
                     ("queued", "admitted", "prefill", "decode")):
            r = self._req()
            for s in path:
                lifecycle.transition(r, s)
            lifecycle.transition(r, "timed_out", reason="deadline")
            assert r.state == "timed_out"

    def test_record_fields(self):
        mem = MemorySink()
        router = MetricRouter([mem])
        r = self._req(deadline_s=5.0)
        lifecycle.transition(r, "queued", now=100.5)
        lifecycle.emit_request_record(router, 3, r)
        lifecycle.transition(r, "admitted", now=101.0)
        lifecycle.transition(r, "prefill", now=101.2)
        r.first_token_t = 101.5
        lifecycle.transition(r, "completed", now=102.0)
        lifecycle.emit_request_record(router, 7, r)
        router.close()
        first, last = mem.records[0], mem.records[-1]
        assert first["kind"] == "request" and first["state"] == "queued"
        assert "terminal" not in first and first["step"] == 3
        assert last["terminal"] is True
        assert last["queue_wait_s"] == 1.0
        assert last["ttft_s"] == 1.5
        assert last["total_s"] == 2.0
        assert r.expires_at() == 105.0

    def test_no_router_is_noop(self):
        assert lifecycle.emit_request_record(None, 0, self._req()) is None


# -- block allocator --------------------------------------------------------


class TestBlockAllocator:
    def test_alloc_free_roundtrip(self):
        a = kvcache.BlockAllocator(8)
        ids = a.alloc(3)
        assert len(set(ids)) == 3 and a.free_blocks == 5
        a.free(ids)
        assert a.free_blocks == 8 and a.used_blocks == 0

    def test_all_or_nothing(self):
        a = kvcache.BlockAllocator(4)
        assert a.alloc(3) is not None
        assert a.alloc(2) is None           # only 1 left: no partial grant
        assert a.free_blocks == 1           # nothing leaked by the refusal
        assert a.alloc(1) is not None

    def test_double_free_refused(self):
        a = kvcache.BlockAllocator(4)
        ids = a.alloc(2)
        a.free(ids)
        with pytest.raises(ValueError, match="not allocated"):
            a.free(ids)

    def test_blocks_needed(self):
        assert kvcache.blocks_needed(1, 16) == 1
        assert kvcache.blocks_needed(16, 16) == 1
        assert kvcache.blocks_needed(17, 16) == 2


# -- cache spec bridge ------------------------------------------------------


class _Leaf:
    def __init__(self, shape, dtype="float32"):
        self.shape, self.dtype = shape, dtype


class TestCacheSpec:
    def _shapes(self):
        return {
            "transformer": {
                "layers_0": {"attention": {
                    "cached_key": _Leaf((1, 4, 32, 8)),
                    "cached_value": _Leaf((1, 4, 32, 8)),
                    "cache_index": _Leaf(()),
                }},
            }
        }

    def test_classify_and_pool_shapes(self):
        spec = kvcache.CacheSpec.from_cache_shapes(self._shapes())
        assert len(spec.kv_leaves) == 2 and len(spec.index_leaves) == 1
        pools = spec.pool_shapes(num_blocks=10, block_size=16)
        for shape, _ in pools.values():
            assert shape == (10, 4, 16, 8)

    def test_build_and_extract_roundtrip(self):
        spec = kvcache.CacheSpec.from_cache_shapes(self._shapes())
        kv = {kvcache.CacheSpec.key(l.path): f"arr-{i}"
              for i, l in enumerate(spec.kv_leaves)}
        cache = spec.build_cache(kv, 7)
        att = cache["transformer"]["layers_0"]["attention"]
        assert att["cache_index"] == 7
        assert spec.kv_from_cache(cache) == kv

    def test_refuses_unknown_layouts(self):
        bad = self._shapes()
        bad["transformer"]["layers_0"]["attention"]["prompt_len_local"] = (
            _Leaf(()))
        with pytest.raises(ValueError, match="refuses layouts"):
            kvcache.CacheSpec.from_cache_shapes(bad)
        with pytest.raises(ValueError, match="single-sequence"):
            kvcache.CacheSpec.from_cache_shapes({
                "x": {"cached_key": _Leaf((2, 4, 32, 8)),
                      "cache_index": _Leaf(())},
            })
        with pytest.raises(ValueError, match="no cached_key"):
            kvcache.CacheSpec.from_cache_shapes(
                {"x": {"cache_index": _Leaf(())}})


# -- serving chaos faults ---------------------------------------------------


class TestServingFaults:
    def test_slow_decode_consumed_once(self):
        plan = FaultPlan(slow_decode_steps={3}, slow_decode_s=0.01)
        t0 = time.monotonic()
        assert plan.maybe_slow_decode(3) is True
        assert time.monotonic() - t0 >= 0.01
        assert plan.maybe_slow_decode(3) is False  # consumed
        assert plan.maybe_slow_decode(4) is False

    def test_abandon_and_malformed_ordinals(self):
        plan = FaultPlan(abandon_requests={1}, malformed_requests={2})
        assert not plan.take_abandon(0) and plan.take_abandon(1)
        assert not plan.take_abandon(1)            # consumed
        assert plan.take_malformed(2) and not plan.take_malformed(2)

    def test_burst(self):
        plan = FaultPlan(burst_steps={5}, burst_n=3)
        assert plan.take_burst(4) == 0
        assert plan.take_burst(5) == 3
        assert plan.take_burst(5) == 0             # consumed

    def test_persistent_rearms(self):
        plan = FaultPlan(burst_steps={5}, burst_n=2, persistent=True)
        assert plan.take_burst(5) == 2 and plan.take_burst(5) == 2

    def test_spec_strings_parse(self):
        plan = FaultPlan(slow_decode_steps="3,5-6",
                         abandon_requests="0,2")
        assert plan.slow_decode_steps == frozenset({3, 5, 6})
        assert plan.abandon_requests == frozenset({0, 2})


# -- Poisson load generator -------------------------------------------------


class _FakeEngine:
    """Duck-typed engine: records submissions/cancels, everything
    queues."""

    def __init__(self):
        self.submitted = []
        self.cancelled = []
        self._rid = 0

    def submit(self, prompt, max_new_tokens, temperature=0.0,
               deadline_s=None):
        req = lifecycle.Request(
            rid=self._rid, prompt=np.asarray(prompt),
            max_new_tokens=max_new_tokens, temperature=temperature,
            deadline_s=deadline_s, submit_t=time.monotonic(),
        )
        self._rid += 1
        lifecycle.transition(
            req, "rejected" if req.prompt_len == 0 else "queued",
            reason="malformed" if req.prompt_len == 0 else None,
        )
        self.submitted.append(req)
        return req

    def cancel(self, rid):
        self.cancelled.append(rid)
        return True


class TestPoissonLoadGenerator:
    def test_seeded_schedule_is_deterministic(self):
        a = PoissonLoadGenerator(rate_rps=10, vocab=64, n_requests=5,
                                 seed=3)
        b = PoissonLoadGenerator(rate_rps=10, vocab=64, n_requests=5,
                                 seed=3)
        assert np.array_equal(a._arrivals, b._arrivals)

    def test_pump_submits_due_arrivals(self):
        clock = {"t": 0.0}
        gen = PoissonLoadGenerator(
            rate_rps=100, vocab=64, n_requests=10, seed=0,
            time_fn=lambda: clock["t"])
        eng = _FakeEngine()
        gen.pump(eng)          # anchors t0; nothing due at t=0
        clock["t"] = 1000.0    # everything due
        gen.pump(eng)
        assert gen.done and len(eng.submitted) == 10
        lens = {r.prompt_len for r in eng.submitted}
        assert all(4 <= n <= 24 for n in lens)

    def test_burst_and_malformed_and_abandon(self):
        clock = {"t": 0.0}
        plan = FaultPlan(burst_steps={0}, burst_n=3,
                         malformed_requests={1}, abandon_requests={0})
        gen = PoissonLoadGenerator(
            rate_rps=0.001, vocab=64, n_requests=5, seed=0,
            fault_plan=plan, time_fn=lambda: clock["t"])
        eng = _FakeEngine()
        new = gen.pump(eng)    # no Poisson arrivals due, but the burst
        assert len(new) == 3
        # ordinal 1 (inside the burst) was malformed -> rejected
        assert eng.submitted[1].state == "rejected"
        # ordinal 0 abandon is pending until the NEXT pump
        assert eng.cancelled == []
        gen.pump(eng)
        assert eng.cancelled == [eng.submitted[0].rid]

    def test_percentile_contract(self):
        assert percentile([], 99.0) is None
        assert percentile([1.0], 50.0) == 1.0
        assert percentile([1.0, 3.0], 50.0) == 2.0

    def test_report_math(self):
        gen = PoissonLoadGenerator(rate_rps=1, vocab=8, n_requests=1)
        r = lifecycle.Request(rid=0, prompt=np.array([1], np.int32),
                              max_new_tokens=3, submit_t=10.0)
        lifecycle.transition(r, "queued", now=10.0)
        lifecycle.transition(r, "admitted", now=10.5)
        r.first_token_t = 11.0
        r.tokens_out = [1, 2, 3]
        lifecycle.transition(r, "prefill", now=11.0)
        lifecycle.transition(r, "completed", now=12.0)
        gen.submitted.append(r)
        rep = gen.report()
        assert rep.ttft_s == [1.0]
        assert rep.per_token_s == [0.5]     # (12-11) / (3-1)
        assert rep.summary()["ttft_p50_s"] == 1.0


# -- taxonomy / router integration ------------------------------------------


class TestServingTelemetryIntegration:
    def test_serving_phases_in_closed_taxonomy(self):
        assert {"prefill", "decode", "drain"} <= set(spans.PHASES)
        assert {"prefill", "decode"} <= set(spans.PRODUCTIVE_PHASES)
        assert "drain" in accountant.BADPUT_PHASES
        assert "prefill" not in accountant.BADPUT_PHASES
        # priority: incident > step > prefill > decode > ... > drain
        pri = list(spans.PHASE_PRIORITY)
        assert (pri.index("incident") < pri.index("prefill")
                < pri.index("decode") < pri.index("drain")
                < pri.index("init"))

    def test_stdout_sink_skips_request_kind(self, capsys):
        from apex_tpu.monitor.router import make_record

        sink = StdoutSink()
        sink.emit(make_record("request", 1, id=0, state="queued"))
        sink.emit(make_record("metrics", 1, loss=1.0))
        out = capsys.readouterr().out
        assert "queued" not in out and "step     1" in out

    def test_responder_bundle_extra_merged(self):
        from apex_tpu.resilience.health import IncidentResponder

        r = IncidentResponder(
            10.0, exit_fn=lambda code: None,
            bundle_extra=lambda: {"requests": [{"id": 7}], "queued": 2},
        )
        r._dump({"step": 3, "overdue_s": 1.0, "deadline_s": 10.0})
        assert r.incidents[0]["requests"] == [{"id": 7}]
        assert r.incidents[0]["queued"] == 2

    def test_responder_bundle_extra_failure_isolated(self):
        from apex_tpu.resilience.health import IncidentResponder

        def boom():
            raise RuntimeError("garnish failed")

        r = IncidentResponder(10.0, exit_fn=lambda code: None,
                              bundle_extra=boom)
        r._dump({"step": 3})
        assert len(r.incidents) == 1    # the bundle survived its garnish


class TestTerminationNotice:
    def test_flag_only_latch(self):
        from apex_tpu.utils.autoresume import TerminationNotice

        n = TerminationNotice(install_handlers=False, grace_s=5.0)
        assert not n.signaled and n.grace_deadline() is None
        n.request()
        assert n.signaled
        assert n.grace_deadline() == pytest.approx(
            time.monotonic() + 5.0, abs=0.5)
        n.close()

    def test_real_sigterm_supersedes_router_death_hook(self):
        """The regression shape that wedged the suite: the router
        module's SIGTERM teardown hook flushes and RE-RAISES to die by
        the signal. A TerminationNotice installed over it must observe
        the signal (flag) without chaining into that death — with a
        notice installed, SIGTERM means drain, not die."""
        import apex_tpu.monitor.router as rmod
        from apex_tpu.utils.autoresume import TerminationNotice

        prev = signal.getsignal(signal.SIGTERM)
        prev_installed = rmod._TEARDOWN["installed"]
        try:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            rmod._TEARDOWN["installed"] = False
            rmod._install_teardown()
            hook = signal.getsignal(signal.SIGTERM)
            assert getattr(hook, "_apex_tpu_router_teardown", False)
            n = TerminationNotice(grace_s=None)
            os.kill(os.getpid(), signal.SIGTERM)
            # the handler runs in the main thread on delivery; being
            # alive to assert IS the point
            for _ in range(100):
                if n.signaled:
                    break
                time.sleep(0.01)
            assert n.signaled and n.grace_deadline() is None
            n.close()
            assert signal.getsignal(signal.SIGTERM) is hook
        finally:
            rmod._TEARDOWN["installed"] = prev_installed
            signal.signal(signal.SIGTERM, prev)


# -- slow tier: the gate, the wedge, and the ACCEPTANCE overload drill ------


def test_serving_selftest_gate():
    """The ``python -m apex_tpu.serving --selftest`` gate exits 0 —
    correctness vs models.generate, admission/shed/deadline/drain, and
    zero post-warmup recompiles on a tiny GPT."""
    from apex_tpu.serving.__main__ import main

    assert main([]) == 0


def test_serving_wedged_decode_bundle():
    """A chaos wedge inside the scheduler loop escalates through the
    incident ladder, and the forensic bundle carries the engine's
    in-flight request table."""
    import jax.numpy as jnp
    import jax

    from apex_tpu.models import GPTModel
    from apex_tpu.resilience.health import IncidentResponder
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer import TransformerConfig

    tcfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4,
        vocab_size=37, max_position_embeddings=0,
        position_embedding_type="rope", hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    model = GPTModel(config=tcfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4), jnp.int32))
    mem = MemorySink()
    router = MetricRouter([mem])
    plan = FaultPlan(hang_steps={2}, hang_timeout_s=2.0)
    responder = IncidentResponder(
        0.4, router=router, window=mem, dump_after=2.0, poll_s=0.05,
        exit_fn=lambda code: None,
    )
    cfg = ServingConfig(lanes=2, block_size=8, num_blocks=4,
                        max_seq_len=16, prefill_buckets=(8,), seed=0)
    eng = ServingEngine(model, variables, cfg, router=router,
                        fault_plan=plan, watchdog=responder)
    eng.start()
    responder.bundle_extra = eng.inflight_table
    responder.start()
    try:
        rid = eng.submit(np.array([1, 2, 3], np.int32),
                         max_new_tokens=12).rid
        n = 0
        while not eng.idle and n < 60:
            eng.tick()      # tick 2 wedges for 2 s; dump fires at 0.8 s
            n += 1
    finally:
        responder.stop()
        router.close()
    assert responder.incidents, "the dump level never fired"
    bundle = responder.incidents[0]
    assert bundle["queued"] == 0
    assert [row["id"] for row in bundle["requests"]] == [rid]
    assert bundle["requests"][0]["state"] == "decode"
    # the wedge released; the request still finished (no silent drop)
    assert eng.requests()[0].state == "completed"


def _audit_stream(path):
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    records.append(json.loads(line))
                except ValueError:
                    pass
    return records


def test_serving_overload_drill(tmp_path):
    """ISSUE 13 acceptance: Poisson burst at >2x sustainable with
    slow-decode + client-abandon (+ malformed, + burst) faults and a
    MID-LOAD SIGTERM. From the jsonl stream: every submitted request
    reaches exactly one terminal state, p99 TTFT of admitted requests
    stays within the configured budget (excess SHED, not queued), the
    drain completes within the grace budget, the goodput partition
    identity holds digit-for-digit, and zero post-warmup recompiles."""
    jsonl = str(tmp_path / "serving.jsonl")
    ttft_budget = 2.0
    grace = 60.0
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        APEX_TPU_PREEMPTION_GRACE_S=str(grace),
    )
    args = [
        "x", "--requests", "600", "--rate", "100",
        "--ttft-budget", str(ttft_budget), "--queue-depth", "8",
        "--deadline", "30", "--metrics-jsonl", jsonl,
        "--chaos-slow-decode-steps", "30,60", "--chaos-slow-decode-s",
        "0.3", "--chaos-abandon", "5,15,25",
        "--chaos-malformed", "10,20", "--chaos-burst-steps", "40",
        "--chaos-burst-n", "12",
    ]
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        f"import sys; sys.argv={args!r}\n"
        "exec(open('examples/serving/serve_gpt.py').read())\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        # mid-load: wait for real traffic, then deliver the SIGTERM
        t0 = time.monotonic()
        while time.monotonic() - t0 < 300:
            time.sleep(0.5)
            if os.path.exists(jsonl):
                n = sum(1 for r in _audit_stream(jsonl)
                        if r.get("kind") == "request")
                if n > 60:
                    break
        else:
            proc.kill()
            pytest.fail("no serving traffic observed")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, f"drill rc={proc.returncode}\n{out[-2000:]}"
    assert "termination notice: draining" in out

    records = _audit_stream(jsonl)
    req_records = [r for r in records if r.get("kind") == "request"]
    assert req_records, "no request records in the stream"

    # 1. exactly one terminal state per submitted request — no silent
    # drops, even with abandons, malformed payloads, shed and a drain
    seen = {r["id"] for r in req_records}
    terminal = {}
    for r in req_records:
        if r.get("terminal"):
            terminal.setdefault(r["id"], []).append(r["state"])
    assert set(terminal) == seen
    assert all(len(v) == 1 for v in terminal.values())
    states = {v[0] for v in terminal.values()}
    assert states <= lifecycle.TERMINAL_STATES

    # 2. the overload was real and was SHED with reasons
    reasons = {}
    for r in req_records:
        if r.get("terminal") and r.get("reason"):
            reasons[r["reason"]] = reasons.get(r["reason"], 0) + 1
    assert reasons.get("ttft_budget", 0) + reasons.get("queue_full", 0) \
        > 0, f"nothing shed under >2x load: {reasons}"
    assert reasons.get("malformed", 0) >= 1
    assert reasons.get("client_cancel", 0) >= 1

    # 3. p99 TTFT of ADMITTED requests inside the budget: shedding kept
    # the queue honest instead of letting it grow
    ttfts = [r["ttft_s"] for r in req_records
             if r.get("terminal") and "ttft_s" in r]
    assert ttfts, "no admitted requests measured"
    assert percentile(ttfts, 99.0) <= ttft_budget

    # 4. drain completed within the grace budget
    m = [l for l in out.splitlines() if l.startswith("serving drain:")]
    assert m, f"no drain line in:\n{out[-1500:]}"
    drain_s = float(m[0].split()[2].rstrip("s,"))
    assert drain_s < grace

    # 5. goodput partition identity, digit-for-digit through json
    good = [r for r in records if r.get("kind") == "goodput"]
    assert good, "no goodput summary record"
    g = good[-1]
    total = g["productive_s"]
    for phase in accountant.BADPUT_PHASES:
        total = total + g[f"badput_{phase}_s"]
    assert total + g["unattributed_s"] == g["wall_s"]
    assert g["productive_s"] > 0.0

    # 6. zero post-warmup recompiles in steady state
    assert "steady-state compiles 0" in out
    post_warmup = [r for r in records
                   if r.get("kind") == "compile" and r.get("recompile")]
    assert post_warmup == []


def test_serving_cancel_and_drain_hardening():
    """ISSUE 16 satellites: cancel() from every live state (queued,
    prefill, decode) books exactly one terminal record and reclaims the
    lane/blocks; a SECOND drain returns the first report marked
    ``redundant=True`` and submit-after-drain sheds with a booked
    ``draining`` rejection — records, never exceptions."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTModel
    from apex_tpu.serving import ServingConfig, ServingEngine
    from apex_tpu.transformer import TransformerConfig

    tcfg = TransformerConfig(
        num_layers=1, hidden_size=32, num_attention_heads=4,
        vocab_size=37, max_position_embeddings=0,
        position_embedding_type="rope", hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    model = GPTModel(config=tcfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 4), jnp.int32))
    mem = MemorySink()
    router = MetricRouter([mem])
    cfg = ServingConfig(lanes=2, block_size=8, num_blocks=8,
                        max_seq_len=32, prefill_buckets=(8,), seed=0)
    eng = ServingEngine(model, variables, cfg, router=router)
    eng.start()
    pool = cfg.num_blocks

    # fill both lanes, then a third request has to WAIT in the queue
    a = eng.submit(np.array([1, 2, 3], np.int32), max_new_tokens=8)
    b = eng.submit(np.array([4, 5, 6], np.int32), max_new_tokens=8)
    eng.tick()      # one admission per tick (max_prefills_per_tick=1)
    eng.tick()
    assert a.state == "decode" and b.state == "decode"
    c = eng.submit(np.array([7, 8, 9], np.int32), max_new_tokens=8)
    assert c.state == "queued"

    # 1. cancel from QUEUED: never placed, so the pool is untouched
    free_before = eng.allocator.free_blocks
    assert eng.cancel(c.rid) is True
    assert c.state == "cancelled" and c.reason == "client_cancel"
    assert eng.allocator.free_blocks == free_before
    assert eng.cancel(c.rid) is False     # terminal: cancel is a no-op

    # 2. cancel from DECODE: the lane and its blocks come back
    lane_a, blocks_a = a.lane, a.blocks
    assert eng.cancel(a.rid) is True
    assert a.state == "cancelled"
    assert lane_a not in eng._active
    assert eng.allocator.free_blocks == free_before + len(blocks_a)

    # 3. cancel from PREFILL: the state is intra-tick (admission runs
    # the prefill in the same tick), so build the mid-prefill shape the
    # cancel path must handle — lane and blocks assigned, not yet in a
    # decode lane — and cancel through the engine's one eviction path
    free_mid = eng.allocator.free_blocks
    req = lifecycle.Request(
        rid=997, prompt=np.array([1, 2], np.int32), max_new_tokens=4,
        submit_t=eng.time_fn(),
    )
    for state in ("queued", "admitted", "prefill"):
        lifecycle.transition(req, state, now=eng.time_fn())
    req.lane = eng._free_lane()
    req.blocks = eng.allocator.alloc(2)
    eng._requests[997] = req
    assert eng.cancel(997) is True
    assert req.state == "cancelled"
    assert eng.allocator.free_blocks == free_mid

    n = 0
    while not eng.idle and n < 60:
        eng.tick()
        n += 1
    assert b.state == "completed"
    assert eng.allocator.free_blocks == pool

    # 4. drain re-entrancy: the second call replays the first report
    first = eng.drain(grace_s=5.0)
    assert "redundant" not in first
    second = eng.drain()
    assert second["redundant"] is True
    assert second["finished"] == first["finished"]
    assert second["evicted"] == first["evicted"]

    # 5. submit-after-drain: a booked rejection, never an exception
    late = eng.submit(np.array([1, 2, 3], np.int32), max_new_tokens=4)
    assert late.terminal and late.state == "rejected"
    assert late.reason == "draining"
    router.close()

    # every id that ever appeared reached EXACTLY one terminal record
    terminal = {}
    for r in mem.snapshot():
        if r.get("kind") == "request" and r.get("terminal"):
            terminal.setdefault(r["id"], []).append(r["state"])
    assert set(terminal) == {a.rid, b.rid, c.rid, 997, late.rid}
    assert all(len(v) == 1 for v in terminal.values())
