"""MoE / expert-parallelism tests (no reference counterpart — EP is an
extension; test strategy follows the repo's fused-vs-oracle style)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import parallel_state
from apex_tpu.transformer import TransformerConfig
from apex_tpu.transformer.moe import (
    MoEMLP,
    _dispatch_indices,
    load_balancing_loss,
    router_probs,
)

H, FFN, TOK = 16, 32, 64


def cfg():
    return TransformerConfig(
        num_layers=1,
        hidden_size=H,
        num_attention_heads=4,
        vocab_size=32,
        max_position_embeddings=8,
        ffn_hidden_size=FFN,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )


def naive_moe(params, x, num_experts, top_k, capacity):
    """Loop oracle: route, drop overflow per expert, weight by gate."""
    gate_w = np.asarray(params["router"], np.float32)
    w_in = np.asarray(params["w_in"], np.float32)
    w_out = np.asarray(params["w_out"], np.float32)
    logits = np.asarray(x, np.float32) @ gate_w
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    order = np.argsort(-probs, axis=-1)
    out = np.zeros_like(np.asarray(x, np.float32))
    for k in range(top_k):
        idx = order[:, k]
        counts = {e: 0 for e in range(num_experts)}
        for t in range(x.shape[0]):
            e = int(idx[t])
            if counts[e] >= capacity:
                continue
            counts[e] += 1
            hdn = np.asarray(x, np.float32)[t] @ w_in[e]
            hdn = np.asarray(jax.nn.gelu(jnp.asarray(hdn)))
            out[t] += probs[t, e] * (hdn @ w_out[e])
    return out


class TestRouting:
    def test_dispatch_positions_and_capacity(self):
        idx = jnp.array([0, 1, 0, 0, 1, 0])
        pos = _dispatch_indices(idx, num_experts=2, capacity=2)
        np.testing.assert_array_equal(pos, [0, 0, 1, -1, 1, -1])

    def test_router_and_aux(self, rng):
        x = jax.random.normal(rng, (TOK, 4))
        probs, gate_vals, idx = router_probs(x, 4, 2)
        np.testing.assert_allclose(np.asarray(probs).sum(-1), 1.0, rtol=1e-5)
        assert idx.shape == (TOK, 2)
        aux = load_balancing_loss(probs, idx, 4)
        # ~1 when routing is near-uniform (random inputs); blows up when
        # collapsed onto one expert
        assert 0.5 < float(aux) < 4.0
        collapsed = jnp.zeros((TOK, 4)).at[:, 0].set(10.0)
        p2, _, i2 = router_probs(collapsed, 4, 1)
        assert float(load_balancing_loss(p2, i2, 4)) > 3.0


class TestMoELocal:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_naive(self, rng, top_k):
        e = 4
        mod = MoEMLP(
            config=cfg(), num_experts=e, top_k=top_k, expert_axis=None,
            capacity_factor=1.0,
        )
        x = jax.random.normal(rng, (TOK, H), jnp.float32)
        params = mod.init(jax.random.fold_in(rng, 1), x)["params"]
        out, aux = mod.apply({"params": params}, x)
        capacity = max(1, int(1.0 * TOK / e))  # per-pass capacity
        want = naive_moe(params, x, e, top_k, capacity)
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-5)
        assert float(aux) > 0

    def test_grads_flow_to_router_and_experts(self, rng):
        mod = MoEMLP(config=cfg(), num_experts=4, expert_axis=None)
        x = jax.random.normal(rng, (TOK, H))
        params = mod.init(jax.random.fold_in(rng, 1), x)["params"]

        def loss(p):
            out, aux = mod.apply({"params": p}, x)
            return jnp.sum(out**2) + 0.01 * aux

        g = jax.grad(loss)(params)
        for name in ("router", "w_in", "w_out"):
            assert float(jnp.abs(g[name]).sum()) > 0, name


class TestMoEExpertParallel:
    def test_ep_matches_local(self, rng):
        """ep=4 all_to_all dispatch must equal the single-device MoE."""
        ep = 4
        mesh = parallel_state.initialize_model_parallel(
            devices=jax.devices()[:ep]
        )  # dp=4 used as the expert axis
        e = 8
        local = MoEMLP(config=cfg(), num_experts=e, expert_axis=None)
        x = jax.random.normal(rng, (TOK, H), jnp.float32)
        params = local.init(jax.random.fold_in(rng, 1), x)["params"]
        want, aux_want = local.apply({"params": params}, x)

        ep_mod = MoEMLP(config=cfg(), num_experts=e, expert_axis="dp")
        local_e = e // ep
        # shard the expert weights: rank r holds experts [r*local_e, ...)
        shard_spec = {
            "router": P(),
            "w_in": P("dp"),
            "w_out": P("dp"),
        }

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(shard_spec, P()),
            out_specs=(P(), P()), check_vma=False,
        )
        def run(params, x):
            out, aux = ep_mod.apply({"params": params}, x)
            return out, aux

        got, aux_got = run(params, x)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(aux_got, aux_want, rtol=1e-5)


class TestMoEInTransformer:
    def test_layer_with_moe_mlp(self, rng):
        from apex_tpu.transformer import ParallelTransformerLayer

        c = cfg()
        import dataclasses
        c = dataclasses.replace(c, num_moe_experts=4)
        layer = ParallelTransformerLayer(config=c)
        h = jax.random.normal(rng, (8, 2, H), jnp.float32)
        variables = layer.init(rng, h)
        out, inter = layer.apply(
            variables, h, mutable=["intermediates"]
        )
        assert out.shape == h.shape
        aux = inter["intermediates"]["moe_aux_loss"][0]
        assert float(aux) > 0
        from apex_tpu.transformer.moe import total_moe_aux_loss
        total = total_moe_aux_loss(inter, c)
        np.testing.assert_allclose(total, c.moe_aux_loss_coeff * aux, rtol=1e-6)
