"""Native host runtime (csrc/apex_tpu_C.cpp) tests: the C++ path must load
on this image and agree exactly with the numpy fallback (ref style: the
extension-build matrix tests, tests/docker_extension_builds/run.sh)."""

import numpy as np
import pytest

from apex_tpu import _native
from apex_tpu.data import IndexedTokenDataset, LMDataset, write_token_file


class TestNativeLib:
    def test_library_compiles_and_loads(self):
        assert _native.available(), "g++ is baked into the image; the native path must build"

    def test_gather_rows_matches_numpy(self):
        data = np.arange(100, dtype=np.int32)
        offs = np.array([0, 10, 50, 93], np.int64)
        out = _native.gather_rows(data, offs, 7)
        want = np.stack([data[o : o + 7] for o in offs])
        np.testing.assert_array_equal(out, want)
        with pytest.raises(IndexError):
            _native.gather_rows(data, np.array([95], np.int64), 7)

    def test_gather_rows_u16(self):
        data = np.arange(50, dtype=np.uint16)
        out = _native.gather_rows(data, np.array([3, 9], np.int64), 4)
        np.testing.assert_array_equal(out, [[3, 4, 5, 6], [9, 10, 11, 12]])

    def test_flatten_unflatten_round_trip(self):
        rng = np.random.RandomState(0)
        bufs = [rng.randn(3, 4).astype(np.float32), rng.randn(7).astype(np.float32)]
        flat = _native.flatten(bufs)
        np.testing.assert_array_equal(
            flat, np.concatenate([b.ravel() for b in bufs])
        )
        back = _native.unflatten(flat, [(3, 4), (7,)])
        for b, w in zip(back, bufs):
            np.testing.assert_array_equal(b, w)

    def test_permutation_is_deterministic_bijection(self):
        p1 = _native.permutation(1000, seed=42)
        p2 = _native.permutation(1000, seed=42)
        p3 = _native.permutation(1000, seed=43)
        np.testing.assert_array_equal(p1, p2)
        assert not np.array_equal(p1, p3)
        assert sorted(p1.tolist()) == list(range(1000))

    def test_lm_sample_offsets(self):
        offs = _native.lm_sample_offsets(101, 10)
        np.testing.assert_array_equal(offs, np.arange(10) * 10)


class TestIndexedDataset:
    def test_lm_dataset_batches(self, tmp_path):
        tokens = np.arange(1000, dtype=np.int32)
        prefix = str(tmp_path / "corpus")
        write_token_file(prefix, tokens, doc_offsets=[0, 500])
        ds = IndexedTokenDataset(prefix)
        assert len(ds) == 1000
        np.testing.assert_array_equal(ds.doc_offsets, [0, 500])
        lm = LMDataset(ds, seq_len=16)
        assert len(lm) == (1000 - 1) // 16
        x, y = lm.batch([0, 3])
        np.testing.assert_array_equal(x[0], np.arange(16))
        np.testing.assert_array_equal(y[0], np.arange(1, 17))
        np.testing.assert_array_equal(x[1], np.arange(48, 64))
        perm = lm.epoch_permutation(epoch=1)
        assert sorted(perm.tolist()) == list(range(len(lm)))


class TestFallbackParity:
    def test_permutation_fallback_bit_equal(self, monkeypatch):
        """The numpy fallback must produce the SAME shuffle as the native
        path (reproducible resume without the compiler)."""
        native = _native.permutation(257, seed=123)
        monkeypatch.setattr(_native, "_load", lambda: None)
        fallback = _native.permutation(257, seed=123)
        np.testing.assert_array_equal(native, fallback)

    def test_dtype_sidecar_round_trip(self, tmp_path):
        tokens = np.arange(100, dtype=np.uint16)
        prefix = str(tmp_path / "u16")
        write_token_file(prefix, tokens)
        ds = IndexedTokenDataset(prefix)  # dtype discovered from sidecar
        assert ds.tokens.dtype == np.uint16
        np.testing.assert_array_equal(ds.tokens[:5], [0, 1, 2, 3, 4])
        with pytest.raises(ValueError):
            IndexedTokenDataset(prefix, dtype=np.int32)
